#!/usr/bin/env python
"""Headline benchmark: one RunOnce scale-up simulation at reference-killing scale.

Scenario (BASELINE.json config #5 shape): 50k pending pods × 5k candidate
nodes × 20 node groups, with taints/tolerations, nodeSelectors, GPU extended
resources and self-anti-affinity groups. The reference's own positioning for
this problem: 1000-node clusters with a ≤60 s scale-up SLO and microbenchmarks
that disclaim absolute numbers (BASELINE.md); our target is the sim in
< 200 ms on one TPU chip.

Measures: steady-state on-device latency of ops.autoscale_step.scale_up_sim —
the filter-out-schedulable pack + all 20 binpacking expansion options +
expander scoring (reference hot loops A+B, SURVEY.md §3.1) — after
compilation. Host-side string→tensor encoding happens once per cluster
*change* in production and is reported separately on stderr, not in the metric
(the reference benchmark likewise builds its snapshot outside the timed loop,
core/bench/benchmark_runonce_test.go:404-418).

Methodology: the TPU in this environment sits behind a network tunnel whose
per-synchronization round trip (~70 ms) dwarfs device time, so single-dispatch
wall clock measures the tunnel, not the simulator. We therefore time chains of
data-dependent sims (each iteration consumes a scalar from the previous
output, so nothing overlaps) and difference two chain lengths:
  per_sim = (T(chain k2) - T(chain k1)) / (k2 - k1)
which cancels the fixed sync cost exactly — the standard throughput
methodology for accelerators behind an async dispatch queue. p50 over
`--iters` chain pairs.

Prints exactly one JSON line:
  {"metric": ..., "value": <p50 ms>, "unit": "ms", "vs_baseline": <200/value>}

Resilience — the NEVER-NULL contract: the TPU sits behind a network tunnel
that can flap or hang at init. The bench must still produce a measured
number every round (rounds 1-5 lesson: five consecutive null JSONs = flying
blind on speed). Three layers:

  * backend AUTODETECT in a subprocess (`probe_backend`): a hung tunnel
    hangs the probe child, which is killed at its timeout — the parent
    process never touches the broken backend, so it can still run JAX on
    CPU afterwards;
  * a TOTAL init budget (`InitBudget`, env KA_TPU_BENCH_TOTAL_BUDGET_S,
    default 180 s) spanning backend init + encode + upload + compile,
    replacing the old compounding 5×120 s retry ladder: every retry's
    timeout is clamped to the remaining budget and backoff stops at the
    deadline. The probe runs ahead of the budget under its own timeout
    (child and parent share no init warmth — one budget across both would
    degrade a healthy-but-slow tunnel); worst-case wall to degradation is
    probe timeout + budget, still minutes, not tens of minutes;
  * graceful DEGRADATION: when the probe or any budget-capped init stage
    fails, the bench re-runs itself as a CPU floor child
    (`--floor-for <metric>`): reduced smoke shapes on the CPU backend, the
    SAME headline metric name, `"backend": "cpu-floor"` — a deterministic
    lower-bound data point that keeps the perf trajectory measurable.

Every JSON line carries a `"backend"` field: `tpu`, `cpu-floor` (smoke
shapes on CPU — both the deliberate `--smoke` mode and automatic
degradation; the `"mode"` field distinguishes `smoke` from `floor`), or
the jax platform for an explicit full-shape CPU run. A null `value` is
only possible under `--require-tpu`, which disables degradation for
rounds that must not silently fall back. docs/BENCH.md documents the contract.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import traceback

import numpy as np

RETRIES = int(os.environ.get("KA_TPU_BENCH_RETRIES", "5"))
BACKOFF_S = float(os.environ.get("KA_TPU_BENCH_BACKOFF_S", "3"))
BACKOFF_CAP_S = 60.0
INIT_TIMEOUT_S = float(os.environ.get("KA_TPU_BENCH_INIT_TIMEOUT_S", "120"))
# total wall-clock allowance for everything before the first measured sample
# (probe + backend init + encode + upload + compile). The old ladder could
# compound to 5 attempts × 120 s PER STAGE; this deadline spans them all.
TOTAL_BUDGET_S = float(os.environ.get("KA_TPU_BENCH_TOTAL_BUDGET_S", "180"))

# the run_bench double-buffer demo's tracer, recorded into the flight
# recorder by bench_trace so the overlapping encode/fetch spans land in the
# dumped Perfetto file (CI asserts the overlap)
_PIPELINE_TRACER = None

# per-member tracers from one synchronized multi-tenant round (--tenants
# with --trace): each carries the merged server-side `batch` span, so the
# dumped Perfetto file shows the coalescing window (docs/SERVING.md)
_TENANT_TRACERS: list = []


class InitBudget:
    """Deadline shared by every init stage: `clamp(s)` bounds a stage's
    timeout by the remaining budget (raising TimeoutError once exhausted so
    callers degrade instead of starting a stage they cannot finish), and
    `deadline` stops `with_retries` backoff from sleeping past the end."""

    def __init__(self, total_s: float = TOTAL_BUDGET_S, clock=time.monotonic):
        self.total_s = total_s
        self._clock = clock
        self._t0 = clock()

    @property
    def deadline(self) -> float:
        return self._t0 + self.total_s

    def remaining(self) -> float:
        return max(self.deadline - self._clock(), 0.0)

    def clamp(self, seconds: float) -> float:
        rem = self.remaining()
        if rem <= 0:
            raise TimeoutError(
                f"total init budget ({self.total_s:.0f}s, "
                f"KA_TPU_BENCH_TOTAL_BUDGET_S) exhausted")
        return min(seconds, rem)


def with_timeout(fn, seconds=INIT_TIMEOUT_S):
    """Run fn() with a hard wall-clock bound. A DOWN tunnel makes backend
    discovery HANG (observed live) rather than raise — without this, no retry
    ever fires and no error JSON is ever printed. The worker is a DAEMON
    thread (ThreadPoolExecutor would block interpreter exit joining the hung
    worker), so a never-returning call cannot wedge the process.

    `seconds` may be a callable (e.g. `lambda: budget.clamp(120)`) so each
    retry attempt re-reads the remaining init budget."""
    import threading

    def wrapped():
        secs = seconds() if callable(seconds) else seconds
        result: list = []
        error: list = []

        def run():
            try:
                result.append(fn())
            except Exception as e:  # noqa: BLE001 — forwarded to caller
                error.append(e)

        t = threading.Thread(target=run, daemon=True, name="bench-init")
        t.start()
        t.join(timeout=secs)
        if t.is_alive():
            raise TimeoutError(
                f"backend touch exceeded {secs:.0f}s (tunnel hang?)")
        if error:
            raise error[0]
        return result[0]

    return wrapped


def with_retries(fn, what: str, attempts: int = RETRIES,
                 backoff_s: float = BACKOFF_S, sleep=time.sleep,
                 deadline: float | None = None, clock=time.monotonic):
    """Run fn() with bounded exponential-backoff retries; re-raises the last
    error after `attempts` failures. Transient tunnel/backend errors surface
    as assorted RuntimeErrors, so every Exception is retryable here.

    `deadline` (a `clock()` timestamp — InitBudget.deadline) caps the TOTAL
    ladder: once sleeping the next backoff would cross it, the last error is
    re-raised immediately instead of burning more wall clock on a tunnel
    that is not coming back."""
    last: Exception | None = None
    for k in range(max(attempts, 1)):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — deliberately broad (see docstring)
            last = e
            if k + 1 >= attempts:
                break
            delay = min(backoff_s * (2 ** k), BACKOFF_CAP_S)
            if deadline is not None and clock() + delay >= deadline:
                print(f"[bench] {what} failed (attempt {k + 1}/{attempts}) "
                      f"and the init budget is exhausted; giving up",
                      file=sys.stderr)
                break
            print(f"[bench] {what} failed (attempt {k + 1}/{attempts}): "
                  f"{type(e).__name__}: {e}; retrying in {delay:.0f}s",
                  file=sys.stderr)
            sleep(delay)
    raise last  # type: ignore[misc]


def probe_backend(timeout_s: float) -> str | None:
    """Backend autodetect in a SUBPROCESS: returns the default jax platform
    ('tpu', 'cpu', ...) or None when discovery crashed or hung. A hung
    tunnel hangs the child, which is killed at the timeout — the parent
    never touches the broken backend, so its own interpreter can still
    import jax on the CPU floor path afterwards (an in-process daemon-thread
    probe would leave the backend lock wedged forever)."""
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=max(timeout_s, 1.0))
    except subprocess.TimeoutExpired:
        print(f"[bench] backend probe hung past {timeout_s:.0f}s (tunnel "
              f"down?)", file=sys.stderr)
        return None
    if proc.returncode != 0:
        err_lines = proc.stderr.strip().splitlines()
        print(f"[bench] backend probe failed: "
              f"{err_lines[-1] if err_lines else 'no stderr'}",
              file=sys.stderr)
        return None
    out = proc.stdout.strip().splitlines()
    return out[-1].strip() if out else None


def run_floor_child(metric: str, args) -> int:
    """Degraded mode: re-run ourselves as a CPU floor child emitting the
    SAME headline metric name with `backend: cpu-floor` — reduced shapes, a
    deterministic lower-bound number, never a null. The child is a fresh
    process because this interpreter may already have touched (and wedged
    on) the TPU backend."""
    cmd = [sys.executable, os.path.abspath(__file__), "--floor-for", metric]
    if args.trace:
        cmd += ["--trace", args.trace]
    if args.schedulable_world:
        cmd += ["--schedulable-world"]
    if args.tenants:
        cmd += ["--tenants", str(args.tenants),
                "--tenant-rounds", str(args.tenant_rounds)]
        if args.tail_dump:
            cmd += ["--tail-dump", args.tail_dump]
        if args.chaos:
            # the chaos schedule is host-side orchestration — it degrades
            # WITH the floor instead of vanishing from the evidence
            cmd += ["--chaos"]
    if args.no_batching:
        cmd += ["--no-batching"]
    if args.journal:
        # the record→replay round trip is host-side — it degrades WITH the
        # floor instead of silently disappearing from the evidence
        cmd += ["--journal", args.journal]
    if args.world_store:
        # same contract: the delta-vs-full churn evidence survives a dead
        # tunnel on the CPU floor
        cmd += ["--world-store"]
    if getattr(args, "lineage", ""):
        # the lineage ring + offline index are host dict work over the
        # journal — the provenance evidence degrades WITH the floor
        cmd += ["--lineage", args.lineage]
    if args.chaos_local:
        # the control-loop chaos schedule is host-side orchestration — it
        # degrades WITH the floor instead of vanishing from the evidence
        cmd += ["--chaos-local"]
    if getattr(args, "shadow_audit", False):
        # the audit contract is host-side verification over whatever
        # backend serves — it degrades WITH the floor
        cmd += ["--shadow-audit"]
    if args.device_stats:
        # the residency census and compile census are host-side bookkeeping
        # over whatever backend serves; the block degrades WITH the floor
        # (device_stats_source flips to host-fallback) instead of vanishing
        cmd += ["--device-stats"]
    if getattr(args, "fused", False):
        # fused-vs-phased identity and round-trip evidence is backend-
        # independent composition — it degrades WITH the floor
        cmd += ["--fused"]
    if getattr(args, "whatif", False):
        # the multiverse-vs-serial evidence is backend-independent
        # composition too — it degrades WITH the floor
        cmd += ["--whatif"]
    if getattr(args, "all", False):
        # the child re-expands --all itself (and owns the combined line;
        # this parent's stdout tee never saw the child's fd writes)
        cmd += ["--all"]
    if getattr(args, "history", ""):
        # same reason: the child's records bypass our tee (inherited fd),
        # so the CHILD appends them — run id shared via KA_BENCH_RUN_ID
        cmd += ["--history", args.history]
        if getattr(args, "check_regressions", False):
            cmd += ["--check-regressions"]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    print(f"[bench] degrading to CPU floor metric: {' '.join(cmd[1:])}",
          file=sys.stderr)
    try:
        proc = subprocess.run(cmd, env=env, timeout=1200)
        return proc.returncode
    except subprocess.TimeoutExpired as e:
        # even a wedged CPU floor must leave a parseable artifact — this is
        # the last line of the never-null contract's defense
        emit_failure(metric, e, backend="cpu-floor")
        return 1


# bench JSON record schema (mirrors perfwatch.history.SCHEMA_VERSION —
# kept as a literal so importing it never touches the package tree before
# the backend probe; tests assert the two stay equal). v2 added
# schema_version + the propagated run_id: the floor child used to emit
# unversioned records uncorrelated with the parent that spawned it.
SCHEMA_VERSION = 2


def bench_run_id() -> str:
    """The run correlation id: one id for the whole invocation INCLUDING
    a degraded floor child — the child inherits KA_BENCH_RUN_ID through
    the environment, so parent and child records join in the history."""
    rid = os.environ.get("KA_BENCH_RUN_ID", "")
    if not rid:
        rid = f"{int(time.time()):x}-{os.getpid():04x}"
        os.environ["KA_BENCH_RUN_ID"] = rid
    return rid


class _MetricTee:
    """stdout wrapper: buffers writes line-wise, STAMPS each parseable
    {"metric": ...} JSON line with schema_version + run_id before it
    reaches the terminal, and captures it keyed by metric name (last
    write wins — the re-printed headline dedups itself). The capture
    feeds --all's combined line, the --history appends and the final
    summary table; non-JSON output passes through untouched."""

    def __init__(self, stream, stamp: dict | None = None):
        self.stream = stream
        self.stamp = stamp or {}
        self.results: dict = {}
        self._buf = ""

    def write(self, s):
        self._buf += s
        while "\n" in self._buf:
            line, self._buf = self._buf.split("\n", 1)
            stripped = line.strip()
            if stripped.startswith("{"):
                try:
                    obj = json.loads(stripped)
                except ValueError:
                    obj = None
                if isinstance(obj, dict) and obj.get("metric"):
                    for k, v in self.stamp.items():
                        obj.setdefault(k, v)
                    self.results[obj["metric"]] = obj
                    line = json.dumps(obj)
            self.stream.write(line + "\n")
        return len(s)

    def flush(self):
        self.stream.flush()

    def detach(self) -> dict:
        """Flush any partial line through unstamped and return captures."""
        if self._buf:
            self.stream.write(self._buf)
            self._buf = ""
        self.stream.flush()
        return self.results

    def __getattr__(self, name):
        return getattr(self.stream, name)


def emit_failure(metric: str, err: Exception, backend: str | None = None) -> None:
    """The evidence-preserving failure path: one parseable JSON line. Only
    reachable when degradation is disabled (--require-tpu) or the CPU floor
    itself failed."""
    print(json.dumps({
        "metric": metric,
        "value": None,
        "unit": "ms",
        "vs_baseline": 0.0,
        "backend": backend,
        "error": f"{type(err).__name__}: {err}",
    }))


def build_world(n_nodes: int, n_pods: int, n_groups: int, n_nodegroups: int,
                schedulable: bool = False):
    from kubernetes_autoscaler_tpu.models.api import Taint, Toleration
    from kubernetes_autoscaler_tpu.models.cluster_state import DEFAULT_DIMS
    from kubernetes_autoscaler_tpu.models.encode import (
        encode_cluster,
        encode_node_groups,
    )
    from kubernetes_autoscaler_tpu.utils.testing import build_test_node, build_test_pod

    rng = np.random.RandomState(0)
    zones = ["us-a", "us-b", "us-c"]
    nodes = []
    for i in range(n_nodes):
        taints = [Taint("dedicated", "infra", "NoSchedule")] if i % 10 == 0 else []
        nodes.append(
            build_test_node(
                f"node-{i}",
                cpu_milli=16000,
                mem_mib=65536,
                pods=110,
                labels={"pool": "a" if i % 2 else "b", "disk": "ssd" if i % 3 else "hdd"},
                taints=taints,
                zone=zones[i % 3],
                gpus=8 if i % 25 == 0 else 0,
            )
        )

    per_group = n_pods // n_groups
    pods = []
    for g in range(n_groups):
        cpu = int(rng.choice([250, 500, 1000, 2000, 4000]))
        mem = int(rng.choice([256, 512, 2048, 8192]))
        sel = {"disk": "ssd"} if g % 4 == 0 else {}
        tol = [Toleration(key="dedicated", operator="Equal", value="infra",
                          effect="NoSchedule")] if g % 5 == 0 else []
        gpus = 1 if g % 7 == 0 else 0
        if schedulable:
            # --schedulable-world: no constraint diversity AND demand that
            # fits EXISTING capacity, so every pod schedules and the LAZY
            # reason pass must never dispatch (CI asserts
            # reason_extraction_dispatches == 0 on this shape)
            sel, tol, gpus = {}, [], 0
            cpu, mem = 250, 256
        for i in range(per_group):
            p = build_test_pod(
                f"pod-{g}-{i}", cpu_milli=cpu, mem_mib=mem, owner_name=f"rs-{g}",
                node_selector=sel, tolerations=tol, gpus=gpus,
            )
            pods.append(p)

    t0 = time.perf_counter()
    enc = encode_cluster(nodes, pods, node_bucket=256, group_bucket=64)
    encode_s = time.perf_counter() - t0

    # Pre-existing load: 40% of every node's cpu/mem already requested
    # (reference scale-down benchmark shape, benchmark_runonce_test.go:424-453).
    import jax.numpy as jnp

    alloc = np.asarray(enc.nodes.cap) * 0
    cap = np.asarray(enc.nodes.cap)
    alloc[:, 0] = (cap[:, 0] * 0.4).astype(np.int32)
    alloc[:, 1] = (cap[:, 1] * 0.4).astype(np.int32)
    alloc[:, 3] = (cap[:, 3] * 0.3).astype(np.int32)
    enc.nodes = enc.nodes.replace(alloc=jnp.asarray(alloc))

    templates = []
    for k in range(n_nodegroups):
        cpu = [4000, 8000, 16000, 32000][k % 4]
        mem = [16384, 32768, 65536, 131072][k % 4]
        tmpl = build_test_node(
            f"template-{k}", cpu_milli=cpu, mem_mib=mem, pods=110,
            labels={"pool": "a" if k % 2 else "b", "disk": "ssd" if k % 3 else "hdd"},
            zone=zones[k % 3], gpus=8 if k % 5 == 0 else 0,
        )
        templates.append((tmpl, 1000, float(1 + k)))
    groups = encode_node_groups(templates, enc.registry, enc.zone_table)
    return enc, groups, encode_s


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=5000)
    ap.add_argument("--pods", type=int, default=50000)
    ap.add_argument("--pod-groups", type=int, default=25)
    ap.add_argument("--nodegroups", type=int, default=20)
    ap.add_argument("--max-new-nodes", type=int, default=1024)
    ap.add_argument("--iters", type=int, default=7)
    ap.add_argument("--chain", type=int, default=25, help="long chain length k2")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-backend smoke mode: small shapes on "
                         "JAX_PLATFORMS=cpu so the bench records a real "
                         "(non-null) value even when the TPU tunnel is down; "
                         "scale-down/e2e phases off unless forced")
    ap.add_argument("--wavefront", type=int, default=1,
                    help="batch the existing-nodes pack scan into conflict-"
                         "free wavefronts (ops/pack.py) — serial depth W "
                         "instead of G; 0 = serial scan")
    ap.add_argument("--mesh-devices", type=int, default=0,
                    help="shard the sim over an N-device mesh (nodes axis → "
                         "NODES_AXIS, nodegroup options → PODS_AXIS); 0 = "
                         "single device. With --smoke, virtual CPU devices "
                         "are forced.")
    ap.add_argument("--scaledown", type=int, default=None,
                    help="also time the scale-down planner (device sweep + "
                         "host confirmation) at --nodes scale; stderr only")
    ap.add_argument("--e2e", type=int, default=None,
                    help="also measure END-TO-END RunOnce (encode deltas + "
                         "sim + plan + confirm) at --nodes/--pods scale; "
                         "prints a second runonce_e2e_p50 JSON line")
    ap.add_argument("--e2e-loops", type=int, default=8)
    ap.add_argument("--trace", default="",
                    help="write a Perfetto/Chrome trace of recorded RunOnce "
                         "loops (flight recorder, metrics/trace.py) — "
                         "planner + orchestrator phase spans and a sidecar "
                         "RPC sharing the final loop's trace id — to this "
                         "path; runs even in --smoke mode")
    ap.add_argument("--schedulable-world", action="store_true",
                    help="drop the gpu/selector/toleration diversity from "
                         "the pending pods so every group fits some "
                         "template — the all-schedulable shape CI uses to "
                         "assert the reason plane stays off the hot path "
                         "(reason_extraction_dispatches == 0)")
    ap.add_argument("--tenants", type=int, default=0,
                    help="multi-tenant serving smoke: spin N synthetic "
                         "tenants at mixed shapes against a localhost "
                         "sidecar and measure clusters/sec through the "
                         "batched (shape-class vmapped) dispatch, plus a "
                         "serial --no-batching comparison run; prints a "
                         "multi_tenant_clusters_per_sec JSON line "
                         "(docs/SERVING.md)")
    ap.add_argument("--no-batching", action="store_true",
                    help="with --tenants: serve every request through the "
                         "legacy serial per-tenant dispatch (the baseline "
                         "the batched speedup is measured against)")
    ap.add_argument("--tenant-rounds", type=int, default=40,
                    help="scale-up sims per tenant in the measured window")
    ap.add_argument("--tail-dump", default="",
                    help="with --tenants: write the tail sampler's retained "
                         "request traces (slow/breached/failed only) as one "
                         "Perfetto file here")
    ap.add_argument("--chaos", action="store_true",
                    help="with --tenants: run the seeded fault-injection "
                         "schedule (docs/ROBUSTNESS.md) after the primary "
                         "window — one poison tenant, one transient "
                         "dispatch fault, a harvest delay, and an "
                         "in-process sidecar kill/checkpoint/rehydrate "
                         "restart — and add a `chaos` block to the JSON "
                         "asserting healthy-tenant bit-identity and "
                         "0 recompiles after rehydration")
    ap.add_argument("--world-store", action="store_true",
                    help="device-resident world-state smoke (ISSUE 11 / "
                         "docs/WORLD_STORE.md): drive an N-loop churn "
                         "sequence through two identical autoscalers — "
                         "WorldStore delta path vs per-loop full encode — "
                         "assert decision/verdict byte-identity, and print "
                         "a world_store_churn JSON line with encode_p50_ms "
                         "(both paths), h2d bytes per loop, full_encodes "
                         "and steady-state jit-cache growth (never-null on "
                         "the CPU floor — the store is host+device "
                         "bookkeeping, backend-independent)")
    ap.add_argument("--device-stats", action="store_true",
                    help="emit the device-side observability block (ISSUE "
                         "14): HBM residency ledger census reconciled "
                         "against device memory_stats (host-RSS fallback "
                         "on CPU, device_stats_source=host-fallback), "
                         "per-tenant attribution, the hbm-budget admission "
                         "reject, compile-census variants, a profiler "
                         "capture round trip, and the disabled-path guard "
                         "ns/op — never-null on both floors")
    ap.add_argument("--chaos-local", action="store_true",
                    help="run the LOCAL control loop's seeded chaos "
                         "schedule (docs/ROBUSTNESS.md 'Control loop'): a "
                         "hung dispatch aborted at its phase budget with "
                         "zero driver-thread deaths, a device loss healed "
                         "by the WorldStore digest probe with decisions "
                         "bit-identical to a cold encode, scale-down "
                         "withheld (BackendDegraded surfaced) while "
                         "degraded and re-enabled after the recovery "
                         "hysteresis, and a kill/restart resuming the "
                         "unneeded-since timers — printed as a "
                         "local_chaos_control_loop JSON line (never-null "
                         "on the CPU floor)")
    ap.add_argument("--shadow-audit", action="store_true",
                    help="online shadow-audit smoke (audit/shadow.py): "
                         "measured audit overhead fraction + zero "
                         "divergence on a healthy run, a forced single-"
                         "bit verdict corruption detected within one "
                         "loop with a complete evidence bundle and the "
                         "suspect ladder transition, post-heal decisions "
                         "bit-identical to a cold encode, and the "
                         "sidecar's per-window lane audit")
    ap.add_argument("--journal", default="", metavar="DIR",
                    help="record a short RunOnce sequence into a "
                         "deterministic flight journal under DIR, measure "
                         "the journaling overhead against loop walltime, "
                         "then REPLAY the journal in-process and print a "
                         "journal_record_replay_smoke JSON line with the "
                         "drift report (never-null on the CPU floor — "
                         "journaling and replay are host-side; "
                         "docs/REPLAY.md)")
    ap.add_argument("--lineage", default="", metavar="DIR",
                    help="run a lineage_smoke phase: record the shared "
                         "journaled story world under DIR with the live "
                         "lineage ring on, report the ring's steady-loop "
                         "overhead fraction, the offline LineageIndex "
                         "build rate and why/timeline/diff query p50s, "
                         "and verify the index reconstructs the injected "
                         "refusal→scale-up→resolution story "
                         "(docs/LINEAGE.md)")
    ap.add_argument("--fused", action="store_true",
                    help="fused single-dispatch loop smoke (ISSUE 17 / "
                         "docs/FUSED_LOOP.md): drive twin worlds through "
                         "identical churn plus a steady window — fused "
                         "one-program loop vs the phased three-dispatch "
                         "path — assert loop-for-loop decision identity, "
                         "and print a fused_loop_e2e JSON line with both "
                         "p50s, the speedup ratio, per-loop device round "
                         "trips, the speculative hit rate on the steady "
                         "window and steady-state recompiles (never-null "
                         "on the CPU floor — the fused program is backend-"
                         "independent composition)")
    ap.add_argument("--whatif", action="store_true",
                    help="counterfactual multiverse smoke (docs/WHATIF.md): "
                         "branch a live fused world, fan out B=16 variant "
                         "lanes, rollout T=32 simulated loops in ONE "
                         "device dispatch — assert the null lane's decision "
                         "trajectory is byte-identical to T live fused "
                         "RunOnce loops, zero steady-state recompiles "
                         "across lanes/knob churn, and print a "
                         "whatif_multiverse JSON line with the aggregate "
                         "fused-steps/sec speedup vs the serial phased "
                         "loop on the same worlds (never-null on the CPU "
                         "floor — pure backend-independent composition)")
    ap.add_argument("--all", action="store_true",
                    help="run every never-null bench mode in this one "
                         "process (fused, whatif, world-store, journal, "
                         "lineage, chaos-local, device-stats, shadow-audit) "
                         "and "
                         "emit a single combined JSON line at the end — "
                         "one cooperating TPU-tunnel window banks real-TPU "
                         "numbers for every mode")
    ap.add_argument("--require-tpu", action="store_true",
                    help="disable the CPU-floor degradation: a missing/hung "
                         "TPU backend emits the null-value error JSON and "
                         "exits 1 (the ONLY path that may produce a null)")
    ap.add_argument("--history", default="", metavar="DIR",
                    help="append every emitted mode record to the perfwatch "
                         "history store at DIR (docs/BENCH.md 'Trajectory & "
                         "regression gate'); forwarded through the floor "
                         "child so degraded rounds bank their cpu-floor "
                         "rows under the shared run id")
    ap.add_argument("--check-regressions", action="store_true",
                    help="with --history: after appending, judge this run "
                         "against its lineage baselines and print the "
                         "verdicts (report-only; `perfwatch gate` is the "
                         "exiting surface)")
    ap.add_argument("--floor-for", default="", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.all:
        # expand into every never-null mode (the headline + scale-down +
        # e2e phases are already on by default); --journal needs a dir
        args.world_store = True
        args.chaos_local = True
        args.device_stats = True
        args.shadow_audit = True
        args.fused = True
        args.whatif = True
        if not args.journal:
            import tempfile

            args.journal = tempfile.mkdtemp(prefix="bench-all-journal-")
        if not args.lineage:
            import tempfile

            # own dir: --journal wipes and replays ITS dir; the lineage
            # story must index an undisturbed recording
            args.lineage = tempfile.mkdtemp(prefix="bench-all-lineage-")

    if args.require_tpu and (args.smoke or args.floor_for):
        # --smoke IS an explicit CPU run — combining it with --require-tpu
        # would silently skip the probe/require check and exit 0 on CPU,
        # contradicting the "only null path" promise. Refuse loudly.
        ap.error("--require-tpu is incompatible with --smoke "
                 "(smoke is an explicit CPU-backend run)")

    if args.floor_for:
        # internal degraded-child mode (run_floor_child): smoke shaping +
        # forced CPU, but the HEADLINE metric name, so the perf trajectory
        # keeps a measured floor point when the tunnel is down
        args.smoke = True

    if args.smoke:
        # fixed small shape: the point is a real steady-state number from
        # the CPU backend, not scale — tunnel-independent trajectory evidence
        args.nodes, args.pods = 128, 1500
        args.pod_groups, args.nodegroups = 12, 4
        args.max_new_nodes = 32
        args.iters, args.chain = 3, 8
        if args.scaledown is None:
            args.scaledown = 0
        if args.e2e is None:
            args.e2e = 0
        os.environ["JAX_PLATFORMS"] = "cpu"
        if args.mesh_devices > 1 and "xla_force_host_platform_device_count" \
                not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={args.mesh_devices}"
            ).strip()
    if args.scaledown is None:
        args.scaledown = 1
    if args.e2e is None:
        args.e2e = 1

    kp = args.pods // 1000
    kn = args.nodes // 1000 if args.nodes >= 1000 else args.nodes
    unit_n = "knodes" if args.nodes >= 1000 else "nodes"
    metric = (args.floor_for or
              f"scaleup_sim_p50_ms_{kp}kpods_{kn}{unit_n}_{args.nodegroups}ng")

    # one correlation id for the whole invocation — set BEFORE any floor
    # child can be spawned so parent + child records join in the history
    run_id = bench_run_id()

    can_degrade = not (args.smoke or args.floor_for or args.require_tpu)
    if not (args.smoke or args.floor_for):
        # backend autodetect BEFORE this process touches jax: a hung tunnel
        # is contained in the killed probe child. The probe has its own
        # timeout and the init budget starts AFTER it — a healthy-but-slow
        # tunnel pays full init cost twice (child + parent; separate
        # processes share no warmth), and double-charging one budget would
        # degrade a working TPU. Worst-case wall to degradation is still
        # probe timeout + budget ≈ minutes.
        platform = probe_backend(INIT_TIMEOUT_S)
        if args.require_tpu and platform != "tpu":
            emit_failure(metric, RuntimeError(
                f"--require-tpu but backend probe found "
                f"{platform or 'no usable backend'}"), backend=platform)
            sys.exit(1)
        if platform is None:
            # discovery hung or crashed → the floor child keeps the round
            # measured (probe child was killed; our interpreter is clean)
            sys.exit(run_floor_child(metric, args))

    # the tee is always on now: every record leaves stamped with
    # schema_version + run_id, and the captures feed --history / --all
    tee = _MetricTee(sys.stdout,
                     stamp={"schema_version": SCHEMA_VERSION,
                            "run_id": run_id})
    sys.stdout = tee
    t_bench = time.perf_counter()
    try:
        run_bench(args, metric, budget=InitBudget())
    except Exception as e:  # noqa: BLE001 — evidence-preserving failure path
        traceback.print_exc(file=sys.stderr)
        if can_degrade:
            sys.stdout = tee.stream
            sys.exit(run_floor_child(metric, args))
        emit_failure(metric, e,
                     backend="cpu-floor" if args.smoke or args.floor_for
                     else None)
        _finish(args, tee, run_id, time.perf_counter() - t_bench)
        sys.exit(1)
    _finish(args, tee, run_id, time.perf_counter() - t_bench)


def _finish(args, tee: _MetricTee, run_id: str, bench_wall_s: float) -> None:
    """The epilogue behind every exit that emitted records: the --all
    combined line, the --history appends (with their measured overhead —
    CI asserts append_ms ≤ 1% of bench wall), the advisory regression
    check, and the --all per-mode summary table."""
    results = tee.detach()
    sys.stdout = tee.stream
    if args.all:
        print(json.dumps({
            "metric": "bench_all_combined",
            "schema_version": SCHEMA_VERSION,
            "run_id": run_id,
            "modes": sorted(results),
            "results": results,
        }), flush=True)
    mode_records = {name: obj for name, obj in results.items()
                    if name not in ("bench_all_combined", "perfwatch_log")}
    verdicts = None
    if args.history and mode_records:
        try:
            from kubernetes_autoscaler_tpu.perfwatch.history import (
                PerfHistory,
                git_commit,
            )

            t0 = time.perf_counter()
            hist = PerfHistory(args.history)
            commit = git_commit()
            now = time.time()
            for name in sorted(mode_records):
                hist.append_bench_record(mode_records[name], run_id=run_id,
                                         commit=commit, ts=now)
            append_ms = (time.perf_counter() - t0) * 1000.0
            print(json.dumps({
                "metric": "perfwatch_log",
                "schema_version": SCHEMA_VERSION,
                "run_id": run_id,
                "history": args.history,
                "appended": len(mode_records),
                "append_ms": round(append_ms, 3),
                "bench_wall_ms": round(bench_wall_s * 1000.0, 3),
            }), flush=True)
        except Exception as e:  # noqa: BLE001 — evidence, not control flow
            print(f"[bench] history append failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    if args.history and args.check_regressions:
        try:
            from kubernetes_autoscaler_tpu.perfwatch.detect import (
                RegressionDetector,
                gating_regressions,
            )
            from kubernetes_autoscaler_tpu.perfwatch.history import (
                PerfHistory,
            )
            from kubernetes_autoscaler_tpu.perfwatch.report import (
                verdict_lines,
            )

            rows = PerfHistory(args.history).load()
            verdicts = RegressionDetector().check_run(rows, run_id)
            for line in verdict_lines(verdicts):
                print(f"[bench] {line}", file=sys.stderr)
            n_reg = len(gating_regressions(verdicts))
            print(f"[bench] regression check: {len(verdicts)} verdicts, "
                  f"{n_reg} gating regressions (advisory — `perfwatch "
                  f"gate` is the exiting surface)", file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"[bench] regression check failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    if args.all and mode_records:
        try:
            from kubernetes_autoscaler_tpu.perfwatch.report import (
                mode_summary_table,
            )

            print("[bench] per-mode summary:", file=sys.stderr)
            print(mode_summary_table(mode_records, verdicts),
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001
            print(f"[bench] summary table failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)


def run_bench(args, metric: str, budget: InitBudget | None = None) -> None:
    if budget is None:
        budget = InitBudget()

    # kernel-module import runs module-level jnp constants, so even the import
    # is a backend touch — the whole init stage retries as one unit
    def _init():
        import jax

        if args.smoke:
            # the axon sitecustomize force-registers the TPU backend over
            # JAX_PLATFORMS; the config knob wins if set before first use
            jax.config.update("jax_platforms", "cpu")

        from kubernetes_autoscaler_tpu.ops.autoscale_step import scale_up_sim

        return jax, jax.devices()[0], scale_up_sim

    jax, dev, scale_up_sim = with_retries(
        with_timeout(_init, seconds=lambda: budget.clamp(INIT_TIMEOUT_S)),
        "backend init", deadline=budget.deadline)
    # the trajectory's provenance field: every JSON line says what actually
    # produced the number (tpu | cpu-floor | an explicit CPU run's platform)
    backend = ("cpu-floor" if args.smoke or args.floor_for
               else str(dev.platform))
    import jax.numpy as jnp

    from kubernetes_autoscaler_tpu.metrics.metrics import Registry
    from kubernetes_autoscaler_tpu.metrics.phases import PhaseStats
    from kubernetes_autoscaler_tpu.models.cluster_state import DEFAULT_DIMS

    # first-class registry metrics (not just bench-JSON fields): phase
    # histograms + event counters (wavefront cache, transfers) ride the
    # normal exposition path, and steady_state_recompiles lands as a gauge
    registry = Registry()
    phases = PhaseStats(owner="bench", registry=registry)

    mesh = None
    if args.mesh_devices > 1:
        from kubernetes_autoscaler_tpu.parallel.mesh import make_mesh

        n_dev = min(args.mesh_devices, len(jax.devices()))
        pods_par = 2 if n_dev % 2 == 0 else 1
        mesh = make_mesh(n_dev, nodes_parallel=n_dev // pods_par)

    # encode ships tensors to the device, so it is also a tunnel touch
    def _encode():
        with phases.phase("encode"):
            return build_world(args.nodes, args.pods,
                               args.pod_groups, args.nodegroups,
                               schedulable=args.schedulable_world)

    enc, groups, encode_s = with_retries(
        with_timeout(_encode,
                     seconds=lambda: budget.clamp(max(INIT_TIMEOUT_S, 180))),
        "world encode + upload", deadline=budget.deadline,
    )

    def _upload():
        if mesh is None:
            return jax.device_put(
                (enc.nodes, enc.specs, enc.scheduled, groups), dev)
        # mesh run: node tensors sharded over NODES_AXIS, the rest
        # replicated — inputs must span the mesh's devices, not chip 0
        from kubernetes_autoscaler_tpu.parallel.mesh import cluster_shardings

        node_spec, _pod_spec, repl = cluster_shardings(mesh)
        nodes_s = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, node_spec(x.ndim)), enc.nodes)
        rest = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, repl),
            (enc.specs, enc.scheduled, groups))
        return (nodes_s, *rest)

    nodes, specs, sched, groups = with_retries(
        with_timeout(_upload, seconds=lambda: budget.clamp(INIT_TIMEOUT_S)),
        "device upload", deadline=budget.deadline)

    # wavefront plan: host coloring of the mask-overlap graph, ONCE per
    # composition (the chain only churns counts → the cache would hit every
    # loop in production). Mutually exclusive with the sharded pack.
    # The mask fetch is the PREDICATE-PLANE transfer whose bit-packing win
    # the JSON reports: bool leaves ride 1 bit/verdict (ops/bitplane via
    # ops/hostfetch), and the moved-vs-logical byte counters around this
    # block measure the reduction (CI asserts ≥4×).
    plan = None
    plane_fetch = None
    if args.wavefront and mesh is None:
        from kubernetes_autoscaler_tpu.ops.pack import WavefrontCache
        from kubernetes_autoscaler_tpu.ops.schedule import plan_wavefronts

        wf_cache = WavefrontCache()
        moved0 = phases.events.get("batched_fetch_bytes_moved", 0)
        logical0 = phases.events.get("batched_fetch_bytes_logical", 0)
        with phases.phase("fetch"):
            plan = with_retries(
                with_timeout(
                    lambda: plan_wavefronts(nodes, specs, wf_cache,
                                            phases=phases),
                    seconds=lambda: budget.clamp(INIT_TIMEOUT_S)),
                "wavefront planning", deadline=budget.deadline)
        moved = phases.events.get("batched_fetch_bytes_moved", 0) - moved0
        logical = phases.events.get("batched_fetch_bytes_logical", 0) - logical0
        plane_fetch = {
            "bytes_moved": moved,
            "bytes_logical": logical,
            "reduction": round(logical / moved, 2) if moved else None,
        }
        g_active = plan.n_active
        print(f"[bench] wavefronts: W={plan.n_waves} of G={g_active} "
              f"(worthwhile={plan.worthwhile}); plane fetch "
              f"{moved}B moved vs {logical}B logical "
              f"({plane_fetch['reduction']}x)", file=sys.stderr)
        if not plan.worthwhile:
            plan = None   # overlap-heavy composition: keep the serial scan

    @jax.jit
    def step(nodes, specs, sched, groups, token, plan):
        # Thread a device scalar through each iteration so chained sims are
        # data-dependent. The bump is always 0 — token is out.best from the
        # previous sim, which lives in [-1, NG) and never hits the sentinel —
        # but XLA cannot know that, so iterations serialize.
        bump = jnp.where(token == jnp.int32(-(1 << 30)), 1, 0).astype(jnp.int32)
        specs = specs.replace(count=specs.count + bump)
        return scale_up_sim.__wrapped__(
            nodes, specs, sched, groups,
            DEFAULT_DIMS, args.max_new_nodes, "least-waste",
            None, False, mesh, plan,
        )

    t0 = time.perf_counter()
    out = with_retries(
        with_timeout(
            lambda: jax.block_until_ready(step(nodes, specs, sched, groups,
                                               jnp.int32(0), plan)),
            seconds=lambda: budget.clamp(max(INIT_TIMEOUT_S, 300))),
        "compile + first dispatch", deadline=budget.deadline,
    )
    compile_s = time.perf_counter() - t0
    # Force the tunnel into synchronous mode so every block below is a real
    # round trip (any D2H readback does this; see module docstring).
    _ = int(out.best)

    # perf canary (CI's regression-gate demo): a PER-CHAINED-SIM delay.
    # Chain differencing cancels any fixed per-call overhead — only a
    # per-iteration cost moves the headline p50, so the injected slowdown
    # must ride inside the k-loop to be a faithful "the sim got slower"
    canary_ms = float(os.environ.get("KA_BENCH_PERF_CANARY_MS", "0") or 0)

    def chain(k: int) -> float:
        t0 = time.perf_counter()
        tok = jnp.int32(0)
        for _ in range(k):
            o = step(nodes, specs, sched, groups, tok, plan)
            tok = o.best
            if canary_ms:
                time.sleep(canary_ms / 1000.0)
        jax.block_until_ready(o)
        return (time.perf_counter() - t0) * 1000.0

    k2 = max(args.chain, 2)
    k1 = max(k2 // 5, 1)
    with_retries(lambda: chain(2), "warm-up chain")  # warm dispatch path
    compiles_before = step._cache_size()

    def measure():
        samples = []
        for _ in range(args.iters):
            with phases.phase("dispatch"):
                samples.append((chain(k2) - chain(k1)) / (k2 - k1))
        return samples

    # the measurement loop is past the init budget but still a tunnel touch:
    # a mid-run hang must surface as a TimeoutError (→ degrade/error JSON),
    # not wedge the process with zero evidence emitted
    samples = with_retries(with_timeout(measure, seconds=900),
                           "measurement loop")
    p50 = float(np.percentile(samples, 50))
    # steady-state recompile accounting: any growth of the jit cache during
    # the measurement loop means a shape/plan leak — the JSON asserts zero
    steady_recompiles = step._cache_size() - compiles_before
    registry.gauge(
        "steady_state_recompiles",
        help="jit-cache growth across the steady measurement loop "
             "(nonzero = a shape or plan leak recompiling XLA programs)",
    ).set(float(steady_recompiles))

    with phases.phase("fetch"):
        best = int(out.best)
        best_sched = int(out.estimate.scheduled[best].sum())
        best_nodes = int(out.estimate.node_count[best])

    # Reason-plane accounting (the LAZY contract, measured): groups left
    # pending that NO expansion option schedules get one masked
    # reason_mask_for_groups dispatch over the template plane — exactly what
    # the orchestrator does. On an all-schedulable world this block performs
    # ZERO dispatches and reason_overhead_ms stays 0 (CI-asserted); the
    # steady (second-call) wall clock is reported so the trajectory catches
    # hot-path regressions from the reason layer.
    reason_dispatches = 0
    reason_ms = 0.0
    rem = np.asarray(out.remaining)
    sched_ng = np.asarray(out.estimate.scheduled)        # [NG, G]
    valid_g = np.asarray(enc.specs.valid)
    refused_g = valid_g & (rem > 0) & (sched_ng.max(axis=0) <= 0)
    if refused_g.any():
        from kubernetes_autoscaler_tpu.ops import predicates as preds

        tmpl_nodes = groups.as_node_tensors(DEFAULT_DIMS)
        gmask = jnp.asarray(refused_g)

        def _reason_pass():
            return np.asarray(
                preds.reason_mask_for_groups(tmpl_nodes, specs, gmask))

        _reason_pass()                       # compile + warm
        t0 = time.perf_counter()
        bits = _reason_pass()
        reason_ms = (time.perf_counter() - t0) * 1000.0
        reason_dispatches = 1
        phases.bump("reason_extraction_dispatches")
        gvalid = np.asarray(groups.valid)
        summaries = {
            int(g): preds.summarize_reason_row(bits[g], gvalid)[0]
            for g in np.nonzero(refused_g)[0]
        }
        print(f"[bench] reason pass: {int(refused_g.sum())} refused groups "
              f"in {reason_ms:.2f}ms — {json.dumps(summaries)}",
              file=sys.stderr)

    # ---- double-buffered transfers (PR 1's batched phases, overlapped):
    # loop i's batched result fetch is issued ASYNC and harvested only after
    # loop i+1's encode upload + dispatch are in flight, so the device→host
    # copy hides under the next loop's work. The spans land on a dedicated
    # tracer (recorded into the flight recorder by the --trace phase): the
    # next loop's encode/dispatch spans nest INSIDE the still-open
    # async-fetch span — the overlap CI asserts on the dumped timeline. ----
    double_buffer = None
    if mesh is None:
        from kubernetes_autoscaler_tpu.metrics import trace as trace_mod
        from kubernetes_autoscaler_tpu.ops.hostfetch import fetch_pytree_async

        global _PIPELINE_TRACER
        pipe_tracer = trace_mod.Tracer(process="bench")
        _PIPELINE_TRACER = pipe_tracer
        counts_h = np.asarray(enc.specs.count)
        pipe_loops = 4
        t0 = time.perf_counter()
        with trace_mod.active(pipe_tracer):
            with pipe_tracer.span("pipeline", cat="bench"):
                handle = None
                tok = jnp.int32(0)
                for _ in range(pipe_loops):
                    with phases.phase("encode"):
                        # next loop's world delta upload (async under jax)
                        cdev = jax.device_put(jnp.asarray(counts_h), dev)
                        specs_i = specs.replace(count=cdev)
                    with phases.phase("dispatch"):
                        o = step(nodes, specs_i, sched, groups, tok, plan)
                        tok = o.best
                    if handle is not None:
                        # harvest the PREVIOUS loop's fetch only now — its
                        # copy overlapped this loop's encode + dispatch
                        handle.get()
                    handle = fetch_pytree_async(
                        {"best": o.best,
                         "node_count": o.estimate.node_count},
                        phases=phases)
                handle.get()
        pipe_ms = (time.perf_counter() - t0) * 1000.0
        # measured overlap: encode/dispatch span time spent inside an open
        # async-fetch window
        fetch_iv = [(s[2], s[2] + (s[3] or 0)) for s in pipe_tracer.spans
                    if s[0] == "fetch" and (s[5] or {}).get("async")]
        overlap_ns = 0
        for s in pipe_tracer.spans:
            if s[0] in ("encode", "dispatch") and s[3]:
                a0, a1 = s[2], s[2] + s[3]
                overlap_ns += sum(
                    max(0, min(a1, f1) - max(a0, f0)) for f0, f1 in fetch_iv)
        double_buffer = {
            "loops": pipe_loops,
            "wall_ms": round(pipe_ms, 3),
            "overlapped_ms": round(overlap_ns / 1e6, 3),
        }
        print(f"[bench] double-buffer: {pipe_loops} loops in "
              f"{pipe_ms:.2f}ms, {double_buffer['overlapped_ms']:.3f}ms of "
              f"encode/dispatch under an in-flight fetch", file=sys.stderr)

    # compile census (ISSUE 14): name the primary program's variant — shape
    # signature + lowered cost analysis (flops / bytes accessed). Mode
    # "cost" on purpose: no AOT re-compile against the init budget; the
    # figures come from the lowering alone.
    from kubernetes_autoscaler_tpu.metrics import device as device_obs

    primary_census = device_obs.CompileCensus(registry=registry,
                                              mode="cost")
    try:
        census_rec = with_timeout(
            lambda: primary_census.record(
                "bench_step", step,
                (nodes, specs, sched, groups, jnp.int32(0), plan)),
            seconds=60)()
    except Exception as e:  # noqa: BLE001 — census is evidence, not gating
        census_rec = {"error": f"{type(e).__name__}: {e}"}

    checks = int(np.asarray(enc.specs.count).sum()) * args.nodes
    print(
        f"[bench] device={jax.devices()[0].platform} encode={encode_s:.2f}s "
        f"compile={compile_s:.1f}s p50={p50:.2f}ms best_group={best} "
        f"scheduled={best_sched} "
        f"new_nodes={best_nodes} "
        f"steady_recompiles={steady_recompiles} "
        f"fit_checks/s={checks / (p50 / 1e3):.3e}",
        file=sys.stderr,
    )
    # the metric JSON prints FIRST: a tunnel hang in the optional scale-down
    # phase must never lose the already-measured evidence. It is re-printed
    # as the LAST line after the optional phases so both first-line and
    # last-line consumers read the headline metric; the runonce_e2e line
    # sits between them. The "phases" object decomposes the number into its
    # cost domains (metrics/phases.py) instead of shipping it opaque;
    # "spans" is the live PhaseStats breakdown (encode/dispatch/fetch totals,
    # span counts, wavefront-cache events).
    primary_line = json.dumps({
        "metric": metric,
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(200.0 / p50, 2),
        "backend": backend,
        "mode": ("floor" if args.floor_for
                 else "smoke" if args.smoke else "full"),
        **({"floor_shapes": {"nodes": args.nodes, "pods": args.pods,
                             "pod_groups": args.pod_groups,
                             "nodegroups": args.nodegroups}}
           if args.floor_for else {}),
        "steady_state_recompiles": steady_recompiles,
        "wavefronts": (None if plan is None
                       else {"w": plan.n_waves, "g": plan.n_active}),
        "mesh_devices": args.mesh_devices,
        # reason plane: dispatches MUST be 0 when every group schedules (the
        # lazy contract; CI asserts it on --schedulable-world smoke runs),
        # and the overhead is the steady wall clock of the masked second
        # dispatch + fetch when groups were refused
        "reason_extraction_dispatches": reason_dispatches,
        "reason_overhead_ms": round(reason_ms, 3),
        # bit-packed predicate-plane transfer accounting (wavefront-plan
        # mask fetch): moved vs what the unpacked layout would have shipped
        "plane_fetch": plane_fetch,
        # encode/dispatch work overlapped with in-flight async fetches
        "double_buffer": double_buffer,
        # the headline program as a NAMED compile-census variant (shape
        # signature + lowered flops/bytes; metrics/device.CompileCensus)
        "compile_census": census_rec,
        "phases": {
            "encode_ms": round(encode_s * 1000.0, 1),
            "compile_ms": round(compile_s * 1000.0, 1),
            "device_sim_ms": round(p50, 3),
        },
        "spans": phases.snapshot(),
    })
    print(primary_line, flush=True)

    if args.scaledown:
        try:
            with_timeout(lambda: bench_scaledown(args), seconds=420)()
        except Exception as e:  # stderr-only extra: never sink the metric
            print(f"[bench] scale-down phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    if args.e2e:
        try:
            with_timeout(lambda: bench_runonce_e2e(args), seconds=900)()
        except Exception as e:
            print(f"[bench] e2e phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            print(json.dumps({
                "metric": e2e_metric(args), "value": None, "unit": "ms",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)
    if args.tenants:
        try:
            with_timeout(lambda: bench_multi_tenant(args), seconds=900)()
        except Exception as e:
            print(f"[bench] multi-tenant phase failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "multi_tenant_clusters_per_sec", "value": None,
                "unit": "clusters/s", "tenants": args.tenants,
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)

    if args.world_store:
        try:
            with_timeout(lambda: bench_world_store(args), seconds=600)()
        except Exception as e:
            print(f"[bench] world-store phase failed: {type(e).__name__}: "
                  f"{e}", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "world_store_churn", "value": None, "unit": "ms",
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)

    if getattr(args, "chaos_local", False):
        try:
            with_timeout(lambda: bench_chaos_local(args), seconds=600)()
        except Exception as e:
            print(f"[bench] chaos-local phase failed: {type(e).__name__}: "
                  f"{e}", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "local_chaos_control_loop", "value": None,
                "unit": "ms",
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)

    if getattr(args, "device_stats", False):
        try:
            with_timeout(lambda: bench_device_stats(args), seconds=600)()
        except Exception as e:
            print(f"[bench] device-stats phase failed: {type(e).__name__}: "
                  f"{e}", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "device_stats", "value": None, "unit": "MiB",
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)

    if getattr(args, "fused", False):
        try:
            with_timeout(lambda: bench_fused(args), seconds=600)()
        except Exception as e:
            print(f"[bench] fused phase failed: {type(e).__name__}: "
                  f"{e}", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "fused_loop_e2e", "value": None, "unit": "ms",
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)

    if getattr(args, "whatif", False):
        try:
            with_timeout(lambda: bench_whatif(args), seconds=600)()
        except Exception as e:
            print(f"[bench] whatif phase failed: {type(e).__name__}: "
                  f"{e}", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "whatif_multiverse", "value": None, "unit": "ms",
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)

    if getattr(args, "shadow_audit", False):
        try:
            with_timeout(lambda: bench_shadow_audit(args), seconds=600)()
        except Exception as e:
            print(f"[bench] shadow-audit phase failed: {type(e).__name__}: "
                  f"{e}", file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "shadow_audit_smoke", "value": None,
                "unit": "percent_overhead",
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)

    if args.journal:
        try:
            with_timeout(lambda: bench_journal(args), seconds=600)()
        except Exception as e:
            print(f"[bench] journal phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "journal_record_replay_smoke", "value": None,
                "unit": "percent_overhead",
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)

    if getattr(args, "lineage", ""):
        try:
            with_timeout(lambda: bench_lineage(args), seconds=600)()
        except Exception as e:
            print(f"[bench] lineage phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            traceback.print_exc(file=sys.stderr)
            print(json.dumps({
                "metric": "lineage_smoke", "value": None,
                "unit": "percent_overhead",
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)

    if args.trace:
        try:
            with_timeout(lambda: bench_trace(args, args.trace), seconds=600)()
            print("[bench] registry exposition:\n" + registry.expose_text(),
                  file=sys.stderr)
        except Exception as e:
            print(f"[bench] trace phase failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    if args.scaledown or args.e2e or args.trace or args.tenants \
            or args.journal or args.world_store \
            or getattr(args, "lineage", "") \
            or getattr(args, "chaos_local", False) \
            or getattr(args, "device_stats", False) \
            or getattr(args, "shadow_audit", False) \
            or getattr(args, "fused", False) \
            or getattr(args, "whatif", False):
        print(primary_line, flush=True)


def bench_scaledown(args) -> None:
    """Scale-down loop timing at --nodes scale: the device drain sweep
    (planner.update) and the HOST confirmation pass (nodes_to_delete) that
    round-2 review flagged as unmeasured. Reported on stderr; the loop budget
    it must fit is BASELINE.json's 200 ms."""
    import jax

    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.scaledown.planner import Planner
    from kubernetes_autoscaler_tpu.models.encode import encode_cluster
    from kubernetes_autoscaler_tpu.simulator.drainability.rules import (
        apply_drainability,
    )
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    n_nodes = args.nodes
    pods_per_node = max(args.pods // max(n_nodes, 1), 1)
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=16000, mem_mib=65536, pods=110)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=2 * n_nodes)
    nodes, pods = [], []
    for i in range(n_nodes):
        nd = build_test_node(f"n{i}", cpu_milli=16000, mem_mib=65536, pods=110)
        fake.add_existing_node("ng1", nd)
        nodes.append(nd)
        for j in range(pods_per_node):
            # ~40% cpu utilization so ~60% of nodes can consolidate away
            p = build_test_pod(f"p{i}-{j}", cpu_milli=6400 // pods_per_node,
                               mem_mib=2048 // pods_per_node,
                               owner_name=f"rs{i % 17}", node_name=nd.name)
            fake.add_pod(p)
            pods.append(p)
    t0 = time.perf_counter()
    enc = encode_cluster(nodes, pods, node_bucket=256, group_bucket=64)
    apply_drainability(enc)
    encode_s = time.perf_counter() - t0
    opts = AutoscalingOptions(
        node_shape_bucket=256, group_shape_bucket=64,
        max_pods_per_node=max(pods_per_node + 6, 16), drain_chunk=256,
        max_scale_down_parallelism=n_nodes, max_drain_parallelism=n_nodes,
        max_empty_bulk_delete=n_nodes,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=0.0, scale_down_unready_time_s=0.0),
    )
    planner = Planner(fake.provider, opts)
    t0 = time.perf_counter()
    planner.update(enc, nodes, now=1000.0)
    plan = planner.nodes_to_delete(enc, nodes, now=1000.0)
    compile_s = time.perf_counter() - t0
    # steady state: second loop hits every jit cache (and the marshal cache)
    planner.phases.reset()
    t0 = time.perf_counter()
    planner.update(enc, nodes, now=1001.0)
    update_ms = (time.perf_counter() - t0) * 1000.0
    t0 = time.perf_counter()
    plan = planner.nodes_to_delete(enc, nodes, now=1001.0)
    host_ms = (time.perf_counter() - t0) * 1000.0
    from kubernetes_autoscaler_tpu.core.scaledown import native_confirm

    print(
        f"[bench-scaledown] nodes={n_nodes} resident_pods={len(pods)} "
        f"encode={encode_s:.2f}s compile={compile_s:.1f}s "
        f"update={update_ms:.1f}ms confirm={host_ms:.1f}ms "
        f"planned_deletions={len(plan)} "
        f"native_confirm={'yes' if native_confirm.available() else 'no'} "
        f"confirm_within_loop_budget={'yes' if host_ms <= 200.0 else 'NO'} "
        f"(strict 50ms target: {'yes' if host_ms <= 50.0 else 'no — '}"
        f"{'C++ pass ~ms; remainder is Python policy pre-screen' if host_ms > 50.0 else ''})",
        file=sys.stderr,
    )
    print(f"[bench-scaledown] steady-loop phase breakdown: "
          f"{json.dumps(planner.phases.snapshot())}", file=sys.stderr)

    # worst-case confirm variant: every resident pod PDB-guarded (round-3
    # review item #6 — this shape used to abandon the native path entirely)
    from kubernetes_autoscaler_tpu.core.scaledown.pdb import (
        PodDisruptionBudget,
        RemainingPdbTracker,
    )

    budgets = [PodDisruptionBudget("all", match_labels={},
                                   disruptions_allowed=len(pods))]
    budgets += [PodDisruptionBudget(f"rs{k}", match_labels={},
                                    namespace="default",
                                    disruptions_allowed=len(pods))
                for k in range(17)]
    pdb_planner = Planner(fake.provider, opts,
                          pdb_tracker=RemainingPdbTracker(budgets))
    pdb_planner.update(enc, nodes, now=2000.0)
    pdb_planner.nodes_to_delete(enc, nodes, now=2000.0)  # warm
    pdb_planner.update(enc, nodes, now=2001.0)
    t0 = time.perf_counter()
    plan_pdb = pdb_planner.nodes_to_delete(enc, nodes, now=2001.0)
    pdb_ms = (time.perf_counter() - t0) * 1000.0
    print(
        f"[bench-scaledown] all-PDB confirm ({len(budgets)} budgets): "
        f"{pdb_ms:.1f}ms planned={len(plan_pdb)} "
        f"within_50ms_target={'yes' if pdb_ms <= 50.0 else 'no'}",
        file=sys.stderr,
    )


def bench_multi_tenant(args) -> None:
    """--tenants N: the fleet-serving smoke (docs/SERVING.md, ISSUE 7).

    Spins N synthetic tenants at MIXED shapes (two shape classes) against a
    localhost gRPC sidecar and storms scale-up sims from one thread per
    tenant, rounds synchronized so requests genuinely coalesce. Measures:

      clusters_per_sec           served sims / wall over the measured window
      batch_occupancy_p50        member tenants per coalesced dispatch
      shape_class_hit_rate       classifications landing in warm classes
                                 during the window (must be 1.0 post-warmup)
      recompiles_per_new_tenant  XLA compiles charged to tenants admitted
                                 AFTER the warmup window (must be 0)
      steady_state_recompiles    jit-cache growth across the window (0)

    Unless --no-batching, a second serving stack with batching disabled runs
    the same storm, and the JSON carries serial_clusters_per_sec +
    speedup_vs_serial — the acceptance evidence that batching converts
    single-cluster latency into fleet throughput. Never-null contract: the
    whole phase runs on the CPU floor backend (tenant worlds are smoke-
    scale); grpc/native-codec absence degrades to in-process service calls
    with a stderr note."""
    import threading

    import jax

    from kubernetes_autoscaler_tpu.sidecar.server import (
        SimParams,
        SimulatorService,
    )
    from kubernetes_autoscaler_tpu.sidecar.wire import DeltaWriter
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    n_tenants = max(args.tenants, 1)
    rounds = max(args.tenant_rounds, 2)
    mib = 1024 * 1024
    ngs = [
        {"id": "ng-4c", "template": {"name": "t4", "capacity": {
            "cpu": 4.0, "memory": 16384 * mib, "pods": 110}},
         "max_new": 32, "price": 1.0},
        {"id": "ng-8c", "template": {"name": "t8", "capacity": {
            "cpu": 8.0, "memory": 32768 * mib, "pods": 110}},
         "max_new": 32, "price": 1.9},
    ]

    def tenant_delta(i: int) -> bytes:
        # mixed shapes: even tenants ~8 nodes (class n16...), odd tenants
        # ~24 nodes (class n32...) — two classes, so windows split and the
        # per-class batching is actually exercised
        small = i % 2 == 0
        n_nodes = 8 if small else 24
        n_pods = 30 if small else 90
        w = DeltaWriter()
        for k in range(n_nodes):
            w.upsert_node(build_test_node(
                f"t{i}-n{k}", cpu_milli=2000 + 1000 * (k % 3),
                mem_mib=8192, pods=110))
        for k in range(n_pods):
            w.upsert_pod(build_test_pod(
                f"t{i}-p{k}", cpu_milli=300 + 100 * (i % 4), mem_mib=256,
                owner_name=f"t{i}-rs{k % 3}",
                node_name=f"t{i}-n{k % n_nodes}" if k % 3 == 0 else ""))
        return w.payload()

    try:
        import grpc  # noqa: F401
        have_grpc = True
    except ImportError:
        have_grpc = False
        print("[bench-tenants] grpc unavailable — driving the service "
              "in-process (same dispatch path, no wire hop)",
              file=sys.stderr)

    def run_serving(batching: bool, tail_dump: str = "") -> dict:
        import tempfile

        # lane width = expected per-class occupancy (tenants split over two
        # shape classes): padding is wasted compute on the lane-serial CPU
        # floor, so lanes match the real batch and window_max (the coalescing
        # cap) closes the window early once every tenant's request arrived
        slo_dir = tempfile.mkdtemp(prefix="katpu-slo-") if batching else ""
        svc = SimulatorService(
            node_bucket=16, group_bucket=16,
            batch_lanes=(min(max(n_tenants // 2, 1), 16) if batching else 0),
            batch_window_ms=25.0, batch_window_max=n_tenants,
            queue_depth=max(4 * n_tenants, 64),
            slo_dump_dir=slo_dir)
        server = None
        # per-tenant server-side lifecycle blocks (request-phase
        # decomposition) collected during the measured window
        lifecycles: dict = {i: [] for i in range(n_tenants + 2)}
        lc_lock = threading.Lock()
        try:
            if have_grpc:
                from kubernetes_autoscaler_tpu.sidecar.server import (
                    SimulatorClient,
                    make_grpc_server,
                )

                server, port = make_grpc_server(
                    svc, port=0, max_workers=4 * n_tenants)
                server.start()
                clients = {}

                def client(i):
                    if i not in clients:
                        clients[i] = SimulatorClient(port, tenant=f"t{i}")
                    return clients[i]

                for i in range(n_tenants):
                    client(i)   # eager: the storm threads only read the dict

                def up(i):
                    r = client(i).scale_up_sim(
                        max_new_nodes=32, node_groups=ngs)
                    return r, client(i).last_lifecycle

                def down(i):
                    return client(i).scale_down_sim(threshold=0.5)

                def apply(i, payload):
                    return client(i)._call_json("ApplyDelta", payload)
            else:
                def up(i):
                    r = svc.scale_up_sim(SimParams(
                        max_new_nodes=32, node_groups=ngs), tenant=f"t{i}")
                    return r, r.pop("lifecycle", None)

                def down(i):
                    return svc.scale_down_sim(SimParams(threshold=0.5),
                                              tenant=f"t{i}")

                def apply(i, payload):
                    return svc.apply_delta(payload, tenant=f"t{i}")

            for i in range(n_tenants):
                ack = apply(i, tenant_delta(i))
                assert not ack.get("error"), ack

            barrier = threading.Barrier(n_tenants)
            errors: list = []

            def storm(k: int):
                def worker(i):
                    try:
                        for _ in range(k):
                            barrier.wait(60)
                            _, lc = up(i)
                            if lc:
                                with lc_lock:
                                    lifecycles[i].append(lc)
                    except Exception as e:  # noqa: BLE001
                        errors.append(e)
                        raise
                threads = [threading.Thread(target=worker, args=(i,))
                           for i in range(n_tenants)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                if errors:
                    raise errors[0]

            storm(2)                      # warmup: compiles + caches warm
            for i in range(n_tenants):
                down(i)                   # warm the scale-down program too
            svc.occupancies.clear()
            svc.gaps.clear()              # gap stats from the window only
            for v in lifecycles.values():
                v.clear()
            hits0, misses0 = svc.ladder.hits, svc.ladder.misses
            cache0 = svc._sim_cache_size()

            def world_h2d() -> float:
                # sum over every tenant-labelled series: the resident-lane
                # upload meter (ISSUE 11 — zero on a steady window, because
                # ApplyDelta-clean tenants stack their device arrays as-is)
                return svc.registry.counter(
                    "world_store_h2d_bytes_total").total()

            h2d0 = world_h2d()
            t0 = time.perf_counter()
            storm(rounds)
            wall = time.perf_counter() - t0
            steady_world_h2d = world_h2d() - h2d0
            steady_recompiles = svc._sim_cache_size() - cache0
            d_hits = svc.ladder.hits - hits0
            d_misses = svc.ladder.misses - misses0
            hit_rate = (d_hits / (d_hits + d_misses)
                        if d_hits + d_misses else 1.0)
            occ = list(svc.occupancies)
            # per-tenant latency percentiles + phase decomposition (ISSUE
            # 8): server-side e2e percentiles and the mean contiguous phase
            # breakdown; phase_sum_over_e2e ≈ 1.0 is the "phases sum to
            # end-to-end" contract, CI-asserted within 5%
            per_tenant = {}
            for i in range(n_tenants):
                lcs = lifecycles[i]
                if not lcs:
                    continue
                e2es = [lc["e2e_ms"] for lc in lcs]
                phase_keys = sorted({k for lc in lcs
                                     for k in lc["phases_ms"]})
                sums = [sum(lc["phases_ms"].values()) for lc in lcs]
                per_tenant[f"t{i}"] = {
                    "requests": len(lcs),
                    "p50": round(float(np.percentile(e2es, 50)), 3),
                    "p95": round(float(np.percentile(e2es, 95)), 3),
                    "p99": round(float(np.percentile(e2es, 99)), 3),
                    "phases_ms_mean": {
                        k: round(float(np.mean(
                            [lc["phases_ms"].get(k, 0.0) for lc in lcs])), 4)
                        for k in phase_keys},
                    "phase_sum_over_e2e": round(float(np.mean(
                        [s / e if e else 1.0
                         for s, e in zip(sums, e2es)])), 4),
                }
            gap = svc.gap_stats()
            # new-tenant segment: one fresh tenant per shape class, admitted
            # AFTER warmup — the ≈0-recompile guarantee, measured
            cache1 = svc._sim_cache_size()
            for j in (n_tenants, n_tenants + 1):
                ack = apply(j, tenant_delta(j))
                assert not ack.get("error"), ack
                up(j)
                down(j)
            new_tenant_recompiles = (svc._sim_cache_size() - cache1) / 2.0
            # forced SLO breach (gRPC path only — the breach hook lives in
            # traced_call): an impossible budget for t0, one more request,
            # then the tenant-scoped dump must exist and hold only t0's
            # retained traces
            slo_evidence = None
            if batching and have_grpc:
                svc.slo.set("t0", 1e-6)
                up(0)
                dumps = sorted(os.listdir(slo_dir)) if slo_dir else []
                slo_evidence = {
                    "breaches_t0": svc.registry.counter(
                        "tenant_slo_breaches_total").value(tenant="t0"),
                    "tenant_dump": (os.path.join(slo_dir, dumps[0])
                                    if dumps else None),
                }
            if tail_dump:
                svc.tail.dump(tail_dump)
            if batching and getattr(args, "trace", None):
                # one extra synchronized round under per-member tracers:
                # the merged server spans put each member's `batch` span
                # (shape class, occupancy, member ids) on its timeline, and
                # bench_trace records these tracers into the Perfetto dump
                # so the coalescing window is visible there. Four members =
                # two per-class batches of occupancy 2 at mixed shapes.
                from kubernetes_autoscaler_tpu.metrics import trace as _tr

                n_traced = min(n_tenants, 4)
                tbar = threading.Barrier(n_traced)

                def traced(i):
                    t = _tr.Tracer()
                    with _tr.active(t):
                        with t.span(f"tenant-{i}", cat="bench"):
                            tbar.wait(60)
                            up(i)
                    _TENANT_TRACERS.append(t)

                tthreads = [threading.Thread(target=traced, args=(i,))
                            for i in range(n_traced)]
                for t in tthreads:
                    t.start()
                for t in tthreads:
                    t.join()
            return {
                "clusters_per_sec": n_tenants * rounds / wall,
                "wall_s": wall,
                "occupancy_p50": (float(np.percentile(occ, 50))
                                  if occ else None),
                "hit_rate": hit_rate,
                "steady_recompiles": steady_recompiles,
                "steady_world_h2d_bytes": steady_world_h2d,
                "recompiles_per_new_tenant": new_tenant_recompiles,
                "stats": svc.batch_stats(),
                "per_tenant": per_tenant,
                "dispatch_gap": gap,
                "tail_sampler": svc.tail.stats(),
                "slo": slo_evidence,
            }
        finally:
            if server is not None:
                server.stop(None)
            svc.close()

    def run_chaos() -> dict:
        """--chaos (docs/ROBUSTNESS.md): the seeded fault schedule against
        an in-process serving stack — (A) a poison tenant whose every
        dispatch fails (bisection must isolate + quarantine it while
        healthy co-members stay BIT-IDENTICAL to a fault-free reference),
        (B) a one-shot transient dispatch fault (bisection recovers
        everyone, nobody quarantined), (C) a harvest delay (latency only),
        and (D) a sidecar kill → checkpoint → rehydrate restart (identical
        results, zero recompiles, zero re-sends). Also measures the
        disabled fault-plane guard at ns/op — the zero-overhead contract,
        CI-asserted."""
        import tempfile

        from kubernetes_autoscaler_tpu.sidecar import faults
        from kubernetes_autoscaler_tpu.sidecar.admission import Quarantined

        n = min(max(n_tenants, 4), 8)
        tenants = [f"t{i}" for i in range(n)]
        lanes = max(n // 2, 2)

        # the zero-overhead half of the contract: with no plan installed
        # every hook site is ONE global load + identity test
        faults.clear()
        iters = 200_000
        g0 = time.perf_counter_ns()
        for _ in range(iters):
            if faults.PLAN is not None:  # pragma: no cover
                raise AssertionError("disabled plane fired")
        guard_ns = (time.perf_counter_ns() - g0) / iters

        def mk_service(**kw):
            return SimulatorService(
                node_bucket=16, group_bucket=16, batch_lanes=lanes,
                batch_window_ms=25.0, batch_window_max=n,
                queue_depth=4 * n, quarantine_ttl_s=10.0, **kw)

        def chaos_storm(svc) -> dict:
            res: dict = {}
            bar = threading.Barrier(n)

            def worker(t):
                bar.wait(60)
                try:
                    up = svc.scale_up_sim(SimParams(
                        max_new_nodes=32, node_groups=ngs), tenant=t)
                    down = svc.scale_down_sim(SimParams(threshold=0.5),
                                              tenant=t)
                    up.pop("lifecycle", None)
                    down.pop("lifecycle", None)
                    res[t] = (up, down)
                except Exception as e:  # noqa: BLE001
                    res[t] = e
            threads = [threading.Thread(target=worker, args=(t,))
                       for t in tenants]
            for th in threads:
                th.start()
            for th in threads:
                th.join(300)
            return res

        poison = "t1"
        svc = mk_service()
        try:
            for i, t in enumerate(tenants):
                ack = svc.apply_delta(tenant_delta(i), tenant=t)
                assert not ack.get("error"), ack
            ref = chaos_storm(svc)
            assert all(not isinstance(r, Exception) for r in ref.values())

            # (A) poison tenant: every dispatch containing it fails
            faults.install([{"hook": "dispatch", "tenant": poison,
                             "times": 0}], seed=20260804,
                           registry=svc.registry)
            res_a = chaos_storm(svc)
            healthy_ok = all(res_a[t] == ref[t]
                             for t in tenants if t != poison)
            poison_err = isinstance(res_a[poison], Exception)
            qs = svc.quarantine_stats()
            poison_outcome = ("quarantined" if poison in qs
                              else "not-quarantined")
            quarantine_reason = qs.get(poison, {}).get("reason")
            # the quarantine sentence holds while the chaos is active
            try:
                svc.scale_down_sim(SimParams(threshold=0.5), tenant=poison)
                sentence_holds = False
            except Quarantined:
                sentence_holds = True
            faults.clear()
            # early parole via world re-send, then (B) one transient fault
            ack = svc.apply_delta(tenant_delta(1), tenant=poison)
            assert not ack.get("error"), ack
            faults.install([{"hook": "dispatch", "times": 1}],
                           seed=20260805, registry=svc.registry)
            res_b = chaos_storm(svc)
            transient_ok = (all(res_b[t] == ref[t] for t in tenants)
                            and not svc.quarantine_stats())
            faults.clear()
            # (C) harvest delay: pure latency, results identical
            faults.install([{"hook": "harvest", "kind": "delay",
                             "delay_ms": 30, "times": 2}],
                           seed=20260806, registry=svc.registry)
            res_c = chaos_storm(svc)
            harvest_delay_ok = all(res_c[t] == ref[t] for t in tenants)
            faults.clear()
            counters = {
                "faults_injected": {
                    h: svc.registry.counter("faults_injected_total").value(
                        hook=h, kind=k)
                    for h, k in (("dispatch", "raise"),
                                 ("harvest", "delay"))},
                "quarantined_total": svc.registry.counter(
                    "tenant_quarantined_total").total(),
                "paroled_total": svc.registry.counter(
                    "tenant_paroled_total").total(),
                "window_failures": svc.registry.counter(
                    "window_failures_total").total(),
                "redispatches": svc.registry.counter(
                    "window_redispatches_total").total(),
            }
            # (D) sidecar kill/restart: checkpoint → rehydrate → identical
            ckdir = tempfile.mkdtemp(prefix="katpu-chaos-ck-")
            ck = svc.checkpoint(ckdir)
        finally:
            svc.close()
        svc2 = mk_service(rehydrate_dir=ckdir)
        try:
            cache0 = svc2._sim_cache_size()
            res_d = chaos_storm(svc2)
            restart_identical = all(res_d[t] == ref[t] for t in tenants)
            # MEASURED zero-re-send evidence: a world re-send (ApplyDelta)
            # exits a tenant's rehydrated mode, so any tenant no longer
            # rehydrated after the storm was re-sent — not assumed zero
            still = sum(1 for t in tenants
                        if (svc2._tenant_peek(t) is not None
                            and svc2._tenant_peek(t).rehydrated))
            restart = {
                "checkpointed": ck["tenants"],
                "rehydrated": svc2.rehydration["restored"],
                "digest_mismatch": svc2.rehydration["digest_mismatch"],
                "identical": restart_identical,
                "resends": n - still,
                "recompiles_per_new_tenant": svc2.registry.gauge(
                    "recompiles_per_new_tenant").value(),
                "jit_cache_growth": svc2._sim_cache_size() - cache0,
            }
        finally:
            svc2.close()
        return {
            "tenants": n,
            "poison_tenant": poison,
            "healthy_identical": bool(healthy_ok),
            "poison_errored": bool(poison_err),
            "poison_outcome": poison_outcome,
            "quarantine_reason": quarantine_reason,
            "sentence_holds": bool(sentence_holds),
            "transient_recovered_identical": bool(transient_ok),
            "harvest_delay_identical": bool(harvest_delay_ok),
            **counters,
            "restart": restart,
            "disabled_overhead_ns_per_check": round(guard_ns, 2),
        }

    batching = not args.no_batching
    tail_dump = getattr(args, "tail_dump", "") or ""
    primary = run_serving(batching=batching, tail_dump=tail_dump)
    chaos = run_chaos() if getattr(args, "chaos", False) else None
    serial = None
    if batching:
        serial = run_serving(batching=False)
    print(f"[bench-tenants] tenants={n_tenants} rounds={rounds} "
          f"batching={batching} cps={primary['clusters_per_sec']:.1f} "
          f"occupancy_p50={primary['occupancy_p50']} "
          f"hit_rate={primary['hit_rate']:.3f} "
          f"new_tenant_recompiles={primary['recompiles_per_new_tenant']} "
          f"dispatch_gap_p50_ms={primary['dispatch_gap']['p50_ms']} "
          f"tail={json.dumps(primary['tail_sampler'])} "
          f"stats={json.dumps(primary['stats'])}"
          + (f" serial_cps={serial['clusters_per_sec']:.1f}"
             f" speedup={primary['clusters_per_sec'] / serial['clusters_per_sec']:.2f}x"
             if serial else ""),
          file=sys.stderr)
    print(json.dumps({
        "metric": "multi_tenant_clusters_per_sec",
        "value": round(primary["clusters_per_sec"], 2),
        "unit": "clusters/s",
        "tenants": n_tenants,
        "rounds": rounds,
        "batching": batching,
        # same provenance contract as the primary line: report the platform
        # the sims actually ran on, never assume tpu (an explicit
        # JAX_PLATFORMS=cpu run must not record cpu numbers as tpu evidence)
        "backend": ("cpu-floor" if args.smoke or args.floor_for
                    else jax.default_backend()),
        "transport": "grpc" if have_grpc else "in-process",
        "batch_occupancy_p50": primary["occupancy_p50"],
        "shape_class_hit_rate": round(primary["hit_rate"], 4),
        "recompiles_per_new_tenant": primary["recompiles_per_new_tenant"],
        "steady_state_recompiles": primary["steady_recompiles"],
        # world residency (ISSUE 11): a steady window re-uses every
        # tenant's resident device lanes — zero world bytes host→device
        "steady_world_h2d_bytes": primary["steady_world_h2d_bytes"],
        # serving-grade observability (ISSUE 8): WHERE the serving time
        # goes, per tenant — never-null on the CPU floor (the decomposition
        # is host-side stamping, backend-independent)
        "per_tenant": primary["per_tenant"],
        "dispatch_gap_p50_ms": primary["dispatch_gap"]["p50_ms"],
        "dispatch_gap": primary["dispatch_gap"],
        "tail_sampler": primary["tail_sampler"],
        "slo": primary["slo"],
        # fault-domain isolation evidence (docs/ROBUSTNESS.md): the seeded
        # chaos schedule's verdicts — healthy-tenant bit-identity under a
        # poison member, transient recovery, warm-restart identity, and
        # the disabled fault-plane guard cost (CI-asserted)
        **({"chaos": chaos} if chaos else {}),
        **({"tail_dump": tail_dump} if tail_dump else {}),
        **({"serial_clusters_per_sec": round(serial["clusters_per_sec"], 2),
            "speedup_vs_serial": round(primary["clusters_per_sec"]
                                       / serial["clusters_per_sec"], 2)}
           if serial else {}),
    }), flush=True)


def bench_trace(args, path: str) -> None:
    """Flight-recorder smoke (docs/OBSERVABILITY.md): a few RunOnce loops at
    toy scale with the tracer on, dumped as ONE Perfetto file. The pending
    pods fit no template, so the scale-up orchestrator runs its full phase
    set without scaling and the scale-down planner runs in the SAME loop —
    one trace carries nested spans from both, plus a sidecar RPC (gRPC over
    localhost) sharing the final loop's trace id across processes."""
    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
    from kubernetes_autoscaler_tpu.metrics import trace
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=16000, mem_mib=65536, pods=110)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=64)
    for i in range(32):
        nd = build_test_node(f"n{i}", cpu_milli=16000, mem_mib=65536, pods=110)
        fake.add_existing_node("ng1", nd)
        per_pod = 1600 if i < 8 else 6400   # low-util band → planner verdicts
        for j in range(2):
            fake.add_pod(build_test_pod(
                f"r{i}-{j}", cpu_milli=per_pod, mem_mib=1024,
                owner_name=f"rs{i % 5}", node_name=nd.name))
    for i in range(4):   # unfittable: orchestrator runs, never scales
        fake.add_pod(build_test_pod(f"big{i}", cpu_milli=64000, mem_mib=1024,
                                    owner_name="big-rs"))
    opts = AutoscalingOptions(
        node_shape_bucket=64, group_shape_bucket=16,
        max_new_nodes_static=32, max_pods_per_node=16, drain_chunk=32,
        scale_down_delay_after_add_s=0.0,
        scale_down_delay_after_failure_s=0.0,
        flight_recorder_capacity=8,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=3600.0,   # plan, never actuate
            scale_down_unready_time_s=3600.0),
    )
    a = StaticAutoscaler(fake.provider, fake, options=opts, eviction_sink=fake)
    a.run_once(now=1000.0)   # cold loop (compiles) — recorded in the ring
    a.run_once(now=1010.0)   # steady loop — recorded
    # final loop under an OWNED tracer so the sidecar RPC lands inside the
    # same trace id as the RunOnce spans
    tracer = trace.Tracer()
    with trace.active(tracer):
        with tracer.span("bench-loop", cat="bench"):
            a.run_once(now=1020.0)
            _trace_sidecar_rpc()
    a.flight_recorder.record(tracer)
    if _PIPELINE_TRACER is not None:
        # the run_bench double-buffer demo's spans (async fetch windows with
        # the next loop's encode/dispatch nested inside) join the dump so
        # the overlap is assertable on the one Perfetto file
        a.flight_recorder.record(_PIPELINE_TRACER)
    for t in _TENANT_TRACERS:
        # the multi-tenant traced round (--tenants): each member timeline
        # carries its merged `batch` span, so the dump shows the
        # coalescing window across tenants
        a.flight_recorder.record(t)
    out = a.flight_recorder.dump(path)
    doc = a.flight_recorder.to_chrome_trace()
    by_cat: dict = {}
    for e in doc["traceEvents"]:
        if e.get("ph") == "X":
            by_cat[e["cat"]] = by_cat.get(e["cat"], 0) + 1
    print(f"[bench-trace] wrote {out}: {len(doc['traceEvents'])} events, "
          f"spans by category {json.dumps(by_cat, sort_keys=True)}, "
          f"trace_ids={doc['otherData']['trace_ids']}", file=sys.stderr)


def _trace_sidecar_rpc() -> None:
    """One ApplyDelta + ScaleDownSim against a localhost gRPC sidecar under
    the ACTIVE tracer — the cross-process hop on the bench trace. Degrades
    to a stderr note when grpc or the native codec is unavailable (the
    local-process spans still make a complete trace)."""
    try:
        from kubernetes_autoscaler_tpu.sidecar.server import (
            SimulatorClient,
            SimulatorService,
            make_grpc_server,
        )
        from kubernetes_autoscaler_tpu.sidecar.wire import DeltaWriter
        from kubernetes_autoscaler_tpu.utils.testing import (
            build_test_node,
            build_test_pod,
        )

        service = SimulatorService(node_bucket=16, group_bucket=16)
        server, port = make_grpc_server(service, port=0)
        server.start()
        try:
            c = SimulatorClient(port)
            w = DeltaWriter()
            w.upsert_node(build_test_node("s1", cpu_milli=4000, mem_mib=8192))
            w.upsert_pod(build_test_pod("sp1", cpu_milli=500, mem_mib=256,
                                        owner_name="rs"))
            c.apply_delta(w)
            c.scale_down_sim(threshold=0.5)
        finally:
            server.stop(None)
    except Exception as e:  # noqa: BLE001 — optional phase, never fatal
        print(f"[bench-trace] sidecar RPC skipped: {type(e).__name__}: {e}",
              file=sys.stderr)


def e2e_metric(args) -> str:
    kp = args.pods // 1000
    kn = args.nodes // 1000 if args.nodes >= 1000 else args.nodes
    unit_n = "knodes" if args.nodes >= 1000 else "nodes"
    return f"runonce_e2e_p50_ms_{kp}kpods_{kn}{unit_n}"


def bench_runonce_e2e(args) -> None:
    """END-TO-END RunOnce at bench scale: tensor-snapshot delta maintenance
    (models/incremental.py) + filter-out-schedulable pack + scale-down plan +
    confirmation, per control loop, under realistic per-loop churn (500 pod
    add/delete + 50 kubelet binds). This is the number the 200 ms target in
    BASELINE.json describes; round-3 review item #1. Steady-state p50 over
    --e2e-loops loops after one cold (compile + seed-encode) loop.

    The world is size-stable: the pending pods all FIT existing capacity
    (filter-out-schedulable packs all --pods of them each loop — reference
    hot loop A at full scale) and a low-utilization band keeps the planner's
    device sweep + host confirm busy without actuations changing the shape.
    """
    import numpy as np

    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    n_nodes = args.nodes
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=16000, mem_mib=65536, pods=110)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=4 * n_nodes)
    for i in range(n_nodes):
        nd = build_test_node(f"n{i}", cpu_milli=16000, mem_mib=65536, pods=110)
        fake.add_existing_node("ng1", nd)
        per_pod = 1600 if i < n_nodes // 16 else 3200   # low-util band
        for j in range(2):
            fake.add_pod(build_test_pod(
                f"r{i}-{j}", cpu_milli=per_pod, mem_mib=1024,
                owner_name=f"rs{i % 17}", node_name=nd.name))
    for i in range(args.pods):
        fake.add_pod(build_test_pod(f"p{i}", cpu_milli=500, mem_mib=512,
                                    owner_name=f"prs{i % args.pod_groups}"))
    opts = AutoscalingOptions(
        node_shape_bucket=256, group_shape_bucket=64,
        max_new_nodes_static=256, max_pods_per_node=16, drain_chunk=256,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=3600.0,   # plan, never actuate
            scale_down_unready_time_s=3600.0),
    )
    a = StaticAutoscaler(fake.provider, fake, options=opts, eviction_sink=fake)
    t0 = time.perf_counter()
    a.run_once(now=1000.0)
    cold_s = time.perf_counter() - t0
    # the phase breakdown must decompose the STEADY p50, not the cold
    # compile loop (bench_scaledown resets for the same reason)
    a.planner.phases.reset()
    samples = []
    seq = 0
    burst = 0
    # churn bounded by the world size so toy-scale runs (CI smoke) don't
    # remove pods that never existed
    churn = min(500, args.pods)
    binds = min(50, churn)
    for loop in range(max(args.e2e_loops, 2)):
        for k in range(churn):  # churn: new pods arrive, old ones finish
            fake.remove_pod(f"p{seq + k}")
            fake.add_pod(build_test_pod(
                f"p{args.pods + seq + k}", cpu_milli=500, mem_mib=512,
                owner_name=f"prs{(seq + k) % args.pod_groups}"))
        for k in range(binds):   # kubelet binds
            fake.bind(f"p{args.pods + seq + k}", f"n{(seq + k) % n_nodes}")
        seq += churn
        if loop % 4 == 2:
            # an unfittable burst: the SCALE-UP path fires for real —
            # orchestrator + expander + executor — and the provider
            # materializes nodes the next loop sees (node-add churn
            # exercises the encoder realign/growth paths on device)
            burst += 1
            for k in range(200):
                fake.add_pod(build_test_pod(
                    f"burst{burst}-{k}", cpu_milli=14000, mem_mib=4096,
                    owner_name=f"burst-rs{burst}"))
        t0 = time.perf_counter()
        a.run_once(now=1010.0 + 10.0 * loop)
        samples.append((time.perf_counter() - t0) * 1000.0)
        if loop % 4 == 3 and burst:
            for k in range(200):   # the burst resolves; demand drains away
                fake.remove_pod(f"burst{burst}-{k}")
    # first churn loop still warms scatter/shape caches — steady = the rest
    steady = samples[1:] if len(samples) > 1 else samples
    p50 = float(np.percentile(steady, 50))
    h = a.metrics.histogram("function_duration_seconds")
    sums = {k[0][1]: v for k, v in h._sums.items()}
    enc = a._encoder
    print(
        f"[bench-e2e] nodes={n_nodes} pods={args.pods} cold={cold_s:.1f}s "
        f"loops={samples} p50={p50:.1f}ms "
        f"encode_total={sums.get('snapshot_build', 0):.2f}s "
        f"pack_total={sums.get('filter_out_schedulable', 0):.2f}s "
        f"plan_total={sums.get('scale_down_update', 0):.2f}s "
        f"confirm_total={sums.get('scale_down_confirm', 0):.2f}s "
        f"full_encodes={enc.full_encodes if enc else -1}",
        file=sys.stderr,
    )
    phase_snap = a.planner.phases.snapshot()
    print(f"[bench-e2e] planner phase breakdown: {json.dumps(phase_snap)}",
          file=sys.stderr)
    print(json.dumps({
        "metric": e2e_metric(args),
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(200.0 / p50, 2) if p50 > 0 else 0.0,
        "phases": phase_snap["totals_ms"],
        # reason plane on the e2e loop: extraction dispatch counts per owner
        # (zero on this all-fitting world = the lazy contract end-to-end)
        # and the event sink's flow counters
        "reason_extraction_dispatches": (
            a.planner.phases.events.get("reason_extraction_dispatches", 0)
            + a.scale_up_orchestrator.phases.events.get(
                "reason_extraction_dispatches", 0)),
        "event_sink": {"emitted": a.event_sink.emitted,
                       "deduped": a.event_sink.deduped,
                       "dropped": a.event_sink.dropped},
    }), flush=True)


def bench_world_store(args) -> None:
    """--world-store: delta-applied device residency as bench-evidenced
    contract (ISSUE 11 / docs/WORLD_STORE.md). Two identical worlds under
    identical churn drive two autoscalers — WorldStore (incremental) vs
    per-loop full encode — and every loop's decisions AND verdict plane
    must match byte-for-byte while the store's encode cost and h2d traffic
    sit far below the full-encode baseline. Host+device bookkeeping only:
    the numbers exist on the CPU floor."""
    import numpy as np

    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
    from kubernetes_autoscaler_tpu.metrics.metrics import Registry
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    from kubernetes_autoscaler_tpu.models.api import Toleration

    n_nodes = min(args.nodes, 192)
    # the win scales with the standing world (full lowering is O(pods),
    # the delta program O(churn)) — floor the pending set so the smoke
    # shape measures the contrast, cap it so CI stays cheap
    n_pend = min(max(args.pods * 2, 4000), 8000)
    loops = 12
    churn = 8

    def mk_pending(i: int):
        # constraint diversity matters: the full-encode baseline pays the
        # string→tensor lowering (selector/toleration hashing) per pod per
        # loop, the delta path only for churned pods — the realistic shape
        # of the win (build_world uses the same mix)
        g = i % 12
        return build_test_pod(
            f"p{i}", cpu_milli=500, mem_mib=512, owner_name=f"prs{g}",
            labels={"app": f"a{g % 3}"},
            node_selector={"disk": "ssd"} if g % 4 == 0 else None,
            tolerations=[Toleration(key="dedicated", operator="Equal",
                                    value="infra", effect="NoSchedule")]
            if g % 5 == 0 else None,
        )

    def build():
        fake = FakeCluster()
        tmpl = build_test_node("tmpl", cpu_milli=16000, mem_mib=65536,
                               pods=110,
                               labels={"pool": "a", "disk": "ssd"})
        fake.add_node_group("ng1", tmpl, min_size=0, max_size=4 * n_nodes)
        for i in range(n_nodes):
            nd = build_test_node(
                f"n{i}", cpu_milli=16000, mem_mib=65536, pods=110,
                labels={"pool": "a" if i % 2 else "b",
                        "disk": "ssd" if i % 3 else "hdd"})
            fake.add_existing_node("ng1", nd)
            for j in range(2):
                fake.add_pod(build_test_pod(
                    f"r{i}-{j}", cpu_milli=3200, mem_mib=1024,
                    owner_name=f"rs{i % 17}", node_name=nd.name))
        for i in range(n_pend):
            fake.add_pod(mk_pending(i))
        return fake

    def opts(inc: bool) -> AutoscalingOptions:
        return AutoscalingOptions(
            incremental_encode=inc,
            node_shape_bucket=64, group_shape_bucket=16,
            max_new_nodes_static=64, max_pods_per_node=16, drain_chunk=64,
            node_group_defaults=NodeGroupDefaults(
                scale_down_unneeded_time_s=3600.0,   # plan, never actuate
                scale_down_unready_time_s=3600.0),
        )

    def _jit_cache_sizes() -> int:
        """Module-level jit caches the RunOnce hot path dispatches into —
        growth across the steady window means a shape/plan leak, exactly
        PR 2's steady_state_recompiles, with the store enabled."""
        import kubernetes_autoscaler_tpu.ops.autoscale_step as a_mod
        import kubernetes_autoscaler_tpu.ops.binpack as bp
        import kubernetes_autoscaler_tpu.ops.drain as dr
        import kubernetes_autoscaler_tpu.ops.pack as pk
        import kubernetes_autoscaler_tpu.ops.predicates as pr
        import kubernetes_autoscaler_tpu.ops.scoring as sc

        total = 0
        for mod in (a_mod, bp, dr, pk, pr, sc):
            for v in vars(mod).values():
                if hasattr(v, "_cache_size"):
                    total += v._cache_size()
        return total

    worlds = [build(), build()]
    regs = [Registry(), Registry()]
    autos = [StaticAutoscaler(w.provider, w, options=opts(inc),
                              registry=reg, eviction_sink=w)
             for w, reg, inc in zip(worlds, regs, (True, False))]
    for a in autos:
        a.capture_verdicts = True

    def encode_sum(a) -> float:
        h = a.metrics.histogram("function_duration_seconds")
        return h._sums.get((("function", "snapshot_build"),), 0.0)

    encode_ms = [[], []]          # per-loop snapshot_build wall, both paths
    h2d_per_loop: list[int] = []  # store path
    identical = True
    seq = 0
    cache0 = None
    for loop in range(loops):
        for w in worlds:
            for k in range(churn):
                w.remove_pod(f"p{seq + k}")
                w.add_pod(mk_pending(n_pend + seq + k))
            for k in range(2):
                w.bind(f"p{n_pend + seq + k}", f"n{(seq + k) % n_nodes}")
        seq += churn
        now = 1000.0 + 10.0 * loop
        stats = []
        for idx, (w, a) in enumerate(zip(worlds, autos)):
            e0 = encode_sum(a)
            st = a.run_once(now=now)
            encode_ms[idx].append((encode_sum(a) - e0) * 1000.0)
            # verdict plane keyed by equivalence group: row NUMBERING is
            # encode-path-dependent (the store keeps historical rows, a
            # full encode renumbers per listing) — identity must hold on
            # the group-keyed view, byte-for-byte
            verdict = tuple(sorted(
                (key, int(cnt)) for key, cnt in zip(
                    a.last_verdict_keys or [],
                    a.last_verdict_plane
                    if a.last_verdict_plane is not None else [])
                if key is not None))
            stats.append((
                sorted(st.scale_up.increases.items())
                if st.scale_up else None,
                sorted(st.unneeded_nodes), sorted(st.scale_down_deleted),
                st.pending_pods,
                verdict,
            ))
        identical = identical and stats[0] == stats[1]
        h2d_per_loop.append(autos[0]._world_store.last_h2d_bytes)
        if loop == 0:
            cache0 = _jit_cache_sizes()
    steady_recompiles = _jit_cache_sizes() - cache0

    store = autos[0]._world_store
    enc_inc = encode_ms[0][1:]     # steady: skip the seed/compile loop
    enc_full = encode_ms[1][1:]
    p50_inc = float(np.percentile(enc_inc, 50))
    p50_full = float(np.percentile(enc_full, 50))
    h2d_full = h2d_per_loop[0]
    h2d_delta_p50 = float(np.percentile(h2d_per_loop[1:], 50))
    print(f"[bench-world-store] nodes={n_nodes} resident={2 * n_nodes} "
          f"pending={n_pend} loops={loops} "
          f"encode_p50_ms delta={p50_inc:.2f} full={p50_full:.2f} "
          f"({p50_full / max(p50_inc, 1e-9):.1f}x) "
          f"h2d full={h2d_full}B delta_p50={h2d_delta_p50:.0f}B "
          f"({h2d_full / max(h2d_delta_p50, 1e-9):.1f}x) "
          f"modes={json.dumps(store.stats()['modes'])} "
          f"identical={identical}", file=sys.stderr)
    print(json.dumps({
        "metric": "world_store_churn",
        "value": round(p50_inc, 3),
        "unit": "ms",
        "backend": ("cpu-floor" if args.smoke or args.floor_for
                    else __import__("jax").default_backend()),
        "loops": loops,
        "churn_per_loop": churn,
        "nodes": n_nodes,
        "encode_p50_ms": round(p50_inc, 3),
        "full_encode_p50_ms": round(p50_full, 3),
        "encode_speedup_vs_full": round(p50_full / max(p50_inc, 1e-9), 2),
        "full_encodes": store.encoder.full_encodes,
        "h2d_bytes_full_loop": h2d_full,
        "h2d_bytes_per_loop_p50": h2d_delta_p50,
        "h2d_reduction_vs_full": round(
            h2d_full / max(h2d_delta_p50, 1e-9), 2),
        "modes": store.stats()["modes"],
        "verdicts_identical": identical,
        "steady_state_recompiles": steady_recompiles,
    }), flush=True)


def bench_fused(args) -> None:
    """--fused: the single-dispatch fused RunOnce as bench-evidenced
    contract (ISSUE 17 / docs/FUSED_LOOP.md). Twin worlds under identical
    deterministic churn — fused one-program loop vs the phased
    three-dispatch path — must agree loop for loop on every decision
    surface digest, then a steady no-churn window measures what the fusion
    is for: loop p50 both ways, device round trips per loop (budget: <=2),
    the speculative next-loop hit rate, and zero steady-state recompiles
    of the fused program."""
    import numpy as np

    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
    from kubernetes_autoscaler_tpu.metrics.metrics import Registry
    from kubernetes_autoscaler_tpu.ops import autoscale_step
    from kubernetes_autoscaler_tpu.replay import journal as rj
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    n_nodes = min(args.nodes, 192)
    # pending must FIT existing capacity: a steady window only exists when
    # the loop neither scales up nor actuates, so the speculative program's
    # world fingerprint holds from one loop to the next
    n_pend = min(max(args.pods // 4, 200), 2000)
    churn_loops, steady_loops, churn = 6, 8, 8

    def mk_pending(i: int):
        return build_test_pod(f"p{i}", cpu_milli=250, mem_mib=256,
                              owner_name=f"prs{i % 12}")

    def build():
        fake = FakeCluster()
        tmpl = build_test_node("tmpl", cpu_milli=16000, mem_mib=65536,
                               pods=110)
        fake.add_node_group("ng1", tmpl, min_size=0, max_size=4 * n_nodes)
        for i in range(n_nodes):
            nd = build_test_node(f"n{i}", cpu_milli=16000, mem_mib=65536,
                                 pods=110)
            fake.add_existing_node("ng1", nd)
            for j in range(2):
                fake.add_pod(build_test_pod(
                    f"r{i}-{j}", cpu_milli=3200, mem_mib=1024,
                    owner_name=f"rs{i % 17}", node_name=nd.name))
        for i in range(n_pend):
            fake.add_pod(mk_pending(i))
        return fake

    def opts(fused: bool) -> AutoscalingOptions:
        return AutoscalingOptions(
            fused_loop=fused,
            node_shape_bucket=64, group_shape_bucket=16,
            max_new_nodes_static=64, max_pods_per_node=16, drain_chunk=64,
            # plan-only shape: no deletions AND no soft-taint actuation —
            # a tainted node is a changed world composition, which would
            # (correctly) discard every speculative dispatch and leave the
            # steady window with nothing to measure
            max_bulk_soft_taint_count=0,
            node_group_defaults=NodeGroupDefaults(
                scale_down_unneeded_time_s=3600.0,   # plan, never actuate
                scale_down_unready_time_s=3600.0),
        )

    worlds = [build(), build()]
    autos = [StaticAutoscaler(w.provider, w, options=opts(fused),
                              registry=Registry(), eviction_sink=w)
             for w, fused in zip(worlds, (True, False))]
    for a in autos:
        a.capture_verdicts = True

    wall_ms = [[], []]            # per-loop wall, fused / phased
    steady_wall_ms = [[], []]     # the no-churn window only
    round_trips: list[int] = []
    steady_trips: list[int] = []
    spec_hits = spec_discards = 0
    identical = True
    fused_loops = 0
    seq = 0
    cache_after_warm = None
    for loop in range(churn_loops + steady_loops):
        steady = loop >= churn_loops
        if not steady:
            for w in worlds:
                for k in range(churn):
                    w.remove_pod(f"p{(seq + k) % n_pend}")
                    w.add_pod(mk_pending(n_pend + seq + k))
            seq += churn
        now = 1000.0 + 10.0 * loop
        digests = []
        for idx, a in enumerate(autos):
            t0 = time.perf_counter()
            st = a.run_once(now=now)
            dt = (time.perf_counter() - t0) * 1000.0
            wall_ms[idx].append(dt)
            if steady:
                steady_wall_ms[idx].append(dt)
            digests.append(rj.surface_digests(rj.collect_outputs(a, st)))
            if idx == 0:
                fused_loops += st.fused_mode == "fused"
                round_trips.append(st.loop_device_round_trips)
                if steady:
                    steady_trips.append(st.loop_device_round_trips)
                    spec_hits += st.speculation == "hit"
                spec_discards += st.speculation == "discard"
        identical = identical and digests[0] == digests[1]
        if loop == 0:
            cache_after_warm = autoscale_step.run_once_fused._cache_size()
    steady_recompiles = (autoscale_step.run_once_fused._cache_size()
                         - cache_after_warm)

    p50_fused = float(np.percentile(steady_wall_ms[0], 50))
    p50_phased = float(np.percentile(steady_wall_ms[1], 50))
    hit_rate = spec_hits / max(len(steady_wall_ms[0]), 1)
    print(f"[bench-fused] nodes={n_nodes} pending={n_pend} "
          f"loops={churn_loops}+{steady_loops} fused_loops={fused_loops} "
          f"steady_p50_ms fused={p50_fused:.2f} phased={p50_phased:.2f} "
          f"({p50_phased / max(p50_fused, 1e-9):.2f}x) "
          f"round_trips max={max(round_trips)} "
          f"steady_max={max(steady_trips)} "
          f"spec hits={spec_hits}/{len(steady_wall_ms[0])} "
          f"discards={spec_discards} recompiles={steady_recompiles} "
          f"identical={identical}", file=sys.stderr)
    print(json.dumps({
        "metric": "fused_loop_e2e",
        "value": round(p50_fused, 3),
        "unit": "ms",
        "backend": ("cpu-floor" if args.smoke or args.floor_for
                    else __import__("jax").default_backend()),
        "nodes": n_nodes,
        "pending": n_pend,
        "loops": churn_loops + steady_loops,
        "fused_loops": fused_loops,
        "fused_p50_ms": round(p50_fused, 3),
        "phased_p50_ms": round(p50_phased, 3),
        "fused_speedup_vs_phased": round(
            p50_phased / max(p50_fused, 1e-9), 2),
        "loop_device_round_trips_max": max(round_trips),
        "steady_round_trips_max": max(steady_trips),
        "speculative_hits": spec_hits,
        "speculative_hit_rate_steady": round(hit_rate, 3),
        "speculative_discards": spec_discards,
        "decisions_identical": identical,
        "steady_state_recompiles": steady_recompiles,
    }), flush=True)


def bench_whatif(args) -> None:
    """--whatif: the counterfactual multiverse as bench-evidenced contract
    (docs/WHATIF.md). Branch a live fused world, fan out B=16 hypothesis
    lanes (lane 0 = null), rollout T=32 simulated loops in ONE device
    dispatch, and assert the three gates CI rides:
    - the null lane's decision trajectory is byte-identical to T live
      fused RunOnce loops on the same steady world
    - steady-state recompiles == 0 across all B lanes AND a second rollout
      with different per-lane knob VALUES (knobs are traced, never static)
    - aggregate fused-steps/sec >= the speedup gate vs the serial phased
      control loop on a twin world — evaluating B lanes over T steps the
      old way costs B*T full phased loops; one batched dispatch is what
      the multiverse is for
    """
    import numpy as np

    import jax

    from kubernetes_autoscaler_tpu.whatif import kernel as wkernel
    from kubernetes_autoscaler_tpu.whatif import report as wreport
    from kubernetes_autoscaler_tpu.whatif import variants as wvariants
    from kubernetes_autoscaler_tpu.whatif.generator import (
        WorkloadSpec,
        generate_workload,
        lane_workloads,
    )
    from kubernetes_autoscaler_tpu.whatif.synthetic import (
        synthetic_autoscaler,
        synthetic_branch,
    )

    b_lanes, t_steps = 16, 32
    n_nodes = min(max(args.nodes // 4, 16), 48)

    # branch a LIVE fused world in equilibrium with its own decisions:
    # resident pods pin every node (no drains), and the pending pods are
    # too large for any group template, so placement/scale-up stay
    # plan-only verdicts on BOTH sides — the live loop re-presents the
    # same pending pods each loop (nothing ever binds them), and the
    # rollout's compressed actuation is a bitwise no-op
    branch, auto = synthetic_branch(n_nodes=n_nodes, n_pending=12,
                                    seed=7, loops=2, pending_milli=64000)
    live_verd, live_pend = [], []
    for k in range(t_steps):
        st = auto.run_once(now=2000.0 + 10.0 * k)
        if st.fused_mode != "fused":
            raise RuntimeError(f"live loop {k} fell off the fused path "
                               f"({st.fused_mode})")
        dec = auto._fused_ctx["decision"]
        live_verd.append(np.array(dec.verdict))
        live_pend.append(np.array(dec.pending_after))
    live_digest = wreport._digest(np.stack(live_verd), np.stack(live_pend))

    def mk_variants(knob: float):
        vs = [wvariants.VariantSpec(name="null")]
        for i in range(b_lanes - 1):
            kind = i % 4
            if kind == 0:
                vs.append(wvariants.VariantSpec(
                    name=f"price{i}", price_scale=0.5 + 0.25 * i * knob))
            elif kind == 1:
                vs.append(wvariants.VariantSpec(
                    name=f"thresh{i}",
                    threshold=min(0.2 + 0.05 * i * knob, 0.95)))
            elif kind == 2:
                vs.append(wvariants.VariantSpec(
                    name=f"cap{i}", max_new_cap=1 + i))
            else:
                vs.append(wvariants.VariantSpec(
                    name=f"fail{i}", fail_nodes=(i % n_nodes,)))
        return vs

    lanes = wvariants.build_lanes(branch, mk_variants(1.0)[1:],
                                  pad_to=b_lanes)
    assert len(lanes.variants) == b_lanes
    stt = lanes.statics
    kw = dict(dims=stt["dims"], max_new_nodes=stt["max_new_nodes"],
              max_pods_per_node=stt["max_pods_per_node"],
              chunk=stt["chunk"])
    wl = WorkloadSpec(kind="quiet")
    g = int(np.asarray(lanes.specs.count).shape[1])
    n = int(np.asarray(lanes.nodes.valid).shape[1])
    adds, fails = generate_workload(wl, t_steps, g, n)
    adds_b, fails_b = lane_workloads(lanes.variants, adds, fails)

    def cache_size():
        return (wkernel.rollout_multiverse._cache_size()
                + wkernel.multiverse_step._cache_size())

    def run_rollout(ln):
        traj = wkernel.rollout_multiverse(
            ln.nodes, ln.specs, ln.scheduled, ln.groups, ln.limit_cap,
            ln.thresholds, adds_b, fails_b, **kw)
        jax.block_until_ready(traj)
        return traj

    # warm-up compiles, then the timed window must grow the cache by 0 —
    # including a rollout over a DIFFERENT variant set (knob values are
    # traced; only shapes key the compile)
    t0 = time.perf_counter()
    traj = run_rollout(lanes)
    compile_s = time.perf_counter() - t0
    warm = cache_size()
    lanes2 = wvariants.build_lanes(branch, mk_variants(1.3)[1:],
                                   pad_to=b_lanes)
    rollout_wall = []
    for ln in (lanes, lanes2, lanes):
        t0 = time.perf_counter()
        traj = run_rollout(ln)
        rollout_wall.append(time.perf_counter() - t0)
    steady_recompiles = cache_size() - warm
    rollout_s = float(np.median(rollout_wall))

    null_digest = wreport.trajectory_digests(traj, 1)[0]
    null_identical = null_digest == live_digest

    # serial phased baseline: the actual phased control loop (encode +
    # phase-by-phase dispatches + host policy + fetches) on a twin of the
    # branch world — what evaluating B lanes x T steps costs without the
    # multiverse is B*T of these loops, so steps/sec is 1 / loop-p50
    _fake_p, phased = synthetic_autoscaler(
        n_nodes=n_nodes, n_pending=12, seed=7, pending_milli=64000,
        fused_loop=False)
    for k in range(2):
        phased.run_once(now=1000.0 + 10.0 * k)   # warm the phased programs
    phased_wall = []
    for k in range(8):
        t0 = time.perf_counter()
        phased.run_once(now=2000.0 + 10.0 * k)
        phased_wall.append(time.perf_counter() - t0)
    serial_loop_s = float(np.median(phased_wall))

    steps = b_lanes * t_steps
    fused_sps = steps / max(rollout_s, 1e-9)
    serial_sps = 1.0 / max(serial_loop_s, 1e-9)
    speedup = fused_sps / max(serial_sps, 1e-9)
    print(f"[bench-whatif] lanes={b_lanes} steps={t_steps} nodes={n_nodes} "
          f"rollout={rollout_s * 1000:.1f}ms "
          f"phased_loop_p50={serial_loop_s * 1000:.1f}ms "
          f"fused_steps/s={fused_sps:.0f} serial_steps/s={serial_sps:.0f} "
          f"speedup={speedup:.1f}x null_identical={null_identical} "
          f"recompiles={steady_recompiles} compile={compile_s:.1f}s",
          file=sys.stderr)
    print(json.dumps({
        "metric": "whatif_multiverse",
        "value": round(rollout_s * 1000.0, 3),
        "unit": "ms",
        "backend": ("cpu-floor" if args.smoke or args.floor_for
                    else __import__("jax").default_backend()),
        "lanes": b_lanes,
        "rollout_steps": t_steps,
        "nodes": n_nodes,
        "fused_steps_per_sec": round(fused_sps, 1),
        "serial_steps_per_sec": round(serial_sps, 1),
        "serial_baseline": "phased-control-loop",
        "serial_loop_p50_ms": round(serial_loop_s * 1000.0, 3),
        "speedup_vs_serial_phased": round(speedup, 2),
        "null_lane_identical": null_identical,
        "steady_state_recompiles": steady_recompiles,
        "compile_ms": round(compile_s * 1000.0, 1),
    }), flush=True)


def bench_chaos_local(args) -> None:
    """--chaos-local (docs/ROBUSTNESS.md "Control loop"): the seeded chaos
    schedule against the LOCAL control loop — (A) a hung device dispatch is
    aborted at its phase budget by the backend supervisor's guard and the
    run_loop driver survives every failed loop (zero driver-thread deaths),
    (C) while degraded/recovering, scale-down actuation is withheld with
    BackendDegraded surfaced on the reason plane and re-enables only after
    the recovery hysteresis, (B) a device loss (every resident buffer
    deleted) is healed by the WorldStore digest probe — post-rebuild
    decisions bit-identical to a cold-encode comparator, counted as
    encoder_encodes_total{mode=full,cause=device_lost} — and (D) a
    kill/restart rehydrates the crash-consistent restart record so the
    unneeded-since countdowns resume (no premature deletion, no reset).
    Host-side orchestration: the numbers exist on the CPU floor."""
    import tempfile
    import threading

    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.loop import LoopTrigger, run_loop
    from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
    from kubernetes_autoscaler_tpu.sidecar import faults
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    # comfortably above the toy world's warm dispatch (~0.3-0.8s on a CPU
    # floor / shared CI runner) and far below the injected 30s hang — the
    # budget must separate slow from hung, not race the scheduler
    phase_deadline_s = 2.0

    def opts(**kw) -> AutoscalingOptions:
        base = dict(
            scale_down_delay_after_add_s=0.0,
            scale_down_delay_after_failure_s=0.0,
            node_shape_bucket=16, group_shape_bucket=16,
            max_new_nodes_static=32, max_pods_per_node=32, drain_chunk=8,
            backend_phase_deadline_s=phase_deadline_s,
            backend_probe_deadline_s=2.0,
            backend_suspect_threshold=2,
            backend_recovery_probes=1,
            backend_recovery_hysteresis_loops=2,
            # matures AFTER the two warmup loops (cadence 10 logical s) and
            # DURING the degraded window, so the withheld loops block a
            # genuinely due deletion
            node_group_defaults=NodeGroupDefaults(
                scale_down_unneeded_time_s=30.0,
                scale_down_unready_time_s=30.0),
        )
        base.update(kw)
        return AutoscalingOptions(**base)

    def idle_world():
        fake = FakeCluster()
        tmpl = build_test_node("tmpl", cpu_milli=4000, mem_mib=8192)
        fake.add_node_group("ng1", tmpl, min_size=1, max_size=10)
        fake.add_existing_node("ng1", build_test_node(
            "busy", cpu_milli=4000, mem_mib=8192))
        fake.add_existing_node("ng1", build_test_node(
            "idle", cpu_milli=4000, mem_mib=8192))
        for i in range(3):
            fake.add_pod(build_test_pod(
                f"b{i}", cpu_milli=1000, mem_mib=512,
                owner_name="rs", node_name="busy"))
        return fake

    # ---- legs A + C: hung dispatch → degraded within budget; scale-down
    #      withheld while degraded, re-enabled after the hysteresis ----
    fake = idle_world()
    a = StaticAutoscaler(fake.provider, fake, options=opts(),
                         eviction_sink=fake)
    trigger = LoopTrigger(scan_interval_s=0.001)
    base_threads = threading.active_count()
    now = [1000.0]

    def one_loop():
        """One driver iteration (run_loop's catch = the survival contract)
        at a controlled logical clock; returns (status, wall_ms)."""
        wt = a.walltime
        a.walltime = lambda: now[0]
        t0 = time.perf_counter()
        try:
            h = run_loop(a, trigger, max_iterations=1,
                         error_backoff_initial_s=0.0)
        finally:
            a.walltime = wt
        now[0] += 10.0
        return h[0], (time.perf_counter() - t0) * 1000.0

    # warm the jit caches with the guard relaxed — a cold compile is slow,
    # not hung; production sets the deadline above compile time, the bench
    # arms the tight budget only once the world is warm
    a.supervisor.phase_deadline_s = 60.0
    s0, _ = one_loop()   # baseline: candidate planned, countdown starts
    one_loop()
    assert a.supervisor.state == "healthy", a.supervisor.stats()
    assert "idle" in a.planner.state.unneeded, s0
    assert "idle" in fake.nodes, "countdown must outlive the warmup"
    a.supervisor.phase_deadline_s = phase_deadline_s
    faults.install([{"hook": "local_dispatch", "kind": "hang",
                     "delay_ms": 30_000, "times": 2}], seed=20260804,
                   registry=a.metrics)
    abort_ms = []
    try:
        for _ in range(2):
            st, wall = one_loop()
            assert not st.ran and "PhaseDeadlineExceeded" in st.error, st
            abort_ms.append(wall)
    finally:
        faults.clear()
    degraded_state = a.supervisor.state
    hang_injected = a.metrics.counter("faults_injected_total").value(
        hook="local_dispatch", kind="hang")

    withheld_loops = 0
    deleted_at_state = None
    reason_surfaced = False
    for _ in range(5):
        st, _ = one_loop()
        if st.scale_down_withheld:
            withheld_loops += 1
            reason_surfaced = reason_surfaced or (
                a.planner.unremovable.reason("idle") == "BackendDegraded"
                and bool(a.event_sink.find(kind="NoScaleDown", obj="idle",
                                           reason="BackendDegraded"))
                and a.metrics.gauge("unremovable_nodes_count").value(
                    reason="BackendDegraded") >= 1.0)
        if st.scale_down_deleted:
            deleted_at_state = st.backend_state
            break
    transitions = [f"{t['from']}>{t['to']}" for t in a.supervisor.transitions]

    # ---- leg B: device loss → digest-probe rebuild, decisions
    #      bit-identical to a cold-encode comparator ----
    def churn_world():
        fake = FakeCluster()
        tmpl = build_test_node("tmpl", cpu_milli=16000, mem_mib=65536,
                               pods=110, labels={"pool": "a", "disk": "ssd"})
        fake.add_node_group("ng1", tmpl, min_size=0, max_size=64)
        for i in range(12):
            nd = build_test_node(
                f"n{i}", cpu_milli=16000, mem_mib=65536, pods=110,
                labels={"pool": "a" if i % 2 else "b",
                        "disk": "ssd" if i % 3 else "hdd"})
            fake.add_existing_node("ng1", nd)
            for j in range(2):
                fake.add_pod(build_test_pod(
                    f"r{i}-{j}", cpu_milli=3200, mem_mib=1024,
                    owner_name=f"rs{i % 5}", node_name=nd.name))
        for i in range(40):
            fake.add_pod(build_test_pod(
                f"p{i}", cpu_milli=500, mem_mib=512,
                owner_name=f"prs{i % 4}",
                node_selector={"disk": "ssd"} if i % 4 == 0 else None))
        return fake

    plan_never = NodeGroupDefaults(scale_down_unneeded_time_s=3600.0,
                                   scale_down_unready_time_s=3600.0)
    worlds = [churn_world(), churn_world()]
    # inline guards (deadline 0) for this leg: its world shapes cold-compile
    # fresh kernels, and a slow compile under an armed watchdog would read
    # as a hang — the leg exercises the HEAL path, not the deadline
    autos = [StaticAutoscaler(
        w.provider, w, eviction_sink=w,
        options=opts(incremental_encode=inc, node_group_defaults=plan_never,
                     backend_phase_deadline_s=0.0))
        for w, inc in zip(worlds, (True, False))]
    for x in autos:
        x.capture_verdicts = True

    def decisions(x, st):
        verdict = tuple(sorted(
            (key, int(cnt)) for key, cnt in zip(
                x.last_verdict_keys or [],
                x.last_verdict_plane
                if x.last_verdict_plane is not None else [])
            if key is not None))
        return (sorted(st.scale_up.increases.items()) if st.scale_up
                else None,
                sorted(st.unneeded_nodes), st.pending_pods, verdict)

    identical = True
    for loop in range(3):
        for w in worlds:
            w.remove_pod(f"p{loop}")
            w.add_pod(build_test_pod(f"q{loop}", cpu_milli=500, mem_mib=512,
                                     owner_name=f"prs{loop % 4}"))
        sts = [x.run_once(now=1000.0 + 10 * loop) for x in autos]
        identical = identical and (decisions(autos[0], sts[0])
                                   == decisions(autos[1], sts[1]))
    store = autos[0]._world_store
    lost_planes = 0
    for key, dev in list(store.device_store._dev.items()):
        if hasattr(dev, "delete"):
            dev.delete()
            lost_planes += 1
    autos[0].supervisor.record_failure("dispatch", "error-XlaRuntimeError")
    for w in worlds:
        w.add_pod(build_test_pod("q-loss", cpu_milli=500, mem_mib=512,
                                 owner_name="prs0"))
    sts = [x.run_once(now=1100.0) for x in autos]
    loss_identical = decisions(autos[0], sts[0]) == decisions(autos[1], sts[1])
    device_loss = {
        "lost_planes": lost_planes,
        "heal_outcome": (autos[0].supervisor.last_heal or {}).get("outcome"),
        "rebuild_cause_counter": autos[0].metrics.counter(
            "encoder_encodes_total").value(mode="full", cause="device_lost"),
        "identical_to_cold_encode": bool(identical and loss_identical),
        "resident_again": None,
    }
    for w in worlds:
        w.add_pod(build_test_pod("q-after", cpu_milli=500, mem_mib=512,
                                 owner_name="prs1"))
    sts = [x.run_once(now=1110.0) for x in autos]
    device_loss["resident_again"] = store.last_mode == "delta"
    device_loss["identical_to_cold_encode"] = bool(
        device_loss["identical_to_cold_encode"]
        and decisions(autos[0], sts[0]) == decisions(autos[1], sts[1]))

    # ---- leg D: crash-kill → restart record resumes the countdowns ----
    ckdir = tempfile.mkdtemp(prefix="katpu-chaos-local-")
    rpath = os.path.join(ckdir, "restart_state.json")

    def mk_restart(fk):
        # inline guards here too: this leg pins restart-timer continuity,
        # and every "restarted" autoscaler re-runs a cold first loop
        return StaticAutoscaler(
            fk.provider, fk, eviction_sink=fk,
            options=opts(restart_state_path=rpath,
                         max_bulk_soft_taint_count=0,
                         backend_phase_deadline_s=0.0,
                         node_group_defaults=NodeGroupDefaults(
                             scale_down_unneeded_time_s=60.0,
                             scale_down_unready_time_s=60.0)))

    fk = idle_world()
    r1 = mk_restart(fk)
    r1.run_once(now=1000.0)          # countdown starts at 1000
    r1.run_once(now=1010.0)
    del r1                           # the "kill": nothing is flushed beyond
    r2 = mk_restart(fk)              # the per-loop atomic record
    early = r2.run_once(now=1030.0)  # < 1000+60: must NOT delete
    resumed_since = r2.planner.unneeded_nodes.since.get("idle")
    late = r2.run_once(now=1065.0)   # ≥ 1000+60 but < 1030+60: only correct
    restart = {                      # if the countdown RESUMED, not reset
        "rehydrated": r2.metrics.counter("restart_state_total").value(
            event="rehydrated") == 1,
        "resumed_since": resumed_since,
        "premature_deletion": bool(early.scale_down_deleted),
        "deleted_on_schedule": late.scale_down_deleted == ["idle"],
    }

    detect_p50 = float(np.percentile(abort_ms, 50)) if abort_ms else None
    chaos = {
        "phase_deadline_ms": phase_deadline_s * 1000.0,
        "hung_dispatch": {
            "hangs_injected": hang_injected,
            "abort_ms": [round(x, 1) for x in abort_ms],
            "degraded_within_budget": bool(
                abort_ms and max(abort_ms)
                < phase_deadline_s * 1000.0 * 4 + 500.0),
            "state_after": degraded_state,
            # every hung loop came back through run_loop's catch with a
            # recorded failed status — the driver thread never died
            "driver_deaths": 2 - len(abort_ms),
            "abandoned_workers": max(
                threading.active_count() - base_threads, 0),
        },
        "gating": {
            "withheld_loops": withheld_loops,
            "reason_surfaced": reason_surfaced,
            "reenabled_after_hysteresis": deleted_at_state == "healthy",
            "transitions": transitions,
        },
        "device_loss": device_loss,
        "restart": restart,
    }
    print(f"[bench-chaos-local] {json.dumps(chaos)}", file=sys.stderr)
    print(json.dumps({
        "metric": "local_chaos_control_loop",
        "value": round(detect_p50, 2) if detect_p50 is not None else None,
        "unit": "ms",
        "backend": ("cpu-floor" if args.smoke or args.floor_for
                    else __import__("jax").default_backend()),
        **chaos,
    }), flush=True)


def _journal_story_run(args, jdir: str) -> dict:
    """The shared 8-loop journaled story world (--journal and --lineage
    both drive it): pod churn every loop, a taint flip at loop 2, an
    unfittable burst at loop 3 that fires real scale-up, burst removal at
    loop 5. Runs the loops with the journal (and the live lineage ring)
    on and returns the autoscaler plus per-loop walltime and overhead
    samples — the two modes measure different numerators over the same
    denominator."""
    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
    from kubernetes_autoscaler_tpu.models.api import Node, Taint
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    os.makedirs(jdir, exist_ok=True)
    for f in os.listdir(jdir):   # stale records would replay another world
        if f.startswith("journal-") and f.endswith(".jsonl"):
            os.remove(os.path.join(jdir, f))

    n_nodes, loops = min(args.nodes, 48), 8
    fake = FakeCluster()
    tmpl = build_test_node("tmpl", cpu_milli=8000, mem_mib=16384, pods=64)
    fake.add_node_group("ng1", tmpl, min_size=0, max_size=4 * n_nodes)
    fake.add_node_group("ng2", build_test_node(
        "tmpl2", cpu_milli=16000, mem_mib=32768, pods=64),
        min_size=0, max_size=n_nodes, price_per_node=2.0)
    for i in range(n_nodes):
        nd = build_test_node(f"n{i}", cpu_milli=8000, mem_mib=16384, pods=64)
        fake.add_existing_node("ng1", nd)
        fake.add_pod(build_test_pod(f"r{i}", cpu_milli=5000, mem_mib=2048,
                                    owner_name=f"rs{i % 5}",
                                    node_name=nd.name))
    for i in range(min(args.pods, 200)):
        fake.add_pod(build_test_pod(f"p{i}", cpu_milli=400, mem_mib=256,
                                    owner_name=f"prs{i % 4}"))
    holder = {"now": 1000.0}
    opts = AutoscalingOptions(
        journal_dir=jdir, journal_max_mb=16.0,
        node_shape_bucket=64, group_shape_bucket=16,
        max_new_nodes_static=64, max_pods_per_node=16,
        enable_dynamic_resource_allocation=False,
        enable_csi_node_aware_scheduling=False,
        scale_down_delay_after_add_s=0.0,
        node_group_defaults=NodeGroupDefaults(
            scale_down_unneeded_time_s=3600.0),
    )
    a = StaticAutoscaler(fake.provider, fake, options=opts,
                         eviction_sink=fake, walltime=lambda: holder["now"])
    seq = 0
    caps: dict[str, int] = {}
    loop_ms, journal_ms, lineage_ms = [], [], []
    for k in range(loops):
        # mixed deltas: churn replaces objects (the replace-on-update
        # contract the incremental encoder and the journal both ride)
        for j in range(8):
            fake.remove_pod(f"p{seq + j}")
            fake.add_pod(build_test_pod(
                f"p{200 + seq + j}", cpu_milli=400, mem_mib=256,
                owner_name=f"prs{(seq + j) % 4}"))
        seq += 8
        if k == 2:   # taint flip (fresh Node object, same name)
            old = fake.nodes["n1"]
            fake.nodes["n1"] = Node(
                name=old.name, labels=dict(old.labels),
                capacity=dict(old.capacity),
                allocatable=dict(old.allocatable),
                taints=[Taint("bench/flip", "1", "NoSchedule")], ready=True)
            # pin every group at its current target: the burst below must
            # be REFUSED (capped-by-limits) for two loops before scale-up
            # can help — the open-then-resolved refusal is the causal
            # chain the lineage story reconstructs
            for g in fake.provider.node_groups():
                caps[g.id()] = g._max
                g._max = g.target_size()
        if k == 3:   # unfittable burst: refused while capped (k=3), then
            # real scale-up once uncapped (k=4) → node-add churn
            for j in range(6):
                fake.add_pod(build_test_pod(
                    f"burst{j}", cpu_milli=7000, mem_mib=4096,
                    owner_name="burst-rs"))
        if k == 4:   # lift the cap: scale-up fires and the refusal resolves
            for g in fake.provider.node_groups():
                g._max = caps[g.id()]
        if k == 5:
            for j in range(6):
                fake.remove_pod(f"burst{j}")
        holder["now"] = 1000.0 + 10.0 * k
        j0 = a.journal.overhead_ns
        l0 = a.lineage_ring.overhead_ns if a.lineage_ring is not None else 0
        t0 = time.perf_counter()
        a.run_once(now=holder["now"])
        loop_ms.append((time.perf_counter() - t0) * 1000.0)
        journal_ms.append((a.journal.overhead_ns - j0) / 1e6)
        lineage_ms.append(
            (a.lineage_ring.overhead_ns - l0) / 1e6
            if a.lineage_ring is not None else 0.0)
    return {"autoscaler": a, "loops": loops, "loop_ms": loop_ms,
            "journal_ms": journal_ms, "lineage_ms": lineage_ms}


def bench_journal(args) -> None:
    """--journal DIR: the record→replay round trip as bench-evidenced
    contract. Records the shared story world (`_journal_story_run`) into
    a flight journal, measures journaling overhead against steady loop
    walltime (the ≤2% acceptance bound CI asserts), then replays the
    journal in-process and reports the drift — zero on a healthy build.
    Everything here is host-side, so the numbers exist on the CPU floor."""
    import numpy as np

    from kubernetes_autoscaler_tpu.replay.harness import replay_journal

    jdir = args.journal
    r = _journal_story_run(args, jdir)
    a, loops = r["autoscaler"], r["loops"]
    loop_ms, journal_ms = r["loop_ms"], r["journal_ms"]
    # steady-state overhead: the cold loop pays compiles in the denominator
    # and first-snapshot serialization in the numerator — exclude both
    steady_loop = sum(loop_ms[1:])
    steady_journal = sum(journal_ms[1:])
    frac = steady_journal / steady_loop if steady_loop > 0 else 0.0
    jstats = a.journal.stats()
    cursor = a.journal.cursor()

    t0 = time.perf_counter()
    report = replay_journal(jdir)
    replay_ms = (time.perf_counter() - t0) * 1000.0
    print(json.dumps({
        "metric": "journal_record_replay_smoke",
        "value": round(frac * 100.0, 4),
        "unit": "percent_overhead",
        # the ACTUAL jax platform both legs ran on (journal + replay are
        # host-side either way, but the replayed sim dispatches are not)
        "backend": report["backend"]["replayed"].get("platform", "cpu"),
        "loops": loops,
        "journal_overhead_ms": round(steady_journal, 3),
        "journal_overhead_frac": round(frac, 5),
        "loop_p50_ms": round(float(np.percentile(loop_ms[1:], 50)), 3),
        "journal": {**jstats, "cursor": list(cursor) if cursor else None},
        "replay": {
            "loops": report["loops"],
            "zero_drift": report["zeroDrift"],
            "drift_loops": report["driftLoops"],
            "problems": report["problems"],
            "replay_ms": round(replay_ms, 1),
            "backend": report["backend"],
        },
    }), flush=True)


def bench_lineage(args) -> None:
    """--lineage DIR: the decision-lineage engine as bench-evidenced
    contract (lineage/; docs/LINEAGE.md). Drives the shared journaled
    story world with the live ring on, measures the ring's steady-loop
    overhead fraction (the ≤1% bound CI asserts), then builds the
    OFFLINE LineageIndex over the journal dir and reports the index
    build rate plus why/timeline/diff query p50s — and proves the index
    reconstructs the injected story (burst refused → scale-up won →
    resolved) from the journal alone. Host-side end to end: the numbers
    exist on the CPU floor."""
    import numpy as np

    from kubernetes_autoscaler_tpu.lineage.index import LineageIndex

    jdir = args.lineage
    r = _journal_story_run(args, jdir)
    a, loops = r["autoscaler"], r["loops"]
    loop_ms, lineage_ms = r["loop_ms"], r["lineage_ms"]
    # same steady-state convention as --journal: the cold loop pays
    # compiles in the denominator — exclude loop 0 from both sides
    steady_loop = sum(loop_ms[1:])
    steady_ring = sum(lineage_ms[1:])
    frac = steady_ring / steady_loop if steady_loop > 0 else 0.0

    t0 = time.perf_counter()
    idx = LineageIndex(jdir)
    build_s = time.perf_counter() - t0
    stats = idx.stats()
    build_rate = stats["records"] / build_s if build_s > 0 else 0.0

    # query p50s over the story's own objects (offline index, cold cache)
    keys = list(idx.objects) or [("node", "n0")]
    last = idx.last_loop if idx.last_loop is not None else 0

    def _p50(call, reps=32):
        samples = []
        for i in range(reps):
            q0 = time.perf_counter()
            call(i)
            samples.append((time.perf_counter() - q0) * 1000.0)
        return round(float(np.percentile(samples, 50)), 4)

    why_p50 = _p50(lambda i: idx.why(*keys[i % len(keys)]))
    timeline_p50 = _p50(lambda i: idx.timeline(None, None))
    diff_p50 = _p50(lambda i: idx.diff(max(last - (i % loops), 1)))

    # the story contract: the index alone must yield the causal chain the
    # world injected — a refused pod-group, the winning scale-up, and the
    # refusal resolving after it
    story = {"refusedGroup": None, "wonGroup": None, "resolved": False,
             "resolvedAfterScaleUp": False}
    for (kind, name), obj in idx.objects.items():
        for e in obj["entries"]:
            ev = e.get("event")
            if kind == "pod-group" and ev == "refused" \
                    and story["refusedGroup"] is None:
                story["refusedGroup"] = name
            if kind == "pod-group" and ev == "resolved":
                story["resolved"] = True
                if e.get("afterScaleUp"):
                    story["resolvedAfterScaleUp"] = True
            if kind == "nodegroup" and ev == "scale-up" and e.get("won"):
                story["wonGroup"] = name
    story_ok = bool(story["refusedGroup"] and story["wonGroup"]
                    and story["resolved"])

    print(json.dumps({
        "metric": "lineage_smoke",
        "value": round(frac * 100.0, 4),
        "unit": "percent_overhead",
        "backend": "host",   # the ring and index are host dict work
        "loops": loops,
        "lineage_overhead_ms": round(steady_ring, 3),
        "lineage_overhead_frac": round(frac, 5),
        "loop_p50_ms": round(float(np.percentile(loop_ms[1:], 50)), 3),
        "index_build_ms": round(build_s * 1000.0, 3),
        "index_build_records_per_s": round(build_rate, 1),
        "query_p50_ms": {"why": why_p50, "timeline": timeline_p50,
                         "diff": diff_p50},
        "index": stats,
        "ring": a.lineage_ring.stats() if a.lineage_ring is not None
        else None,
        "story": story,
        "story_ok": story_ok,
        "journal_dir": jdir,
    }), flush=True)


def bench_shadow_audit(args) -> None:
    """--shadow-audit: the online fidelity-verification contract as bench
    evidence (audit/shadow.py; docs/OBSERVABILITY.md "Shadow audit").

    Leg 1 (healthy): a journaled, audited control loop vs an UN-audited
    cold-encode comparator over identical churned worlds — measured audit
    overhead fraction (steady loops; the ≤1% acceptance bound CI asserts),
    zero divergence, sample/skip accounting, and loop-for-loop decision
    identity (the audit must be a pure observer).
    Leg 2 (forced corruption): one `flip_bit` fault on the fetched verdict
    plane — detected within ONE loop, complete evidence bundle (journal
    cursor + per-bit diff + trace id), backend_transitions_total
    {to=suspect,cause=audit_divergence}, a forced full/audit_divergence
    re-encode, a clean re-audit of the same sample, and post-heal
    decisions bit-identical to the cold-encode comparator.
    Leg 3 (sidecar): the per-window round-robin lane audit over a small
    batched fleet — checks flow, zero divergence, no quarantines.
    Host-side orchestration throughout: never-null on the CPU floor."""
    import tempfile
    import threading

    import jax

    from kubernetes_autoscaler_tpu.config.options import (
        AutoscalingOptions,
        NodeGroupDefaults,
    )
    from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
    from kubernetes_autoscaler_tpu.sidecar import faults
    from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    adir = tempfile.mkdtemp(prefix="katpu-audit-")
    jdir = os.path.join(adir, "journal")

    def world():
        fake = FakeCluster()
        tmpl = build_test_node("tmpl", cpu_milli=16000, mem_mib=65536,
                               pods=110, labels={"pool": "a", "disk": "ssd"})
        fake.add_node_group("ng1", tmpl, min_size=0, max_size=64)
        for i in range(16):
            nd = build_test_node(
                f"n{i}", cpu_milli=16000, mem_mib=65536, pods=110,
                labels={"pool": "a" if i % 2 else "b",
                        "disk": "ssd" if i % 3 else "hdd"})
            fake.add_existing_node("ng1", nd)
            for j in range(2):
                fake.add_pod(build_test_pod(
                    f"r{i}-{j}", cpu_milli=3000, mem_mib=1024,
                    owner_name=f"rs{i % 5}", node_name=nd.name))
        for i in range(40):
            fake.add_pod(build_test_pod(
                f"p{i}", cpu_milli=500, mem_mib=512,
                owner_name=f"prs{i % 4}",
                node_selector={"disk": "ssd"} if i % 4 == 0 else None))
        return fake

    plan_never = NodeGroupDefaults(scale_down_unneeded_time_s=3600.0,
                                   scale_down_unready_time_s=3600.0)

    def opts(**kw) -> AutoscalingOptions:
        base = dict(
            scale_down_delay_after_add_s=0.0,
            node_shape_bucket=64, group_shape_bucket=16,
            max_new_nodes_static=64, max_pods_per_node=32, drain_chunk=8,
            enable_dynamic_resource_allocation=False,
            enable_csi_node_aware_scheduling=False,
            node_group_defaults=plan_never,
        )
        base.update(kw)
        return AutoscalingOptions(**base)

    worlds = [world(), world()]
    audited = StaticAutoscaler(
        worlds[0].provider, worlds[0], eviction_sink=worlds[0],
        options=opts(shadow_audit=True, shadow_audit_dir=adir,
                     flight_recorder_dir=os.path.join(adir, "flight"),
                     journal_dir=jdir, journal_max_mb=16.0))
    # the comparator COLD-encodes every loop (incremental off): the
    # decision-identity baseline both legs compare against
    cold = StaticAutoscaler(
        worlds[1].provider, worlds[1], eviction_sink=worlds[1],
        options=opts(incremental_encode=False))
    for x in (audited, cold):
        x.capture_verdicts = True

    def decisions(x, st):
        verdict = tuple(sorted(
            (key, int(cnt)) for key, cnt in zip(
                x.last_verdict_keys or [],
                x.last_verdict_plane
                if x.last_verdict_plane is not None else [])
            if key is not None))
        return (sorted(st.scale_up.increases.items()) if st.scale_up
                else None,
                sorted(st.unneeded_nodes), st.pending_pods, verdict)

    # ---- leg 1: healthy loops, measured overhead, decision identity ----
    loops, warmup = 20, 4
    aud = audited.shadow_auditor
    loop_ms, audit_ms = [], []
    identical = True
    seq = 0
    for k in range(loops):
        for w in worlds:
            w.remove_pod(f"p{seq % 40}")
            w.add_pod(build_test_pod(
                f"p{40 + seq}", cpu_milli=500, mem_mib=512,
                owner_name=f"prs{seq % 4}"))
        seq += 1
        a0 = aud.overhead_ns
        t0 = time.perf_counter()
        st_a = audited.run_once(now=1000.0 + 10.0 * k)
        loop_ms.append((time.perf_counter() - t0) * 1000.0)
        audit_ms.append((aud.overhead_ns - a0) / 1e6)
        st_c = cold.run_once(now=1000.0 + 10.0 * k)
        if k >= 1:   # loop 0 differs only in startup-recovery bookkeeping
            identical = identical and (decisions(audited, st_a)
                                       == decisions(cold, st_c))
    steady_loop = sum(loop_ms[warmup:])
    # the audit's own meter, minus forgiven jit/oracle warmup (the token
    # bucket excludes it from the budget for the same reason)
    steady_audit = sum(audit_ms[warmup:])
    frac = steady_audit / steady_loop if steady_loop > 0 else 0.0
    healthy = {
        "loops": loops,
        "audit_overhead_ms": round(steady_audit, 3),
        "audit_overhead_frac": round(frac, 5),
        "warmup_ms": round(aud.warmup_ms, 3),
        "checks": {s: dict(c) for s, c in aud.checks.items()},
        "samples": sum(c["ok"] + c["divergent"]
                       for c in aud.checks.values()),
        "skips": sum(c["skipped"] for c in aud.checks.values()),
        "divergence": aud.divergences,
        "identical_to_cold_encode": bool(identical),
    }

    # ---- leg 2: forced single-bit corruption of the fetched plane ----
    faults.install([{"hook": "verdict_plane", "kind": "flip_bit",
                     "times": 1}], seed=11, registry=audited.metrics)
    try:
        div_before = aud.divergences
        st_a = audited.run_once(now=1000.0 + 10.0 * loops)
        cold.run_once(now=1000.0 + 10.0 * loops)
        detected = (aud.divergences == div_before + 1
                    and st_a.audit_divergence)
        bundle = {}
        if st_a.audit_bundle_path:
            with open(st_a.audit_bundle_path) as f:
                b = json.load(f)
            bundle = {
                "path": st_a.audit_bundle_path,
                "has_cursor": bool(b.get("journalCursor")),
                "has_trace": bool(b.get("traceId")),
                "has_bit_diff": any(
                    d.get("xorBits") is not None or d.get("flipped")
                    for d in b.get("divergences", [])),
                "surfaces": sorted({d["surface"]
                                    for d in b.get("divergences", [])}),
            }
        suspect = audited.metrics.counter(
            "backend_transitions_total").value(
            **{"from": "healthy", "to": "suspect",
               "cause": "audit_divergence"})
        # post-heal loop: forced full/audit_divergence re-encode + the
        # single re-audit of the SAME sample (fault exhausted → clean)
        for w in worlds:
            w.add_pod(build_test_pod("q-heal", cpu_milli=500, mem_mib=512,
                                     owner_name="prs0"))
        st_a = audited.run_once(now=1000.0 + 10.0 * (loops + 1))
        st_c = cold.run_once(now=1000.0 + 10.0 * (loops + 1))
        injection = {
            "detected_within_one_loop": bool(detected),
            "bundle": bundle,
            "suspect_transitions": suspect,
            "flight_dump_reason_audit": audited.metrics.counter(
                "flight_recorder_dumps_total").value(
                reason="audit_divergence"),
            "rebuild_cause_counter": audited.metrics.counter(
                "encoder_encodes_total").value(
                mode="full", cause="audit_divergence"),
            "reaudit_clean": (aud.pending_recheck is None
                              and not st_a.audit_divergence),
            "backend_state_after": audited.supervisor.state,
            "post_heal_identical": bool(
                decisions(audited, st_a) == decisions(cold, st_c)),
        }
    finally:
        faults.clear()

    # ---- leg 3: sidecar per-window lane audit ----
    from kubernetes_autoscaler_tpu.sidecar.server import (
        SimParams,
        SimulatorService,
    )
    from kubernetes_autoscaler_tpu.sidecar.wire import DeltaWriter

    mib = 1024 * 1024
    ngs = [{"id": "ng-4c", "template": {"name": "t4", "capacity": {
        "cpu": 4.0, "memory": 16384 * mib, "pods": 110}},
        "max_new": 32, "price": 1.0}]
    svc = SimulatorService(node_bucket=16, group_bucket=16, batch_lanes=2,
                           batch_window_ms=5.0, shadow_audit=True)
    try:
        for i in range(3):
            w = DeltaWriter()
            for k in range(8):
                w.upsert_node(build_test_node(
                    f"d{i}-n{k}", cpu_milli=2000 + 1000 * (k % 3),
                    mem_mib=8192, pods=110))
            for k in range(24):
                w.upsert_pod(build_test_pod(
                    f"d{i}-p{k}", cpu_milli=300, mem_mib=256,
                    owner_name=f"d{i}-rs{k % 3}",
                    node_name=f"d{i}-n{k % 8}" if k % 3 == 0 else ""))
            ack = svc.apply_delta(w.payload(), tenant=f"aud{i}")
            assert not ack.get("error"), ack

        def one(i: int, kind: str) -> None:
            if kind == "up":
                svc.scale_up_sim(SimParams(max_new_nodes=16,
                                           node_groups=ngs),
                                 tenant=f"aud{i}")
            else:
                svc.scale_down_sim(SimParams(threshold=0.5),
                                   tenant=f"aud{i}")

        for _r in range(3):
            for kind in ("up", "down"):
                ths = [threading.Thread(target=one, args=(i, kind))
                       for i in range(3)]
                for t in ths:
                    t.start()
                for t in ths:
                    t.join()
        svc.audit_quiesce(60.0)   # audits run async on the worker thread
        sstats = svc.audit_stats()
        sidecar = {
            "checks": sstats["checks"],
            "divergence": sstats["divergences"],
            "overhead_ms": sstats["overhead_ms"],
            "quarantined": len(svc.quarantine_stats()),
        }
    finally:
        svc.close()

    print(json.dumps({
        "metric": "shadow_audit_smoke",
        "value": round(frac * 100.0, 4),
        "unit": "percent_overhead",
        "backend": jax.default_backend(),
        "audit_overhead_frac": healthy["audit_overhead_frac"],
        "healthy": healthy,
        "injection": injection,
        "sidecar": sidecar,
    }), flush=True)


def bench_device_stats(args) -> None:
    """--device-stats: the device-side observability block (ISSUE 14 /
    docs/OBSERVABILITY.md "Device surfaces"), never-null on both floors.

    Drives a small in-process multi-tenant serving stack and reports:
    (1) the HBM residency ledger census — per-owner/per-tenant tagged
    bytes reconciled against `device.memory_stats()` totals on real
    accelerators, or against host RSS with `device_stats_source:
    host-fallback` on CPU backends (the never-null degradation);
    (2) the `hbm-budget` admission reject: a tenant whose projected
    residency breaches the budget is rejected with the structured
    validation reason, with no OOM and no quarantine of innocents;
    (3) the compile census variant table (which entry point compiled, at
    which shape signature, charged to which tenant, at what flop/temp-HBM
    cost); (4) a Profilez-armed capture round trip (capture dir + stamped
    meta.json); (5) the disabled-path guard cost in ns/op (the PR 12
    zero-overhead contract, CI-bounded)."""
    import tempfile

    import jax

    from kubernetes_autoscaler_tpu.metrics import device
    from kubernetes_autoscaler_tpu.sidecar.server import (
        SimParams,
        SimulatorService,
    )
    from kubernetes_autoscaler_tpu.sidecar.admission import (
        WorldValidationError,
    )
    from kubernetes_autoscaler_tpu.sidecar.wire import DeltaWriter
    from kubernetes_autoscaler_tpu.utils.testing import (
        build_test_node,
        build_test_pod,
    )

    mib = 1024 * 1024
    ngs = [{"id": "ng-4c", "template": {"name": "t4", "capacity": {
        "cpu": 4.0, "memory": 16384 * mib, "pods": 110}},
        "max_new": 32, "price": 1.0}]

    def tenant_delta(i: int) -> bytes:
        w = DeltaWriter()
        for k in range(8):
            w.upsert_node(build_test_node(
                f"d{i}-n{k}", cpu_milli=2000 + 1000 * (k % 3),
                mem_mib=8192, pods=110))
        for k in range(24):
            w.upsert_pod(build_test_pod(
                f"d{i}-p{k}", cpu_milli=300, mem_mib=256,
                owner_name=f"d{i}-rs{k % 3}",
                node_name=f"d{i}-n{k % 8}" if k % 3 == 0 else ""))
        return w.payload()

    n_tenants = 4
    prof_dir = tempfile.mkdtemp(prefix="katpu-devprof-")
    svc = SimulatorService(
        node_bucket=16, group_bucket=16, batch_lanes=2,
        batch_window_ms=10.0, device_profile_dir=prof_dir,
        profile_min_interval_s=0.0)
    try:
        for i in range(n_tenants):
            ack = svc.apply_delta(tenant_delta(i), tenant=f"dev{i}")
            assert not ack.get("error"), ack
        for _round in range(2):      # warm + steady
            for i in range(n_tenants):
                svc.scale_up_sim(SimParams(max_new_nodes=16,
                                           node_groups=ngs),
                                 tenant=f"dev{i}")
                svc.scale_down_sim(SimParams(threshold=0.5),
                                   tenant=f"dev{i}")
        rec = svc.hbm_stats()
        tenants = {t: b for t, b in rec["tenants"].items()
                   if t.startswith("dev")}
        # reconciliation contract: on a real device every tagged byte is a
        # subset of bytes_in_use (the documented slack is the UNTAGGED
        # remainder — allocator overhead + XLA temp space); on the host
        # fallback tagged-census-only is the report
        reconciles = (rec["source"] != "device"
                      or 0 < rec["tagged_bytes"] <= rec["bytes_in_use"])

        # (2) hbm-budget admission: shrink the budget under this world's
        # projected residency — the NEXT tenant rejects with the reason,
        # resident tenants keep serving, nobody is quarantined
        svc.hbm_budget_frac = 1e-12
        svc.hbm_limit_bytes = 1
        svc._hbm_limit_cache = None
        ack = svc.apply_delta(tenant_delta(n_tenants),
                              tenant=f"dev{n_tenants}")
        assert not ack.get("error"), ack
        budget_reject = None
        try:
            svc.scale_up_sim(SimParams(max_new_nodes=16, node_groups=ngs),
                             tenant=f"dev{n_tenants}")
        except WorldValidationError as e:
            budget_reject = e.reason
        svc.hbm_budget_frac = 0.0       # innocents keep serving
        svc.hbm_limit_bytes = 0
        svc._hbm_limit_cache = None
        innocent = svc.scale_up_sim(
            SimParams(max_new_nodes=16, node_groups=ngs), tenant="dev0")
        budget = {
            "reject_reason": budget_reject,
            "taxonomy_count": svc.registry.counter(
                "world_validation_rejects_total").value(
                reason="hbm-budget"),
            "innocents_ok": bool(innocent.get("best") is not None),
            "quarantined": len(svc.quarantine_stats()),
        }

        # (3) profiler round trip: arm via the Profilez surface, capture
        # the next dispatch, verify the stamped meta
        armed = svc.profilez(json.dumps({"arm": True,
                                         "reason": "bench"}).encode())
        svc.scale_up_sim(SimParams(max_new_nodes=16, node_groups=ngs),
                         tenant="dev1")
        pstats = device.PROFILER.stats() if device.PROFILER else {}
        cap = pstats.get("last") or {}
        meta_ok = False
        if cap.get("path"):
            try:
                with open(os.path.join(cap["path"], "meta.json")) as f:
                    meta = json.load(f)
                meta_ok = meta.get("reason") == "bench"
            except OSError:
                pass
        profiler = {
            "armed_ok": bool(armed.get("armed_now")),
            "captured": bool(cap.get("path")),
            "meta_ok": meta_ok,
            "captures": pstats.get("captures", 0),
            "throttled": pstats.get("throttled", 0),
        }

        census = svc.census.variants()

        # (5) disabled-path guard: one module-global load + identity test
        # per hot-path site (measure LAST — disabling drops the ledger)
        saved = device.LEDGER
        device.disable_ledger()
        iters = 200_000
        g0 = time.perf_counter_ns()
        for _ in range(iters):
            if device.LEDGER is not None:  # pragma: no cover
                raise AssertionError("disabled ledger fired")
        guard_ns = (time.perf_counter_ns() - g0) / iters
        device.LEDGER = saved

        print(json.dumps({
            "metric": "device_stats",
            "value": round(rec["tagged_bytes"] / mib, 4),
            "unit": "MiB",
            "backend": jax.default_backend(),
            "device_stats_source": rec["source"],
            "bytes_in_use": rec["bytes_in_use"],
            "bytes_limit": rec["bytes_limit"],
            "tagged_bytes": rec["tagged_bytes"],
            "untagged_bytes": rec["untagged_bytes"],
            "headroom_ratio": rec["headroom_ratio"],
            "reconciles": reconciles,
            "by_owner_tenant": rec["by_owner_tenant"],
            "tenant_hbm_bytes": tenants,
            "tenants_attributed": sum(1 for b in tenants.values() if b > 0),
            "budget": budget,
            "compile_census": census,
            "profiler": profiler,
            "disabled_guard_ns": round(guard_ns, 1),
        }), flush=True)
    finally:
        svc.close()


if __name__ == "__main__":
    main()
