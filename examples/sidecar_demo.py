#!/usr/bin/env python
"""End-to-end sidecar demo: what a Go control plane does, in 80 lines.

Starts the TPU simulation sidecar (TLS, self-signed), pushes a cluster as
KAD1/KAUX deltas the way `go/katpusim` would (docs/SIDECAR_WIRE.md), then
asks the two simulation questions the control loop needs:

  * ScaleUpSim  — can the pending pods fit; which node group, how many nodes?
  * ScaleDownSim — which nodes are drainable, where would their pods go?

Run:  python examples/sidecar_demo.py        (CPU or TPU; ~30 s cold compile)
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Default to CPU so the demo runs anywhere (KATPU_DEMO_PLATFORM=tpu to
# target an attached TPU). BOTH knobs, in this order (tests/conftest.py does
# the same): the env var before the first jax import keeps other platform
# plugins from initializing at backend discovery; the config knob pins the
# default platform.
platform = os.environ.get("KATPU_DEMO_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = platform
import jax  # noqa: E402

jax.config.update("jax_platforms", platform)

from kubernetes_autoscaler_tpu.models.api import Toleration  # noqa: E402
from kubernetes_autoscaler_tpu.sidecar.server import (  # noqa: E402
    SimulatorClient,
    SimulatorService,
    make_grpc_server,
)
from kubernetes_autoscaler_tpu.sidecar.wire import DeltaWriter  # noqa: E402
from kubernetes_autoscaler_tpu.utils.certs import CertManager  # noqa: E402
from kubernetes_autoscaler_tpu.utils.testing import (  # noqa: E402
    build_test_node,
    build_test_pod,
)
# cold compiles: ~1-3 min on a busy CPU; seconds on TPU after the first run


def main() -> None:
    with tempfile.TemporaryDirectory() as td:
        cm = CertManager(td)  # self-signed serving pair, rotated on expiry
        server, port = make_grpc_server(SimulatorService(), port=0,
                                        cert_file=cm.cert_path,
                                        key_file=cm.key_path)
        server.start()
        print(f"sidecar listening on :{port} (TLS)", flush=True)
        client = SimulatorClient(port, cert_file=cm.cert_path)

        # ---- loop 1: upload the world as one delta -------------------------
        w = DeltaWriter()
        for i in range(8):
            w.upsert_node(build_test_node(
                f"n{i}", cpu_milli=8000, mem_mib=16384, pods=32,
                zone=["a", "b"][i % 2]), group_id=0)
        for i in range(6):  # residents at ~50% utilization
            w.upsert_pod(build_test_pod(
                f"r{i}", cpu_milli=4000, mem_mib=4096, owner_name="rs-web",
                node_name=f"n{i}"), movable=True)
        for i in range(20):  # pending demand beyond the free capacity
            w.upsert_pod(build_test_pod(
                f"p{i}", cpu_milli=3000, mem_mib=2048, owner_name="rs-api",
                tolerations=[Toleration("dedicated", "Exists", "", "")]))
        ack = client.apply_delta(w)
        print(f"delta applied, snapshot version {ack['version']}", flush=True)

        mib = 1024 * 1024
        up = client.scale_up_sim(
            max_new_nodes=16, strategy="least-waste",
            node_groups=[{"id": "ng-big", "max_new": 16, "price": 2.0,
                          "template": {
                              "name": "tmpl", "labels": {},
                              "capacity": {"cpu": 16.0,
                                           "memory": 32768 * mib,
                                           "pods": 64}}}])
        print(f"scale-up: {up}", flush=True)

        # ---- loop 2: the bound pods churn; ask about scale-down -----------
        w2 = DeltaWriter()
        w2.delete_pod("uid-default/r5")            # a resident finished
        ack = client.apply_delta(w2)
        down = client.scale_down_sim(threshold=0.6)
        print(f"scale-down (after delta v{ack['version']}): {down}", flush=True)
        server.stop(1.0)


if __name__ == "__main__":
    main()
