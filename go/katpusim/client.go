// Thin gRPC client for katpu.simulator.v1.TpuSimulator.
//
// ON-WIRE CONVENTION: the service passes RAW bytes — the KAD1 payload for
// ApplyDelta, UTF-8 JSON for sim params — and returns UTF-8 JSON documents.
// protos/simulator.proto documents the rpc SHAPE; the implementation on
// both sides uses identity serializers (no protobuf framing), exactly so no
// codegen is needed anywhere (see the proto's own header comment and
// sidecar/server.py make_grpc_server). Mirrors the reference's
// out-of-process precedent (expander/grpcplugin, externalgrpc).
package katpusim

import (
	"context"
	"encoding/json"
	"fmt"

	"google.golang.org/grpc"
	"google.golang.org/grpc/encoding"
)

const (
	methodApplyDelta   = "/katpu.simulator.v1.TpuSimulator/ApplyDelta"
	methodScaleUpSim   = "/katpu.simulator.v1.TpuSimulator/ScaleUpSim"
	methodScaleDownSim = "/katpu.simulator.v1.TpuSimulator/ScaleDownSim"
	methodHealth       = "/katpu.simulator.v1.TpuSimulator/Health"
)

// rawCodec moves bytes through grpc-go untouched (identity serialization —
// the same convention sidecar/server.py registers).
type rawCodec struct{}

func (rawCodec) Marshal(v any) ([]byte, error) { return v.([]byte), nil }
func (rawCodec) Unmarshal(d []byte, v any) error {
	*(v.(*[]byte)) = append([]byte(nil), d...)
	return nil
}
func (rawCodec) Name() string { return "katpu-raw" }

func init() { encoding.RegisterCodec(rawCodec{}) }

// Ack is the JSON response of ApplyDelta/Health.
type Ack struct {
	Version uint64 `json:"version"`
	Error   string `json:"error,omitempty"`
}

// Client talks to the TPU simulation sidecar.
type Client struct{ cc *grpc.ClientConn }

// Dial connects (use grpc.WithTransportCredentials for TLS — the sidecar
// serves TLS when started with --grpc-cert/--grpc-key).
func Dial(target string, opts ...grpc.DialOption) (*Client, error) {
	opts = append(opts,
		grpc.WithDefaultCallOptions(grpc.CallContentSubtype("katpu-raw")))
	cc, err := grpc.NewClient(target, opts...)
	if err != nil {
		return nil, err
	}
	return &Client{cc: cc}, nil
}

func (c *Client) Close() error { return c.cc.Close() }

func (c *Client) invoke(ctx context.Context, method string, payload []byte,
) ([]byte, error) {
	var resp []byte
	if err := c.cc.Invoke(ctx, method, payload, &resp); err != nil {
		return nil, err
	}
	return resp, nil
}

// ApplyDelta uploads one KAD1(/KAUX) payload; returns the snapshot version
// after applying it.
func (c *Client) ApplyDelta(ctx context.Context, payload []byte) (uint64, error) {
	resp, err := c.invoke(ctx, methodApplyDelta, payload)
	if err != nil {
		return 0, err
	}
	var ack Ack
	if err := json.Unmarshal(resp, &ack); err != nil {
		return 0, fmt.Errorf("bad ack: %w", err)
	}
	if ack.Error != "" {
		return 0, fmt.Errorf("sidecar: %s", ack.Error)
	}
	return ack.Version, nil
}

func (c *Client) sim(ctx context.Context, method string, params any,
	out any) error {
	p, err := json.Marshal(params)
	if err != nil {
		return err
	}
	resp, err := c.invoke(ctx, method, p)
	if err != nil {
		return err
	}
	var probe struct {
		Error string `json:"error"`
	}
	if err := json.Unmarshal(resp, &probe); err == nil && probe.Error != "" {
		return fmt.Errorf("sidecar: %s", probe.Error)
	}
	return json.Unmarshal(resp, out)
}

// ScaleUpSim runs loop A+B (filter-out-schedulable + all expansion options +
// expander scoring). params/result shapes: protos/simulator.proto comments.
func (c *Client) ScaleUpSim(ctx context.Context, params any, out any) error {
	return c.sim(ctx, methodScaleUpSim, params, out)
}

// ScaleDownSim runs loop C (eligibility + batched drain sweep).
func (c *Client) ScaleDownSim(ctx context.Context, params any, out any) error {
	return c.sim(ctx, methodScaleDownSim, params, out)
}

// Health pings the service.
func (c *Client) Health(ctx context.Context) error {
	resp, err := c.invoke(ctx, methodHealth, nil)
	if err != nil {
		return err
	}
	var ack Ack
	if err := json.Unmarshal(resp, &ack); err != nil {
		return fmt.Errorf("bad health ack: %w", err)
	}
	if ack.Error != "" {
		return fmt.Errorf("sidecar: %s", ack.Error)
	}
	return nil
}
