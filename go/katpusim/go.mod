module katpu.dev/katpusim

go 1.22

require google.golang.org/grpc v1.64.0
