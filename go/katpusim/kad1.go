// Package katpusim is the Go half of the TPU-simulator sidecar boundary:
// a KAD1/KAUX delta encoder plus a thin gRPC client for the
// katpu.simulator.v1.TpuSimulator service (protos/simulator.proto).
//
// The byte format is specified in docs/SIDECAR_WIRE.md and pinned by the
// golden fixtures under kubernetes_autoscaler_tpu/sidecar/goldens/: encode
// the inputs listed in goldens/manifest.json with this writer, byte-compare
// the KAD1 dense body against the committed payload_N arrays, and
// parse-compare the KAUX JSON trailer against the manifest's aux documents
// (the repo's own CI replays the same bytes through the native codec,
// tests/test_wire_conformance.py).
//
// This package deliberately has no protobuf dependency: the service moves
// RAW bytes with identity serializers on both sides — see client.go.
package katpusim

import (
	"encoding/binary"
	"encoding/json"
	"fmt"
	"hash/crc32"
	"math"
)

// Resource-vector slot layout (models/resources.py): cpu-milli, memory-MiB,
// ephemeral-MiB, pods, then up to four extended-resource slots assigned
// first-come-first-served per snapshot.
const NumResources = 8

// Taint/toleration effect encodings on the wire.
const (
	EffectNoSchedule = 0
	EffectNoExecute  = 1
	EffectOther      = 2 // PreferNoSchedule etc. — never filters
)

// Op codes.
const (
	opUpsertNode = 1
	opDeleteNode = 2
	opUpsertPod  = 3
	opDeletePod  = 4
)

// Fold32 is the label/taint/port hash shared with the Python encoder and the
// C++ codec (utils/hashing.py / kacodec.cc): FNV-1a 64 folded to a nonzero
// signed int32. Exposed so Go-side tooling can precompute hashes; the WIRE
// itself carries strings, not hashes.
func Fold32(s string) int32 {
	const offset = 0xCBF29CE484222325
	const prime = 0x100000001B3
	h := uint64(offset)
	for i := 0; i < len(s); i++ {
		h ^= uint64(s[i])
		h *= prime
	}
	h32 := uint32(h ^ (h >> 32))
	if h32 == 0 {
		h32 = 1
	}
	return int32(h32)
}

// Taint is one node taint as the wire carries it.
type Taint struct {
	Key, Value string
	Effect     byte // EffectNoSchedule / EffectNoExecute / EffectOther
}

// Toleration is one pod toleration as the wire carries it.
type Toleration struct {
	Key    string
	Exists bool // operator: false=Equal, true=Exists
	Value  string
	Effect byte // EffectOther means "all effects" (empty effect in k8s)
}

// HostPort is one requested host port.
type HostPort struct {
	Port uint16
	UDP  bool
}

// Node is the dense node record. Cap is the allocatable vector in the
// NumResources slot layout; the encoder side owns slot assignment for
// extended resources and must keep it stable across deltas.
type Node struct {
	Name          string
	Labels        [][2]string // ordered; ordering is part of the byte stream
	Taints        []Taint
	Cap           [NumResources]int32
	Ready         bool
	Unschedulable bool
	GroupID       int32 // index into the control plane's node-group list, -1 none
	Zone          string
}

// Pod is the dense pod record. Req includes pod overhead
// (noderesources/fit.go:299). EqKey is the equivalence-group key: an OPAQUE
// writer-chosen string — pods with equal keys must be schedulable-equivalent
// (reference: core/scaleup/equivalence/groups.go:40, controller UID + spec
// hash); "" means the pod is its own group.
type Pod struct {
	UID       string
	NodeName  string // "" = pending
	Req       [NumResources]int32
	Selector  [][2]string // ordered (sort by key for canonical bytes)
	Tols      []Toleration
	Ports     []HostPort
	Movable   bool // drainability: evictable, must reschedule
	Blocks    bool // drainability: forbids draining its node
	AntiSelf  bool // hostname self-anti-affinity (one per node)
	Lossy     bool // dense row incomplete -> host-check tier
	EqKey     string
}

// AuxRecord is the KAUX constraint side-channel record for one pod
// (docs/SIDECAR_WIRE.md §KAUX). JSON field names are the wire contract.
type AuxRecord struct {
	EqKey     string            `json:"k"`
	Namespace string            `json:"ns"`
	Labels    map[string]string `json:"l"`
	NodeName  string            `json:"n"`
	DenseOK   bool              `json:"dok"`
	Spread    *AuxSpread        `json:"s,omitempty"`
	Affinity  *AuxAffinity      `json:"a,omitempty"`
	Anti      []AuxAnti         `json:"x,omitempty"`
}

// AuxSpread carries the pod's first DoNotSchedule topologySpreadConstraint.
// Sel must already contain the matchLabelKeys merge (vendored
// common.go:96-104 — a static per-pod operation the encoder performs).
type AuxSpread struct {
	TopologyKey string            `json:"key"`
	MaxSkew     int               `json:"w"`
	Sel         map[string]string `json:"sel"`
	Extra       bool              `json:"extra"` // more constraints exist
	MinDomains  int               `json:"md"`
	NodeAffinityPolicy string     `json:"nap"` // "Honor" | "Ignore"
	NodeTaintsPolicy   string     `json:"ntp"` // "Ignore" | "Honor"
}

// AuxAffinity is the required pod-affinity record ("a"): it carries
// "extra" (more terms exist than the dense tier models).
type AuxAffinity struct {
	TopologyKey string             `json:"key"`
	Sel         map[string]string  `json:"sel"`
	Namespaces  []string           `json:"nss"`
	NamespaceSelector *map[string]string `json:"nssel"` // nil = absent
	Extra       bool               `json:"extra"`
}

// AuxAnti is one required anti-affinity term ("x" entries): the Python
// encoder emits NO "extra" key here, and conformance is a parse-compare —
// the shapes are deliberately distinct types.
type AuxAnti struct {
	TopologyKey string             `json:"key"`
	Sel         map[string]string  `json:"sel"`
	Namespaces  []string           `json:"nss"`
	NamespaceSelector *map[string]string `json:"nssel"` // nil = absent
}

// DeltaWriter builds one KAD1 payload (optionally with a KAUX trailer).
// Mirrors kubernetes_autoscaler_tpu/sidecar/wire.py DeltaWriter. The KAD1
// body is byte-stable across implementations; the KAUX trailer is JSON and
// compared semantically (docs/SIDECAR_WIRE.md §Conformance).
type DeltaWriter struct {
	body   []byte
	count  uint32
	auxUp  map[string]AuxRecord
	auxDel []string
	err    error // first overflow/validation error; surfaced by Payload()
}

func (w *DeltaWriter) fail(format string, args ...any) {
	if w.err == nil {
		w.err = fmt.Errorf(format, args...)
	}
}

func NewDeltaWriter() *DeltaWriter {
	return &DeltaWriter{auxUp: map[string]AuxRecord{}}
}

func (w *DeltaWriter) str(s string) {
	if len(s) > math.MaxUint16 {
		// the Python reference raises on overflow; emitting a truncated
		// string would desync the stream for the decoder
		w.fail("string field exceeds %d bytes", math.MaxUint16)
		s = s[:0]
	}
	w.body = binary.LittleEndian.AppendUint16(w.body, uint16(len(s)))
	w.body = append(w.body, s...)
}

func (w *DeltaWriter) countU8(n int, what string) byte {
	if n > math.MaxUint8 {
		w.fail("%s count %d exceeds %d", what, n, math.MaxUint8)
		return 0
	}
	return byte(n)
}

func (w *DeltaWriter) countU16(n int, what string) uint16 {
	if n > math.MaxUint16 {
		w.fail("%s count %d exceeds %d", what, n, math.MaxUint16)
		return 0
	}
	return uint16(n)
}

func (w *DeltaWriter) i32(v int32) {
	w.body = binary.LittleEndian.AppendUint32(w.body, uint32(v))
}

// UpsertNode appends op=1.
func (w *DeltaWriter) UpsertNode(n Node) *DeltaWriter {
	w.body = append(w.body, opUpsertNode)
	w.str(n.Name)
	w.body = binary.LittleEndian.AppendUint16(
		w.body, w.countU16(len(n.Labels), "label"))
	for _, kv := range n.Labels {
		w.str(kv[0])
		w.str(kv[1])
	}
	w.body = append(w.body, w.countU8(len(n.Taints), "taint"))
	for _, t := range n.Taints {
		w.str(t.Key)
		w.str(t.Value)
		w.body = append(w.body, t.Effect)
	}
	for _, c := range n.Cap {
		w.i32(c)
	}
	var flags byte
	if n.Ready {
		flags |= 1
	}
	if n.Unschedulable {
		flags |= 2
	}
	w.body = append(w.body, flags)
	w.i32(n.GroupID)
	w.str(n.Zone)
	w.count++
	return w
}

// DeleteNode appends op=2.
func (w *DeltaWriter) DeleteNode(name string) *DeltaWriter {
	w.body = append(w.body, opDeleteNode)
	w.str(name)
	w.count++
	return w
}

// UpsertPod appends op=3. aux, when non-nil, rides the KAUX trailer (labels
// and topology constraints feed the constrained device tier; see
// docs/SIDECAR_WIRE.md for when a record is required).
func (w *DeltaWriter) UpsertPod(p Pod, aux *AuxRecord) *DeltaWriter {
	w.body = append(w.body, opUpsertPod)
	w.str(p.UID)
	w.str(p.NodeName)
	for _, c := range p.Req {
		w.i32(c)
	}
	w.body = binary.LittleEndian.AppendUint16(
		w.body, w.countU16(len(p.Selector), "selector"))
	for _, kv := range p.Selector {
		w.str(kv[0])
		w.str(kv[1])
	}
	w.body = append(w.body, w.countU8(len(p.Tols), "toleration"))
	for _, t := range p.Tols {
		w.str(t.Key)
		if t.Exists {
			w.body = append(w.body, 1)
		} else {
			w.body = append(w.body, 0)
		}
		w.str(t.Value)
		w.body = append(w.body, t.Effect)
	}
	w.body = append(w.body, w.countU8(len(p.Ports), "hostPort"))
	for _, hp := range p.Ports {
		w.body = binary.LittleEndian.AppendUint16(w.body, hp.Port)
		if hp.UDP {
			w.body = append(w.body, 1)
		} else {
			w.body = append(w.body, 0)
		}
	}
	var flags byte
	if p.Movable {
		flags |= 1
	}
	if p.Blocks {
		flags |= 2
	}
	if p.AntiSelf {
		flags |= 4
	}
	if p.Lossy {
		flags |= 8
	}
	w.body = append(w.body, flags)
	w.str(p.EqKey)
	w.count++
	if aux != nil {
		if w.auxUp == nil {
			w.auxUp = map[string]AuxRecord{}
		}
		if aux.Anti != nil {
			for i := range aux.Anti {
				if aux.Anti[i].Namespaces == nil {
					aux.Anti[i].Namespaces = []string{}
				}
			}
		}
		if aux.Affinity != nil && aux.Affinity.Namespaces == nil {
			aux.Affinity.Namespaces = []string{}
		}
		for i, d := range w.auxDel {
			if d == p.UID {
				w.auxDel = append(w.auxDel[:i], w.auxDel[i+1:]...)
				break
			}
		}
		w.auxUp[p.UID] = *aux
	} else {
		if _, had := w.auxUp[p.UID]; had {
			delete(w.auxUp, p.UID)
		}
		w.auxDel = appendUnique(w.auxDel, p.UID)
	}
	return w
}

// DeletePod appends op=4.
func (w *DeltaWriter) DeletePod(uid string) *DeltaWriter {
	w.body = append(w.body, opDeletePod)
	w.str(uid)
	w.count++
	delete(w.auxUp, uid)
	w.auxDel = appendUnique(w.auxDel, uid)
	return w
}

func appendUnique(xs []string, s string) []string {
	for _, x := range xs {
		if x == s {
			return xs
		}
	}
	return append(xs, s)
}

// Payload assembles [KAD1][u32 count][records] with the optional
// [json][u32 len][u32 crc32][KAUX] trailer.
func (w *DeltaWriter) Payload() ([]byte, error) {
	if w.err != nil {
		return nil, w.err
	}
	out := append([]byte("KAD1"), 0, 0, 0, 0)
	binary.LittleEndian.PutUint32(out[4:], w.count)
	out = append(out, w.body...)
	if len(w.auxUp) > 0 || len(w.auxDel) > 0 {
		doc, err := json.Marshal(map[string]any{
			"up": w.auxUp, "del": w.auxDel,
		})
		if err != nil {
			return nil, err
		}
		out = append(out, doc...)
		out = binary.LittleEndian.AppendUint32(out, uint32(len(doc)))
		out = binary.LittleEndian.AppendUint32(out, crc32.ChecksumIEEE(doc))
		out = append(out, "KAUX"...)
	}
	return out, nil
}
