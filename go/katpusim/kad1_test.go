package katpusim

// Conformance: replay the exported golden scenarios (testdata/<name>.json,
// written by kubernetes_autoscaler_tpu/sidecar/go_fixtures.py) through this
// encoder and compare against the committed payload bytes
// (testdata/<name>_<i>.bin):
//   - the KAD1 body must be BYTE-IDENTICAL,
//   - the KAUX trailer is JSON and compares SEMANTICALLY (map ordering is
//     implementation-defined), per docs/SIDECAR_WIRE.md §Conformance.
//
// Only the standard library is required: `go vet ./... && go test ./...`.

import (
	"bytes"
	"encoding/binary"
	"encoding/json"
	"os"
	"path/filepath"
	"reflect"
	"sort"
	"testing"
)

type fixtureRecord struct {
	Op   string `json:"op"`
	Name string `json:"name"`
	// upsert_node
	Labels [][2]string `json:"labels"`
	Taints []struct {
		Key    string `json:"key"`
		Value  string `json:"value"`
		Effect byte   `json:"effect"`
	} `json:"taints"`
	Cap           []int32 `json:"cap"`
	Ready         bool    `json:"ready"`
	Unschedulable bool    `json:"unschedulable"`
	GroupID       int32   `json:"group_id"`
	Zone          string  `json:"zone"`
	// upsert_pod
	UID      string      `json:"uid"`
	Node     string      `json:"node"`
	Req      []int32     `json:"req"`
	Selector [][2]string `json:"selector"`
	Tols     []struct {
		Key    string `json:"key"`
		Exists bool   `json:"exists"`
		Value  string `json:"value"`
		Effect byte   `json:"effect"`
	} `json:"tolerations"`
	Ports []struct {
		Port uint16 `json:"port"`
		UDP  bool   `json:"udp"`
	} `json:"ports"`
	Movable  bool            `json:"movable"`
	Blocks   bool            `json:"blocks"`
	AntiSelf bool            `json:"anti_self"`
	Lossy    bool            `json:"lossy"`
	EqKey    string          `json:"eqkey"`
	Aux      json.RawMessage `json:"aux"`
}

type fixtureDelta struct {
	Payload    string          `json:"payload"`
	Records    []fixtureRecord `json:"records"`
	AuxDeletes []string        `json:"aux_deletes"`
	HasAux     bool            `json:"has_aux"`
}

type fixtureFile struct {
	Scenario string         `json:"scenario"`
	Deltas   []fixtureDelta `json:"deltas"`
}

// splitPayload separates [KAD1][u32 count][body] from the optional
// [json][u32 len][u32 crc][KAUX] trailer.
func splitPayload(t *testing.T, p []byte) (body []byte, aux []byte) {
	t.Helper()
	if len(p) < 8 || string(p[:4]) != "KAD1" {
		t.Fatalf("bad magic")
	}
	rest := p[8:]
	if bytes.HasSuffix(rest, []byte("KAUX")) {
		n := len(rest)
		docLen := int(binary.LittleEndian.Uint32(rest[n-12 : n-8]))
		return rest[:n-12-docLen], rest[n-12-docLen : n-12]
	}
	return rest, nil
}

func replay(t *testing.T, d fixtureDelta) *DeltaWriter {
	t.Helper()
	w := NewDeltaWriter()
	for _, rec := range d.Records {
		switch rec.Op {
		case "upsert_node":
			n := Node{Name: rec.Name, Labels: rec.Labels, Ready: rec.Ready,
				Unschedulable: rec.Unschedulable, GroupID: rec.GroupID,
				Zone: rec.Zone}
			for _, tn := range rec.Taints {
				n.Taints = append(n.Taints,
					Taint{Key: tn.Key, Value: tn.Value, Effect: tn.Effect})
			}
			copy(n.Cap[:], rec.Cap)
			w.UpsertNode(n)
		case "delete_node":
			w.DeleteNode(rec.Name)
		case "upsert_pod":
			p := Pod{UID: rec.UID, NodeName: rec.Node,
				Selector: rec.Selector, Movable: rec.Movable,
				Blocks: rec.Blocks, AntiSelf: rec.AntiSelf,
				Lossy: rec.Lossy, EqKey: rec.EqKey}
			copy(p.Req[:], rec.Req)
			for _, tl := range rec.Tols {
				p.Tols = append(p.Tols, Toleration{Key: tl.Key,
					Exists: tl.Exists, Value: tl.Value, Effect: tl.Effect})
			}
			for _, hp := range rec.Ports {
				p.Ports = append(p.Ports, HostPort{Port: hp.Port, UDP: hp.UDP})
			}
			var aux *AuxRecord
			if len(rec.Aux) > 0 && string(rec.Aux) != "null" {
				aux = &AuxRecord{}
				if err := json.Unmarshal(rec.Aux, aux); err != nil {
					t.Fatalf("aux unmarshal: %v", err)
				}
			}
			w.UpsertPod(p, aux)
		case "delete_pod":
			w.DeletePod(rec.UID)
		default:
			t.Fatalf("unknown op %q", rec.Op)
		}
	}
	return w
}

func normalizeAux(t *testing.T, doc []byte) map[string]any {
	t.Helper()
	if doc == nil {
		return nil
	}
	var m map[string]any
	if err := json.Unmarshal(doc, &m); err != nil {
		t.Fatalf("aux json: %v", err)
	}
	// the del list is order-insensitive (a uid is deleted or not)
	if del, ok := m["del"].([]any); ok {
		ss := make([]string, len(del))
		for i, v := range del {
			ss[i] = v.(string)
		}
		sort.Strings(ss)
		anys := make([]any, len(ss))
		for i, s := range ss {
			anys[i] = s
		}
		m["del"] = anys
	}
	return m
}

func TestGoldenScenariosByteConformance(t *testing.T) {
	matches, err := filepath.Glob(filepath.Join("testdata", "*.json"))
	if err != nil || len(matches) == 0 {
		t.Fatalf("no fixtures (run go_fixtures.py): %v", err)
	}
	for _, path := range matches {
		raw, err := os.ReadFile(path)
		if err != nil {
			t.Fatal(err)
		}
		var fx fixtureFile
		if err := json.Unmarshal(raw, &fx); err != nil {
			t.Fatalf("%s: %v", path, err)
		}
		t.Run(fx.Scenario, func(t *testing.T) {
			for i, d := range fx.Deltas {
				want, err := os.ReadFile(filepath.Join("testdata", d.Payload))
				if err != nil {
					t.Fatal(err)
				}
				got, err := replay(t, d).Payload()
				if err != nil {
					t.Fatalf("delta %d: %v", i, err)
				}
				wantBody, wantAux := splitPayload(t, want)
				gotBody, gotAux := splitPayload(t, got)
				if !bytes.Equal(wantBody, gotBody) {
					for j := range wantBody {
						if j >= len(gotBody) || wantBody[j] != gotBody[j] {
							t.Fatalf("delta %d: KAD1 bodies differ at byte %d "+
								"(want len %d, got len %d)",
								i, j, len(wantBody), len(gotBody))
						}
					}
					t.Fatalf("delta %d: got KAD1 body overruns want "+
						"(want len %d, got len %d)",
						i, len(wantBody), len(gotBody))
				}
				if (wantAux == nil) != (gotAux == nil) {
					t.Fatalf("delta %d: aux presence differs (want %v, got %v)",
						i, wantAux != nil, gotAux != nil)
				}
				if !reflect.DeepEqual(normalizeAux(t, wantAux),
					normalizeAux(t, gotAux)) {
					t.Fatalf("delta %d: KAUX trailers differ semantically\n"+
						"want: %s\ngot:  %s", i, wantAux, gotAux)
				}
			}
		})
	}
}

func TestFold32MatchesPythonHash(t *testing.T) {
	// pinned values from kubernetes_autoscaler_tpu/utils/hashing.fold32
	// (string -> fnv1a32 folded to signed-int32 avoiding 0)
	cases := map[string]int32{}
	raw, err := os.ReadFile(filepath.Join("testdata", "fold32_cases.json"))
	if err != nil {
		t.Skip("fold32 fixture not exported")
	}
	if err := json.Unmarshal(raw, &cases); err != nil {
		t.Fatal(err)
	}
	for s, want := range cases {
		if got := Fold32(s); got != want {
			t.Fatalf("Fold32(%q) = %d, want %d", s, got, want)
		}
	}
}
