"""TPU-native cluster-autoscaling simulation framework.

A from-scratch re-design of the capabilities of openshift/kubernetes-autoscaler
(reference layout surveyed in /root/repo/SURVEY.md) around one idea: the Cluster
Autoscaler's scale-up/scale-down *simulation* — scheduler-predicate checking
(reference: cluster-autoscaler/simulator/clustersnapshot/predicate/plugin_runner.go:54),
binpacking node estimation (reference: cluster-autoscaler/estimator/binpacking_estimator.go:102)
and drain/reschedulability analysis (reference: cluster-autoscaler/simulator/cluster.go:131) —
is evaluated as a vectorized pods×nodes×nodegroups tensor program on TPU via JAX/XLA/pjit,
instead of serial Go loops.

Package layout (maps SURVEY.md §1 layers):
  models/        L2 state model: host object model + tensorized ClusterState + functional snapshot
  ops/           L3 kernels: predicate masks, FFD binpack scan, drain masks, expander scoring
  parallel/      device-mesh sharding of the pods/nodes axes (ICI), multi-host (DCN)
  simulator/     L3 simulation API mirroring the reference ClusterSnapshot verbs + drainability
  estimator/     L4 scale-up sizing (reference: estimator/)
  expander/      L4 node-group choice strategies (reference: expander/)
  processors/    L4 policy hook points (reference: processors/processors.go:38-79)
  core/          L5 orchestration: StaticAutoscaler.RunOnce, scaleup/, scaledown/
  clusterstate/  node-group health model (reference: clusterstate/clusterstate.go:122)
  cloudprovider/ L1 SPI + test provider (reference: cloudprovider/cloud_provider.go:117)
  vpa/           Vertical Pod Autoscaler (reference: vertical-pod-autoscaler/)
  balancer/      Balancer controller (reference: balancer/)
  nanny/         Addon Resizer (reference: addon-resizer/)
  sidecar/       native (C++) snapshot-delta codec + gRPC boundary for external control planes
"""

__version__ = "0.1.0"
