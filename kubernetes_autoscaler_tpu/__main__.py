"""Process entry: flags → leader election → HTTP mux → control loop.

Reference counterpart: cluster-autoscaler/main.go:200-331 — flag parsing,
leader election, the /metrics /healthz /snapshotz HTTP mux, then the loop
driver. Standalone mode runs against a JSON scenario file on the in-memory
provider (the reference's equivalent harness is the kwok/test provider);
deployment mode is driven through the sidecar gRPC service instead
(sidecar/server.py).

Scenario JSON shape:
{
  "node_groups": [{"id": "ng1", "min": 0, "max": 10,
                   "template": {"cpu_milli": 4000, "mem_mib": 8192, ...}}],
  "nodes":  [{"group": "ng1", "name": "n1", "cpu_milli": 4000, ...}],
  "pods":   [{"name": "p1", "cpu_milli": 500, "mem_mib": 512,
              "owner_name": "rs", "node_name": ""}]
}
"""

from __future__ import annotations

import json
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_autoscaler_tpu.config.flags import parse_options
from kubernetes_autoscaler_tpu.core.loop import LoopTrigger, run_loop
from kubernetes_autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from kubernetes_autoscaler_tpu.debuggingsnapshot import DebuggingSnapshotter
from kubernetes_autoscaler_tpu.metrics.metrics import (
    default_registry,
    expose_all_text,
)
from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
from kubernetes_autoscaler_tpu.utils.leaderelection import FileLeaderElector
from kubernetes_autoscaler_tpu.utils.testing import build_test_node, build_test_pod


def cluster_from_scenario(path: str) -> FakeCluster:
    with open(path) as f:
        doc = json.load(f)
    fake = FakeCluster()
    for g in doc.get("node_groups", []):
        t = dict(g.get("template", {}))
        name = t.pop("name", f"template-{g['id']}")
        tmpl = build_test_node(name, **t)
        fake.add_node_group(g["id"], tmpl, min_size=g.get("min", 0),
                            max_size=g.get("max", 10))
    for n in doc.get("nodes", []):
        spec = {k: v for k, v in n.items() if k not in ("group",)}
        fake.add_existing_node(n["group"], build_test_node(**spec))
    for p in doc.get("pods", []):
        fake.add_pod(build_test_pod(**p))
    return fake


def make_mux(autoscaler: StaticAutoscaler, snapshotter: DebuggingSnapshotter):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # klog-quiet
            pass

        def _send(self, code: int, body: str, ctype="text/plain"):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/metrics":
                # default registry + any registered extra registries (an
                # in-process sidecar's katpu_sidecar_* series) — the same
                # families the sidecar Metricz RPC serves, one scrape.
                # OpenMetrics content type: histogram bucket lines may carry
                # exemplar suffixes (`# {trace_id="…"} v`), which are
                # OpenMetrics syntax — a classic text/plain parser would
                # reject the whole scrape
                self._send(200, expose_all_text(),
                           ctype="application/openmetrics-text; "
                                 "version=1.0.0; charset=utf-8")
            elif self.path == "/healthz":
                ok = autoscaler.health.healthy()
                self._send(200 if ok else 500, "ok" if ok else "loop stalled")
            elif self.path == "/statusz":
                st = autoscaler.last_status
                self._send(200, st.to_json() if st else "{}",
                           "application/json")
            elif self.path == "/snapshotz":
                if snapshotter is None:
                    self._send(404, "debugging snapshots disabled "
                                    "(--debugging-snapshot-enabled=false)")
                    return
                handle = snapshotter.request_snapshot()
                payload = handle.wait(timeout=120.0)
                self._send(200 if payload else 504, payload or "timed out",
                           "application/json")
            elif self.path == "/whyz" or self.path.startswith("/whyz?"):
                # decision lineage (lineage/index.py, docs/LINEAGE.md):
                #   /whyz                 → per-object summary digest
                #   /whyz?object=K/NAME   → that object's causal chain
                #   /whyz?loop=K          → object-level diff across K
                ring = autoscaler.lineage_ring
                if ring is None:
                    self._send(404, "lineage ring disabled "
                                    "(--lineage-ring=false)")
                    return
                from urllib.parse import parse_qs, urlparse

                from kubernetes_autoscaler_tpu.lineage import query as lq
                qs = parse_qs(urlparse(self.path).query)
                if qs.get("object"):
                    kind, name = lq.parse_object(qs["object"][0])
                    body = ring.why(kind, name, surface="whyz")
                    body["segments"] = lq.coalesce_segments(
                        body.get("entries") or [])
                elif qs.get("loop"):
                    try:
                        body = ring.diff(int(qs["loop"][0]),
                                         surface="whyz")
                    except ValueError:
                        self._send(400, "loop must be an integer")
                        return
                else:
                    body = ring.snapshot_summary(surface="whyz")
                self._send(200, json.dumps(body, indent=2, sort_keys=True,
                                           default=str),
                           "application/json")
            elif self.path == "/profilez":
                # --profiling consumer (reference: net/http/pprof behind
                # --profiling, main.go:264-266): per-phase wall-time stats
                # from the function_duration histograms as JSON
                if not autoscaler.options.profiling:
                    self._send(404, "profiling disabled (--profiling=false)")
                    return
                import json as _json

                h = default_registry.histogram("function_duration_seconds")
                out = {}
                for key in list(h._sums):
                    label = dict(key).get("function", "?")
                    out[label] = {
                        "count": int(sum(h._counts.get(key, []))),
                        "sum_seconds": h._sums.get(key, 0.0),
                    }
                self._send(200, _json.dumps(out, indent=2),
                           "application/json")
            else:
                self._send(404, "not found")

    return Handler


def main(argv: list[str] | None = None) -> int:
    options, args = parse_options(argv)
    if not args.scenario:
        print("standalone mode needs --scenario <file>; deployment mode is "
              "driven via the sidecar gRPC service (sidecar/server.py)")
        return 2

    fake = cluster_from_scenario(args.scenario)
    snapshotter = (DebuggingSnapshotter()
                   if options.debugging_snapshot_enabled else None)
    autoscaler = StaticAutoscaler(
        fake.provider, fake, options=options, eviction_sink=fake,
        debugging_snapshotter=snapshotter,
    )

    host, _, port = args.address.rpartition(":")
    server = ThreadingHTTPServer((host or "0.0.0.0", int(port)),
                                 make_mux(autoscaler, snapshotter))
    threading.Thread(target=server.serve_forever, daemon=True).start()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # non-main thread (tests)

    def run():
        trigger = LoopTrigger(options.scan_interval_s)
        max_it = args.max_iterations or None
        run_loop(autoscaler, trigger, max_iterations=max_it, stop=stop)
        return 0

    try:
        if args.leader_elect:
            elector = FileLeaderElector(args.leader_elect_lease_file)
            rc = elector.run_or_die(run, stop=stop)
            return 0 if rc is None else rc   # stop during standby = clean exit
        return run()
    finally:
        server.shutdown()
        autoscaler.provider.cleanup()


if __name__ == "__main__":
    raise SystemExit(main())
