from kubernetes_autoscaler_tpu.audit.shadow import (  # noqa: F401
    AUDIT_CHECKS_HELP,
    AUDIT_SURFACES,
    ShadowAuditor,
    sample_indices,
)
