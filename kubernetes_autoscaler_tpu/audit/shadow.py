"""Online shadow audit: continuous sampled fidelity verification of TPU
verdicts (docs/OBSERVABILITY.md "Shadow audit").

Every fidelity check before this layer was OFFLINE: the property suites,
the PR 9 replay oracle, the PR 11 cross-encode-mode oracle. In production
a silently miscompiled kernel, a corrupted HBM buffer, or a stale resident
plane emits *wrong autoscaling decisions with perfect-looking metrics* —
the supervisor (PR 13) survives a device that hangs, but nothing before
this detected a device that is fast and wrong. The ShadowAuditor closes
that gap: each RunOnce it draws a deterministic, journal-cursor-seeded
sample of the loop's device verdicts and re-derives them through an
independent host path:

  plane    the whole filter-out-schedulable verdict plane, digest-compared
           against an independent re-fetch of the SAME device buffer — a
           corrupted fetch (or an unstable read) diverges. Runs every
           audited loop (it costs one tiny d2h transfer), so a corruption
           is detected within ONE loop of appearing.
  scaleup  K sampled (pod-group × node) predicate verdicts: the device
           re-evaluates `ops/predicates.reason_mask` over the sampled
           groups (one masked dispatch) and the host recomputes the SAME
           uint16 reason bits from the encoder's mirrors
           (`ops/predicates.host_reason_row`, the numpy twin built on the
           host_predicate_row hash contract). A divergence names the exact
           flipped bits — the PR 9 drift-localization vocabulary, online.
  drain    K sampled DRAINABLE verdicts, re-checked through the planner's
           ConfirmOracle reference path: the device's claimed per-pod
           destinations are replayed move-by-move against the exact host
           oracle (utils/oracle_cache.ConfirmOracle). Only the unsafe
           direction is audited — a false "drainable" deletes a node;
           a false "unremovable" merely waits.

Sampling determinism (docs/REPLAY.md "Shadow-audit cursor seeding"): the
sample for loop k is seeded by the journal cursor AT THE TOP of loop k —
record k-1's digest plus the loop index — hashed through sha256, never a
process RNG. Replaying a journal reproduces the record digests, therefore
the seeds, therefore the exact cells audited: a recorded divergence is
re-examinable offline.

Budget (the audit must never become the hot path): a token bucket refilled
per loop with `--shadow-audit-budget-ms` (or, at the default 0, an
adaptive ~0.5% of the loop-walltime EWMA — half the 1% overhead target,
leaving headroom). Each step spends its measured cost; a step only starts
while the bucket is positive, so expensive loops push the bucket negative
and later loops skip (counted as outcome=skipped in
`shadow_audit_checks_total{surface,outcome}`) until the debt amortizes.
The first execution of each step is jit/oracle warmup and is forgiven
(recorded as `warmup_ms`), mirroring how the bench excludes loop 0. The
always-on plane check and a pending post-heal re-audit bypass the bucket.

Divergence is ACTED ON, not just counted (the supervisor coupling lives in
core/static_autoscaler.py): a self-contained evidence bundle is written,
the BackendSupervisor ladder takes healthy→suspect with
cause="audit_divergence", the WorldStore is heal()ed with a FORCED
full/audit_divergence re-encode, and the same sample is re-audited once —
persistent divergence degrades the backend (scale-down withheld, scale-up
refused with the `AuditDivergence` reason) instead of actuating on
corrupt bits.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

import numpy as np

from kubernetes_autoscaler_tpu.metrics import trace as _trace

AUDIT_SURFACES = ("plane", "scaleup", "drain")

AUDIT_CHECKS_HELP = ("Shadow-audit verdict re-checks, by surface "
                     "(plane / scaleup / drain) and outcome "
                     "(ok / divergent / skipped)")
AUDIT_OVERHEAD_HELP = ("Wall-clock seconds spent in the shadow audit "
                       "(budget-bounded; the bench reports the fraction)")

# adaptive refill when --shadow-audit-budget-ms is 0: half the 1% overhead
# target, as a fraction of the loop-walltime EWMA
_ADAPTIVE_FRAC = 0.005
# the bucket never banks more than this many CURRENT refills: a long idle
# stretch (or one cold compile-inflated loop feeding the EWMA) must not
# bank enough to audit every loop for dozens of loops — the cap is applied
# against the current refill, so an inflated grant deflates as the EWMA
# converges to the steady loop
_BUCKET_CAP_REFILLS = 4.0


def sample_indices(seed: str, tag: str, k: int, n: int) -> list[int]:
    """k distinct indices in [0, n), derived from a sha256 stream over
    (seed, tag, counter) — deterministic and platform/process independent
    (NOT random.Random: the journal replay contract demands byte-stable
    sampling across interpreters). Same seed ⇒ same cells."""
    if n <= 0 or k <= 0:
        return []
    out: list[int] = []
    seen: set[int] = set()
    ctr = 0
    want = min(k, n)
    # 64 draws per wanted index bounds the worst-case collision walk
    while len(out) < want and ctr < 64 * want + 64:
        h = hashlib.sha256(f"{seed}:{tag}:{ctr}".encode()).digest()
        ctr += 1
        idx = int.from_bytes(h[:8], "big") % n
        if idx not in seen:
            seen.add(idx)
            out.append(idx)
    return out


class ShadowAuditor:
    """One per StaticAutoscaler; owned and driven by the control-loop
    thread (no locks, like the JournalWriter)."""

    def __init__(self, registry=None, event_sink=None, samples: int = 4,
                 budget_ms: float = 0.0, bundle_dir: str = ""):
        self.registry = registry
        self.event_sink = event_sink
        self.samples = max(int(samples), 1)
        self.budget_ms = float(budget_ms)
        self.bundle_dir = bundle_dir
        # token bucket (ms); starts with one generous grant so loop 0 audits
        self.bucket_ms = 5.0
        self.loop_ewma_ms: float | None = None
        self._warmed: set[str] = set()
        self.warmup_ms = 0.0
        self.overhead_ns = 0
        self.loop_index = 0
        self.checks = {s: {"ok": 0, "divergent": 0, "skipped": 0}
                       for s in AUDIT_SURFACES}
        self.divergences = 0
        self.last_report: dict | None = None
        self.last_bundle_path = ""
        # per-loop sample provenance (bounded): {"loop", "seed", "cells",
        # "drain"} — the replay determinism pin reads this
        self.sample_log: list[dict] = []
        # the divergent sample awaiting its single post-heal re-audit;
        # persistent divergence (re-audit diverges again) sets `degraded`
        self.pending_recheck: dict | None = None
        self.degraded = False
        # captured per loop by StaticAutoscaler
        self._ctx: dict | None = None
        self._seed = ""
        # replay stitching (replay/harness.py): the replayed autoscaler
        # has no live journal, so the harness feeds each record's `parent`
        # digest here — the SAME cursor the recorder seeded from, so the
        # replay reproduces the exact cells (docs/REPLAY.md)
        self.parent_override: str | None = None

    # ---- wiring points (StaticAutoscaler) --------------------------------

    def scale_up_untrusted(self) -> bool:
        """Orchestrator gate: True while a persistent audit divergence is
        unhealed — every scale-up option would be derived from a verdict
        plane the audit proved corrupt, so all are refused with the
        AuditDivergence reason."""
        return self.degraded

    def capture_world(self, enc, parent_digest: str = "") -> None:
        """Pin the pre-placement device tensors + host mirrors of this
        loop's encode (jax arrays are immutable, so holding the references
        keeps the exact planes the verdicts were computed from alive even
        after the snapshot layers replace enc.nodes/enc.specs). The sample
        seed is the journal cursor at the TOP of the loop: the previous
        record's digest — the cursor a replay of this loop runs under."""
        if not parent_digest and self.parent_override is not None:
            parent_digest = self.parent_override
        self._seed = f"{parent_digest}:{self.loop_index}"
        self._ctx = {
            "nodes_t": enc.nodes,
            "specs_t": enc.specs,
            "mirrors": enc.host_arrays,
            "scheduled_pods": enc.scheduled_pods,
            "node_objs": enc.node_objs,
            "registry": enc.registry,
            "namespaces": enc.namespaces,
            "verdict_dev": None,
            "verdict_host": None,
        }

    def capture_verdict(self, verdict_dev, verdict_host) -> None:
        """The filter-out-schedulable plane: the device array (truth) and
        the fetched host copy every downstream consumer (journal, status,
        scale-up) actually reads."""
        if self._ctx is not None:
            self._ctx["verdict_dev"] = verdict_dev
            self._ctx["verdict_host"] = verdict_host

    def note_healed(self) -> None:
        """StaticAutoscaler ran the forced post-divergence rebuild: the
        pending sample's re-audit is now meaningful (it runs against a
        cold re-encode, so a second divergence really is persistent)."""
        if self.pending_recheck is not None:
            self.pending_recheck["healed"] = True

    def note_loop_ms(self, loop_ms: float) -> None:
        """Loop-walltime EWMA feed (run_once's finally) — the adaptive
        budget's denominator. The first loop is compile-dominated (often
        100× a steady loop): a later sample far below the current estimate
        resets it outright, and upward outliers are clamped, so the EWMA
        tracks the STEADY loop rather than letting one cold loop inflate
        the audit budget for dozens of loops."""
        if self.loop_ewma_ms is None:
            self.loop_ewma_ms = loop_ms
        elif loop_ms < 0.25 * self.loop_ewma_ms:
            self.loop_ewma_ms = loop_ms
        else:
            self.loop_ewma_ms = (0.8 * self.loop_ewma_ms
                                 + 0.2 * min(loop_ms,
                                             4.0 * self.loop_ewma_ms))

    # ---- budget ----------------------------------------------------------

    def _refill(self) -> float:
        if self.budget_ms > 0:
            return self.budget_ms
        return max(_ADAPTIVE_FRAC * (self.loop_ewma_ms or 10.0), 0.05)

    def _spend(self, step: str, cost_ms: float) -> None:
        if step not in self._warmed:
            # first execution = jit/oracle warmup; forgiven, like the
            # bench excludes loop 0 — steady-state stays budget-honest
            self._warmed.add(step)
            self.warmup_ms += cost_ms
            return
        self.bucket_ms -= cost_ms

    def _count(self, surface: str, outcome: str, n: int = 1,
               **labels) -> None:
        self.checks[surface][outcome] += n
        if self.registry is not None:
            self.registry.counter(
                "shadow_audit_checks_total", help=AUDIT_CHECKS_HELP).inc(
                n, surface=surface, outcome=outcome, **labels)

    # ---- the per-loop entry ---------------------------------------------

    def run_once_audit(self, planner=None, cursor=None, now: float = 0.0,
                       trace_id: str = "") -> dict | None:
        """Audit this loop's captured verdicts. Returns None when nothing
        was captured; otherwise a report dict — `divergent` True means the
        caller (StaticAutoscaler) must drive the supervisor ladder, and
        `persistent` True means the post-heal re-audit diverged AGAIN."""
        ctx, self._ctx = self._ctx, None
        if ctx is None or ctx["mirrors"] is None:
            return None
        t0 = time.perf_counter_ns()
        loop = self.loop_index
        self.loop_index += 1
        seed = self._seed
        refill = self._refill()
        self.bucket_ms = min(self.bucket_ms + refill,
                             _BUCKET_CAP_REFILLS * refill)
        report = {"loop": loop, "seed": seed, "divergent": False,
                  "persistent": False, "divergences": [], "cells": [],
                  "drainCandidates": [], "skipped": []}
        tracer = _trace.current_tracer()
        span = tracer.begin("shadow_audit", cat="audit", loop=loop) \
            if tracer is not None else None
        try:
            # the single re-audit of a divergent sample is only meaningful
            # AFTER the forced rebuild ran (note_healed): while the
            # supervisor ladder has not yet let the heal happen (e.g. it
            # degraded immediately from `recovering`), re-checking the
            # un-rebuilt world would convict a healable corruption as
            # "persistent" — hold the pending sample instead
            recheck = (self.pending_recheck
                       if (self.pending_recheck is not None
                           and self.pending_recheck.get("healed"))
                       else None)
            # 1) plane digest: always on — one tiny independent d2h fetch;
            #    the within-one-loop detection guarantee rides this step
            s0 = time.perf_counter_ns()
            self._audit_plane(ctx, report)
            self._spend("plane", (time.perf_counter_ns() - s0) / 1e6)
            # 2) scaleup cells (bucket-gated; a pending re-audit bypasses
            #    the bucket — the heal protocol mandates it)
            if recheck is not None and recheck.get("cells"):
                s0 = time.perf_counter_ns()
                self._audit_scaleup(ctx, report, recheck["cells"])
                self._spend("scaleup", (time.perf_counter_ns() - s0) / 1e6)
            elif self.bucket_ms > 0:
                cells = self._pick_cells(ctx, seed)
                if cells:
                    s0 = time.perf_counter_ns()
                    self._audit_scaleup(ctx, report, cells)
                    self._spend("scaleup",
                                (time.perf_counter_ns() - s0) / 1e6)
            else:
                self._count("scaleup", "skipped", self.samples)
                report["skipped"].append("scaleup:budget")
            # 3) drain verdicts (bucket-gated)
            if self.bucket_ms > 0 or (recheck is not None
                                      and recheck.get("drain")):
                s0 = time.perf_counter_ns()
                self._audit_drain(ctx, planner, report, seed,
                                  forced=(recheck or {}).get("drain"))
                self._spend("drain", (time.perf_counter_ns() - s0) / 1e6)
            else:
                self._count("drain", "skipped", self.samples)
                report["skipped"].append("drain:budget")

            self.sample_log.append({"loop": loop, "seed": seed,
                                    "cells": list(report["cells"]),
                                    "drain": list(
                                        report["drainCandidates"])})
            if len(self.sample_log) > 256:
                del self.sample_log[:-256]

            if report["divergences"]:
                report["divergent"] = True
                self.divergences += 1
                if recheck is not None:
                    # the single post-heal re-audit diverged AGAIN: the
                    # divergence survives a forced cold re-encode — this
                    # is persistent, the backend degrades
                    report["persistent"] = True
                    self.degraded = True
                self.pending_recheck = {
                    "cells": list(report["cells"]),
                    "drain": list(report["drainCandidates"]),
                    "loop": loop,
                    # set by note_healed() when the forced rebuild runs;
                    # the re-audit waits for it
                    "healed": False,
                }
                report["bundlePath"] = self._write_bundle(
                    report, cursor, trace_id, now)
                self._emit_events(report, now)
            elif recheck is not None:
                # the re-audit of the divergent sample came back clean:
                # the forced re-encode healed it — stand down
                self.pending_recheck = None
                self.degraded = False
            self.last_report = report
            return report
        finally:
            dt_ns = time.perf_counter_ns() - t0
            self.overhead_ns += dt_ns
            if self.registry is not None:
                self.registry.counter(
                    "shadow_audit_overhead_seconds_total",
                    help=AUDIT_OVERHEAD_HELP).inc(dt_ns / 1e9)
                self.registry.gauge(
                    "shadow_audit_pending_recheck",
                    help="1 while a divergent sample awaits its post-heal "
                         "re-audit (persistent divergence degrades the "
                         "backend)").set(
                    1.0 if self.pending_recheck is not None else 0.0)
            if tracer is not None:
                tracer.end(span,
                           divergent=bool(report["divergences"]),
                           cells=len(report["cells"]),
                           skipped=report["skipped"])

    # ---- surface 1: the verdict-plane digest ----------------------------

    def _audit_plane(self, ctx: dict, report: dict) -> None:
        dev = ctx.get("verdict_dev")
        host = ctx.get("verdict_host")
        if dev is None or host is None:
            self._count("plane", "skipped")
            report["skipped"].append("plane:no-verdict")
            return
        # a FRESH device read, not jax.Array's cached host copy: the first
        # np.asarray(dev) (the consumer fetch) populates the array's cached
        # _npy_value and a plain re-read would return that same buffer —
        # one DMA, two views, transfer corruption invisible. Adding 0 is a
        # new dispatch producing a new buffer, so this really does cross
        # the tunnel a second time.
        refetched = np.asarray(dev + 0).astype(np.int32)
        host = np.asarray(host).astype(np.int32)
        d_ref = hashlib.sha256(refetched.tobytes()).hexdigest()[:16]
        d_host = hashlib.sha256(host.tobytes()).hexdigest()[:16]
        report["planeDigest"] = d_ref
        if d_ref == d_host:
            self._count("plane", "ok")
            return
        self._count("plane", "divergent")
        rows = np.nonzero(refetched != host)[0] \
            if refetched.shape == host.shape else np.arange(host.shape[0])
        for r in rows[:8].tolist():
            dv = int(refetched[r]) if r < refetched.shape[0] else None
            hv = int(host[r]) if r < host.shape[0] else None
            report["divergences"].append({
                "surface": "plane", "row": int(r),
                "device": dv, "fetched": hv,
                "xorBits": (dv ^ hv) if dv is not None and hv is not None
                else None,
            })

    # ---- surface 2: sampled (pod-group × node) predicate cells ----------

    def _pick_cells(self, ctx: dict, seed: str) -> list[tuple[int, int]]:
        m = ctx["mirrors"]
        pending = np.nonzero(m["specs.valid"].astype(bool)
                             & (m["specs.count"] > 0))[0]
        valid_nodes = np.nonzero(m["nodes.valid"].astype(bool))[0]
        if pending.size == 0 or valid_nodes.size == 0:
            return []
        rows = sample_indices(seed, "scaleup-row", self.samples,
                              int(pending.size))
        cols = sample_indices(seed, "scaleup-col", self.samples,
                              int(valid_nodes.size))
        # K cells by pairing the row/col streams (a single pending group
        # still audits K distinct nodes; dedup keeps the set distinct)
        cells: list[tuple[int, int]] = []
        seen: set[tuple[int, int]] = set()
        for i in range(self.samples):
            cell = (int(pending[rows[i % len(rows)]]),
                    int(valid_nodes[cols[(i + i // len(cols))
                                         % len(cols)]]))
            if cell not in seen:
                seen.add(cell)
                cells.append(cell)
        return cells

    def _audit_scaleup(self, ctx: dict, report: dict,
                       cells: list) -> None:
        from kubernetes_autoscaler_tpu.ops import predicates as preds

        m = ctx["mirrors"]
        cells = [tuple(c) for c in cells]
        g_dim = int(m["specs.valid"].shape[0])
        rows = sorted({gi for gi, _ in cells if 0 <= gi < g_dim})
        if not rows:
            self._count("scaleup", "skipped")
            return
        mask = np.zeros((g_dim,), dtype=bool)
        mask[rows] = True
        # one masked device dispatch over the sampled rows (shares the lazy
        # reason pass's jit cache), one small fetch
        import jax.numpy as jnp

        bits_dev = np.asarray(preds.reason_mask_for_groups(
            ctx["nodes_t"], ctx["specs_t"], jnp.asarray(mask))[
            np.asarray(rows)])
        report["cells"] = [[int(g), int(n)] for g, n in cells]
        row_of = {gi: k for k, gi in enumerate(rows)}
        host_rows = {gi: preds.host_reason_row(m, gi) for gi in rows}
        for gi, nj in cells:
            if gi not in row_of or nj >= bits_dev.shape[1]:
                self._count("scaleup", "skipped")
                continue
            dv = int(bits_dev[row_of[gi], nj])
            hv = int(host_rows[gi][nj])
            if dv == hv:
                self._count("scaleup", "ok")
                continue
            self._count("scaleup", "divergent")
            report["divergences"].append({
                "surface": "scaleup", "cell": [int(gi), int(nj)],
                "device": dv, "host": hv,
                "flipped": preds.reason_bit_names(dv ^ hv),
                "deviceReasons": preds.reason_bit_names(dv),
                "hostReasons": preds.reason_bit_names(hv),
            })

    # ---- surface 3: sampled drain verdicts (ConfirmOracle path) ---------

    def _audit_drain(self, ctx: dict, planner, report: dict, seed: str,
                     forced=None) -> None:
        """Re-check sampled DRAINABLE verdicts by replaying the device's
        claimed per-pod destinations against the planner's exact host
        oracle. Restricted to candidates whose movable pods are all
        exactly-encoded and unconstrained (the same screen the planner's
        native tier applies) — outside that, encoded and exact semantics
        legitimately differ and the planner's own confirm pass is already
        the authority; those samples count as skipped, never as drift."""
        st = getattr(planner, "state", None)
        removal = getattr(st, "removal", None)
        cand = getattr(st, "candidate_indices", None)
        if removal is None or cand is None or getattr(
                st, "injected_pods", None):
            self._count("drain", "skipped",
                        self.samples if forced is None else len(forced))
            report["skipped"].append("drain:no-candidates")
            return
        m = ctx["mirrors"]
        drainable = np.asarray(removal.drainable)
        pod_slot = np.asarray(removal.pod_slot)
        dest_node = np.asarray(removal.dest_node)
        cand = np.asarray(cand)
        drained_rows = np.nonzero(drainable[:cand.shape[0]])[0]
        if drained_rows.size == 0:
            return
        if forced:
            picked = [k for k in forced if k in set(drained_rows.tolist())]
        else:
            picked = [int(drained_rows[i]) for i in sample_indices(
                seed, "drain", self.samples, int(drained_rows.size))]
        if not picked:
            return
        from kubernetes_autoscaler_tpu.utils.oracle_cache import (
            ConfirmOracle,
        )

        movable = m["scheduled.movable"].astype(bool)
        group_ref = m["scheduled.group_ref"]
        hostcheck = m["specs.needs_host_check"].astype(bool)
        constrained = np.zeros_like(hostcheck)
        if "specs.spread_kind" in m:
            constrained = ((m["specs.spread_kind"] > 0)
                           | (m["specs.aff_kind"] > 0)
                           | m["specs.anti_self_zone"].astype(bool))
        node_objs = ctx["node_objs"] or []
        sched = ctx["scheduled_pods"]
        report["drainCandidates"] = [int(k) for k in picked]
        # loop-invariant world view, built once: ConfirmOracle copies its
        # inputs in __init__, so the same dict/list seed every fresh
        # per-candidate oracle (rebuilding them per candidate was O(K×pods)
        # of budget spend converting later samples into skips)
        by_node: dict[str, list] = {}
        for q in sched:
            if q is not None:
                by_node.setdefault(q.node_name, []).append(q)
        live = [nd for nd in node_objs if nd is not None]
        for k in picked:
            c = int(cand[k])
            cand_node = node_objs[c] if c < len(node_objs) else None
            if cand_node is None:
                self._count("drain", "skipped")
                continue
            moves = []
            eligible = True
            for s in range(pod_slot.shape[1]):
                slot = int(pod_slot[k, s])
                if slot < 0 or slot >= len(sched) or not movable[slot]:
                    continue
                g = int(group_ref[slot])
                if hostcheck[g] or constrained[g]:
                    eligible = False
                    break
                moves.append((slot, int(dest_node[k, s])))
            if not eligible:
                self._count("drain", "skipped")
                report["skipped"].append(f"drain:{k}:inexact")
                continue
            # fresh per-candidate oracle (its __init__ copies the shared
            # world view): the device verdict is "drainable in isolation",
            # so each sample replays alone
            oracle = ConfirmOracle(live, by_node,
                                   registry=ctx["registry"],
                                   namespaces=ctx["namespaces"])
            bad = None
            for slot, dest in moves:
                pod = sched[slot]
                dest_obj = (node_objs[dest]
                            if 0 <= dest < len(node_objs) else None)
                if dest_obj is None or dest_obj.name == cand_node.name:
                    bad = {"slot": slot, "dest": int(dest),
                           "why": "no-destination-recorded"}
                    break
                if not oracle.check(pod, dest_obj):
                    from kubernetes_autoscaler_tpu.ops.predicates import (
                        host_reason_row,
                        reason_bit_names,
                    )

                    hv = int(host_reason_row(m, int(group_ref[slot]))[dest])
                    bad = {"slot": slot, "dest": int(dest),
                           "destNode": dest_obj.name,
                           "why": "oracle-refused",
                           "hostReasons": reason_bit_names(hv)}
                    break
                oracle.move(pod, pod.node_name, dest_obj.name)
            if bad is None:
                self._count("drain", "ok")
            else:
                self._count("drain", "divergent")
                report["divergences"].append({
                    "surface": "drain", "candidate": int(k),
                    "node": cand_node.name, **bad})

    # ---- evidence --------------------------------------------------------

    def _write_bundle(self, report: dict, cursor, trace_id: str,
                      now: float) -> str:
        """One self-contained JSON evidence bundle per divergent loop:
        journal cursor + record digest, the sampled cells, device-vs-host
        verdicts with the per-bit reason diff, and the retained trace id —
        everything a post-mortem (or an offline replay of the named
        cursor) needs. Atomic write; a full disk never sinks the loop."""
        from kubernetes_autoscaler_tpu.replay.journal import (
            backend_identity,
        )

        bundle = {
            "kind": "shadow-audit-divergence",
            "loop": report["loop"],
            "now": now,
            "seed": report["seed"],
            "journalCursor": list(cursor) if cursor is not None else None,
            "traceId": trace_id,
            "cells": report["cells"],
            "drainCandidates": report["drainCandidates"],
            "divergences": report["divergences"],
            "persistent": report["persistent"],
            "backend": backend_identity(),
        }
        if not self.bundle_dir:
            return ""
        try:
            os.makedirs(self.bundle_dir, exist_ok=True)
            path = os.path.join(
                self.bundle_dir,
                f"audit-{report['loop']:06d}-{trace_id or 'notrace'}.json")
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, indent=1)
            os.replace(tmp, path)
        except OSError:
            return ""
        self.last_bundle_path = path
        if self.registry is not None:
            self.registry.counter(
                "shadow_audit_bundles_total",
                help="Divergence evidence bundles persisted").inc()
        return path

    def _emit_events(self, report: dict, now: float) -> None:
        if self.event_sink is None:
            return
        for d in report["divergences"][:4]:
            obj = (f"cell-{d['cell'][0]}x{d['cell'][1]}"
                   if "cell" in d else
                   d.get("node") or f"row-{d.get('row', '?')}")
            self.event_sink.emit(
                "AuditDivergence", obj=obj, reason=d["surface"],
                message=(f"device verdict diverged from the host oracle "
                         f"on the {d['surface']} surface"
                         + (f" (flipped: {', '.join(d['flipped'])})"
                            if d.get("flipped") else "")),
                now=now)

    # ---- surfaces --------------------------------------------------------

    def snapshot_payload(self) -> dict:
        """The /snapshotz + Statusz audit section."""
        return {
            "loop": self.loop_index,
            "checks": {s: dict(c) for s, c in self.checks.items()},
            "divergences": self.divergences,
            "degraded": self.degraded,
            "pendingRecheck": (dict(self.pending_recheck)
                               if self.pending_recheck else None),
            "lastBundle": self.last_bundle_path,
            "overheadMs": round(self.overhead_ns / 1e6, 3),
            "warmupMs": round(self.warmup_ms, 3),
            "bucketMs": round(self.bucket_ms, 3),
        }

    def stats(self) -> dict:
        ok = sum(c["ok"] for c in self.checks.values())
        skipped = sum(c["skipped"] for c in self.checks.values())
        return {**self.snapshot_payload(), "ok": ok, "skipped": skipped}
