"""Balancer: split a replica count across failure domains by policy.

Reference counterpart: balancer/ — the Balancer CRD
(pkg/apis/balancer.x-k8s.io/v1alpha1/types.go:46-63) and its controller
(pkg/controller), with `proportional` and `priority` policies
(pkg/policy/proportional.go, priority.go), per-domain min/max constraints and
fallback for domains with unschedulable pods.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class TargetSpec:
    """One sub-deployment / failure domain."""

    name: str
    min_replicas: int = 0
    max_replicas: int = 1 << 30
    proportion: float = 0.0        # proportional policy weight
    priority: int = 0              # priority policy rank (higher first)


@dataclass
class BalancerSpec:
    name: str
    replicas: int
    policy: str = "proportional"   # proportional | priority
    targets: list[TargetSpec] = field(default_factory=list)
    fallback_on_problem: bool = True


def distribute(spec: BalancerSpec,
               problem_domains: set[str] = frozenset()) -> dict[str, int]:
    """Compute per-target replica counts (reference: policy.BalancePlacement)."""
    targets = spec.targets
    excluded: list[str] = []
    if spec.fallback_on_problem and problem_domains:
        healthy = [t for t in targets if t.name not in problem_domains]
        if healthy:
            excluded = [t.name for t in targets if t.name in problem_domains]
            targets = healthy

    # Excluded domains are explicitly zeroed (not dropped) so reconcile()
    # scales the unhealthy domain DOWN instead of leaving stale replicas
    # running alongside the rebalanced ones.
    alloc = {name: 0 for name in excluded}
    alloc.update({t.name: t.min_replicas for t in targets})
    remaining = spec.replicas - sum(alloc.values())
    if remaining < 0:
        # mins exceed replicas: trim from lowest-priority / lowest-weight tail
        order = sorted(targets, key=lambda t: (t.priority, t.proportion))
        for t in order:
            give_back = min(alloc[t.name], -remaining)
            alloc[t.name] -= give_back
            remaining += give_back
            if remaining >= 0:
                break
        return alloc

    if spec.policy == "priority":
        for t in sorted(targets, key=lambda t: -t.priority):
            take = min(remaining, t.max_replicas - alloc[t.name])
            alloc[t.name] += take
            remaining -= take
            if remaining == 0:
                break
    else:  # proportional (largest-remainder method, capped by max)
        weights = {t.name: max(t.proportion, 0.0) for t in targets}
        total_w = sum(weights.values()) or float(len(targets))
        if sum(weights.values()) == 0:
            weights = {t.name: 1.0 for t in targets}
        shares = {n: remaining * w / total_w for n, w in weights.items()}
        floors = {n: int(s) for n, s in shares.items()}
        caps = {t.name: t.max_replicas for t in targets}
        for t in targets:
            take = min(floors[t.name], caps[t.name] - alloc[t.name])
            alloc[t.name] += take
            remaining -= take
        # distribute remainders by largest fractional part, then overflow
        frac_order = sorted(targets, key=lambda t: -(shares[t.name] - floors[t.name]))
        i = 0
        while remaining > 0 and i < 10_000:
            progressed = False
            for t in frac_order:
                if remaining == 0:
                    break
                if alloc[t.name] < caps[t.name]:
                    alloc[t.name] += 1
                    remaining -= 1
                    progressed = True
            if not progressed:
                break
            i += 1
    return alloc


class BalancerController:
    """Reconcile loop: read spec + domain health, write per-target replicas
    (reference: balancer/pkg/controller/controller.go)."""

    def __init__(self, set_replicas):
        self.set_replicas = set_replicas   # (target_name, count) -> None

    def reconcile(self, spec: BalancerSpec,
                  problem_domains: set[str] = frozenset()) -> dict[str, int]:
        placement = distribute(spec, problem_domains)
        for name, count in placement.items():
            self.set_replicas(name, count)
        return placement
