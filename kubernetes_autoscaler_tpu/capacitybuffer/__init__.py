from kubernetes_autoscaler_tpu.capacitybuffer.api import (
    BufferStatus,
    CapacityBuffer,
)
from kubernetes_autoscaler_tpu.capacitybuffer.controller import BufferController
from kubernetes_autoscaler_tpu.capacitybuffer.translators import translate_buffer

__all__ = [
    "BufferController",
    "BufferStatus",
    "CapacityBuffer",
    "translate_buffer",
]
