"""CapacityBuffer API: headroom expressed as pod templates.

Reference counterpart: cluster-autoscaler/apis/capacitybuffer/.../v1beta1
(the CapacityBuffer CRD) and SURVEY.md §2.7 — a buffer describes spare
capacity the autoscaler must hold: either an explicit pod template ×
replicas, or a percentage of a scalable workload's replica count. The
controller translates active buffers into fake pending pods injected every
loop so scale-up provisions the headroom before real pods need it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from kubernetes_autoscaler_tpu.models.api import Pod, Workload

# ProvisioningStrategy values (reference: v1beta1 types — only the active
# strategy triggers injection; others park the buffer).
ACTIVE_PROVISIONING_STRATEGY = "buffer.x-k8s.io/active-capacity"

# Condition types mirrored from the reference's status handling.
READY_FOR_PROVISIONING = "ReadyForProvisioning"
PROVISIONING = "Provisioning"


@dataclass
class BufferStatus:
    """reference: CapacityBufferStatus — resolved template + replica count
    plus conditions explaining why a buffer is (not) being provisioned, and
    the generation bookkeeping that lets reconciles skip unchanged specs."""

    pod_template: Optional[Pod] = None
    replicas: int = 0
    conditions: dict[str, str] = field(default_factory=dict)  # type -> True/False/reason
    observed_generation: int = 0          # reference: Status.ObservedGeneration
    pod_template_generation: int = 0      # reference: Status.PodTemplateGeneration

    def ready(self) -> bool:
        return self.conditions.get(READY_FOR_PROVISIONING) == "True"


@dataclass
class CapacityBuffer:
    """One buffer object. Exactly one of `pod_template` / `scalable_ref`
    drives translation (reference: spec.podTemplateRef vs spec.scalableRef)."""

    name: str
    namespace: str = "default"
    pod_template: Optional[Pod] = None
    replicas: Optional[int] = None
    # percentage of a scalable workload's desired replicas (scalableRef path)
    scalable_ref: Optional[Workload] = None
    percentage: Optional[float] = None
    # minimum replicas when percentage rounds down to zero
    limits_min_replicas: int = 0
    provisioning_strategy: str = ACTIVE_PROVISIONING_STRATEGY
    # spec generation, bumped by whoever mutates the spec (the CRD machinery
    # in the reference); reconcile skips generations it already observed
    generation: int = 1
    # pod-template object generation (reference: PodTemplate.Generation)
    pod_template_generation: int = 1
    status: BufferStatus = field(default_factory=BufferStatus)

    def bump(self) -> None:
        """Test/fixture helper: record a spec mutation."""
        self.generation += 1
