"""Buffer controller: reconcile buffers and feed injection.

Reference counterpart: capacitybuffer/controller (wired by
InitializeAndRunDefaultBufferController, builder/autoscaler.go:209) — each
reconcile pass runs filters (strategy gate) → translators (resolve status) →
status updater. The autoscaler side then injects `pending_pods()` into every
loop's unschedulable list via BufferPodListProcessor.
"""

from __future__ import annotations

from kubernetes_autoscaler_tpu.capacitybuffer.api import (
    ACTIVE_PROVISIONING_STRATEGY,
    READY_FOR_PROVISIONING,
    CapacityBuffer,
)
from kubernetes_autoscaler_tpu.capacitybuffer.translators import (
    fake_pods_for,
    translate_buffer,
)
from kubernetes_autoscaler_tpu.models.api import Pod


class BufferController:
    def __init__(self, buffers: list[CapacityBuffer] | None = None):
        self.buffers: list[CapacityBuffer] = list(buffers or [])

    def reconcile(self) -> list[CapacityBuffer]:
        """Filter + translate every buffer; returns the active set
        (reference: controller loop over filters/translators/updater)."""
        active = []
        for buf in self.buffers:
            # strategy filter (reference: capacitybuffer/filters) — foreign
            # strategies are parked, not provisioned
            if buf.provisioning_strategy != ACTIVE_PROVISIONING_STRATEGY:
                buf.status.conditions[READY_FOR_PROVISIONING] = "False"
                buf.status.conditions["reason"] = "UnsupportedProvisioningStrategy"
                continue
            translate_buffer(buf)
            if buf.status.ready():
                active.append(buf)
        return active

    def pending_pods(self) -> list[Pod]:
        """Fake pending pods for all active buffers — injected each loop."""
        out: list[Pod] = []
        for buf in self.reconcile():
            out.extend(fake_pods_for(buf))
        return out


class BufferPodListProcessor:
    """PodListProcessor injecting buffer headroom pods into the pending list
    (reference: the capacity-buffer injection step of the default pod-list
    chain; SURVEY.md §2.7 capacitybuffer row)."""

    def __init__(self, controller: BufferController):
        self.controller = controller

    def process(self, pods: list[Pod], ctx) -> list[Pod]:
        return pods + self.controller.pending_pods()
