"""Buffer controller: reconcile buffers and feed injection.

Reference counterpart: capacitybuffer/controller (wired by
InitializeAndRunDefaultBufferController, builder/autoscaler.go:209) — each
reconcile pass runs filters (strategy gate) → translators (resolve status) →
status updater. The autoscaler side then injects `pending_pods()` into every
loop's unschedulable list via BufferPodListProcessor.
"""

from __future__ import annotations

from kubernetes_autoscaler_tpu.capacitybuffer.api import (
    ACTIVE_PROVISIONING_STRATEGY,
    READY_FOR_PROVISIONING,
    CapacityBuffer,
)
from kubernetes_autoscaler_tpu.capacitybuffer.translators import (
    fake_pods_for,
    translate_buffer,
)
from kubernetes_autoscaler_tpu.models.api import Pod


class BufferController:
    """Reconcile = filter chain → translate → quota clamp → status update.

    `status_sink(buffer)` is the persistence seam (the reference's updater/
    writes Status back through the CRD client); `headroom_quota` caps the
    TOTAL buffer headroom per resource (reference: controller/resourcequotas.go
    trimming buffers that would exceed the capacity quotas)."""

    def __init__(self, buffers: list[CapacityBuffer] | None = None,
                 filters=None, status_sink=None,
                 headroom_quota: dict[str, float] | None = None):
        from kubernetes_autoscaler_tpu.capacitybuffer.filters import (
            default_filters,
        )

        self.buffers: list[CapacityBuffer] = list(buffers or [])
        self.filters = filters if filters is not None else default_filters()
        self.status_sink = status_sink
        self.headroom_quota = headroom_quota or {}

    def reconcile(self) -> list[CapacityBuffer]:
        """Returns the active set (reference: controller loop over
        filters/translators/updater)."""
        to_process = list(self.buffers)
        skipped: list[CapacityBuffer] = []
        for f in self.filters:
            to_process, skip = f.filter(to_process)
            skipped.extend(skip)
        for buf in to_process:
            translate_buffer(buf)
            buf.status.observed_generation = buf.generation
            buf.status.pod_template_generation = buf.pod_template_generation
            if self.status_sink is not None:
                try:
                    self.status_sink(buf)
                except Exception:
                    pass
        # generation-skipped buffers stay active if previously resolved ready
        active = [b for b in self.buffers if b.status.ready()]
        return [buf for buf, _ in self.active_with_replicas(active)]

    def active_with_replicas(self, active: list[CapacityBuffer] | None = None
                             ) -> list[tuple[CapacityBuffer, int]]:
        """(buffer, effective replicas) with the quota clamp applied
        TRANSIENTLY per reconcile — status.replicas keeps the spec-resolved
        value so the clamp relaxes the moment quota frees up (the clamp is a
        per-loop admission decision, not a spec mutation)."""
        if active is None:
            active = self.reconcile()
        if not self.headroom_quota:
            return [(b, b.status.replicas) for b in active]
        used: dict[str, float] = {}
        out: list[tuple[CapacityBuffer, int]] = []
        for buf in active:
            tmpl = buf.status.pod_template
            if tmpl is None:
                out.append((buf, buf.status.replicas))
                continue
            replicas = buf.status.replicas
            for res_name, limit in self.headroom_quota.items():
                per = float(tmpl.requests.get(res_name, 0.0))
                if per <= 0:
                    continue
                room = limit - used.get(res_name, 0.0)
                replicas = min(replicas, int(max(room, 0) // per))
            if replicas < buf.status.replicas:
                buf.status.conditions["reason"] = "LimitedByBufferQuota"
            else:
                buf.status.conditions.pop("reason", None)
            if replicas <= 0:
                continue
            for res_name in self.headroom_quota:
                used[res_name] = (used.get(res_name, 0.0)
                                  + float(tmpl.requests.get(res_name, 0.0))
                                  * replicas)
            out.append((buf, replicas))
        return out

    def pending_pods(self) -> list[Pod]:
        """Fake pending pods for all active buffers — injected each loop."""
        out: list[Pod] = []
        for buf, replicas in self.active_with_replicas():
            out.extend(fake_pods_for(buf, replicas=replicas))
        return out


class BufferPodListProcessor:
    """PodListProcessor injecting buffer headroom pods into the pending list
    (reference: the capacity-buffer injection step of the default pod-list
    chain; SURVEY.md §2.7 capacitybuffer row)."""

    def __init__(self, controller: BufferController):
        self.controller = controller

    def process(self, pods: list[Pod], ctx) -> list[Pod]:
        return pods + self.controller.pending_pods()
