"""Buffer filter chain.

Reference counterpart: capacitybuffer/filters/ — composable filters each
splitting the buffer list into (keep, skip): the provisioning-strategy filter
(strategy_filter.go), the status filter (status_filter.go: buffers whose
observed generation matches need no re-translation), and the pod-template
generation filter (podtemplate_generation_filter.go).
"""

from __future__ import annotations

from typing import Protocol

from kubernetes_autoscaler_tpu.capacitybuffer.api import (
    ACTIVE_PROVISIONING_STRATEGY,
    READY_FOR_PROVISIONING,
    CapacityBuffer,
)


class BufferFilter(Protocol):
    def filter(self, buffers: list[CapacityBuffer]
               ) -> tuple[list[CapacityBuffer], list[CapacityBuffer]]:
        """(to_process, skipped)"""
        ...


class StrategyFilter:
    """Only the active provisioning strategy translates; foreign strategies
    park with an explanatory condition (reference: strategy filter)."""

    def filter(self, buffers):
        keep, skip = [], []
        for buf in buffers:
            if buf.provisioning_strategy == ACTIVE_PROVISIONING_STRATEGY:
                keep.append(buf)
            else:
                buf.status.conditions[READY_FOR_PROVISIONING] = "False"
                buf.status.conditions["reason"] = "UnsupportedProvisioningStrategy"
                skip.append(buf)
        return keep, skip


class GenerationFilter:
    """Buffers whose spec generation was already observed keep their resolved
    status untouched — translation is skipped (reference: status_filter +
    podtemplate_generation_filter; the CRD's ObservedGeneration contract)."""

    def filter(self, buffers):
        keep, skip = [], []
        for buf in buffers:
            if (buf.status.observed_generation == buf.generation
                    and buf.status.pod_template is not None):
                skip.append(buf)   # still active if previously ready
            else:
                keep.append(buf)
        return keep, skip


def default_filters() -> list[BufferFilter]:
    return [StrategyFilter(), GenerationFilter()]
