"""Buffer → fake-pod translation.

Reference counterpart: capacitybuffer/translators/ — one translator per spec
shape (pod-template-based, scalable-object-based), each resolving to
(podTemplate, replicas) written into the buffer status; fakepods.Registry then
materializes pending pods from the status. Both steps are merged here:
`translate_buffer` resolves and `fake_pods_for` materializes.
"""

from __future__ import annotations

import copy
import math

from kubernetes_autoscaler_tpu.capacitybuffer.api import (
    PROVISIONING,
    READY_FOR_PROVISIONING,
    CapacityBuffer,
)
from kubernetes_autoscaler_tpu.models.api import OwnerRef, Pod

# Annotation marking injected headroom pods (reference: fake pod names carry
# the capacity-buffer prefix; filters key on it).
FAKE_POD_ANNOTATION = "autoscaler.x-k8s.io/capacity-buffer-pod"


def translate_buffer(buf: CapacityBuffer) -> None:
    """Resolve the buffer spec into status (template + replicas + conditions).

    Mirrors the reference translator chain: an unresolvable spec sets
    ReadyForProvisioning=False with a reason instead of raising."""
    st = buf.status
    if buf.pod_template is not None:
        st.pod_template = buf.pod_template
        st.replicas = int(buf.replicas or 0)
    elif buf.scalable_ref is not None:
        w = buf.scalable_ref
        if w.template is None:
            st.conditions[READY_FOR_PROVISIONING] = "False"
            st.conditions["reason"] = "ScalableRefHasNoTemplate"
            return
        st.pod_template = w.template
        if buf.percentage is not None:
            st.replicas = max(
                math.ceil(w.replicas * buf.percentage / 100.0),
                buf.limits_min_replicas,
            )
        else:
            st.replicas = int(buf.replicas or w.replicas)
    else:
        st.conditions[READY_FOR_PROVISIONING] = "False"
        st.conditions["reason"] = "NoTemplateOrScalableRef"
        return
    if st.replicas <= 0:
        st.conditions[READY_FOR_PROVISIONING] = "False"
        st.conditions["reason"] = "ZeroReplicas"
        return
    st.conditions[READY_FOR_PROVISIONING] = "True"
    st.conditions[PROVISIONING] = "True"


def fake_pods_for(buf: CapacityBuffer, replicas: int | None = None) -> list[Pod]:
    """Materialize pending pods from a resolved buffer status (reference:
    capacitybuffer fakepods registry + simulator/fake/pod.go). `replicas`
    overrides the status count (the controller's per-loop quota clamp).

    The pod OBJECTS are cached per (generation, template, count) on the
    buffer: the loop injects them every tick, and stable object identity is
    what lets the incremental encoder (models/incremental.py) skip
    re-lowering unchanged headroom each loop."""
    st = buf.status
    if not st.ready() or st.pod_template is None:
        return []
    n = st.replicas if replicas is None else replicas
    # cache the LARGEST materialization per (generation, template): the
    # quota clamp moves loop-to-loop in busy clusters, and a prefix slice
    # keeps pods 0..n-1 identity-stable as it shrinks and grows (object
    # identity is what lets the incremental encoder skip re-lowering)
    cache_key = (buf.generation, st.pod_template)
    cached = getattr(buf, "_fake_pods_cache", None)
    if (cached is not None and cached[0][0] == cache_key[0]
            and cached[0][1] is cache_key[1] and len(cached[1]) >= n):
        return list(cached[1][:n])
    out = list(cached[1]) if (
        cached is not None and cached[0][0] == cache_key[0]
        and cached[0][1] is cache_key[1]) else []
    for i in range(len(out), n):
        p = copy.deepcopy(st.pod_template)
        p.name = f"capacity-buffer-{buf.name}-{i}"
        p.namespace = buf.namespace
        p.node_name = ""
        p.phase = "Pending"
        p.annotations[FAKE_POD_ANNOTATION] = buf.name
        # owned by the buffer so drain classification treats them as
        # replicated (they are re-creatable headroom, never blockers)
        p.owner = OwnerRef(kind="CapacityBuffer", name=buf.name,
                           uid=f"buffer-{buf.namespace}-{buf.name}")
        out.append(p)
    buf._fake_pods_cache = (cache_key, out)
    return list(out[:n])


def is_buffer_pod(pod: Pod) -> bool:
    return FAKE_POD_ANNOTATION in pod.annotations
