"""Out-of-process cloud provider over gRPC.

Reference counterpart: cloudprovider/externalgrpc — a full CloudProvider
whose every call crosses a gRPC boundary to an external provider service
(protos/externalgrpc.proto:28-98: NodeGroups, NodeGroupForNode, Refresh,
NodeGroupTargetSize/IncreaseSize/DeleteNodes/DecreaseTargetSize,
NodeGroupNodes, NodeGroupTemplateNodeInfo, NodeGroupGetOptions, GPULabel,
Pricing*, Cleanup). This is the reference's precedent for out-of-process
extension points and the shape the TPU sidecar boundary follows.

Two halves:
  * `serve_cloud_provider(provider)` — host ANY CloudProvider implementation
    as the gRPC service (the role of the user's external provider binary).
  * `ExternalGrpcProvider` — the in-process CloudProvider proxy the
    autoscaler is configured with; caches node-group listings and template
    node infos between Refresh calls exactly like the reference client
    (externalgrpc caches in cloud_provider.go / node_group.go).

Transport: JSON bodies over generic bytes RPCs (the repo-wide convention of
sidecar/server.py — no generated stubs, the wire names mirror the proto).
"""

from __future__ import annotations

import json
from dataclasses import asdict

from kubernetes_autoscaler_tpu.cloudprovider.provider import (
    CloudProvider,
    InstanceStatus,
    NodeGroup,
    NodeGroupError,
    NodeGroupOptions,
    ResourceLimiter,
)
from kubernetes_autoscaler_tpu.models.api import Node, Taint

_SERVICE = "clusterautoscaler.cloudprovider.v1.externalgrpc.CloudProvider"


# ---- Node (de)serialization -------------------------------------------------

def node_to_dict(node: Node) -> dict:
    return {
        "name": node.name,
        "labels": dict(node.labels),
        "annotations": dict(node.annotations),
        "capacity": dict(node.capacity),
        "allocatable": dict(node.allocatable),
        "taints": [asdict(t) for t in node.taints],
        "ready": node.ready,
        "unschedulable": node.unschedulable,
    }


def node_from_dict(d: dict) -> Node:
    return Node(
        name=d["name"],
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        capacity=dict(d.get("capacity", {})),
        allocatable=dict(d.get("allocatable", {})),
        taints=[Taint(**t) for t in d.get("taints", [])],
        ready=d.get("ready", True),
        unschedulable=d.get("unschedulable", False),
    )


def _options_to_dict(o: NodeGroupOptions | None) -> dict | None:
    return None if o is None else asdict(o)


# ---- server half ------------------------------------------------------------

class _ProviderService:
    """Adapts a CloudProvider to the wire methods."""

    def __init__(self, provider: CloudProvider):
        self.provider = provider

    def _group(self, gid: str) -> NodeGroup:
        for g in self.provider.node_groups():
            if g.id() == gid:
                return g
        raise NodeGroupError(f"unknown node group {gid!r}")

    # one method per proto rpc; each takes/returns a JSON-able dict
    def NodeGroups(self, req: dict) -> dict:
        return {"nodeGroups": [
            {"id": g.id(), "minSize": g.min_size(), "maxSize": g.max_size()}
            for g in self.provider.node_groups()
        ]}

    def NodeGroupForNode(self, req: dict) -> dict:
        g = self.provider.node_group_for_node(node_from_dict(req["node"]))
        if g is None:
            return {"nodeGroup": None}
        return {"nodeGroup": {"id": g.id(), "minSize": g.min_size(),
                              "maxSize": g.max_size()}}

    def Refresh(self, req: dict) -> dict:
        self.provider.refresh()
        return {}

    def Cleanup(self, req: dict) -> dict:
        self.provider.cleanup()
        return {}

    def GPULabel(self, req: dict) -> dict:
        return {"label": self.provider.gpu_label()}

    def PricingNodePrice(self, req: dict) -> dict:
        pricing = self.provider.pricing()
        if pricing is None:
            return {"error": "pricing not implemented"}
        return {"price": pricing.node_price(
            node_from_dict(req["node"]), req.get("startTime", 0.0),
            req.get("endTime", 0.0))}

    def NodeGroupTargetSize(self, req: dict) -> dict:
        return {"targetSize": self._group(req["id"]).target_size()}

    def NodeGroupIncreaseSize(self, req: dict) -> dict:
        self._group(req["id"]).increase_size(int(req["delta"]))
        return {}

    def NodeGroupDecreaseTargetSize(self, req: dict) -> dict:
        self._group(req["id"]).decrease_target_size(int(req["delta"]))
        return {}

    def NodeGroupDeleteNodes(self, req: dict) -> dict:
        nodes = [node_from_dict(n) for n in req["nodes"]]
        self._group(req["id"]).delete_nodes(nodes)
        return {}

    def NodeGroupNodes(self, req: dict) -> dict:
        return {"instances": [
            {"name": i.name, "state": i.state, "errorClass": i.error_class}
            for i in self._group(req["id"]).nodes()
        ]}

    def NodeGroupTemplateNodeInfo(self, req: dict) -> dict:
        return {"nodeInfo": node_to_dict(self._group(req["id"]).template_node_info())}

    def NodeGroupGetOptions(self, req: dict) -> dict:
        defaults = NodeGroupOptions(**req.get("defaults", {}))
        return {"options": _options_to_dict(self._group(req["id"]).get_options(defaults))}


_METHODS = [
    "NodeGroups", "NodeGroupForNode", "Refresh", "Cleanup", "GPULabel",
    "PricingNodePrice", "NodeGroupTargetSize", "NodeGroupIncreaseSize",
    "NodeGroupDecreaseTargetSize", "NodeGroupDeleteNodes", "NodeGroupNodes",
    "NodeGroupTemplateNodeInfo", "NodeGroupGetOptions",
]


def serve_cloud_provider(provider: CloudProvider, port: int = 0):
    """Host a CloudProvider as the external gRPC service.

    Returns (server, bound_port); caller starts/stops the server."""
    import grpc
    from concurrent.futures import ThreadPoolExecutor

    service = _ProviderService(provider)

    def make_handler(name):
        fn = getattr(service, name)

        def handler(request: bytes, context):
            try:
                return json.dumps(fn(json.loads(request.decode() or "{}"))).encode()
            except Exception as e:  # error goes on the wire, not the process
                return json.dumps({"error": str(e)}).encode()

        return grpc.unary_unary_rpc_method_handler(
            handler, request_deserializer=lambda b: b,
            response_serializer=lambda b: b)

    server = grpc.server(ThreadPoolExecutor(max_workers=4))
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
        _SERVICE, {m: make_handler(m) for m in _METHODS}),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    return server, bound


# ---- client half ------------------------------------------------------------

class _Client:
    def __init__(self, port: int):
        import grpc

        self.channel = grpc.insecure_channel(f"127.0.0.1:{port}")

    def call(self, method: str, body: dict) -> dict:
        rpc = self.channel.unary_unary(
            f"/{_SERVICE}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        out = json.loads(rpc(json.dumps(body).encode()))
        if isinstance(out, dict) and out.get("error"):
            raise NodeGroupError(out["error"])
        return out


class ExternalNodeGroup(NodeGroup):
    """Client-side proxy for one remote node group.

    Target size and template node info are cached until the provider's next
    Refresh (reference: externalgrpc/node_group.go caches TemplateNodeInfo)."""

    def __init__(self, client: _Client, gid: str, min_size: int, max_size: int):
        self._client = client
        self._id = gid
        self._min = min_size
        self._max = max_size
        self._cached_target: int | None = None
        self._cached_template: Node | None = None

    def invalidate(self) -> None:
        self._cached_target = None
        self._cached_template = None

    def id(self) -> str:
        return self._id

    def min_size(self) -> int:
        return self._min

    def max_size(self) -> int:
        return self._max

    def target_size(self) -> int:
        if self._cached_target is None:
            self._cached_target = int(
                self._client.call("NodeGroupTargetSize", {"id": self._id})["targetSize"])
        return self._cached_target

    def increase_size(self, delta: int) -> None:
        self._client.call("NodeGroupIncreaseSize", {"id": self._id, "delta": delta})
        self._cached_target = None

    def decrease_target_size(self, delta: int) -> None:
        self._client.call("NodeGroupDecreaseTargetSize", {"id": self._id, "delta": delta})
        self._cached_target = None

    def delete_nodes(self, nodes: list[Node]) -> None:
        self._client.call("NodeGroupDeleteNodes", {
            "id": self._id, "nodes": [node_to_dict(n) for n in nodes]})
        self._cached_target = None

    def nodes(self) -> list[InstanceStatus]:
        return [
            InstanceStatus(name=i["name"], state=i.get("state", ""),
                           error_class=i.get("errorClass", ""))
            for i in self._client.call("NodeGroupNodes", {"id": self._id})["instances"]
        ]

    def template_node_info(self) -> Node:
        if self._cached_template is None:
            self._cached_template = node_from_dict(
                self._client.call("NodeGroupTemplateNodeInfo", {"id": self._id})["nodeInfo"])
        return self._cached_template

    def get_options(self, defaults: NodeGroupOptions) -> NodeGroupOptions:
        out = self._client.call("NodeGroupGetOptions", {
            "id": self._id, "defaults": asdict(defaults)})["options"]
        return defaults if out is None else NodeGroupOptions(**out)


class ExternalGrpcProvider(CloudProvider):
    """CloudProvider whose implementation lives in another process.

    Node-group listing is cached between refresh() calls (the reference
    client does the same; the autoscaler calls Refresh once per loop)."""

    def __init__(self, port: int):
        self._client = _Client(port)
        self._by_id: dict[str, ExternalNodeGroup] = {}
        self._groups: list[ExternalNodeGroup] | None = None

    def name(self) -> str:
        return "externalgrpc"

    def node_groups(self) -> list[NodeGroup]:
        if self._groups is None:
            out = []
            for g in self._client.call("NodeGroups", {})["nodeGroups"]:
                # reuse group objects across refreshes so callers holding a
                # reference observe invalidated (fresh) caches, not stale ones
                existing = self._by_id.get(g["id"])
                if existing is not None:
                    existing._min = g["minSize"]
                    existing._max = g["maxSize"]
                    out.append(existing)
                else:
                    ng = ExternalNodeGroup(self._client, g["id"],
                                           g["minSize"], g["maxSize"])
                    self._by_id[g["id"]] = ng
                    out.append(ng)
            self._groups = out
        return list(self._groups)

    def node_group_for_node(self, node: Node) -> NodeGroup | None:
        out = self._client.call("NodeGroupForNode", {"node": node_to_dict(node)})
        g = out.get("nodeGroup")
        if not g:
            return None
        for existing in self.node_groups():
            if existing.id() == g["id"]:
                return existing
        # group absent from the listing (e.g. autoprovisioned): keep ONE
        # proxy object per id so refresh() invalidation reaches every holder
        ng = self._by_id.get(g["id"])
        if ng is None:
            ng = ExternalNodeGroup(self._client, g["id"], g["minSize"], g["maxSize"])
            self._by_id[g["id"]] = ng
        else:
            ng._min = g["minSize"]   # sizes come fresh from the server
            ng._max = g["maxSize"]
        return ng

    def gpu_label(self) -> str:
        return self._client.call("GPULabel", {})["label"]

    def get_resource_limiter(self) -> ResourceLimiter:
        return ResourceLimiter()

    def refresh(self) -> None:
        self._client.call("Refresh", {})
        for g in self._by_id.values():
            g.invalidate()
        self._groups = None

    def cleanup(self) -> None:
        self._client.call("Cleanup", {})
