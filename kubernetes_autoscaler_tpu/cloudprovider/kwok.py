"""Hollow-node scale-test cluster: the kwok/kubemark analog.

Reference counterparts: cloudprovider/kwok (nodes simulated by KWOK inside a
real cluster — scale testing without VMs) and the kubemark hollow-node
harness (proposals/scalability_tests.md:18-25 — 1000 hollow nodes hosting the
GA scale claim of 1000 nodes x 30 pods/node, FAQ.md:148).

`KwokCluster` extends the in-memory FakeCluster with the lifecycle realism
those harnesses provide:

  * boot latency — a scale-up creates cloud instances in `Creating` state;
    they register as NotReady nodes only after `boot_delay_s`;
  * readiness latency — registered nodes turn Ready after `ready_delay_s`
    (exercises ClusterStateRegistry readiness gating and upcoming-node math);
  * boot failures — `fail_next(gid, n)` scripts the next n instances of a
    group to end in a create-error state instead of registering (exercises
    the deleteCreatedNodesWithErrors reaping + group backoff path);
  * hollow pods — `saturate(pods_per_node)` binds filler pods to every
    registered node, the kubemark load shape.

Time is driven by `advance_to(now)`, same as FakeCluster.
"""

from __future__ import annotations

from dataclasses import dataclass

from kubernetes_autoscaler_tpu.models.api import Node, Pod
from kubernetes_autoscaler_tpu.utils.fakecluster import FakeCluster
from kubernetes_autoscaler_tpu.utils.testing import build_test_pod


@dataclass
class _HollowInstance:
    name: str
    group_id: str
    created_at: float
    fail: bool
    registered_at: float | None = None  # set once the node object exists


class KwokCluster(FakeCluster):
    def __init__(self, boot_delay_s: float = 0.0, ready_delay_s: float = 0.0):
        super().__init__()
        self.boot_delay_s = boot_delay_s
        self.ready_delay_s = ready_delay_s
        self._hollow: list[_HollowInstance] = []
        self._fail_budget: dict[str, int] = {}

    # ---- failure scripting ----

    def fail_next(self, gid: str, count: int) -> None:
        """The next `count` instances created in `gid` fail to boot."""
        self._fail_budget[gid] = self._fail_budget.get(gid, 0) + count

    # ---- cloud callback override: instances, not instant nodes ----

    def _on_scale_up(self, gid: str, delta: int) -> None:
        g = next(x for x in self.provider.node_groups() if x.id() == gid)
        for _ in range(delta):
            name = f"{gid}-hollow-{next(self._seq)}"
            fail = self._fail_budget.get(gid, 0) > 0
            if fail:
                self._fail_budget[gid] -= 1
            self._hollow.append(_HollowInstance(name, gid, self._now, fail))
            g.add_unregistered_instance(
                name, state="Creating",
                error_class="OutOfResources" if fail else "")
        # failures surface on the instance immediately (cloud API reports the
        # create error); healthy instances register after boot_delay_s

    def _on_scale_down(self, gid: str, node_name: str) -> None:
        self._hollow = [h for h in self._hollow if h.name != node_name]
        super()._on_scale_down(gid, node_name)

    # ---- time ----

    def advance_to(self, now: float) -> None:
        self._now = now
        for h in self._hollow:
            if h.fail:
                continue
            g = next(x for x in self.provider.node_groups() if x.id() == h.group_id)
            if h.registered_at is None and now >= h.created_at + self.boot_delay_s:
                # register at the LOGICAL boot time so a single large time
                # jump can register and ready the node in one tick
                h.registered_at = h.created_at + self.boot_delay_s
                t = g.template_node_info()
                nd = Node(
                    name=h.name,
                    labels={**t.labels, "kubernetes.io/hostname": h.name},
                    capacity=dict(t.capacity),
                    allocatable=dict(t.allocatable),
                    taints=list(t.taints),
                    ready=now >= h.registered_at + self.ready_delay_s,
                )
                self.nodes[h.name] = nd
                self.provider.add_node(h.group_id, nd)
                g._instances = [i for i in g._instances if i.name != h.name]
            elif (h.registered_at is not None
                  and now >= h.registered_at + self.ready_delay_s):
                self.nodes[h.name].ready = True
        super().advance_to(now)

    # ---- kubemark load shape ----

    def saturate(self, pods_per_node: int, cpu_milli: int = 100,
                 mem_mib: int = 128) -> None:
        """Bind `pods_per_node` hollow pods to every registered node."""
        for nd in list(self.nodes.values()):
            for j in range(pods_per_node):
                p = build_test_pod(
                    f"hollow-{nd.name}-{j}", cpu_milli=cpu_milli,
                    mem_mib=mem_mib, owner_name=f"hollow-rs-{j % 10}",
                    node_name=nd.name)
                p.phase = "Running"
                self.add_pod(p)
