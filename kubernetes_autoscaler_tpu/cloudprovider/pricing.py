"""PricingModel SPI + the simple linear model.

Reference counterpart: cloudprovider.PricingModel (cloud_provider.go:133 via
CloudProvider.Pricing(): `NodePrice(node, start, end)` and
`PodPrice(pod, start, end)`), consumed by the price expander
(expander/price/price.go) and exposed over externalgrpc
(protos/externalgrpc.proto PricingNodePrice/PricingPodPrice).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol

from kubernetes_autoscaler_tpu.models.api import Node, Pod

_HOUR_S = 3600.0
_GIB = 1024.0 ** 3


class PricingModel(Protocol):
    def node_price(self, node: Node, start_s: float, end_s: float) -> float:
        """Theoretical cost of running `node` for [start_s, end_s)."""
        ...

    def pod_price(self, pod: Pod, start_s: float, end_s: float) -> float:
        """Theoretical minimum cost of running `pod`'s requests."""
        ...


@dataclass
class SimplePricingModel:
    """Linear per-resource-hour pricing (the shape of GCE's pricing model,
    cloudprovider/gce/pricing.go: base rate per core + per GiB + per GPU).
    Per-node flat premiums come via `group_price_per_node` so test fixtures
    with explicit per-group prices stay expressive."""

    cpu_per_core_hour: float = 0.033
    mem_per_gib_hour: float = 0.0045
    gpu_per_hour: float = 0.70
    gpu_resource: str = "nvidia.com/gpu"
    group_price_per_node: dict[str, float] | None = None

    def _hours(self, start_s: float, end_s: float) -> float:
        return max(end_s - start_s, 0.0) / _HOUR_S

    def _rate(self, cpu_cores: float, mem_bytes: float, gpus: float) -> float:
        return (cpu_cores * self.cpu_per_core_hour
                + (mem_bytes / _GIB) * self.mem_per_gib_hour
                + gpus * self.gpu_per_hour)

    def node_price(self, node: Node, start_s: float, end_s: float) -> float:
        cap = node.alloc_or_cap()
        return self._rate(
            float(cap.get("cpu", 0.0)),
            float(cap.get("memory", 0.0)),
            float(cap.get(self.gpu_resource, 0.0)),
        ) * self._hours(start_s, end_s)

    def pod_price(self, pod: Pod, start_s: float, end_s: float) -> float:
        req = pod.requests
        return self._rate(
            float(req.get("cpu", 0.0)),
            float(req.get("memory", 0.0)),
            float(req.get(self.gpu_resource, 0.0)),
        ) * self._hours(start_s, end_s)
