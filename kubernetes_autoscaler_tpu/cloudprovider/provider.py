"""CloudProvider SPI — the L1 boundary to node-group actuation.

Reference counterpart: cloudprovider/cloud_provider.go:117-166 (CloudProvider)
and :180+ (NodeGroup). The surface is kept verb-compatible so provider
implementations translate 1:1; everything above it (orchestrators, planner)
depends only on this module.

The reference ships 30+ provider implementations; this framework ships the
in-memory test provider (cloudprovider/test_provider.py — the reference's
cloudprovider/test used by all core tests and benchmarks) and the out-of-
process gRPC provider shape (cloudprovider/externalgrpc — see sidecar/), and
leaves cloud-specific REST adapters to integrators.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.models.api import Node


class NodeGroupError(Exception):
    pass


@dataclass
class InstanceStatus:
    """Cloud instance state (reference: cloud_provider.go Instance/InstanceStatus)."""

    name: str
    state: str = "Running"       # Creating | Running | Deleting
    error_class: str = ""        # "" | OutOfResources | Other


@dataclass
class ResourceLimiter:
    """Cluster-wide min/max for cores, memory (MiB) and custom resources
    (reference: cloud_provider.go:240 ResourceLimiter; consumed by
    resourcequotas default provider)."""

    min_limits: dict[str, int] = field(default_factory=dict)
    max_limits: dict[str, int] = field(default_factory=dict)

    def max_for(self, name: str, default: int = 1 << 60) -> int:
        return self.max_limits.get(name, default)

    def min_for(self, name: str, default: int = 0) -> int:
        return self.min_limits.get(name, default)


@dataclass
class NodeGroupOptions:
    """Per-node-group autoscaling option overrides (reference:
    config.NodeGroupAutoscalingOptions via NodeGroup.GetOptions)."""

    scale_down_utilization_threshold: float | None = None
    scale_down_gpu_utilization_threshold: float | None = None
    scale_down_unneeded_time_s: float | None = None
    scale_down_unready_time_s: float | None = None
    max_node_provision_time_s: float | None = None
    zero_or_max_node_scaling: bool = False
    ignore_daemonsets_utilization: bool | None = None


class NodeGroup(abc.ABC):
    """One elastic set of identical nodes (reference: cloud_provider.go:180)."""

    @abc.abstractmethod
    def id(self) -> str: ...

    @abc.abstractmethod
    def min_size(self) -> int: ...

    @abc.abstractmethod
    def max_size(self) -> int: ...

    @abc.abstractmethod
    def target_size(self) -> int: ...

    @abc.abstractmethod
    def increase_size(self, delta: int) -> None:
        """Ask the cloud for delta more nodes (async; reference IncreaseSize)."""

    def atomic_increase_size(self, delta: int) -> None:
        """All-or-nothing variant (reference AtomicIncreaseSize,
        cloud_provider.go:198-204; default falls back to increase_size)."""
        self.increase_size(delta)

    @abc.abstractmethod
    def delete_nodes(self, nodes: list[Node]) -> None:
        """Delete specific nodes, decreasing target size (reference DeleteNodes)."""

    def force_delete_nodes(self, nodes: list[Node]) -> None:
        self.delete_nodes(nodes)

    @abc.abstractmethod
    def decrease_target_size(self, delta: int) -> None:
        """Lower target without deleting registered nodes (reference
        DecreaseTargetSize; delta < 0)."""

    @abc.abstractmethod
    def nodes(self) -> list[InstanceStatus]:
        """All instances, including creating/deleting ones."""

    @abc.abstractmethod
    def template_node_info(self) -> Node:
        """A sanitized template node for simulation (reference TemplateNodeInfo;
        sanitization mirrors simulator/node_info_utils.go SanitizedNodeInfo)."""

    def exist(self) -> bool:
        return True

    def create(self) -> "NodeGroup":
        raise NodeGroupError("node group auto-provisioning not supported")

    def delete(self) -> None:
        raise NodeGroupError("node group auto-provisioning not supported")

    def autoprovisioned(self) -> bool:
        return False

    def get_options(self, defaults: NodeGroupOptions) -> NodeGroupOptions:
        return defaults


class CloudProvider(abc.ABC):
    """Reference: cloud_provider.go:117."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def node_groups(self) -> list[NodeGroup]: ...

    @abc.abstractmethod
    def node_group_for_node(self, node: Node) -> NodeGroup | None: ...

    def has_instance(self, node: Node) -> bool:
        return self.node_group_for_node(node) is not None

    def pricing(self):
        """Optional PricingModel (reference: cloud_provider.go:133)."""
        return None

    def get_resource_limiter(self) -> ResourceLimiter:
        return ResourceLimiter()

    def gpu_label(self) -> str:
        return "cloud.google.com/gke-accelerator"

    def gpu_resource_name(self) -> str:
        """The extended-resource name GPUs are requested under (reference:
        gpu.ResourceNvidiaGPU in utils/gpu)."""
        return "nvidia.com/gpu"

    def refresh(self) -> None:
        """Called before every RunOnce loop (reference Refresh)."""

    def cleanup(self) -> None:
        """Called on shutdown (reference Cleanup)."""
