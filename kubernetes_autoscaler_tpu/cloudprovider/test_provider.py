"""In-memory cloud provider for tests and benchmarks.

Reference counterpart: cloudprovider/test/test_cloud_provider.go — the
testprovider used across the reference's core tests and RunOnce benchmarks
(core/bench/benchmark_runonce_test.go:404-407: AddNodeGroup WithTemplate /
WithNGSize, onScaleUp/onScaleDown callbacks).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from kubernetes_autoscaler_tpu.cloudprovider.provider import (
    CloudProvider,
    InstanceStatus,
    NodeGroup,
    NodeGroupError,
    NodeGroupOptions,
    ResourceLimiter,
)
from kubernetes_autoscaler_tpu.models.api import Node


class TestNodeGroup(NodeGroup):
    __test__ = False  # fixture class, not a pytest case (collection warning)

    def __init__(
        self,
        gid: str,
        min_size: int,
        max_size: int,
        target: int,
        template: Node,
        provider: "TestCloudProvider",
        options: NodeGroupOptions | None = None,
        price_per_node: float = 1.0,
    ):
        self._id = gid
        self._min = min_size
        self._max = max_size
        self._target = target
        self._template = template
        self._provider = provider
        self._options = options
        self.price_per_node = price_per_node
        self._instances: list[InstanceStatus] = []
        self._exists = True
        self._autoprovisioned = False

    def id(self) -> str:
        return self._id

    def min_size(self) -> int:
        return self._min

    def max_size(self) -> int:
        return self._max

    def target_size(self) -> int:
        return self._target

    def increase_size(self, delta: int) -> None:
        if delta <= 0:
            raise NodeGroupError(f"increase_size: delta must be positive, got {delta}")
        if self._target + delta > self._max:
            raise NodeGroupError(
                f"increase_size: {self._target}+{delta} exceeds max {self._max}"
            )
        if self._provider.on_scale_up:
            self._provider.on_scale_up(self._id, delta)
        self._target += delta

    def delete_nodes(self, nodes: list[Node]) -> None:
        if self._target - len(nodes) < self._min:
            raise NodeGroupError("delete_nodes: would go below min size")
        for nd in nodes:
            self._remove_one(nd)

    def force_delete_nodes(self, nodes: list[Node]) -> None:
        """Forceful path: bypasses the min-size guard (reference
        ForceDeleteNodes bypasses termination protections)."""
        for nd in nodes:
            self._remove_one(nd)

    def _remove_one(self, nd: Node) -> None:
        if self._provider.on_scale_down:
            self._provider.on_scale_down(self._id, nd.name)
        self._provider.remove_node(self._id, nd.name)
        # deleting a never-registered instance clears its cloud-side
        # record too (otherwise a reaped create-error instance would be
        # re-reaped — and the target re-decremented — every loop)
        self._instances = [i for i in self._instances if i.name != nd.name]
        self._target -= 1

    def decrease_target_size(self, delta: int) -> None:
        if delta >= 0:
            raise NodeGroupError("decrease_target_size: delta must be negative")
        if self._target + delta < len(self._provider.nodes_of(self._id)):
            raise NodeGroupError("decrease_target_size: below registered node count")
        self._target += delta

    def nodes(self) -> list[InstanceStatus]:
        regs = [InstanceStatus(n) for n in self._provider.nodes_of(self._id)]
        return regs + list(self._instances)

    def add_unregistered_instance(self, name: str, state: str = "Creating",
                                  error_class: str = "") -> None:
        self._instances.append(InstanceStatus(name, state, error_class))

    def template_node_info(self) -> Node:
        t = self._template
        return Node(
            name=f"template-{self._id}",
            labels=dict(t.labels),
            capacity=dict(t.capacity),
            allocatable=dict(t.allocatable),
            taints=list(t.taints),
            ready=True,
        )

    def get_options(self, defaults: NodeGroupOptions) -> NodeGroupOptions:
        return self._options or defaults

    # ---- auto-provisioning lifecycle (reference: cloud_provider.go
    # Create/Delete/Autoprovisioned; test provider supports them for the
    # NodeGroupManager tests) ----

    def exist(self) -> bool:
        return self._exists

    def autoprovisioned(self) -> bool:
        return self._autoprovisioned

    def create(self) -> "TestNodeGroup":
        if self._exists:
            raise NodeGroupError(f"node group {self._id} already exists")
        if self._id in self._provider._groups:
            # a registered group with this id exists (this object is a stale
            # candidate) — never silently overwrite it
            raise NodeGroupError(f"node group {self._id} already registered")
        self._exists = True
        self._provider._groups[self._id] = self
        return self

    def delete(self) -> None:
        if not self._autoprovisioned:
            raise NodeGroupError(f"node group {self._id} is not autoprovisioned")
        if self._provider.nodes_of(self._id):
            raise NodeGroupError(f"node group {self._id} still has nodes")
        self._exists = False
        self._provider._groups.pop(self._id, None)


@dataclass
class TestCloudProvider(CloudProvider):
    __test__ = False  # fixture class, not a pytest case (collection warning)

    on_scale_up: Callable[[str, int], None] | None = None
    on_scale_down: Callable[[str, str], None] | None = None
    resource_limiter: ResourceLimiter = field(default_factory=ResourceLimiter)
    machine_templates: dict[str, tuple] = field(default_factory=dict)

    def __post_init__(self):
        self._groups: dict[str, TestNodeGroup] = {}
        self._node_to_group: dict[str, str] = {}

    def name(self) -> str:
        return "test"

    def add_node_group(
        self,
        gid: str,
        template: Node,
        min_size: int = 0,
        max_size: int = 1000,
        target: int = 0,
        options: NodeGroupOptions | None = None,
        price_per_node: float = 1.0,
    ) -> TestNodeGroup:
        g = TestNodeGroup(gid, min_size, max_size, target, template, self,
                          options, price_per_node)
        self._groups[gid] = g
        return g

    def add_node(self, gid: str, node: Node) -> None:
        self._node_to_group[node.name] = gid

    def remove_node(self, gid: str, node_name: str) -> None:
        self._node_to_group.pop(node_name, None)

    def nodes_of(self, gid: str) -> list[str]:
        return [n for n, g in self._node_to_group.items() if g == gid]

    def node_groups(self) -> list[NodeGroup]:
        return list(self._groups.values())

    def node_group_for_node(self, node: Node) -> NodeGroup | None:
        gid = self._node_to_group.get(node.name)
        return self._groups.get(gid) if gid else None

    def get_resource_limiter(self) -> ResourceLimiter:
        return self.resource_limiter

    def pricing(self):
        """A linear PricingModel (reference: testprovider's PricingModel).
        Per-group flat prices remain visible through group_price_per_node."""
        from kubernetes_autoscaler_tpu.cloudprovider.pricing import (
            SimplePricingModel,
        )

        return SimplePricingModel(group_price_per_node={
            gid: g.price_per_node for gid, g in self._groups.items()
        })

    # ---- machine catalog for auto-provisioning (reference:
    # GetAvailableMachineTypes + NewNodeGroup, cloud_provider.go:128-131) ----

    def add_machine_type(self, name: str, template: Node,
                         price_per_node: float = 1.0) -> None:
        self.machine_templates[name] = (template, price_per_node)

    def get_available_machine_types(self) -> list[str]:
        return list(self.machine_templates)

    def new_node_group(self, machine_type: str, max_size: int = 1000) -> TestNodeGroup:
        """A candidate group that does not exist until create() is called."""
        if machine_type not in self.machine_templates:
            raise NodeGroupError(f"unknown machine type {machine_type}")
        template, price = self.machine_templates[machine_type]
        g = TestNodeGroup(f"autoprovisioned-{machine_type}", 0, max_size, 0,
                          template, self, None, price)
        g._exists = False
        g._autoprovisioned = True
        return g
