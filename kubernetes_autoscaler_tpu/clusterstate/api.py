"""ClusterAutoscalerStatus: the human/machine-readable status document.

Reference counterpart: clusterstate/api/types.go (SURVEY.md §2.7) — the
`ClusterAutoscalerStatus` object serialized to YAML into the
`cluster-autoscaler-status` ConfigMap after every loop
(static_autoscaler.go:418-421, clusterstate/utils WriteStatusConfigMap):
cluster-wide and per-node-group Health / ScaleUp / ScaleDown conditions with
readiness counts and min/max/target sizes.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.clusterstate.registry import (
    ClusterStateRegistry,
    Readiness,
)

# Condition class values (reference: api/types.go ClusterAutoscalerConditionStatus)
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"
IN_PROGRESS = "InProgress"
NO_ACTIVITY = "NoActivity"
BACKOFF = "Backoff"
CANDIDATES_PRESENT = "CandidatesPresent"
NO_CANDIDATES = "NoCandidates"


@dataclass
class NodeCounts:
    ready: int = 0
    unready: int = 0
    not_started: int = 0
    registered: int = 0

    @classmethod
    def from_readiness(cls, r: Readiness) -> "NodeCounts":
        return cls(ready=r.ready, unready=r.unready,
                   not_started=r.not_started, registered=r.registered)


@dataclass
class NodeGroupStatus:
    name: str
    health: str = HEALTHY
    scale_up: str = NO_ACTIVITY
    scale_down: str = NO_CANDIDATES
    node_counts: NodeCounts = field(default_factory=NodeCounts)
    min_size: int = 0
    max_size: int = 0
    target_size: int = 0


@dataclass
class ClusterAutoscalerStatus:
    autoscaler_status: str = HEALTHY
    # identity of the ConfigMap this document is written as (reference:
    # --status-config-map-name names the object WriteStatusConfigMap updates)
    config_map_name: str = "cluster-autoscaler-status"
    cluster_wide: NodeGroupStatus = field(
        default_factory=lambda: NodeGroupStatus(name="")
    )
    node_groups: list[NodeGroupStatus] = field(default_factory=list)
    last_probe_time: float = 0.0
    message: str = ""
    # reason plane: per-reason verdict histograms for this loop — WHY pods
    # stayed pending (ops/predicates reason taxonomy + no-node-in-group) and
    # WHY nodes stayed unremovable (the reference unremovable enum strings,
    # UnremovableNodes.reason_counts). Empty dicts when everything scheduled
    # / every candidate drained.
    unschedulable_reasons: dict[str, int] = field(default_factory=dict)
    unremovable_reasons: dict[str, int] = field(default_factory=dict)

    def to_dict(self) -> dict:
        def ng(s: NodeGroupStatus) -> dict:
            return {
                "name": s.name,
                "health": {
                    "status": s.health,
                    "nodeCounts": vars(s.node_counts),
                    "minSize": s.min_size,
                    "maxSize": s.max_size,
                    "targetSize": s.target_size,
                },
                "scaleUp": {"status": s.scale_up},
                "scaleDown": {"status": s.scale_down},
            }

        doc = {
            "configMapName": self.config_map_name,
            "autoscalerStatus": self.autoscaler_status,
            "message": self.message,
            "lastProbeTime": self.last_probe_time,
            "clusterWide": ng(self.cluster_wide),
            "nodeGroups": [ng(s) for s in self.node_groups],
        }
        doc["clusterWide"]["scaleUp"]["unschedulableReasons"] = dict(
            self.unschedulable_reasons)
        doc["clusterWide"]["scaleDown"]["unremovableReasons"] = dict(
            self.unremovable_reasons)
        return doc

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)


def build_status(registry: ClusterStateRegistry, now: float,
                 scale_down_candidates: list[str] | None = None,
                 config_map_name: str | None = None,
                 unschedulable_reasons: dict[str, int] | None = None,
                 unremovable_reasons: dict[str, int] | None = None,
                 ) -> ClusterAutoscalerStatus:
    """Assemble the status document from the registry's health model
    (reference: clusterstate.GetStatus). The optional reason histograms come
    from the loop's reason plane — the orchestrator's NoScaleUp totals and
    the planner's UnremovableNodes cache — so the status ConfigMap carries
    the same per-reason verdicts the events and metrics do."""
    st = ClusterAutoscalerStatus(last_probe_time=now)
    if config_map_name:
        st.config_map_name = config_map_name
    if unschedulable_reasons:
        st.unschedulable_reasons = dict(unschedulable_reasons)
    if unremovable_reasons:
        st.unremovable_reasons = dict(unremovable_reasons)
    st.cluster_wide.node_counts = NodeCounts.from_readiness(
        registry.total_readiness
    )
    st.cluster_wide.health = (
        HEALTHY if registry.is_cluster_healthy() else UNHEALTHY
    )
    if registry.scale_up_requests:
        st.cluster_wide.scale_up = IN_PROGRESS
    if registry.scale_down_in_flight:
        st.cluster_wide.scale_down = CANDIDATES_PRESENT
    elif scale_down_candidates:
        st.cluster_wide.scale_down = CANDIDATES_PRESENT

    for g in registry.provider.node_groups():
        gid = g.id()
        s = NodeGroupStatus(
            name=gid,
            min_size=g.min_size(), max_size=g.max_size(),
            target_size=g.target_size(),
            node_counts=NodeCounts.from_readiness(
                registry.readiness.get(gid, Readiness())
            ),
        )
        s.health = HEALTHY if registry.is_node_group_healthy(gid) else UNHEALTHY
        if registry.backoff.is_backed_off(gid, now):
            s.scale_up = BACKOFF
        elif gid in registry.scale_up_requests:
            s.scale_up = IN_PROGRESS
        in_flight_groups = set(registry.scale_down_group.values())
        if gid in in_flight_groups:
            s.scale_down = CANDIDATES_PRESENT
        st.node_groups.append(s)

    if st.cluster_wide.health == UNHEALTHY:
        st.autoscaler_status = UNHEALTHY
    return st
