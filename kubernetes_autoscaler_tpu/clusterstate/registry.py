"""ClusterStateRegistry: node-group health, in-flight scale-ups, upcoming nodes.

Reference counterpart: clusterstate/clusterstate.go:122-156 — tracks per-group
scale-up requests (expiring into failures after max-node-provision-time),
readiness/acceptable ranges, unregistered and long-unregistered instances,
exponential backoff integration, and the upcoming-node counts the orchestrator
injects into the snapshot (GetUpcomingNodes :1104, consumed by
static_autoscaler.go:499 addUpcomingNodesToClusterSnapshot).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.cloudprovider.provider import CloudProvider, NodeGroup
from kubernetes_autoscaler_tpu.config.options import AutoscalingOptions
from kubernetes_autoscaler_tpu.models.api import Node
from kubernetes_autoscaler_tpu.utils.backoff import ExponentialBackoff


@dataclass
class ScaleUpRequest:
    group_id: str
    increase: int
    time: float
    expected_add_time: float


@dataclass
class UnregisteredNode:
    name: str
    group_id: str
    since: float


@dataclass
class AcceptableRange:
    min_nodes: int
    max_nodes: int
    current_target: int


@dataclass
class Readiness:
    ready: int = 0
    unready: int = 0
    not_started: int = 0
    registered: int = 0


class ClusterStateRegistry:
    """Health model consulted by the orchestrators each loop."""

    def __init__(self, provider: CloudProvider, options: AutoscalingOptions):
        self.provider = provider
        self.options = options
        self.backoff = ExponentialBackoff(
            initial_s=options.initial_node_group_backoff_s,
            max_s=options.max_node_group_backoff_s,
            reset_timeout_s=options.node_group_backoff_reset_timeout_s,
        )
        self.scale_up_requests: dict[str, ScaleUpRequest] = {}
        self.scale_down_in_flight: dict[str, float] = {}   # node name -> since
        self.scale_down_group: dict[str, str] = {}         # node name -> group id
        self.readiness: dict[str, Readiness] = {}
        self.acceptable_ranges: dict[str, AcceptableRange] = {}
        self.unregistered: list[UnregisteredNode] = []
        self.failed_scale_ups: dict[str, float] = {}        # group -> last failure
        self.last_scale_up_time: float = 0.0
        self.last_scale_down_time: float = 0.0
        self.total_readiness = Readiness()

    # ---- scale-up bookkeeping (reference: RegisterOrUpdateScaleUp :242) ----

    def register_scale_up(self, group: NodeGroup, increase: int, now: float) -> None:
        prev = self.scale_up_requests.get(group.id())
        provision = self._max_provision_time(group)
        if prev:
            prev.increase += increase
            prev.expected_add_time = now + provision
        else:
            self.scale_up_requests[group.id()] = ScaleUpRequest(
                group.id(), increase, now, now + provision
            )
        self.last_scale_up_time = max(self.last_scale_up_time, now)

    def register_failed_scale_up(self, group: NodeGroup, now: float) -> None:
        from kubernetes_autoscaler_tpu.metrics.metrics import default_registry

        default_registry.counter("failed_scale_ups_total").inc()
        tmpl = group.template_node_info()
        # specifically the provider's GPU resource — hugepages / DRA classes /
        # CSI attach-limit slots are extended resources too and must not
        # inflate the GPU failure metric
        if float(tmpl.alloc_or_cap().get(
                self.provider.gpu_resource_name(), 0)) > 0:
            default_registry.counter("failed_gpu_scale_ups_total").inc()
        """reference: RegisterFailedScaleUp → backoff the group."""
        self.failed_scale_ups[group.id()] = now
        self.backoff.backoff(group.id(), now)
        self.scale_up_requests.pop(group.id(), None)

    def register_scale_down(self, node_name: str, now: float,
                            group_id: str = "") -> None:
        self.scale_down_in_flight[node_name] = now
        self.scale_down_group[node_name] = group_id
        self.last_scale_down_time = max(self.last_scale_down_time, now)

    def _max_provision_time(self, group: NodeGroup) -> float:
        opts = group.get_options(_ng_defaults(self.options))
        return opts.max_node_provision_time_s or self.options.node_group_defaults.max_node_provision_time_s

    # ---- per-loop refresh (reference: UpdateNodes :421) ----

    def update_nodes(self, nodes: list[Node], now: float) -> None:
        registered = {n.name for n in nodes}
        # Scale-down completions: once a deleting node is gone from the
        # registered set, its in-flight entry is done (bounded memory; the
        # reference clears via NodeDeletionTracker result observation).
        self.scale_down_in_flight = {
            n: t for n, t in self.scale_down_in_flight.items() if n in registered
        }
        by_group: dict[str, Readiness] = {}
        known_unreg = {u.name: u for u in self.unregistered}
        total = Readiness()

        # Rebuild the unregistered list from what the cloud currently reports:
        # an instance that registered OR vanished from the provider drops out
        # (prevents re-reaping a long-gone instance every loop).
        still_unregistered: list[UnregisteredNode] = []
        for g in self.provider.node_groups():
            r = Readiness()
            for inst in g.nodes():
                if inst.name in registered:
                    continue
                r.not_started += 1
                prev = known_unreg.get(inst.name)
                still_unregistered.append(
                    prev if prev is not None
                    else UnregisteredNode(inst.name, g.id(), now)
                )
            by_group[g.id()] = r
        self.unregistered = still_unregistered

        self.node_first_seen = {
            n: t for n, t in getattr(self, "node_first_seen", {}).items()
            if n in registered}
        for nd in nodes:
            g = self.provider.node_group_for_node(nd)
            r = by_group.setdefault(g.id() if g else "", Readiness())
            r.registered += 1
            total.registered += 1
            # the node's own creation stamp when the source provides it
            # (reference: CreationTimestamp, clusterstate.go:739); fall back
            # to first-seen so fixture nodes without stamps still classify —
            # a restart must NOT re-open the startup window for old nodes
            since = (nd.creation_time if nd.creation_time > 0
                     else self.node_first_seen.setdefault(nd.name, now))
            if nd.ready:
                r.ready += 1
                total.ready += 1
            elif now - since <= self.options.max_node_startup_time_s:
                # within the startup window an unready node is "not started"
                # — it doesn't count against cluster health (reference:
                # clusterstate.go:739 CreationTimestamp + MaxNodeStartupTime)
                r.not_started += 1
                total.not_started += 1
            else:
                r.unready += 1
                total.unready += 1

        self.readiness = by_group
        self.total_readiness = total

        # expire fulfilled / timed-out scale-up requests
        for gid, req in list(self.scale_up_requests.items()):
            group = next((g for g in self.provider.node_groups() if g.id() == gid), None)
            if group is None:
                del self.scale_up_requests[gid]
                continue
            ready = by_group.get(gid, Readiness()).ready
            if ready >= group.target_size():
                del self.scale_up_requests[gid]
                self.backoff.remove_backoff(gid)
            elif now > req.expected_add_time:
                # timed out: nodes never came up (reference: updateScaleRequests)
                del self.scale_up_requests[gid]
                self.failed_scale_ups[gid] = now
                self.backoff.backoff(gid, now)

        self._update_acceptable_ranges()

    def _update_acceptable_ranges(self) -> None:
        """reference: updateAcceptableRanges (clusterstate.go) — per group,
        registered counts between target-minus-pending-adds and
        target-plus-in-flight-deletes are not 'incorrect size'."""
        sd_group = self.scale_down_group
        deleting_per_group: dict[str, int] = {}
        for node, _ in self.scale_down_in_flight.items():
            gid = sd_group.get(node, "")
            deleting_per_group[gid] = deleting_per_group.get(gid, 0) + 1
        for g in self.provider.node_groups():
            target = g.target_size()
            req = self.scale_up_requests.get(g.id())
            lo = target - (req.increase if req else 0)
            hi = target + deleting_per_group.get(g.id(), 0)
            self.acceptable_ranges[g.id()] = AcceptableRange(lo, hi, target)

    def has_incorrect_size(self, group_id: str) -> bool:
        """Registered count outside the acceptable range (consumed by
        fixNodeGroupSize-style reconciliation)."""
        rng = self.acceptable_ranges.get(group_id)
        r = self.readiness.get(group_id)
        if rng is None or r is None:
            return False
        return not (rng.min_nodes <= r.registered <= rng.max_nodes)

    # ---- health queries (reference: IsClusterHealthy :493) ----

    def is_cluster_healthy(self) -> bool:
        t = self.total_readiness
        unready = t.unready
        if t.registered == 0:
            return True
        if unready <= self.options.ok_total_unready_count:
            return True
        return unready * 100.0 / t.registered <= self.options.max_total_unready_percentage

    def is_node_group_safe_to_scale_up(self, group: NodeGroup, now: float) -> bool:
        if self.backoff.is_backed_off(group.id(), now):
            return False
        return self.is_node_group_healthy(group.id())

    def is_node_group_healthy(self, group_id: str) -> bool:
        r = self.readiness.get(group_id)
        if r is None:
            return True
        unready = r.unready
        if r.registered == 0:
            return True
        if unready <= self.options.ok_total_unready_count:
            return True
        return unready * 100.0 / r.registered <= self.options.max_total_unready_percentage

    # ---- upcoming nodes (reference: GetUpcomingNodes :1104) ----

    def upcoming_nodes(self) -> dict[str, int]:
        """Per group: target - ready-registered = nodes expected to appear."""
        out: dict[str, int] = {}
        for g in self.provider.node_groups():
            r = self.readiness.get(g.id(), Readiness())
            upcoming = g.target_size() - r.registered
            if upcoming > 0:
                out[g.id()] = upcoming
        return out

    def long_unregistered(self, now: float) -> list[UnregisteredNode]:
        cutoff = self.options.unregistered_node_removal_time_s
        return [u for u in self.unregistered if now - u.since > cutoff]


def _ng_defaults(options: AutoscalingOptions):
    from kubernetes_autoscaler_tpu.cloudprovider.provider import NodeGroupOptions

    d = options.node_group_defaults
    return NodeGroupOptions(
        scale_down_utilization_threshold=d.scale_down_utilization_threshold,
        scale_down_gpu_utilization_threshold=d.scale_down_gpu_utilization_threshold,
        scale_down_unneeded_time_s=d.scale_down_unneeded_time_s,
        scale_down_unready_time_s=d.scale_down_unready_time_s,
        max_node_provision_time_s=d.max_node_provision_time_s,
        ignore_daemonsets_utilization=d.ignore_daemonsets_utilization,
    )
