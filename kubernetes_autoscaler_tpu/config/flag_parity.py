"""Flag-parity registry vs the reference's config/flags/flags.go (~125 pflags).

Every reference flag appears in exactly one bucket:

  IMPLEMENTED — parsed by config/flags.py into an AutoscalingOptions field
                with a real behavioral consumer (tests/test_flag_parity.py
                asserts the parser knows each one).
  REJECTED    — accepted on the command line for operator muscle-memory but
                deliberately without effect HERE, each with the architectural
                reason. Passing one logs a warning naming the reason; a flag
                in neither bucket is an ERROR (no silent no-ops — the
                round-1/2 review's Weak #4).

The registry is the single source of truth: parse_options consults it.
"""

from __future__ import annotations

# flag name → AutoscalingOptions field (documentation; parity test checks the
# parser accepts the flag)
IMPLEMENTED: dict[str, str] = {
    "address": "serving address (__main__ HTTP mux)",
    "async-node-groups": "async_node_group_creation",
    "balance-similar-node-groups": "balance_similar_node_groups",
    "balancing-ignore-label": "balancing_ignore_labels",
    "balancing-label": "balancing_labels",
    "capacity-buffer-controller-enabled": "capacity_buffer_controller_enabled",
    "capacity-buffer-pod-injection-enabled": "capacity_buffer_pod_injection_enabled",
    "capacity-quotas-enabled": "capacity_quotas_enabled",
    "cordon-node-before-terminating": "cordon_node_before_terminating",
    "cores-total": "max_cores_total (quota limiter merge)",
    "daemonset-eviction-for-empty-nodes": "daemonset_eviction_for_empty_nodes",
    "daemonset-eviction-for-occupied-nodes": "daemonset_eviction_for_occupied_nodes",
    "debugging-snapshot-enabled": "debugging_snapshot_enabled (__main__ wiring)",
    "emit-per-nodegroup-metrics": "emit_per_nodegroup_metrics",
    "enable-csi-node-aware-scheduling": "enable_csi_node_aware_scheduling",
    "enable-dynamic-resource-allocation": "enable_dynamic_resource_allocation",
    "enable-provisioning-requests": "enable_provisioning_requests",
    "enforce-node-group-min-size": "enforce_node_group_min_size",
    "estimator": "estimator",
    "expander": "expander",
    "expendable-pods-priority-cutoff": "expendable_pods_priority_cutoff",
    "gpu-total": "max_gpu_total (quota limiter merge)",
    "grpc-expander-cert": "grpc_expander_cert",
    "grpc-expander-url": "grpc_expander_url",
    "ignore-daemonsets-utilization": "node_group_defaults.ignore_daemonsets_utilization",
    "ignore-mirror-pods-utilization": "ignore_mirror_pods_utilization",
    "initial-node-group-backoff-duration": "initial_node_group_backoff_s",
    "max-allocatable-difference-ratio": "max_allocatable_difference_ratio",
    "force-delete-unregistered-nodes": "force_delete_unregistered_nodes (min-size-ignoring forceful reap)",
    "scale-down-simulation-timeout": "scale_down_simulation_timeout_s (confirmation-pass deadline)",
    "max-binpacking-time": "max_binpacking_time_s (verify/salvo deadline)",
    "max-bulk-soft-taint-count": "max_bulk_soft_taint_count",
    "max-bulk-soft-taint-time": "max_bulk_soft_taint_time_s",
    "max-drain-parallelism": "max_drain_parallelism",
    "max-failing-time": "max_failing_time_s (liveness)",
    "max-free-difference-ratio": "max_free_difference_ratio",
    "max-graceful-termination-sec": "max_graceful_termination_s (eviction grace cap + termination wait)",
    "max-pod-eviction-time": "max_pod_eviction_time_s (per-pod eviction retry window)",
    "max-inactivity": "max_inactivity_s (liveness)",
    "max-node-group-backoff-duration": "max_node_group_backoff_s",
    "max-node-provision-time": "node_group_defaults.max_node_provision_time_s",
    "max-node-startup-time": "max_node_startup_time_s",
    "max-nodes-per-scaleup": "max_nodes_per_scaleup",
    "max-nodes-total": "max_nodes_total",
    "max-scale-down-parallelism": "max_scale_down_parallelism",
    "max-startup-time": "max_startup_time_s (liveness)",
    "max-total-unready-percentage": "max_total_unready_percentage",
    "memory-difference-ratio": "memory_difference_ratio",
    "memory-total": "max_memory_total_mib (quota limiter merge)",
    "min-replica-count": "min_replica_count",
    "new-pod-scale-up-delay": "new_pod_scale_up_delay_s",
    "node-deletion-candidate-ttl": "node_deletion_candidate_ttl_s (WAL recovery)",
    "node-group-backoff-reset-timeout": "node_group_backoff_reset_timeout_s",
    "node-removal-latency-tracking-enabled": "node_removal_latency_tracking_enabled",
    "ok-total-unready-count": "ok_total_unready_count",
    "parallel-scale-up": "parallel_scale_up (executor workers)",
    "pod-injection-limit": "pod_injection_limit",
    "profiling": "profiling (__main__ /profilez)",
    "salvo-scale-up": "scale_up_salvo_enabled",
    "salvo-scale-up-budget": "salvo_time_budget_s",
    "scale-down-candidates-pool-min-count": "scale_down_candidates_pool_min_count",
    "scale-down-candidates-pool-ratio": "scale_down_candidates_pool_ratio",
    "scale-down-delay-after-add": "scale_down_delay_after_add_s",
    "scale-down-delay-after-delete": "scale_down_delay_after_delete_s",
    "scale-down-delay-after-failure": "scale_down_delay_after_failure_s",
    "scale-down-enabled": "scale_down_enabled",
    "scale-down-gpu-utilization-threshold": "node_group_defaults.scale_down_gpu_utilization_threshold",
    "scale-down-non-empty-candidates-count": "scale_down_non_empty_candidates_count",
    "scale-down-unneeded-time": "node_group_defaults.scale_down_unneeded_time_s",
    "scale-down-unready-enabled": "scale_down_unready_enabled",
    "scale-down-unready-time": "node_group_defaults.scale_down_unready_time_s",
    "scale-down-utilization-threshold": "node_group_defaults.scale_down_utilization_threshold",
    "scale-from-unschedulable": "scale_from_unschedulable",
    "scale-up-from-zero": "scale_up_from_zero",
    "scan-interval": "scan_interval_s",
    "skip-nodes-with-custom-controller-pods": "skip_nodes_with_custom_controller_pods",
    "skip-nodes-with-local-storage": "skip_nodes_with_local_storage",
    "skip-nodes-with-system-pods": "skip_nodes_with_system_pods",
    "status-config-map-name": "status_config_map_name",
    "unremovable-node-recheck-timeout": "unremovable_node_recheck_timeout_s",
    "write-status-configmap": "write_status_configmap",
}

# flag name → why it deliberately has no force in this framework
REJECTED: dict[str, str] = {
    "allowed-scheduler-names": "one simulated scheduler plane; no multi-scheduler routing",
    "aws-use-static-instance-list": "cloud-SDK specific; providers integrate via the SPI/externalgrpc",
    "blocking-system-pod-distruption-timeout": "drainability rules classify system pods per loop; no wait-loop to bound",
    "bulk-mig-instances-listing-enabled": "GCE-SDK specific",
    "bypassed-scheduler-names": "one simulated scheduler plane",
    "capacity-buffer-pod-dry-run-enabled": "buffer translation is always side-effect-free until injection",
    "check-capacity-batch-processing": "check-capacity ProvReqs are evaluated exhaustively each loop on device; no batching needed",
    "check-capacity-processor-instance": "single processor instance per process",
    "check-capacity-provisioning-request-batch-timebox": "no batching (see check-capacity-batch-processing)",
    "check-capacity-provisioning-request-max-batch-size": "no batching (see check-capacity-batch-processing)",
    "cloud-config": "no cloud SDKs in-process; providers attach via the SPI/externalgrpc",
    "cloud-provider": "provider is constructor-injected, not name-selected",
    "cluster-name": "no cloud tagging surface",
    "cluster-snapshot-parallelism": "snapshot is a device tensor; parallelism is the mesh, not host threads",
    "clusterapi-cloud-config-authoritative": "cloud-SDK specific",
    "drain-priority-config": "priority eviction order is built in (actuator.priority_eviction_order); tiered waits belong to the eviction sink",
    "dynamic-node-delete-delay-after-taint-enabled": "deletion issues through the provider synchronously; no apiserver round-trip to pace",
    "enable-proactive-scaleup": "capacity buffers + pod injection cover proactive headroom",
    "fastpath-binpacking-enabled": "no fastpath exists: the full pack is one fused device program",
    "force-delete-failed-nodes": "failed-boot instances are force-reaped unconditionally (no apiserver finalizers to bypass)",
    "frequent-loops-enabled": "the loop driver is always event-driven (core/loop.py LoopTrigger)",
    "gce-concurrent-refreshes": "GCE-SDK specific",
    "gce-mig-instances-min-refresh-wait-time": "GCE-SDK specific",
    "ignore-taint": "superseded upstream by startup-taint; taints are exact hash planes here",
    "kube-api-content-type": "no kube API client; the boundary is ClusterDataSource",
    "kube-client-burst": "no kube API client",
    "kube-client-qps": "no kube API client",
    "kubeconfig": "no kube API client",
    "max-nodegroup-binpacking-duration": "all groups estimate in ONE device dispatch; max-binpacking-time bounds the whole computation",
    "max-node-skip-eval-time-tracker-enabled": "no per-node eval-skip heuristic: the sweep is exhaustive on device",
    "namespace": "no kube API objects to namespace",
    "node-delete-delay-after-taint": "no apiserver propagation delay to wait out",
    "node-deletion-batcher-interval": "empty-node deletions batch per loop already (actuator delete_in_batch path)",
    "node-deletion-delay-timeout": "no delay-deletion annotations without a kube API",
    "node-group-auto-discovery": "groups come from the provider SPI; discovery specs are provider-side",
    "node-info-cache-expire-time": "templates are re-encoded every loop by design; there is no cache to expire",
    "nodes": "per-group min:max bounds come from the provider SPI",
    "predicate-parallelism": "the predicate plane is data-parallel on device by construction",
    "provisioning-request-initial-backoff-time": "failed ProvReqs re-evaluate next loop; exhaustive device evaluation makes backoff caching moot",
    "provisioning-request-max-backoff-cache-size": "no ProvReq backoff cache",
    "provisioning-request-max-backoff-time": "no ProvReq backoff cache",
    "record-duplicated-events": "no kube events API",
    "regional": "GCE-SDK specific",
    "scale-down-delay-type-local": "single-process autoscaler; delays are always local",
    "scaleup-simulation-for-skipped-node-groups-enabled": "no groups are skipped: every group's option is computed in the same kernel",
    "startup-taint": "node readiness comes from the data source; startup taints are a kubelet-lifecycle concern",
    "status-taint": "same as startup-taint",
    "user-agent": "no kube API client",
}


def check_no_overlap() -> None:
    both = set(IMPLEMENTED) & set(REJECTED)
    if both:
        raise AssertionError(f"flags in both buckets: {sorted(both)}")


check_no_overlap()
