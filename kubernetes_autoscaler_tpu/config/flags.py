"""CLI flags → AutoscalingOptions.

Reference counterpart: config/flags/flags.go (~125 pflag definitions feeding
config.AutoscalingOptions; auto-documented into FAQ.md:1000+). Flag names keep
the reference's kebab-case spelling so operator muscle memory transfers;
durations accept Go-style strings ("10s", "5m", "1h30m") and plain seconds.

Flags without behavioral force in this framework (cloud-SDK endpoints,
kubeconfig plumbing) are accepted-and-ignored via `--ignore-unknown` parity
mode rather than erroring, mirroring how operators carry flag soups between
autoscaler versions.
"""

from __future__ import annotations

import argparse
import re

from kubernetes_autoscaler_tpu.config.options import (
    AutoscalingOptions,
    NodeGroupDefaults,
)

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|h|m|s)")
_UNIT_S = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 0.001}


def parse_duration_s(text: str) -> float:
    """Go duration ("1h30m", "10s") or bare seconds ("90")."""
    text = text.strip()
    try:
        return float(text)
    except ValueError:
        pass
    total, pos = 0.0, 0
    for m in _DURATION_RE.finditer(text):
        if m.start() != pos:
            raise ValueError(f"bad duration {text!r}")
        total += float(m.group(1)) * _UNIT_S[m.group(2)]
        pos = m.end()
    if pos != len(text) or pos == 0:
        raise ValueError(f"bad duration {text!r}")
    return total


def _bool(text: str) -> bool:
    return text.lower() in ("1", "true", "t", "yes", "y")


def build_parser() -> argparse.ArgumentParser:
    from kubernetes_autoscaler_tpu.version import version_string

    p = argparse.ArgumentParser(
        prog="kubernetes-autoscaler-tpu",
        description="TPU-native cluster autoscaling framework",
    )
    p.add_argument("--version", action="version", version=version_string())
    dur = parse_duration_s

    # loop (reference flags.go: --scan-interval)
    p.add_argument("--scan-interval", type=dur, default=10.0)

    # scale-up
    p.add_argument("--estimator", default="binpacking")
    p.add_argument("--expander", default="least-waste")
    p.add_argument("--max-nodes-per-scaleup", type=int, default=1000)
    p.add_argument("--max-nodes-total", type=int, default=0)
    p.add_argument("--cores-total", default="0:320000",
                   help="min:max cluster cores (reference --cores-total)")
    p.add_argument("--memory-total", default="0:6400000",
                   help="min:max cluster memory in GiB")
    p.add_argument("--gpu-total", default="0:0",
                   help="min:max cluster GPUs (0:0 = unlimited)")
    p.add_argument("--balance-similar-node-groups", type=_bool, default=False)
    p.add_argument("--balancing-label", action="append", default=[])
    p.add_argument("--balancing-ignore-label", action="append", default=[])
    p.add_argument("--max-allocatable-difference-ratio", type=float, default=0.05)
    p.add_argument("--max-free-difference-ratio", type=float, default=0.05)
    p.add_argument("--memory-difference-ratio", type=float, default=0.015)
    p.add_argument("--new-pod-scale-up-delay", type=dur, default=0.0)
    p.add_argument("--expendable-pods-priority-cutoff", type=int, default=-10)
    p.add_argument("--max-binpacking-time", type=dur, default=300.0)
    p.add_argument("--enforce-node-group-min-size", type=_bool, default=False)
    p.add_argument("--parallel-scale-up", type=_bool, default=True)
    p.add_argument("--scale-up-from-zero", type=_bool, default=True)
    p.add_argument("--scale-from-unschedulable", type=_bool, default=False)
    p.add_argument("--async-node-groups", type=_bool, default=False)
    p.add_argument("--salvo-scale-up", type=_bool, default=False)
    p.add_argument("--salvo-scale-up-budget", type=dur, default=2.0)
    p.add_argument("--node-autoprovisioning-enabled", type=_bool, default=False)
    p.add_argument("--max-autoprovisioned-node-group-count", type=int, default=15)
    p.add_argument("--pod-injection-limit", type=int, default=5000)

    # scale-down
    p.add_argument("--scale-down-enabled", type=_bool, default=True)
    p.add_argument("--scale-down-delay-after-add", type=dur, default=600.0)
    p.add_argument("--scale-down-delay-after-delete", type=dur, default=0.0)
    p.add_argument("--scale-down-delay-after-failure", type=dur, default=180.0)
    p.add_argument("--scale-down-unneeded-time", type=dur, default=600.0)
    p.add_argument("--scale-down-unready-time", type=dur, default=1200.0)
    p.add_argument("--scale-down-utilization-threshold", type=float, default=0.5)
    p.add_argument("--scale-down-gpu-utilization-threshold", type=float, default=0.5)
    p.add_argument("--scale-down-candidates-pool-ratio", type=float, default=1.0)
    p.add_argument("--scale-down-candidates-pool-min-count", type=int, default=50)
    p.add_argument("--scale-down-simulation-timeout", type=dur, default=30.0)
    p.add_argument("--max-scale-down-parallelism", type=int, default=10)
    p.add_argument("--max-drain-parallelism", type=int, default=1)
    p.add_argument("--max-empty-bulk-delete", type=int, default=10)
    p.add_argument("--max-graceful-termination-sec", type=int, default=600)
    p.add_argument("--max-pod-eviction-time", type=float, default=120.0,
                   help="seconds CA keeps retrying a failed pod eviction")
    p.add_argument("--force-delete-unregistered-nodes", type=_bool,
                   default=False)
    p.add_argument("--async-node-deletion", type=_bool, default=False,
                   help="run evict+delete on a background executor (the "
                        "reference always detaches; default off because "
                        "in-process sinks complete instantly)")
    p.add_argument("--skip-nodes-with-system-pods", type=_bool, default=True)
    p.add_argument("--skip-nodes-with-local-storage", type=_bool, default=True)
    p.add_argument("--skip-nodes-with-custom-controller-pods", type=_bool,
                   default=False)
    p.add_argument("--min-replica-count", type=int, default=0)
    p.add_argument("--scale-down-unready-enabled", type=_bool, default=True)
    p.add_argument("--scale-down-non-empty-candidates-count", type=int, default=0,
                   help="0 = unlimited (device sweep is exhaustive; the "
                        "reference's 30 guards a serial simulator)")
    p.add_argument("--max-bulk-soft-taint-count", type=int, default=10)
    p.add_argument("--max-bulk-soft-taint-time", type=dur, default=3.0)
    p.add_argument("--node-deletion-candidate-ttl", type=dur, default=1800.0)
    p.add_argument("--unremovable-node-recheck-timeout", type=dur, default=300.0)
    p.add_argument("--cordon-node-before-terminating", type=_bool, default=False)
    p.add_argument("--daemonset-eviction-for-empty-nodes", type=_bool, default=False)
    p.add_argument("--daemonset-eviction-for-occupied-nodes", type=_bool, default=True)
    p.add_argument("--ignore-mirror-pods-utilization", type=_bool, default=False)

    # cluster health
    p.add_argument("--max-total-unready-percentage", type=float, default=45.0)
    p.add_argument("--ok-total-unready-count", type=int, default=3)
    p.add_argument("--max-node-startup-time", type=dur, default=900.0)
    p.add_argument("--max-node-provision-time", type=dur, default=900.0)
    p.add_argument("--unregistered-node-removal-time", type=dur, default=900.0)

    # backoff
    p.add_argument("--initial-node-group-backoff-duration", type=dur, default=300.0)
    p.add_argument("--max-node-group-backoff-duration", type=dur, default=1800.0)
    p.add_argument("--node-group-backoff-reset-timeout", type=dur, default=10800.0)

    # process / observability (reference: main.go flags)
    p.add_argument("--address", default=":8085",
                   help="metrics/healthz listen address")
    p.add_argument("--leader-elect", type=_bool, default=True)
    p.add_argument("--leader-elect-lease-file", default="/tmp/ka-tpu-leader.lock")
    p.add_argument("--profiling", type=_bool, default=False)
    p.add_argument("--ignore-daemonsets-utilization", type=_bool, default=False)
    p.add_argument("--emit-per-nodegroup-metrics", type=_bool, default=False)
    p.add_argument("--debugging-snapshot-enabled", type=_bool, default=False)
    p.add_argument("--write-status-configmap", type=_bool, default=True)
    p.add_argument("--status-config-map-name", default="cluster-autoscaler-status")
    p.add_argument("--max-inactivity", type=dur, default=600.0)
    p.add_argument("--max-failing-time", type=dur, default=900.0)
    p.add_argument("--max-startup-time", type=dur, default=1200.0)
    p.add_argument("--grpc-expander-url", default="")
    p.add_argument("--grpc-expander-cert", default="")

    # subsystem gates
    p.add_argument("--enable-provisioning-requests", type=_bool, default=True)
    p.add_argument("--capacity-buffer-controller-enabled", type=_bool, default=True)
    p.add_argument("--capacity-buffer-pod-injection-enabled", type=_bool, default=True)
    p.add_argument("--capacity-quotas-enabled", type=_bool, default=True)
    p.add_argument("--enable-dynamic-resource-allocation", type=_bool, default=True)
    p.add_argument("--enable-csi-node-aware-scheduling", type=_bool, default=True)
    p.add_argument("--node-removal-latency-tracking-enabled", type=_bool, default=True)

    # flight recorder / trace layer (no reference analog)
    p.add_argument("--flight-recorder-capacity", type=int, default=8,
                   help="ring-buffer size of retained per-loop traces "
                        "(0 disables the tracer entirely)")
    p.add_argument("--flight-recorder-dir", default="",
                   help="directory for auto-persisted Perfetto dumps on a "
                        "loop-budget breach / raise / armed /snapshotz "
                        "(empty = keep the ring in memory only)")
    p.add_argument("--loop-wallclock-budget", type=dur, default=0.0,
                   help="per-RunOnce wall-clock SLO; a breach dumps the "
                        "flight recorder (0 = no budget)")
    p.add_argument("--journal-dir", default="",
                   help="record every RunOnce into a deterministic flight "
                        "journal under this directory (snapshot+delta "
                        "records; replay with `python -m "
                        "kubernetes_autoscaler_tpu.replay`); empty = off")
    p.add_argument("--journal-max-mb", type=float, default=64.0,
                   help="size bound for the retained journal; older files "
                        "rotate out with drop accounting")
    p.add_argument("--lineage-ring", type=_bool, default=True,
                   help="live decision-lineage ring: the bounded "
                        "per-object provenance view served on /whyz, "
                        "/snapshotz and the sidecar Explain RPC "
                        "(lineage/index.py; pure observer)")
    p.add_argument("--lineage-ring-objects", type=int, default=512,
                   help="objects the lineage ring retains (LRU)")
    p.add_argument("--lineage-ring-loops", type=int, default=128,
                   help="loop rows the lineage ring retains")

    # backend supervisor / degraded-mode control loop (core/supervisor.py;
    # no reference analog — the Go autoscaler has no accelerator to lose)
    p.add_argument("--backend-phase-deadline", type=dur, default=0.0,
                   help="wall-clock budget per guarded device phase "
                        "(encode/dispatch/fetch); a hung op aborts the "
                        "loop and marks the backend suspect (0 = inline "
                        "guards, no watchdog)")
    p.add_argument("--backend-probe-deadline", type=dur, default=5.0,
                   help="deadline for the recovery probe's device round "
                        "trip")
    p.add_argument("--backend-suspect-threshold", type=int, default=2,
                   help="consecutive guarded-phase failures before the "
                        "suspect state escalates to degraded")
    p.add_argument("--backend-recovery-probes", type=int, default=2,
                   help="consecutive probe successes required to leave "
                        "degraded")
    p.add_argument("--backend-recovery-hysteresis", type=int, default=2,
                   help="clean loops in recovering before scale-down "
                        "re-enables (flap damping)")
    p.add_argument("--device-ledger", type=_bool, default=True,
                   help="HBM residency ledger: owner/tenant-tagged census "
                        "of resident device arrays, reconciled against "
                        "device memory_stats each loop (metrics/device.py)")
    p.add_argument("--hbm-watchdog-loops", type=int, default=5,
                   help="consecutive loops of monotonic untagged device-"
                        "byte growth before the leak watchdog fires an "
                        "event + flight-recorder dump")
    p.add_argument("--device-profile-dir", default="",
                   help="breach-armed device profiler: a loop-SLO breach "
                        "arms a bounded jax.profiler.trace capture of the "
                        "next RunOnce into this directory, stamped with "
                        "trace id + journal cursor (empty = off)")
    p.add_argument("--shadow-audit", type=_bool, default=False,
                   help="online shadow audit: each loop, re-verify a "
                        "deterministic journal-cursor-seeded sample of "
                        "device verdicts against the host oracle; a "
                        "divergence emits an evidence bundle and drives "
                        "the backend supervisor ladder (audit/shadow.py)")
    p.add_argument("--shadow-audit-samples", type=int, default=4,
                   help="verdict samples per audited surface per loop")
    p.add_argument("--shadow-audit-budget-ms", type=float, default=0.0,
                   help="per-loop audit budget refill in ms; 0 = adaptive "
                        "~0.5%% of the loop walltime (skipped samples are "
                        "counted — the audit never becomes the hot path)")
    p.add_argument("--shadow-audit-dir", default="",
                   help="directory for divergence evidence bundles "
                        "(default: --flight-recorder-dir)")
    p.add_argument("--restart-state-path", default="",
                   help="persist unneeded-since clocks + in-flight "
                        "scale-ups here each loop and rehydrate on start "
                        "(crash-consistent restart; empty = off)")
    p.add_argument("--restart-state-max-age", type=dur, default=1800.0,
                   help="discard a restart record older than this "
                        "wholesale (stale countdowns must not cause "
                        "premature deletions)")

    # TPU data plane (no reference analog — Go has no tracing/compile cache)
    p.add_argument("--node-shape-bucket", type=int, default=256)
    p.add_argument("--group-shape-bucket", type=int, default=64)
    p.add_argument("--max-new-nodes-static", type=int, default=1024)
    p.add_argument("--drain-chunk", type=int, default=32)
    p.add_argument("--max-pods-per-node", type=int, default=128)
    p.add_argument("--fused-loop", type=_bool, default=True,
                   help="run filter/scale-up/scale-down as ONE fused device "
                        "program with a single batched decision fetch "
                        "(docs/FUSED_LOOP.md); false = phased dispatches")
    p.add_argument("--incremental-encode", type=_bool, default=True,
                   help="maintain the tensor snapshot across loops and apply "
                        "only deltas (reference rationale: DeltaSnapshotStore)")
    p.add_argument("--incremental-resync-loops", type=int, default=240,
                   help="compacting full re-encode every N loops (0 = never)")
    p.add_argument("--incremental-verify-loops", type=int, default=0,
                   help="semantically verify the incremental tensors against "
                        "a fresh encode every N loops; mismatch forces a "
                        "resync and raises an error metric (0 = off)")

    # runner (standalone mode)
    p.add_argument("--scenario", default="",
                   help="JSON scenario file for the in-memory provider")
    p.add_argument("--max-iterations", type=int, default=0,
                   help="0 = run forever")
    return p


def _min_max(text: str) -> tuple[int, int]:
    lo, _, hi = text.partition(":")
    return int(lo or 0), int(hi or 0)


def options_from_args(args: argparse.Namespace) -> AutoscalingOptions:
    _, max_cores = _min_max(args.cores_total)
    _, max_mem_gib = _min_max(args.memory_total)
    _, max_gpus = _min_max(args.gpu_total)
    return AutoscalingOptions(
        enforce_node_group_min_size=args.enforce_node_group_min_size,
        parallel_scale_up=args.parallel_scale_up,
        scale_up_from_zero=args.scale_up_from_zero,
        scale_from_unschedulable=args.scale_from_unschedulable,
        async_node_group_creation=args.async_node_groups,
        scale_up_salvo_enabled=args.salvo_scale_up,
        salvo_time_budget_s=args.salvo_scale_up_budget,
        node_autoprovisioning_enabled=args.node_autoprovisioning_enabled,
        max_autoprovisioned_node_group_count=args.max_autoprovisioned_node_group_count,
        max_gpu_total=max_gpus,
        max_allocatable_difference_ratio=args.max_allocatable_difference_ratio,
        max_free_difference_ratio=args.max_free_difference_ratio,
        memory_difference_ratio=args.memory_difference_ratio,
        balancing_labels=list(args.balancing_label),
        balancing_ignore_labels=list(args.balancing_ignore_label),
        pod_injection_limit=args.pod_injection_limit,
        scale_down_unready_enabled=args.scale_down_unready_enabled,
        scale_down_non_empty_candidates_count=args.scale_down_non_empty_candidates_count,
        max_bulk_soft_taint_count=args.max_bulk_soft_taint_count,
        max_bulk_soft_taint_time_s=args.max_bulk_soft_taint_time,
        node_deletion_candidate_ttl_s=args.node_deletion_candidate_ttl,
        unremovable_node_recheck_timeout_s=args.unremovable_node_recheck_timeout,
        cordon_node_before_terminating=args.cordon_node_before_terminating,
        daemonset_eviction_for_empty_nodes=args.daemonset_eviction_for_empty_nodes,
        daemonset_eviction_for_occupied_nodes=args.daemonset_eviction_for_occupied_nodes,
        ignore_mirror_pods_utilization=args.ignore_mirror_pods_utilization,
        emit_per_nodegroup_metrics=args.emit_per_nodegroup_metrics,
        debugging_snapshot_enabled=args.debugging_snapshot_enabled,
        write_status_configmap=args.write_status_configmap,
        status_config_map_name=args.status_config_map_name,
        max_inactivity_s=args.max_inactivity,
        max_failing_time_s=args.max_failing_time,
        max_startup_time_s=args.max_startup_time,
        profiling=args.profiling,
        grpc_expander_url=args.grpc_expander_url,
        grpc_expander_cert=args.grpc_expander_cert,
        enable_provisioning_requests=args.enable_provisioning_requests,
        capacity_buffer_controller_enabled=args.capacity_buffer_controller_enabled,
        capacity_buffer_pod_injection_enabled=args.capacity_buffer_pod_injection_enabled,
        capacity_quotas_enabled=args.capacity_quotas_enabled,
        enable_dynamic_resource_allocation=args.enable_dynamic_resource_allocation,
        enable_csi_node_aware_scheduling=args.enable_csi_node_aware_scheduling,
        node_removal_latency_tracking_enabled=args.node_removal_latency_tracking_enabled,
        scan_interval_s=args.scan_interval,
        estimator=args.estimator,
        expander=args.expander,
        max_nodes_per_scaleup=args.max_nodes_per_scaleup,
        max_nodes_total=args.max_nodes_total,
        max_cores_total=max_cores,
        max_memory_total_mib=max_mem_gib * 1024,
        balance_similar_node_groups=args.balance_similar_node_groups,
        new_pod_scale_up_delay_s=args.new_pod_scale_up_delay,
        expendable_pods_priority_cutoff=args.expendable_pods_priority_cutoff,
        max_binpacking_time_s=args.max_binpacking_time,
        scale_down_enabled=args.scale_down_enabled,
        scale_down_delay_after_add_s=args.scale_down_delay_after_add,
        scale_down_delay_after_delete_s=args.scale_down_delay_after_delete,
        scale_down_delay_after_failure_s=args.scale_down_delay_after_failure,
        scale_down_candidates_pool_ratio=args.scale_down_candidates_pool_ratio,
        scale_down_candidates_pool_min_count=args.scale_down_candidates_pool_min_count,
        max_scale_down_parallelism=args.max_scale_down_parallelism,
        max_drain_parallelism=args.max_drain_parallelism,
        max_empty_bulk_delete=args.max_empty_bulk_delete,
        max_graceful_termination_s=float(args.max_graceful_termination_sec),
        skip_nodes_with_system_pods=args.skip_nodes_with_system_pods,
        skip_nodes_with_local_storage=args.skip_nodes_with_local_storage,
        skip_nodes_with_custom_controller_pods=args.skip_nodes_with_custom_controller_pods,
        min_replica_count=args.min_replica_count,
        max_total_unready_percentage=args.max_total_unready_percentage,
        ok_total_unready_count=args.ok_total_unready_count,
        max_node_startup_time_s=args.max_node_startup_time,
        unregistered_node_removal_time_s=args.unregistered_node_removal_time,
        initial_node_group_backoff_s=args.initial_node_group_backoff_duration,
        max_node_group_backoff_s=args.max_node_group_backoff_duration,
        node_group_backoff_reset_timeout_s=args.node_group_backoff_reset_timeout,
        node_group_defaults=NodeGroupDefaults(
            scale_down_utilization_threshold=args.scale_down_utilization_threshold,
            scale_down_gpu_utilization_threshold=args.scale_down_gpu_utilization_threshold,
            scale_down_unneeded_time_s=args.scale_down_unneeded_time,
            scale_down_unready_time_s=args.scale_down_unready_time,
            max_node_provision_time_s=args.max_node_provision_time,
            ignore_daemonsets_utilization=args.ignore_daemonsets_utilization,
        ),
        node_shape_bucket=args.node_shape_bucket,
        group_shape_bucket=args.group_shape_bucket,
        max_new_nodes_static=args.max_new_nodes_static,
        drain_chunk=args.drain_chunk,
        max_pods_per_node=args.max_pods_per_node,
        max_pod_eviction_time_s=args.max_pod_eviction_time,
        scale_down_simulation_timeout_s=args.scale_down_simulation_timeout,
        force_delete_unregistered_nodes=args.force_delete_unregistered_nodes,
        async_node_deletion=args.async_node_deletion,
        fused_loop=args.fused_loop,
        incremental_encode=args.incremental_encode,
        incremental_resync_loops=args.incremental_resync_loops,
        incremental_verify_loops=args.incremental_verify_loops,
        flight_recorder_capacity=args.flight_recorder_capacity,
        flight_recorder_dir=args.flight_recorder_dir,
        loop_wallclock_budget_s=args.loop_wallclock_budget,
        journal_dir=args.journal_dir,
        journal_max_mb=args.journal_max_mb,
        lineage_ring=args.lineage_ring,
        lineage_ring_objects=args.lineage_ring_objects,
        lineage_ring_loops=args.lineage_ring_loops,
        backend_phase_deadline_s=args.backend_phase_deadline,
        backend_probe_deadline_s=args.backend_probe_deadline,
        backend_suspect_threshold=args.backend_suspect_threshold,
        backend_recovery_probes=args.backend_recovery_probes,
        backend_recovery_hysteresis_loops=args.backend_recovery_hysteresis,
        device_ledger=args.device_ledger,
        hbm_watchdog_loops=args.hbm_watchdog_loops,
        device_profile_dir=args.device_profile_dir,
        shadow_audit=args.shadow_audit,
        shadow_audit_samples=args.shadow_audit_samples,
        shadow_audit_budget_ms=args.shadow_audit_budget_ms,
        shadow_audit_dir=args.shadow_audit_dir,
        restart_state_path=args.restart_state_path,
        restart_state_max_age_s=args.restart_state_max_age,
    )


def parse_options(argv: list[str] | None = None
                  ) -> tuple[AutoscalingOptions, argparse.Namespace]:
    from kubernetes_autoscaler_tpu.config.flag_parity import REJECTED

    args, unknown = build_parser().parse_known_args(argv)
    # Unknown flags: if the reference defines them and this framework
    # deliberately rejects them (flag_parity.REJECTED), log the reason and
    # continue — operator flag soups keep working. Anything else is an error:
    # a typo'd or truly unknown flag must never become a silent no-op.
    for tok in unknown:
        if not tok.startswith("--"):
            continue
        name = tok[2:].split("=", 1)[0]
        if name in REJECTED:
            import sys

            print(f"[flags] --{name} accepted without effect: {REJECTED[name]}",
                  file=sys.stderr)
        else:
            raise SystemExit(f"unknown flag --{name} (not a reference flag "
                             "this framework implements or rejects)")
    return options_from_args(args), args
