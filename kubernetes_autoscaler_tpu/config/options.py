"""AutoscalingOptions: the framework's single configuration bag.

Reference counterpart: config/autoscaling_options.go:107 (~120 fields fed by
~125 pflags, config/flags/flags.go). Field names keep the reference's meaning;
durations are seconds (floats) instead of time.Duration. config/flags.py maps
CLI flags onto this dataclass.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class NodeGroupDefaults:
    """Per-nodegroup defaults, overridable via NodeGroup.get_options
    (reference: config.NodeGroupAutoscalingOptions)."""

    scale_down_utilization_threshold: float = 0.5
    scale_down_gpu_utilization_threshold: float = 0.5
    scale_down_unneeded_time_s: float = 600.0
    scale_down_unready_time_s: float = 1200.0
    max_node_provision_time_s: float = 900.0
    ignore_daemonsets_utilization: bool = False


@dataclass
class AutoscalingOptions:
    # loop
    scan_interval_s: float = 10.0

    # scale-up
    estimator: str = "binpacking"                  # reference: estimator.go:53 (sole impl)
    expander: str = "least-waste"                  # comma-separated chain, reference flags.go
    max_nodes_per_scaleup: int = 1000              # FAQ.md:1086
    max_nodes_total: int = 0                       # 0 = unlimited
    max_cores_total: int = 320000                  # reference --cores-total max
    max_memory_total_mib: int = 6400000 * 1024     # reference --memory-total max (GiB→MiB)
    balance_similar_node_groups: bool = False
    new_pod_scale_up_delay_s: float = 0.0
    expendable_pods_priority_cutoff: int = -10
    max_binpacking_time_s: float = 5 * 60.0
    # salvo mode (reference: runScaleUpSalvo static_autoscaler.go:669 —
    # iterate scale-up within one loop, re-injecting scaled-up capacity)
    scale_up_salvo_enabled: bool = False
    salvo_max_rounds: int = 5
    salvo_time_budget_s: float = 2.0
    # node-group auto-provisioning (reference: --node-autoprovisioning-enabled,
    # --max-autoprovisioned-node-group-count)
    node_autoprovisioning_enabled: bool = False
    max_autoprovisioned_node_group_count: int = 15
    # async creation (reference: CreateNodeGroupAsync orchestrator.go:453 +
    # async_initializer.go — the loop never blocks on slow cloud creation)
    async_node_group_creation: bool = False

    # scale-up extras
    enforce_node_group_min_size: bool = False      # --enforce-node-group-min-size
    parallel_scale_up: bool = True                 # --parallel-scale-up (executor threads)
    scale_up_from_zero: bool = True                # --scale-up-from-zero
    scale_from_unschedulable: bool = False         # --scale-from-unschedulable
    max_gpu_total: int = 0                         # --gpu-total (0 = unlimited)
    # similar-nodegroup balancing knobs (reference:
    # processors/nodegroupset/compare_nodegroups.go + --balancing-*-label)
    max_allocatable_difference_ratio: float = 0.05
    max_free_difference_ratio: float = 0.05
    memory_difference_ratio: float = 0.015
    balancing_labels: list[str] = field(default_factory=list)
    balancing_ignore_labels: list[str] = field(default_factory=list)
    pod_injection_limit: int = 5000                # --pod-injection-limit

    # subsystem gates (reference feature flags)
    enable_provisioning_requests: bool = True
    capacity_buffer_controller_enabled: bool = True
    # injection can stop while the controller keeps reconciling statuses
    # (two independent reference flags)
    capacity_buffer_pod_injection_enabled: bool = True
    capacity_quotas_enabled: bool = True
    enable_dynamic_resource_allocation: bool = True
    enable_csi_node_aware_scheduling: bool = True
    node_removal_latency_tracking_enabled: bool = True
    max_startup_time_s: float = 20 * 60.0          # --max-startup-time (liveness)

    # scale-down
    scale_down_enabled: bool = True
    scale_down_delay_after_add_s: float = 600.0
    scale_down_delay_after_delete_s: float = 0.0
    scale_down_delay_after_failure_s: float = 180.0
    scale_down_candidates_pool_ratio: float = 1.0
    scale_down_candidates_pool_min_count: int = 50
    scale_down_unready_enabled: bool = True        # --scale-down-unready-enabled
    # --scale-down-non-empty-candidates-count: the reference defaults to 30
    # because its per-candidate drain simulation is serial and slow; the
    # device sweep evaluates every candidate in one dispatch, so the default
    # here is 0 (unlimited). Setting the flag still caps the pool.
    scale_down_non_empty_candidates_count: int = 0
    # wall-clock budget for the host-side CONFIRMATION pass (reference:
    # --scale-down-simulation-timeout bounds its serial simulation,
    # planner.go:297; our device sweep needs no bound, but the sequential
    # confirm loop over pathological shapes — thousands of accepted drains
    # with exact-oracle groups — does). Candidates not reached are simply
    # retried next loop.
    scale_down_simulation_timeout_s: float = 30.0
    max_scale_down_parallelism: int = 10
    max_drain_parallelism: int = 1
    max_empty_bulk_delete: int = 10
    max_graceful_termination_s: float = 600.0
    # per-pod eviction retry window (reference: --max-pod-eviction-time, 2m;
    # drain.go:185 retryUntil)
    max_pod_eviction_time_s: float = 120.0
    # run evict+delete on a background executor so eviction retries never
    # block the control loop (the reference ALWAYS detaches —
    # deleteNodesAsync goroutines, actuator.go:287; default off here because
    # synchronous in-process sinks complete instantly and tests read results
    # from the same loop)
    async_node_deletion: bool = False
    # long-unregistered instances: use NodeGroup.force_delete_nodes and
    # ignore group min size (reference: --force-delete-unregistered-nodes,
    # static_autoscaler.go:990,1018)
    force_delete_unregistered_nodes: bool = False
    skip_nodes_with_system_pods: bool = True
    skip_nodes_with_local_storage: bool = True
    skip_nodes_with_custom_controller_pods: bool = False
    min_replica_count: int = 0
    # soft-taint WAL budgets (reference: --max-bulk-soft-taint-count/-time)
    max_bulk_soft_taint_count: int = 10
    max_bulk_soft_taint_time_s: float = 3.0
    # DeletionCandidate taints older than this are stale on recovery
    # (reference: --node-deletion-candidate-ttl)
    node_deletion_candidate_ttl_s: float = 30 * 60.0
    unremovable_node_recheck_timeout_s: float = 5 * 60.0  # --unremovable-node-recheck-timeout
    cordon_node_before_terminating: bool = False   # --cordon-node-before-terminating
    daemonset_eviction_for_empty_nodes: bool = False
    daemonset_eviction_for_occupied_nodes: bool = True
    ignore_mirror_pods_utilization: bool = False

    # observability / process
    emit_per_nodegroup_metrics: bool = False       # --emit-per-nodegroup-metrics
    debugging_snapshot_enabled: bool = False       # --debugging-snapshot-enabled
    # flight recorder (metrics/trace.py): ring of the last N RunOnce traces,
    # auto-persisted on a loop-budget breach / raise / armed /snapshotz.
    # 0 disables per-loop tracing entirely (the zero-overhead path).
    flight_recorder_capacity: int = 8              # --flight-recorder-capacity
    flight_recorder_dir: str = ""                  # --flight-recorder-dir ("" = ring only)
    # per-loop wall-clock budget; a slower RunOnce counts as an SLO breach
    # and dumps the flight recorder (0 = no budget)
    loop_wallclock_budget_s: float = 0.0           # --loop-wallclock-budget
    # deterministic flight journal (replay/journal.py): record every RunOnce
    # as a self-contained snapshot/delta record replayable bit-for-bit by
    # `python -m kubernetes_autoscaler_tpu.replay`; "" = off
    journal_dir: str = ""                          # --journal-dir
    # size bound for the RETAINED journal (rotation + drop accounting)
    journal_max_mb: float = 64.0                   # --journal-max-mb
    # live decision-lineage ring (lineage/index.py): the bounded per-object
    # provenance view served on /whyz, /snapshotz and the sidecar Explain
    # RPC. Pure observer — fed once per loop from dicts RunOnce already
    # computed, zero extra device dispatches; False removes even that.
    lineage_ring: bool = True                      # --lineage-ring
    lineage_ring_objects: int = 512                # --lineage-ring-objects
    lineage_ring_loops: int = 128                  # --lineage-ring-loops
    # backend supervisor (core/supervisor.py): the control loop's
    # healthy → suspect → degraded → recovering ladder. 0 keeps the phase
    # guards inline (no watchdog thread, zero overhead) while exceptions in
    # guarded phases still drive the ladder; a positive deadline runs
    # encode/dispatch/fetch on sacrificial workers so a hung device op
    # aborts the LOOP at its budget instead of wedging the driver forever
    backend_phase_deadline_s: float = 0.0          # --backend-phase-deadline
    backend_probe_deadline_s: float = 5.0          # --backend-probe-deadline
    # consecutive guarded-phase failures before suspect escalates
    backend_suspect_threshold: int = 2             # --backend-suspect-threshold
    # consecutive probe successes to leave degraded, then clean loops of
    # hysteresis before scale-down re-enables (a flapping tunnel must not
    # thrash full re-encodes)
    backend_recovery_probes: int = 2               # --backend-recovery-probes
    backend_recovery_hysteresis_loops: int = 2     # --backend-recovery-hysteresis
    # device-side observability (metrics/device.py): the HBM residency
    # ledger census published per loop + the leak watchdog (K loops of
    # monotonic untagged growth fires an event + flight-recorder dump)
    device_ledger: bool = True                     # --device-ledger
    hbm_watchdog_loops: int = 5                    # --hbm-watchdog-loops
    # breach-armed device profiler: a loop-SLO breach arms a bounded
    # jax.profiler.trace capture of the NEXT RunOnce into this directory,
    # stamped with trace id + journal cursor; "" = off
    device_profile_dir: str = ""                   # --device-profile-dir
    # online shadow audit (audit/shadow.py): continuous, budget-bounded,
    # journal-cursor-seeded sampled re-verification of device verdicts
    # against the host oracle — divergence drives the supervisor ladder
    shadow_audit: bool = False                     # --shadow-audit
    # samples per audited surface per loop (K)
    shadow_audit_samples: int = 4                  # --shadow-audit-samples
    # per-loop audit budget refill in ms; 0 = adaptive (~0.5% of the loop
    # walltime EWMA — half the 1% overhead target). Exhausted budget skips
    # samples (counted), never stalls the loop.
    shadow_audit_budget_ms: float = 0.0            # --shadow-audit-budget-ms
    # divergence evidence bundles land here; "" falls back to
    # --flight-recorder-dir (bundle next to the Perfetto dump)
    shadow_audit_dir: str = ""                     # --shadow-audit-dir
    # crash-consistent restart record (unneeded-since clocks + in-flight
    # scale-ups keyed to the journal cursor); "" = off
    restart_state_path: str = ""                   # --restart-state-path
    # records older than this are discarded wholesale on rehydration —
    # stale countdown clocks must never cause premature deletions
    restart_state_max_age_s: float = 1800.0        # --restart-state-max-age
    write_status_configmap: bool = True            # --write-status-configmap
    status_config_map_name: str = "cluster-autoscaler-status"
    max_inactivity_s: float = 10 * 60.0            # --max-inactivity (liveness)
    max_failing_time_s: float = 15 * 60.0          # --max-failing-time (liveness)
    profiling: bool = False                        # --profiling (pprof analog)
    grpc_expander_url: str = ""                    # --grpc-expander-url
    grpc_expander_cert: str = ""                   # --grpc-expander-cert

    # cluster health (reference: clusterstate config)
    max_total_unready_percentage: float = 45.0
    ok_total_unready_count: int = 3
    max_node_startup_time_s: float = 15 * 60.0
    unregistered_node_removal_time_s: float = 15 * 60.0

    # backoff (reference: utils/backoff defaults)
    initial_node_group_backoff_s: float = 5 * 60.0
    max_node_group_backoff_s: float = 30 * 60.0
    node_group_backoff_reset_timeout_s: float = 3 * 60 * 60.0

    node_group_defaults: NodeGroupDefaults = field(default_factory=NodeGroupDefaults)

    # TPU data plane
    max_new_nodes_static: int = 1024               # static bin-pool size per option kernel
    node_shape_bucket: int = 256                   # compile-cache shape buckets
    group_shape_bucket: int = 64
    drain_chunk: int = 32
    max_pods_per_node: int = 128
    # single-dispatch fused RunOnce (docs/FUSED_LOOP.md): the loop's three
    # device phases as one compiled program harvested in one batched fetch,
    # with speculative next-loop overlap; False = phased dispatches (the
    # comparison oracle — decisions are bit-identical either way)
    fused_loop: bool = True
    # incremental tensor-snapshot maintenance across loops (the reference's
    # DeltaSnapshotStore rationale, store/delta.go:33-54, moved to the
    # string→tensor boundary); False = full encode_cluster every loop
    incremental_encode: bool = True
    # force a compacting full re-encode every N loops (0 = never); bounds
    # ghost-row growth from long-running node/equivalence churn
    incremental_resync_loops: int = 240
    # every N loops, semantically diff the incrementally-maintained tensors
    # against a fresh encode; a mismatch (= a source violating the replace-
    # on-update contract, e.g. in-place dict mutation) forces a resync and
    # raises the incremental_verify_failures_total metric instead of
    # producing silently stale verdicts. 0 = off (production default)
    incremental_verify_loops: int = 0
