"""Loop driver: ticks RunOnce, re-running immediately after productive loops.

Reference counterpart: loop/trigger.go:56 LoopTrigger (event-driven wakeup on
unschedulable-pod events, else scan-interval tick; immediate re-run after a
productive scale-up/scale-down) and loop/run.go:32 RunAutoscalerOnce (health
check + metrics wrapper).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from kubernetes_autoscaler_tpu.core.static_autoscaler import (
    RunOnceStatus,
    StaticAutoscaler,
)


@dataclass
class LoopTrigger:
    scan_interval_s: float = 10.0

    def __post_init__(self):
        self._event = threading.Event()

    def poke(self) -> None:
        """Unschedulable-pod observer hook (reference: UnschedulablePodObserver)."""
        self._event.set()

    def wait(self, last_productive: bool) -> None:
        """reference: LoopTrigger.Wait :75-103 — immediate re-run after a
        productive loop; otherwise wait for an event or the tick."""
        if last_productive:
            return
        self._event.wait(timeout=self.scan_interval_s)
        self._event.clear()


def run_loop(
    autoscaler: StaticAutoscaler,
    trigger: LoopTrigger | None = None,
    max_iterations: int | None = None,
    stop: threading.Event | None = None,
) -> list[RunOnceStatus]:
    trigger = trigger or LoopTrigger(autoscaler.options.scan_interval_s)
    history: list[RunOnceStatus] = []
    productive = False
    i = 0
    while (max_iterations is None or i < max_iterations) and not (stop and stop.is_set()):
        trigger.wait(productive)
        status = autoscaler.run_once()
        history.append(status)
        productive = bool(
            (status.scale_up and status.scale_up.scaled_up)
            or status.scale_down_deleted
        )
        i += 1
    return history
