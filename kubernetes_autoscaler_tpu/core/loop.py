"""Loop driver: ticks RunOnce, re-running immediately after productive loops.

Reference counterpart: loop/trigger.go:56 LoopTrigger (event-driven wakeup on
unschedulable-pod events, else scan-interval tick; immediate re-run after a
productive scale-up/scale-down) and loop/run.go:32 RunAutoscalerOnce (health
check + metrics wrapper — the loop SURVIVES a raising iteration; the
reference wraps every RunOnce so one bad loop never kills the process).

A raising `run_once()` here is recorded as a failed RunOnceStatus (ran=False,
`error` carries the exception) and the driver backs off exponentially between
retries — a persistently-broken backend costs bounded wall clock per retry
instead of a hot crash loop, and a recovered backend resumes on the next
tick. `PhaseDeadlineExceeded` from the backend supervisor's guards
(core/supervisor.py) lands here like any other error: the supervisor already
booked the incident; the driver's job is only to stay alive.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from kubernetes_autoscaler_tpu.core.static_autoscaler import (
    RunOnceStatus,
    StaticAutoscaler,
)


@dataclass
class LoopTrigger:
    scan_interval_s: float = 10.0

    def __post_init__(self):
        self._event = threading.Event()

    def poke(self) -> None:
        """Unschedulable-pod observer hook (reference: UnschedulablePodObserver)."""
        self._event.set()

    def wait(self, last_productive: bool) -> None:
        """reference: LoopTrigger.Wait :75-103 — immediate re-run after a
        productive loop; otherwise wait for an event or the tick."""
        if last_productive:
            return
        self._event.wait(timeout=self.scan_interval_s)
        self._event.clear()


def run_loop(
    autoscaler: StaticAutoscaler,
    trigger: LoopTrigger | None = None,
    max_iterations: int | None = None,
    stop: threading.Event | None = None,
    error_backoff_initial_s: float = 1.0,
    error_backoff_max_s: float = 30.0,
) -> list[RunOnceStatus]:
    trigger = trigger or LoopTrigger(autoscaler.options.scan_interval_s)
    history: list[RunOnceStatus] = []
    productive = False
    consecutive_errors = 0
    i = 0
    while (max_iterations is None or i < max_iterations) and not (stop and stop.is_set()):
        trigger.wait(productive)
        try:
            status = autoscaler.run_once()
            consecutive_errors = 0
        except Exception as e:  # noqa: BLE001 — the driver must survive
            # (reference: loop/run.go recovers; run_once already marked the
            # health check failed and counted errors_total on its way out)
            consecutive_errors += 1
            status = RunOnceStatus(
                ran=False,
                aborted_reason=f"run_once raised: {type(e).__name__}",
                error=f"{type(e).__name__}: {e}",
                backend_state=autoscaler.supervisor.state
                if getattr(autoscaler, "supervisor", None) is not None
                else "",
                # an OOM-failed loop's device-memory pprof evidence
                # (static_autoscaler dumps it before the supervisor ladder
                # churns the heap)
                hbm_dump_path=getattr(autoscaler, "last_oom_dump", ""),
                # the most recent shadow-audit divergence bundle: a loop
                # that raises AFTER a divergence still points its failed
                # status at the evidence (the restart record carries the
                # same pointer across a crash)
                audit_bundle_path=getattr(
                    autoscaler, "last_audit_bundle", ""),
            )
            # exponent clamped: a backend down for hours must not overflow
            # float range inside the very handler that keeps the driver alive
            delay = min(
                error_backoff_initial_s
                * (2 ** min(consecutive_errors - 1, 20)),
                error_backoff_max_s)
            if delay > 0:
                # interruptible: a stop request mustn't wait out the backoff
                if stop is not None:
                    stop.wait(delay)
                else:
                    time.sleep(delay)
        history.append(status)
        productive = bool(
            status.ran
            and ((status.scale_up and status.scale_up.scaled_up)
                 or status.scale_down_deleted)
        )
        i += 1
    return history
