"""Scale-down actuator: taint, drain, delete.

Reference counterpart: core/scaledown/actuation/ — StartDeletion
(actuator.go): apply the ToBeDeleted taint, evict pods with per-node goroutine
parallelism under budgets (budgets.go, --max-scale-down-parallelism /
--max-drain-parallelism), batch empty-node deletions per group
(delete_in_batch.go), and track in-flight deletions
(deletiontracker/nodedeletiontracker.go).

Eviction here goes through the EvictionSink seam (the kube API in the
reference; the fake cluster in tests; the sidecar's control plane in
deployment) so the actuator logic is transport-independent.
"""

from __future__ import annotations

import concurrent.futures
import copy
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Protocol

from kubernetes_autoscaler_tpu.cloudprovider.provider import (
    CloudProvider,
    NodeGroupError,
)
from kubernetes_autoscaler_tpu.config.options import AutoscalingOptions
from kubernetes_autoscaler_tpu.core.scaledown.planner import NodeToRemove
from kubernetes_autoscaler_tpu.models.api import (
    DELETION_CANDIDATE_TAINT,
    TO_BE_DELETED_TAINT,
    Node,
    Pod,
    Taint,
)


# reference: actuation/drain.go:44-49 — retry cadence for failed evictions and
# the extra wait for pods ignoring SIGTERM (killed at grace-period expiry)
DEFAULT_EVICTION_RETRY_TIME_S = 10.0
DEFAULT_POD_EVICTION_HEADROOM_S = 30.0
# apiv1.DefaultTerminationGracePeriodSeconds
DEFAULT_TERMINATION_GRACE_S = 30.0
# how long an eviction counts as "recent" for planner re-injection
# (reference: NewNodeDeletionTracker(15*time.Minute), builder wiring)
DEFAULT_EVICTIONS_TTL_S = 900.0


class EvictionSink(Protocol):
    """Where evictions land (reference: the eviction API in actuation/drain.go).

    `evict` may RAISE to signal a failed eviction (PDB conflict, API error);
    the actuator retries until --max-pod-eviction-time elapses
    (drain.go:185,240). Optional extensions a sink may provide:
      force_delete(pod, node)      — bypass eviction (reference
                                     forceDeletePod, drain.go:295)
      pods_gone(node_name, pod_names) -> bool
                                   — poll hook for the post-eviction wait
                                     (drain.go allGone loop); sinks whose
                                     evict() is synchronous can omit it
    """

    def evict(self, pod: Pod, node: Node, grace_period_s: float | None = None
              ) -> None: ...


@dataclass
class DeletionResult:
    node: str
    ok: bool
    reason: str = ""


def priority_eviction_order(pods: list[Pod]) -> list[Pod]:
    """reference: actuation/priority.go priority evictor — evict in ascending
    pod-priority tiers so high-priority pods keep running until lower tiers
    have been asked to leave (the reference additionally waits between tiers;
    here tier completion is the sink's concern — evictions are issued in tier
    order)."""
    return sorted(pods, key=lambda p: p.priority)


@dataclass
class NodeDeletionTracker:
    """reference: deletiontracker/nodedeletiontracker.go — in-flight deletion
    registry + recent-eviction registry (RegisterEviction :125,
    RecentEvictions :132 with the expiring-list TTL). Lock-protected: drains
    run in worker threads and detached deletions span loops, so the control
    loop reads this concurrently with the workers' writes."""

    deleting: dict[str, float] = field(default_factory=dict)
    drained: set[str] = field(default_factory=set)   # subset of `deleting` with pods
    results: list[DeletionResult] = field(default_factory=list)
    evictions_ttl_s: float = DEFAULT_EVICTIONS_TTL_S
    _evictions: list[tuple[Pod, float]] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def start(self, node: str, now: float, drain: bool = False) -> None:
        with self._lock:
            self.deleting[node] = now
            if drain:
                self.drained.add(node)

    def finish(self, node: str, ok: bool, reason: str = "") -> None:
        with self._lock:
            self.deleting.pop(node, None)
            self.drained.discard(node)
            self.results.append(DeletionResult(node, ok, reason))

    def in_flight(self) -> int:
        with self._lock:
            return len(self.deleting)

    def is_deleting(self, node: str) -> bool:
        with self._lock:
            return node in self.deleting

    def drain_deletions_in_progress(self) -> list[str]:
        """Names of nodes currently being DRAINED (reference:
        DeletionsInProgress()'s second return — the set the
        currently-drained-nodes pod list processor consumes)."""
        with self._lock:
            return sorted(self.drained)

    def register_eviction(self, pod: Pod, now: float) -> None:
        """reference: RegisterEviction — called per successfully evicted pod
        so the planner can anticipate its recreation (planner.go:230-260)."""
        with self._lock:
            self._evictions.append((pod, now))

    def recent_evictions(self, now: float) -> list[Pod]:
        """Pods evicted within evictions_ttl_s (reference: RecentEvictions,
        expiring-list DropNotNewerThan prune on read)."""
        with self._lock:
            cutoff = now - self.evictions_ttl_s
            self._evictions = [(p, t) for p, t in self._evictions if t > cutoff]
            return [p for p, _ in self._evictions]


class Actuator:
    def __init__(
        self,
        provider: CloudProvider,
        options: AutoscalingOptions,
        eviction_sink: EvictionSink | None = None,
        on_taint: Callable[[Node, str], None] | None = None,
        pdb_tracker=None,
        latency_tracker=None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        on_result: Callable[[DeletionResult], None] | None = None,
        walltime: Callable[[], float] = time.time,
    ):
        self.provider = provider
        self.options = options
        self.eviction_sink = eviction_sink
        self.on_taint = on_taint
        self.tracker = NodeDeletionTracker()
        self.pdb_tracker = pdb_tracker          # core/scaledown/pdb.RemainingPdbTracker
        self.latency_tracker = latency_tracker  # core/scaledown/latencytracker
        self.clock = clock                      # injectable for retry tests
        self.sleep = sleep
        # the RunOnce `now` domain (wall clock in production, logical time in
        # harnesses): eviction timestamps must live in the SAME domain the
        # control loop prunes recent_evictions with — monotonic self.clock
        # would never line up with it
        self.walltime = walltime
        self.eviction_retry_time_s = DEFAULT_EVICTION_RETRY_TIME_S
        self.pod_eviction_headroom_s = DEFAULT_POD_EVICTION_HEADROOM_S
        self._sink_takes_grace: bool | None = None  # resolved on first evict
        # detached-deletion support (reference: deleteNodesAsync goroutines,
        # actuator.go:287 — deletions never block the control loop there).
        # on_result fires ON THE WORKER THREAD — notification only; all
        # bookkeeping belongs in drain_completed(), which the control loop
        # calls at the top of RunOnce (r4 advisor: the old callback mutated
        # ClusterStateRegistry/observers/metrics off-thread)
        self.on_result = on_result
        self._bg: concurrent.futures.ThreadPoolExecutor | None = None
        self._completed: list[DeletionResult] = []
        self._completed_lock = threading.Lock()
        # live Node objects for deferred rollback (workers act on copies)
        self._live_nodes: dict[str, Node] = {}

    # ---- eviction with retry (reference: drain.go evictPod :240) ----

    def _grace_for(self, pod: Pod) -> float:
        """Grace period = pod's own, capped by --max-graceful-termination-sec
        (reference: evictPod maxTermination clamp, drain.go:243-249)."""
        g = (pod.termination_grace_s if pod.termination_grace_s is not None
             else DEFAULT_TERMINATION_GRACE_S)
        cap = self.options.max_graceful_termination_s
        if cap and cap > 0:
            g = min(g, cap)
        return g

    def _evict_once(self, pod: Pod, node: Node, grace_s: float) -> None:
        if self._sink_takes_grace is None:
            import inspect

            try:
                sig = inspect.signature(self.eviction_sink.evict)
                self._sink_takes_grace = "grace_period_s" in sig.parameters
            except (TypeError, ValueError):
                self._sink_takes_grace = False
        if self._sink_takes_grace:
            self.eviction_sink.evict(pod, node, grace_period_s=grace_s)
        else:  # minimal sinks only take (pod, node)
            self.eviction_sink.evict(pod, node)

    def _evict_with_retry(self, pod: Pod, node: Node, retry_until: float,
                          force: bool = False) -> None:
        """Retry eviction every eviction_retry_time_s until the
        --max-pod-eviction-time deadline (drain.go:185 retryUntil, :240 retry
        loop). Under force, a still-failing pod is force-deleted instead of
        failing the drain (drain.go:263 forceDeletePod)."""
        grace = self._grace_for(pod)
        last: Exception | None = None
        first = True
        while first or self.clock() < retry_until:
            if not first:
                self.sleep(self.eviction_retry_time_s)
            first = False
            try:
                self._evict_once(pod, node, grace)
                return
            except Exception as e:  # noqa: BLE001 — sink failure = retryable
                last = e
        if force:
            fd = getattr(self.eviction_sink, "force_delete", None)
            if fd is not None:
                fd(pod, node)
                return
        raise NodeGroupError(
            f"failed to evict pod {pod.namespace}/{pod.name} within allowed "
            f"timeout (last error: {last})")

    def _wait_pods_gone(self, node: Node, pods: list[Pod]) -> None:
        """Post-eviction wait: up to max-graceful-termination + headroom for
        the pods to actually terminate (drain.go allGone polling). Sinks
        without a pods_gone hook are synchronous by contract — no wait."""
        gone = getattr(self.eviction_sink, "pods_gone", None)
        if gone is None or not pods:
            return
        grace = max((self._grace_for(p) for p in pods), default=0.0)
        deadline = self.clock() + grace + self.pod_eviction_headroom_s
        names = [f"{p.namespace}/{p.name}" for p in pods]
        while True:
            if gone(node.name, names):
                return
            if self.clock() >= deadline:
                raise NodeGroupError(
                    f"pods remaining on {node.name} after termination timeout")
            self.sleep(min(self.eviction_retry_time_s, 5.0))

    # ---- taints (reference: utils/taints/taints.go) ----

    def taint_to_be_deleted(self, node: Node) -> None:
        if all(t.key != TO_BE_DELETED_TAINT for t in node.taints):
            node.taints.append(Taint(TO_BE_DELETED_TAINT, str(int(time.time())),
                                     "NoSchedule"))
        if self.on_taint:
            self.on_taint(node, TO_BE_DELETED_TAINT)

    def taint_deletion_candidate(self, node: Node, since: float | None = None) -> None:
        """Soft taint marking scale-down intent — the crash-recovery WAL
        (reference: softtaint.go + planner LoadFromExistingTaints). The taint
        value records when the node's unneeded CLOCK started, so a restarted
        process resumes the clock rather than restarting it."""
        if all(t.key != DELETION_CANDIDATE_TAINT for t in node.taints):
            node.taints.append(Taint(DELETION_CANDIDATE_TAINT,
                                     str(int(since if since is not None
                                             else time.time())),
                                     "PreferNoSchedule"))
        if self.on_taint:
            self.on_taint(node, DELETION_CANDIDATE_TAINT)

    def untaint(self, node: Node, key: str) -> None:
        node.taints = [t for t in node.taints if t.key != key]

    def _rollback_node(self, node: Node) -> None:
        """Failed deletion: remove the hard taint AND undo the cordon
        (reference: CleanToBeDeleted un-cordons on rollback when
        --cordon-node-before-terminating is set) so capacity is not lost."""
        self.untaint(node, TO_BE_DELETED_TAINT)
        if self.options.cordon_node_before_terminating:
            node.unschedulable = False

    # ---- deletion (reference: StartDeletion, actuator.go) ----

    def start_deletion(
        self,
        to_remove: list[NodeToRemove],
        pods_by_slot: dict[int, Pod] | None = None,
        now: float | None = None,
        detach: bool = False,
    ) -> list[DeletionResult]:
        """detach=True runs the evict+delete work on a background executor
        (the reference's deleteNodesAsync goroutines, actuator.go:287): the
        call taints the nodes and returns [] immediately; completed results
        flow through the tracker and the on_result callback. Synchronous
        mode (default) blocks until every node resolves — eviction retries
        can then hold RunOnce for up to --max-pod-eviction-time per pod,
        which is only acceptable with an in-process synchronous sink."""
        return self._start_deletion(to_remove, pods_by_slot, now, force=False,
                                    detach=detach)

    def start_force_deletion(
        self,
        to_remove: list[NodeToRemove],
        pods_by_slot: dict[int, Pod] | None = None,
        now: float | None = None,
    ) -> list[DeletionResult]:
        """Forced variant (reference: Actuator.StartForceDeletion,
        actuator.go:126): bypasses the PDB gate, force-deletes pods whose
        eviction keeps failing (drain.go:263), and deletes nodes via
        NodeGroup.force_delete_nodes (group_deletion_scheduler.go:105)."""
        return self._start_deletion(to_remove, pods_by_slot, now, force=True)

    def _start_deletion(
        self,
        to_remove: list[NodeToRemove],
        pods_by_slot: dict[int, Pod] | None,
        now: float | None,
        force: bool,
        detach: bool = False,
    ) -> list[DeletionResult]:
        # default into the SAME time domain register_eviction stamps with —
        # a logical-clock harness must not get wall-clock tracker.start()
        # timestamps next to logical eviction stamps
        now = self.walltime() if now is None else now
        if detach:
            # taints must land synchronously — the NEXT loop's planner and
            # filter-out-schedulable must see the nodes as leaving
            for r in to_remove:
                if self.options.cordon_node_before_terminating:
                    r.node.unschedulable = True
                self.taint_to_be_deleted(r.node)
                self.tracker.start(r.node.name, now, drain=not r.is_empty)
                self._live_nodes[r.node.name] = r.node
            if self._bg is None:
                self._bg = concurrent.futures.ThreadPoolExecutor(
                    max_workers=max(self.options.max_scale_down_parallelism,
                                    1),
                    thread_name_prefix="ka-delete")
            # the worker gets COPIES of the node/pod objects: the next loop
            # re-reads and re-encodes the live ones concurrently (r4 advisor
            # race); failed-node rollback is deferred to drain_completed()
            # on the control-loop thread, against the live Node
            work = [replace(r, node=self._copy_node(r.node)) for r in to_remove]
            slots = None
            if pods_by_slot is not None:
                needed = {s for r in to_remove
                          for s in (*r.pods_to_move, *r.ds_to_evict)}
                slots = {s: copy.copy(pods_by_slot[s])
                         for s in needed if s in pods_by_slot}

            def run():
                # results land in this shared list AS THEY COMPLETE inside
                # _execute_deletion, so nodes that finished before an
                # unexpected exception still reach _completed — their
                # bookkeeping fires and their _live_nodes entries are
                # reclaimed instead of leaking (ADVICE r5)
                results: list[DeletionResult] = []
                try:
                    self._execute_deletion(
                        work, slots, now, force, pre_tainted=True,
                        defer_rollback=True, out_results=results)
                except Exception as e:  # noqa: BLE001 — a worker must never
                    # strand its nodes: synthesize terminal failures so
                    # drain_completed still rolls back and books them
                    for r in work:
                        # whoever is still in flight got no terminal result
                        if not self.tracker.is_deleting(r.node.name):
                            continue
                        self.tracker.finish(r.node.name, False, repr(e))
                        results.append(
                            DeletionResult(r.node.name, False, repr(e)))
                with self._completed_lock:
                    self._completed.extend(results)
                if self.on_result is not None:
                    for res in results:
                        self.on_result(res)

            self._bg.submit(run)
            return []
        return self._execute_deletion(to_remove, pods_by_slot, now, force)

    @staticmethod
    def _copy_node(node: Node) -> Node:
        nd = copy.copy(node)
        nd.taints = list(node.taints)
        return nd

    def drain_completed(self) -> list[DeletionResult]:
        """Pop finished DETACHED deletions; called at the top of RunOnce so
        registry/observer/metric bookkeeping — and failed-node rollback —
        happen on the control-loop thread (reference: deletion results are
        consumed via NodeDeletionTracker.DeletionResults in RunOnce, not in
        the deletion goroutines)."""
        with self._completed_lock:
            done, self._completed = self._completed, []
        for res in done:
            live = self._live_nodes.pop(res.node, None)
            if live is not None and not res.ok:
                self._rollback_node(live)
        return done

    def _execute_deletion(
        self,
        to_remove: list[NodeToRemove],
        pods_by_slot: dict[int, Pod] | None,
        now: float,
        force: bool,
        pre_tainted: bool = False,
        defer_rollback: bool = False,
        out_results: list[DeletionResult] | None = None,
    ) -> list[DeletionResult]:
        """`out_results`, when given, receives each DeletionResult AS IT
        COMPLETES (appends are atomic under the GIL) — the detached worker
        passes a shared list so partially-finished work survives an
        unexpected crash of the remainder."""
        empty = [r for r in to_remove if r.is_empty]
        drain = [r for r in to_remove if not r.is_empty]

        if not pre_tainted:
            for r in to_remove:
                if self.options.cordon_node_before_terminating:
                    # reference: --cordon-node-before-terminating marks the
                    # node unschedulable before the taint lands
                    r.node.unschedulable = True
                self.taint_to_be_deleted(r.node)
                self.tracker.start(r.node.name, now, drain=not r.is_empty)

        def evict_daemonsets(r: NodeToRemove) -> None:
            """--daemonset-eviction-for-{empty,occupied}-nodes."""
            enabled = (self.options.daemonset_eviction_for_empty_nodes
                       if r.is_empty
                       else self.options.daemonset_eviction_for_occupied_nodes)
            if not enabled or not self.eviction_sink or not pods_by_slot:
                return
            for s in r.ds_to_evict:
                pod = pods_by_slot.get(s)
                if pod is not None:
                    try:  # DS eviction is best-effort (drain.go:106)
                        self._evict_once(pod, r.node, self._grace_for(pod))
                    except Exception:  # noqa: BLE001
                        pass

        results: list[DeletionResult] = \
            out_results if out_results is not None else []
        # empty nodes: batched per group (reference: delete_in_batch.go)
        by_group: dict[str, list[NodeToRemove]] = {}
        for r in empty:
            g = self.provider.node_group_for_node(r.node)
            if g is None:
                self.tracker.finish(r.node.name, False, "NoNodeGroup")
                # a terminal result for every started node — the detached
                # path's deferred bookkeeping/rollback depends on it
                results.append(DeletionResult(r.node.name, False, "NoNodeGroup"))
                continue
            by_group.setdefault(g.id(), []).append(r)
        for gid, rs in by_group.items():
            g = next(x for x in self.provider.node_groups() if x.id() == gid)
            # chunked so one cloud call never exceeds the bulk limit, but every
            # tainted node gets a terminal result (no tainted zombies)
            step = max(self.options.max_empty_bulk_delete, 1)
            for start in range(0, len(rs), step):
                batch = rs[start:start + step]
                try:
                    for r in batch:
                        evict_daemonsets(r)
                    if force:
                        g.force_delete_nodes([r.node for r in batch])
                    else:
                        g.delete_nodes([r.node for r in batch])
                    for r in batch:
                        # append in the same breath as finish: once the
                        # tracker says "not deleting", the detached crash
                        # handler will NOT synthesize a result, so anything
                        # raised between the two (latency observer, a later
                        # batch member) must not lose this one
                        self.tracker.finish(r.node.name, True)
                        results.append(DeletionResult(r.node.name, True))
                        if self.latency_tracker is not None:
                            self.latency_tracker.observe_deletion(r.node.name, now)
                except NodeGroupError as e:
                    for r in batch:
                        if not defer_rollback:
                            self._rollback_node(r.node)
                        self.tracker.finish(r.node.name, False, str(e))
                        results.append(DeletionResult(r.node.name, False, str(e)))

        # drain nodes: parallel per node under the drain budget; each
        # worker appends its result in the same breath as tracker.finish —
        # an exception AFTER finish (latency observer) must not strand a
        # node the crash handler no longer sees as in flight
        def drain_one(r: NodeToRemove) -> DeletionResult:
            try:
                if self.eviction_sink and pods_by_slot:
                    victims = [pods_by_slot[s] for s in r.pods_to_move
                               if s in pods_by_slot]
                    if self.pdb_tracker is not None and not force:
                        # last-moment atomic PDB gate (reference: drain.go
                        # re-checks budgets at eviction time, not just plan
                        # time); atomic because drains run in worker threads.
                        # Forced deletion bypasses PDBs (StartForceDeletion).
                        if not self.pdb_tracker.try_remove_pods(victims):
                            raise NodeGroupError("PDB budget exhausted")
                    # per-NODE retry window shared by every pod eviction of
                    # the node (reference: drain.go:185 — retryUntil is
                    # computed once per node and all pod-eviction goroutines
                    # run against it). This also bounds the worst-case stall
                    # of a synchronous drain at max-pod-eviction-time per
                    # NODE, not per pod (r4 advisor): a persistently failing
                    # sink costs one window, later pods fail fast and the
                    # drain rolls back to retry next loop.
                    retry_until = self.clock() + \
                        self.options.max_pod_eviction_time_s
                    for pod in priority_eviction_order(victims):
                        self._evict_with_retry(pod, r.node, retry_until,
                                               force=force)
                        # planner anticipation feed (reference:
                        # RegisterEviction per evicted pod, drain.go).
                        # Stamped at EVICTION time — detached drains may run
                        # long after dispatch `now` — in the walltime domain
                        # the control loop prunes with
                        self.tracker.register_eviction(pod, self.walltime())
                    self._wait_pods_gone(r.node, victims)
                    from kubernetes_autoscaler_tpu.metrics.metrics import (
                        default_registry,
                    )

                    default_registry.counter("evicted_pods_total").inc(len(victims))
                evict_daemonsets(r)
                g = self.provider.node_group_for_node(r.node)
                if g is None:
                    raise NodeGroupError("no node group")
                if force:
                    g.force_delete_nodes([r.node])
                else:
                    g.delete_nodes([r.node])
                self.tracker.finish(r.node.name, True)
                res = DeletionResult(r.node.name, True)
                results.append(res)
                if self.latency_tracker is not None:
                    self.latency_tracker.observe_deletion(r.node.name, now)
                return res
            except NodeGroupError as e:
                if not defer_rollback:
                    self._rollback_node(r.node)
                self.tracker.finish(r.node.name, False, str(e))
                res = DeletionResult(r.node.name, False, str(e))
                results.append(res)
                return res

        workers = max(self.options.max_drain_parallelism, 1)
        if drain:
            with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as ex:
                list(ex.map(drain_one, drain))  # results append as they land
        return results
