"""Scale-down actuator: taint, drain, delete.

Reference counterpart: core/scaledown/actuation/ — StartDeletion
(actuator.go): apply the ToBeDeleted taint, evict pods with per-node goroutine
parallelism under budgets (budgets.go, --max-scale-down-parallelism /
--max-drain-parallelism), batch empty-node deletions per group
(delete_in_batch.go), and track in-flight deletions
(deletiontracker/nodedeletiontracker.go).

Eviction here goes through the EvictionSink seam (the kube API in the
reference; the fake cluster in tests; the sidecar's control plane in
deployment) so the actuator logic is transport-independent.
"""

from __future__ import annotations

import concurrent.futures
import time
from dataclasses import dataclass, field
from typing import Callable, Protocol

from kubernetes_autoscaler_tpu.cloudprovider.provider import (
    CloudProvider,
    NodeGroupError,
)
from kubernetes_autoscaler_tpu.config.options import AutoscalingOptions
from kubernetes_autoscaler_tpu.core.scaledown.planner import NodeToRemove
from kubernetes_autoscaler_tpu.models.api import (
    DELETION_CANDIDATE_TAINT,
    TO_BE_DELETED_TAINT,
    Node,
    Pod,
    Taint,
)


class EvictionSink(Protocol):
    """Where evictions land (reference: the eviction API in actuation/drain.go)."""

    def evict(self, pod: Pod, node: Node) -> None: ...


@dataclass
class DeletionResult:
    node: str
    ok: bool
    reason: str = ""


def priority_eviction_order(pods: list[Pod]) -> list[Pod]:
    """reference: actuation/priority.go priority evictor — evict in ascending
    pod-priority tiers so high-priority pods keep running until lower tiers
    have been asked to leave (the reference additionally waits between tiers;
    here tier completion is the sink's concern — evictions are issued in tier
    order)."""
    return sorted(pods, key=lambda p: p.priority)


@dataclass
class NodeDeletionTracker:
    """reference: deletiontracker/nodedeletiontracker.go — in-flight registry."""

    deleting: dict[str, float] = field(default_factory=dict)
    results: list[DeletionResult] = field(default_factory=list)

    def start(self, node: str, now: float) -> None:
        self.deleting[node] = now

    def finish(self, node: str, ok: bool, reason: str = "") -> None:
        self.deleting.pop(node, None)
        self.results.append(DeletionResult(node, ok, reason))

    def in_flight(self) -> int:
        return len(self.deleting)


class Actuator:
    def __init__(
        self,
        provider: CloudProvider,
        options: AutoscalingOptions,
        eviction_sink: EvictionSink | None = None,
        on_taint: Callable[[Node, str], None] | None = None,
        pdb_tracker=None,
        latency_tracker=None,
    ):
        self.provider = provider
        self.options = options
        self.eviction_sink = eviction_sink
        self.on_taint = on_taint
        self.tracker = NodeDeletionTracker()
        self.pdb_tracker = pdb_tracker          # core/scaledown/pdb.RemainingPdbTracker
        self.latency_tracker = latency_tracker  # core/scaledown/latencytracker

    # ---- taints (reference: utils/taints/taints.go) ----

    def taint_to_be_deleted(self, node: Node) -> None:
        if all(t.key != TO_BE_DELETED_TAINT for t in node.taints):
            node.taints.append(Taint(TO_BE_DELETED_TAINT, str(int(time.time())),
                                     "NoSchedule"))
        if self.on_taint:
            self.on_taint(node, TO_BE_DELETED_TAINT)

    def taint_deletion_candidate(self, node: Node, since: float | None = None) -> None:
        """Soft taint marking scale-down intent — the crash-recovery WAL
        (reference: softtaint.go + planner LoadFromExistingTaints). The taint
        value records when the node's unneeded CLOCK started, so a restarted
        process resumes the clock rather than restarting it."""
        if all(t.key != DELETION_CANDIDATE_TAINT for t in node.taints):
            node.taints.append(Taint(DELETION_CANDIDATE_TAINT,
                                     str(int(since if since is not None
                                             else time.time())),
                                     "PreferNoSchedule"))
        if self.on_taint:
            self.on_taint(node, DELETION_CANDIDATE_TAINT)

    def untaint(self, node: Node, key: str) -> None:
        node.taints = [t for t in node.taints if t.key != key]

    def _rollback_node(self, node: Node) -> None:
        """Failed deletion: remove the hard taint AND undo the cordon
        (reference: CleanToBeDeleted un-cordons on rollback when
        --cordon-node-before-terminating is set) so capacity is not lost."""
        self.untaint(node, TO_BE_DELETED_TAINT)
        if self.options.cordon_node_before_terminating:
            node.unschedulable = False

    # ---- deletion (reference: StartDeletion, actuator.go) ----

    def start_deletion(
        self,
        to_remove: list[NodeToRemove],
        pods_by_slot: dict[int, Pod] | None = None,
        now: float | None = None,
    ) -> list[DeletionResult]:
        now = time.time() if now is None else now
        empty = [r for r in to_remove if r.is_empty]
        drain = [r for r in to_remove if not r.is_empty]

        for r in to_remove:
            if self.options.cordon_node_before_terminating:
                # reference: --cordon-node-before-terminating marks the node
                # unschedulable before the taint lands
                r.node.unschedulable = True
            self.taint_to_be_deleted(r.node)
            self.tracker.start(r.node.name, now)

        def evict_daemonsets(r: NodeToRemove) -> None:
            """--daemonset-eviction-for-{empty,occupied}-nodes."""
            enabled = (self.options.daemonset_eviction_for_empty_nodes
                       if r.is_empty
                       else self.options.daemonset_eviction_for_occupied_nodes)
            if not enabled or not self.eviction_sink or not pods_by_slot:
                return
            for s in r.ds_to_evict:
                pod = pods_by_slot.get(s)
                if pod is not None:
                    self.eviction_sink.evict(pod, r.node)

        results: list[DeletionResult] = []
        # empty nodes: batched per group (reference: delete_in_batch.go)
        by_group: dict[str, list[NodeToRemove]] = {}
        for r in empty:
            g = self.provider.node_group_for_node(r.node)
            if g is None:
                self.tracker.finish(r.node.name, False, "NoNodeGroup")
                continue
            by_group.setdefault(g.id(), []).append(r)
        for gid, rs in by_group.items():
            g = next(x for x in self.provider.node_groups() if x.id() == gid)
            # chunked so one cloud call never exceeds the bulk limit, but every
            # tainted node gets a terminal result (no tainted zombies)
            step = max(self.options.max_empty_bulk_delete, 1)
            for start in range(0, len(rs), step):
                batch = rs[start:start + step]
                try:
                    for r in batch:
                        evict_daemonsets(r)
                    g.delete_nodes([r.node for r in batch])
                    for r in batch:
                        self.tracker.finish(r.node.name, True)
                        if self.latency_tracker is not None:
                            self.latency_tracker.observe_deletion(r.node.name, now)
                        results.append(DeletionResult(r.node.name, True))
                except NodeGroupError as e:
                    for r in batch:
                        self._rollback_node(r.node)
                        self.tracker.finish(r.node.name, False, str(e))
                        results.append(DeletionResult(r.node.name, False, str(e)))

        # drain nodes: parallel per node under the drain budget
        def drain_one(r: NodeToRemove) -> DeletionResult:
            try:
                if self.eviction_sink and pods_by_slot:
                    victims = [pods_by_slot[s] for s in r.pods_to_move
                               if s in pods_by_slot]
                    if self.pdb_tracker is not None:
                        # last-moment atomic PDB gate (reference: drain.go
                        # re-checks budgets at eviction time, not just plan
                        # time); atomic because drains run in worker threads
                        if not self.pdb_tracker.try_remove_pods(victims):
                            raise NodeGroupError("PDB budget exhausted")
                    for pod in priority_eviction_order(victims):
                        self.eviction_sink.evict(pod, r.node)
                    from kubernetes_autoscaler_tpu.metrics.metrics import (
                        default_registry,
                    )

                    default_registry.counter("evicted_pods_total").inc(len(victims))
                evict_daemonsets(r)
                g = self.provider.node_group_for_node(r.node)
                if g is None:
                    raise NodeGroupError("no node group")
                g.delete_nodes([r.node])
                self.tracker.finish(r.node.name, True)
                if self.latency_tracker is not None:
                    self.latency_tracker.observe_deletion(r.node.name, now)
                return DeletionResult(r.node.name, True)
            except NodeGroupError as e:
                self._rollback_node(r.node)
                self.tracker.finish(r.node.name, False, str(e))
                return DeletionResult(r.node.name, False, str(e))

        workers = max(self.options.max_drain_parallelism, 1)
        if drain:
            with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as ex:
                results.extend(ex.map(drain_one, drain))
        return results
