"""Node-removal latency tracking.

Reference counterpart: core/scaledown/latencytracker/ — measures the wall time
from a node first becoming a confirmed scale-down candidate (unneeded and past
its unneeded-time) to its deletion completing, feeding the
`scaled_down_duration` style metrics (SURVEY.md §2.2 trackers row).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class NodeLatencyTracker:
    started: dict[str, float] = field(default_factory=dict)
    observed: list[tuple[str, float]] = field(default_factory=list)

    def observe_candidates(self, nodes: list[str], now: float) -> None:
        """Start clocks for new candidates; stop clocks for nodes that left the
        candidate set without being deleted (they became needed again)."""
        current = set(nodes)
        for n in list(self.started):
            if n not in current:
                del self.started[n]
        for n in current:
            self.started.setdefault(n, now)

    def observe_deletion(self, node: str, now: float) -> float | None:
        t = self.started.pop(node, None)
        if t is None:
            return None
        latency = now - t
        self.observed.append((node, latency))
        from kubernetes_autoscaler_tpu.metrics.metrics import default_registry

        default_registry.histogram("node_removal_latency_seconds").observe(latency)
        return latency
