"""ctypes binding for the native confirmation pass (kaconfirm.cc in
libkacodec.so) + the planner-facing wrapper.

The native kernel covers the common case AND the constrained tier (zone- and
host-kind topology spread, host/zone required anti-affinity AND required
pod affinity incl. the first-pod exception — round-4 verdict item 4);
`core/scaledown/planner.py` keeps the Python pass as the general fallback
(lossy encodings, host ports, atomic groups, injected phantoms) and
`tests/test_native_confirm.py` + `tests/test_native_constrained.py`
property-test the two against each other.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from dataclasses import dataclass

import numpy as np

_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "sidecar")
_LIB_PATH = os.path.join(_DIR, "libkacodec.so")
_lib = None
_available: bool | None = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        subprocess.run(["make", "-C", _DIR, "-s"], check=True)
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        # a binary built by a different toolchain (e.g. newer libstdc++)
        # fails to load — rebuild once with the local compiler rather than
        # silently abandoning the native tier
        subprocess.run(["make", "-C", _DIR, "-s", "-B"], check=True)
        lib = ctypes.CDLL(_LIB_PATH)
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.ka_confirm_c.restype = ctypes.c_int
    lib.ka_confirm_c.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        i64p, u8p, u8p, i32p,
        ctypes.c_int, i32p, i32p, i32p, i32p, i32p,
        ctypes.c_int, i32p,
        ctypes.c_void_p, ctypes.c_void_p, i64p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
        # constrained tier (20 pointer args after n_zones)
        ctypes.c_int,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        u8p, u8p, i32p,
    ]
    _lib = lib
    return lib


def available() -> bool:
    global _available
    if _available is None:
        try:
            _load()
            _available = True
        except Exception:
            _available = False
    return _available


@dataclass
class ConstraintBlock:
    """Constrained-tier inputs (see kaconfirm.cc ConState). All arrays are
    C-contiguous; count planes are MUTATED by the kernel."""

    n_zones: int
    zone_id: np.ndarray          # i32[N]
    spread_kind: np.ndarray      # u8[G] (0 none, 1 host, 2 zone)
    max_skew: np.ndarray         # i32[G]
    spread_self: np.ndarray      # u8[G]
    has_anti_host: np.ndarray    # u8[G]
    has_anti_zone: np.ndarray    # u8[G]
    aff_kind: np.ndarray         # u8[G] (0 none, 1 host, 2 zone)
    aff_self: np.ndarray         # u8[G]
    one_per_node: np.ndarray     # u8[G] limit_g (anti-self | host ports)
    oracle_moved: np.ndarray     # u8[G] = need_exact (python oracle-moves)
    elig: np.ndarray             # u8[G, N]
    cnt_node: np.ndarray         # i32[G, N]
    anti_host_node: np.ndarray   # i32[G, N]
    anti_zone_node: np.ndarray   # i32[G, N]
    aff_node: np.ndarray         # i32[G, N]
    m_spread: np.ndarray         # u8[G, G]
    m_anti_h: np.ndarray         # u8[G, G]
    m_anti_z: np.ndarray         # u8[G, G]
    m_aff: np.ndarray            # u8[G, G]
    con_path: np.ndarray         # u8[G]


def _vp(a):
    return a.ctypes.data_as(ctypes.c_void_p)


def confirm(
    free: np.ndarray,            # i64[N, R] — mutated
    feas: np.ndarray,            # bool[G, N]
    node_valid: np.ndarray,      # bool[N]
    greq: np.ndarray,            # i32[G, R]
    cand_node: np.ndarray,       # i32[C]
    slot_ids: np.ndarray,        # i32[total]
    slot_group: np.ndarray,      # i32[total]
    slot_off: np.ndarray,        # i32[C+1]
    cand_group_idx: np.ndarray,  # i32[C]
    group_room: np.ndarray,      # i32[n_room] — mutated
    quota_totals: np.ndarray | None,  # i64[R] — mutated
    quota_min: np.ndarray | None,     # i64[R]
    node_cap: np.ndarray,        # i64[N, R]
    empty_budget: int, drain_budget: int, total_budget: int,
    max_slot_id: int,
    slot_pdb_mask: np.ndarray | None = None,   # u64[max_slot_id+1, words]
    pdb_remaining: np.ndarray | None = None,   # i64[n_pdbs] — mutated
    con: ConstraintBlock | None = None,
):
    """Run the native pass; returns (accept u8[C], reason u8[C], dest i32[S]).
    Reasons: 0 ok, 1 no-place, 2 group-room, 3 quota, 4 budget, 5 pdb."""
    lib = _load()
    n, r = free.shape
    g = feas.shape[0]
    c = cand_node.shape[0]
    accept = np.zeros((c,), np.uint8)
    reason = np.zeros((c,), np.uint8)
    dest = np.full((max_slot_id + 1,), -1, np.int32)
    qt = (quota_totals.ctypes.data_as(ctypes.c_void_p)
          if quota_totals is not None else None)
    qm = (quota_min.ctypes.data_as(ctypes.c_void_p)
          if quota_min is not None else None)
    n_pdbs = int(pdb_remaining.shape[0]) if pdb_remaining is not None else 0
    pdb_words = (n_pdbs + 63) // 64
    sp_arr = None
    if n_pdbs > 0:
        sp_arr = np.ascontiguousarray(slot_pdb_mask, np.uint64)
        if sp_arr.ndim == 1:       # single-word legacy layout
            sp_arr = sp_arr[:, None]
        if sp_arr.shape[1] != pdb_words:
            # a mis-strided mask would read out-of-bounds rows natively —
            # fail fast even under python -O
            raise ValueError(
                f"slot_pdb_mask has {sp_arr.shape[1]} words, "
                f"{pdb_words} needed for {n_pdbs} budgets")
    sp = (sp_arr.ctypes.data_as(ctypes.c_void_p)
          if n_pdbs > 0 else None)
    pr = (pdb_remaining.ctypes.data_as(ctypes.c_void_p)
          if n_pdbs > 0 else None)
    if con is not None:
        con_args = [
            int(con.n_zones), _vp(con.zone_id), _vp(con.spread_kind),
            _vp(con.max_skew), _vp(con.spread_self), _vp(con.has_anti_host),
            _vp(con.has_anti_zone), _vp(con.aff_kind), _vp(con.aff_self),
            _vp(con.one_per_node), _vp(con.oracle_moved),
            _vp(con.elig), _vp(con.cnt_node),
            _vp(con.anti_host_node), _vp(con.anti_zone_node),
            _vp(con.aff_node),
            _vp(con.m_spread), _vp(con.m_anti_h), _vp(con.m_anti_z),
            _vp(con.m_aff), _vp(con.con_path),
        ]
    else:
        con_args = [0] + [None] * 20
    rc = lib.ka_confirm_c(
        n, r, g,
        np.ascontiguousarray(free),
        np.ascontiguousarray(feas.astype(np.uint8)),
        np.ascontiguousarray(node_valid.astype(np.uint8)),
        np.ascontiguousarray(greq.astype(np.int32)),
        c,
        np.ascontiguousarray(cand_node.astype(np.int32)),
        np.ascontiguousarray(slot_ids.astype(np.int32)),
        np.ascontiguousarray(slot_group.astype(np.int32)),
        np.ascontiguousarray(slot_off.astype(np.int32)),
        np.ascontiguousarray(cand_group_idx.astype(np.int32)),
        int(group_room.shape[0]),
        group_room,
        qt, qm,
        np.ascontiguousarray(node_cap.astype(np.int64)),
        int(empty_budget), int(drain_budget), int(total_budget),
        n_pdbs, pdb_words, sp, pr,
        *con_args,
        accept, reason, dest,
    )
    if rc < 0:
        raise RuntimeError("ka_confirm rejected its arguments")
    return accept, reason, dest
