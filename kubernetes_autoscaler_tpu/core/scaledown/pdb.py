"""PodDisruptionBudget tracking shared between scale-down planner and actuator.

Reference counterpart: core/scaledown/pdb/ (`RemainingPdbTracker`, basic impl)
— the planner asks whether a candidate node's pods can all be disrupted within
the remaining PDB budgets, and each confirmed removal deducts from those
budgets so two drains in the same loop never overdraw one PDB
(SURVEY.md §2.2 "Deletion tracker / latency tracker / PDB tracker" row).

The PDB object itself is a minimal structural analog of policy/v1
PodDisruptionBudget: a namespaced label selector plus the current
`status.disruptionsAllowed` count.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.models.api import Pod


@dataclass
class PodDisruptionBudget:
    name: str
    namespace: str = "default"
    match_labels: dict[str, str] = field(default_factory=dict)
    disruptions_allowed: int = 0

    def matches(self, pod: Pod) -> bool:
        if pod.namespace != self.namespace:
            return False
        return all(pod.labels.get(k) == v for k, v in self.match_labels.items())


class RemainingPdbTracker:
    """reference: pdb.NewBasicRemainingPdbTracker — per-loop remaining budgets.

    `SetPdbs` resets at loop start (planner.go builds it from the PDB lister);
    `CanRemovePods` is the planner-side query; `RemovePods` is the deduction
    applied once a removal is confirmed.
    """

    def __init__(self, pdbs: list[PodDisruptionBudget] | None = None):
        self._pdbs: list[PodDisruptionBudget] = []
        self._remaining: list[int] = []
        # guards check+deduct: the actuator drains nodes from worker threads
        # and all of them share this tracker
        self._lock = threading.Lock()
        if pdbs:
            self.set_pdbs(pdbs)

    def set_pdbs(self, pdbs: list[PodDisruptionBudget]) -> None:
        with self._lock:
            self._pdbs = list(pdbs)
            self._remaining = [p.disruptions_allowed for p in pdbs]

    def get_pdbs(self) -> list[PodDisruptionBudget]:
        return list(self._pdbs)

    def remaining_snapshot(self) -> list[int]:
        """The LIVE remaining budgets (deductions by concurrent actuator
        drains included) — what any planning pass must gate against."""
        with self._lock:
            return list(self._remaining)

    def matching_pdbs(self, pod: Pod) -> list[int]:
        return [i for i, p in enumerate(self._pdbs) if p.matches(pod)]

    def has_pdb(self, pod: Pod) -> bool:
        return bool(self.matching_pdbs(pod))

    def reservation(self, pods: list[Pod]) -> dict[int, int]:
        """Per-PDB eviction counts `pods` would consume."""
        need: dict[int, int] = {}
        for pod in pods:
            for i in self.matching_pdbs(pod):
                need[i] = need.get(i, 0) + 1
        return need

    def can_remove_pods(self, pods: list[Pod],
                        already_reserved: dict[int, int] | None = None) -> bool:
        """True iff evicting all `pods` stays within every matching budget
        (reference: CanRemovePods returns inParallel + blocking pod info; the
        blocking detail surfaces via `first_blocker` for events).
        `already_reserved` lets a planning pass account for candidates it has
        confirmed earlier in the same loop without mutating the shared state."""
        need = self.reservation(pods)
        reserved = already_reserved or {}
        with self._lock:
            return all(
                self._remaining[i] - reserved.get(i, 0) >= n
                for i, n in need.items()
            )

    def try_remove_pods(self, pods: list[Pod]) -> bool:
        """Atomic check+deduct — the actuator's eviction-time gate. Returns
        False (and deducts nothing) if any budget would overdraw."""
        need = self.reservation(pods)
        with self._lock:
            if any(self._remaining[i] < n for i, n in need.items()):
                return False
            for i, n in need.items():
                self._remaining[i] -= n
            return True

    def first_blocker(self, pods: list[Pod]) -> Pod | None:
        need: dict[int, int] = {}
        for pod in pods:
            for i in self.matching_pdbs(pod):
                need[i] = need.get(i, 0) + 1
                if need[i] > self._remaining[i]:
                    return pod
        return None

    def remove_pods(self, pods: list[Pod]) -> None:
        need = self.reservation(pods)
        with self._lock:
            for i, n in need.items():
                self._remaining[i] -= n

    def remaining(self, pdb_name: str, namespace: str = "default") -> int:
        with self._lock:
            for i, p in enumerate(self._pdbs):
                if p.name == pdb_name and p.namespace == namespace:
                    return self._remaining[i]
        raise KeyError(f"{namespace}/{pdb_name}")

    def namespaced_names_with_pdb(self, pods: list[Pod]) -> frozenset[str]:
        """Feed for the drainability `system` rule (kube-system pods WITH a PDB
        are evictable; simulator/drainability/rules/system)."""
        return frozenset(
            f"{p.namespace}/{p.name}" for p in pods if self.has_pdb(p)
        )
