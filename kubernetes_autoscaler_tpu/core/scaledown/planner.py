"""Scale-down planner: decide which nodes are unneeded and ready to remove.

Reference counterpart: core/scaledown/planner/planner.go —
UpdateClusterState (:120): eligibility screening (eligibility/eligibility.go,
utilization thresholds), per-node removal simulation (bounded by
unneededNodesLimit :385 and a wall-clock timeout :297), unneeded-time accrual,
then NodesToDelete (:151) selecting empty + drainable nodes under quota and
min-size constraints.

TPU re-design: the entire candidate sweep — utilization, eligibility, and the
drain simulation for EVERY candidate — is one device program
(ops/autoscale_step.scale_down_sim); no candidate caps or timeouts are needed.
The greedy confirmation pass over per-candidate results (the role of the
reference's commit-on-success sequencing, simulator/cluster.go:174-188) then
runs natively in C++ for the common case (sidecar/native/kaconfirm.cc;
milliseconds at 5k nodes / 50k pods) with a plan-identical Python fallback
when PDBs, exact-oracle groups, or atomic node groups need per-move host
decisions.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_autoscaler_tpu.cloudprovider.provider import CloudProvider
from kubernetes_autoscaler_tpu.clusterstate.registry import _ng_defaults
from kubernetes_autoscaler_tpu.config.options import AutoscalingOptions
from kubernetes_autoscaler_tpu.core.scaledown.unneeded import (
    UnneededNodes,
    UnremovableNodes,
)
from kubernetes_autoscaler_tpu.metrics.phases import PhaseStats
from kubernetes_autoscaler_tpu.models.api import SCALE_DOWN_DISABLED_KEY, Node
from kubernetes_autoscaler_tpu.models.encode import EncodedCluster
from kubernetes_autoscaler_tpu.ops import utilization as util_ops
from kubernetes_autoscaler_tpu.ops.drain import (
    RemovalResult,
    fetch_result,
    simulate_removals,
)
from kubernetes_autoscaler_tpu.ops.hostfetch import (
    fetch_pytree,
    fetch_pytree_async,
)
from kubernetes_autoscaler_tpu.resourcequotas.tracker import QuotaTracker


# post-placement device state: NEVER mirror-served, always fetched
_ALWAYS_FETCH = ("nodes.alloc", "specs.count")


@dataclass
class FusedScaleDown:
    """Scale-down inputs harvested from the fused RunOnce decision fetch
    (docs/FUSED_LOOP.md): the post-placement utilization vector (host) and
    the device-resident all-nodes drain sweep. `Planner.update` consumes
    these instead of dispatching its own utilization + simulate_removals
    programs; the candidate SUBSET verdict the confirmation pass needs is
    gathered from `removal_dev` rows and fetched in one transfer — the
    loop's second (and last) device round trip."""

    util: np.ndarray      # f32[N] raw node_utilization of the fused world
    removal_dev: object   # RemovalResult (device), C == N, candidate i=row i


def _mirror_hit(enc: "EncodedCluster", key: str, dev) -> bool:
    """One definition of the mirror-substitution contract, shared by
    `_hostarr` and the batched `Planner._fetch_host`: the mirror stands in
    ONLY while `dev` is still the exact handed-out array (token identity),
    and post-placement fields are excluded outright."""
    h = enc.host_arrays
    tok = enc.host_mirror_token
    return (key not in _ALWAYS_FETCH and h is not None and tok is not None
            and key in h and tok.get(key) is dev)


def _hostarr(enc: "EncodedCluster", key: str, dev) -> np.ndarray:
    """Prefer the incremental encoder's host mirror — reading the device
    array costs a device→host round trip per call (~70 ms over the TPU
    tunnel). The mirror substitutes ONLY while `dev` is still the exact
    handed-out array (host_mirror_token identity check): the loop REPLACES
    tensors (placement charging, upcoming-node injection, drainability) and
    the mirrors do not follow those replacements. nodes.alloc/specs.count
    are additionally excluded outright — post-placement state by design."""
    assert key not in _ALWAYS_FETCH
    if _mirror_hit(enc, key, dev):
        return np.asarray(enc.host_arrays[key])
    return np.asarray(dev)


class _HostFetchHandle:
    """Resolved mirror hits + an optional in-flight AsyncFetch for the
    misses; `.get()` merges both (idempotent, closes the async span). The
    blocking remainder of the harvest is timed into the owner's `fetch`
    phase totals via PhaseStats.observe — the async span on the trace
    already covers the full issue→harvest window, so no new span opens."""

    __slots__ = ("_hits", "_async", "_phases")

    def __init__(self, hits: dict, async_fetch, phases=None):
        self._hits = hits
        self._async = async_fetch
        self._phases = phases

    def get(self) -> dict:
        if self._async is not None:
            t0 = time.perf_counter()
            self._hits.update(self._async.get())
            if self._phases is not None:
                self._phases.observe("fetch", time.perf_counter() - t0)
            self._async = None
        return dict(self._hits)


@dataclass
class NodeToRemove:
    node: Node
    is_empty: bool
    pods_to_move: list[int] = field(default_factory=list)   # scheduled-pod slots
    destinations: dict[int, int] = field(default_factory=dict)  # slot -> node idx
    ds_to_evict: list[int] = field(default_factory=list)    # daemonset pod slots
    # (reference: --daemonset-eviction-for-{empty,occupied}-nodes consumes
    # these in the actuator)


@dataclass
class PlannerState:
    unneeded: list[str] = field(default_factory=list)
    utilization: dict[str, float] = field(default_factory=dict)
    removal: RemovalResult | None = None
    candidate_indices: np.ndarray | None = None
    # recently-evicted-pod anticipation (reference: injectRecentlyEvictedPods)
    evictions_injected: int = 0
    evictions_uninjectable: int = 0
    injected_pods: list = field(default_factory=list)   # placed copies
    # injection-prefilter observability: nodes that survived the dense
    # prefilter (summed over pods) and nodes the exact oracle actually ran
    # predicates on — the planner contract is oracle_nodes <= survivors
    evictions_prefilter_survivors: int = 0
    evictions_oracle_nodes: int = 0
    # reason plane: per-failed-candidate drain failure detail from the lazy
    # ops/drain.failure_reasons pass (node name → human-readable attribution
    # like "no destination has room for pod group 3 (req cpu=1500m …)");
    # rides events, /snapshotz and the flight-recorder span attrs
    drain_fail_detail: dict[str, str] = field(default_factory=dict)


@dataclass
class _MarshalArtifacts:
    """Composition-keyed marshalling state for the native constrained tier,
    reused across RunOnce iterations (the scale-down analog of
    orchestrator._group_tensor_cache). Everything here depends only on group
    COMPOSITION — which equivalence rows exist and their exemplars'
    constraint content — never on pod counts or placements, so it survives
    count-only churn untouched. The native kernel reads all of these as
    const (kaconfirm.cc ConState); the count planes it mutates are copied
    per call by the caller."""

    fp: tuple
    g_total: int
    spread_kind: np.ndarray      # u8[G]
    max_skew: np.ndarray         # i32[G]
    spread_self: np.ndarray      # u8[G]
    aff_kind: np.ndarray         # u8[G]
    aff_self: np.ndarray         # u8[G]
    has_anti_host: np.ndarray    # u8[G]
    has_anti_zone: np.ndarray    # u8[G]
    m_spread: np.ndarray         # u8[G, G]
    m_anti_h: np.ndarray         # u8[G, G]
    m_anti_z: np.ndarray         # u8[G, G]
    m_aff: np.ndarray            # u8[G, G]
    # groups whose constraints exceed the native tier's model; the pass must
    # fall back to Python when any of them is actually routed this call
    model_bad: np.ndarray        # bool[G]


class Planner:
    def __init__(self, provider: CloudProvider, options: AutoscalingOptions,
                 quota: QuotaTracker | None = None,
                 pdb_tracker=None, latency_tracker=None):
        self.provider = provider
        self.options = options
        self.quota = quota
        self.unneeded_nodes = UnneededNodes()
        self.unremovable = UnremovableNodes(
            ttl_s=options.unremovable_node_recheck_timeout_s)
        self.state = PlannerState()
        self.pdb_tracker = pdb_tracker          # shared with the actuator
        self.latency_tracker = latency_tracker
        # reason plane: NoScaleDown event sink (events.EventSink, wired by
        # StaticAutoscaler) — every _mark() verdict is also an event
        self.event_sink = None
        # per-phase host-path accounting (metrics/phases.py); the autoscaler
        # attaches its Registry so the breakdown rides /metrics too
        self.phases = PhaseStats(owner="planner")
        # dense prefilter for evicted-pod injection (tests flip this off to
        # property-check plan equality against the unfiltered scan)
        self.inject_prefilter = True
        # constrained-tier marshal cache + the cached eligibility plane
        self._marshal_cache: _MarshalArtifacts | None = None
        self._elig_cache: tuple | None = None   # (key arrays, elig u8[G, N])
        # composition-fingerprint memo (utils/canonical.IdentityMemo): the
        # marshal-cache key walks every exemplar's constraint spec each
        # loop; memoizing per-object identity makes the fingerprint itself
        # O(churn) — the WorldStore discipline extended to this encode-path
        # cache (docs/WORLD_STORE.md)
        from kubernetes_autoscaler_tpu.utils.canonical import IdentityMemo

        self._exemplar_sig_memo = IdentityMemo(self._exemplar_sig)
        self.marshal_cache_hits = 0
        self.marshal_cache_misses = 0
        self.elig_cache_hits = 0
        self.elig_cache_misses = 0
        # occupancy-plane prefetch heuristic: start optimistic, then track
        # whether the previous loop actually produced eligible candidates
        self._prefetch_occupancy = True
        # per-loop host copies harvested from the fused decision fetch
        # (docs/FUSED_LOOP.md): key → (device array identity, host copy).
        # `nodes.alloc`/`specs.count` are _ALWAYS_FETCH under the mirror
        # contract (post-placement state), but the fused decision already
        # shipped exactly those post-placement planes — seeding them here
        # makes nodes_to_delete's big host view transfer-free. The identity
        # check self-invalidates on the next encode.
        self._fused_host_overrides: dict[str, tuple] = {}

    def seed_fused_overrides(self, items: dict[str, tuple]) -> None:
        self._fused_host_overrides = dict(items)

    def _split_mirror_hits(self, enc: EncodedCluster, items: dict
                           ) -> tuple[dict, dict]:
        """Partition `items` into (mirror hits as host arrays, misses) —
        the ONE definition of which reads are free; both the sync and async
        batched-fetch paths dispatch on it."""
        hits: dict[str, np.ndarray] = {}
        miss: dict[str, object] = {}
        for key, dev in items.items():
            ov = self._fused_host_overrides.get(key)
            if ov is not None and ov[0] is dev:
                hits[key] = ov[1]
            elif _mirror_hit(enc, key, dev):
                hits[key] = np.asarray(enc.host_arrays[key])
            else:
                miss[key] = dev
        return hits, miss

    def _fetch_host(self, enc: EncodedCluster, items: dict) -> dict:
        """Batched `_hostarr`: mirror hits are free; ALL misses ride one
        `fetch_pytree` transfer instead of one device→host round trip each
        (~70 ms per transfer over the TPU tunnel). `items` maps mirror key →
        the device array to fall back to; `nodes.alloc`/`specs.count` are
        always fetched (post-placement state — see the `_hostarr` contract)."""
        out, miss = self._split_mirror_hits(enc, items)
        if miss:
            # one batched device→host transfer for every miss; the counter
            # makes transfer traffic visible on the trace and in the
            # phase_events_total registry series (fetch_pytree additionally
            # bumps the moved/logical byte counters — bool planes ride
            # bit-packed, ops/bitplane)
            self.phases.bump("batched_fetch_transfers")
            with self.phases.phase("fetch", leaves=len(miss)):
                out.update(fetch_pytree(miss, phases=self.phases))
        return out

    def _fetch_host_async(self, enc: EncodedCluster, items: dict):
        """Double-buffered `_fetch_host`: mirror hits resolve immediately,
        ALL misses ride one `fetch_pytree_async` transfer issued NOW and
        harvested via the returned handle's `.get()` — the device→host copy
        overlaps whatever host work runs in between (update() issues this
        before the eligibility screen and harvests after it, so the transfer
        hides under the Python policy loop). The in-flight window is a
        `fetch` span (async=true) on the loop trace. Tradeoff vs the lazy
        conditional fetch: the transfer is issued even when the consumer
        branch ends up not needing it — callers should only prefetch items
        they need on the COMMON path."""
        hits, miss = self._split_mirror_hits(enc, items)
        handle = None
        if miss:
            self.phases.bump("batched_fetch_transfers")
            self.phases.bump("batched_fetch_async")
            handle = fetch_pytree_async(miss, phases=self.phases)
        return _HostFetchHandle(hits, handle, phases=self.phases)

    # ---- evicted-pod anticipation (reference: injectRecentlyEvictedPods,
    # planner.go:230-260) ----

    def _inject_evicted(self, enc: EncodedCluster, nodes: list[Node],
                        pods: list) -> None:
        """Charge recently evicted, not-yet-recreated pods onto the snapshot
        before the drain sweep, so consolidation cannot reclaim the capacity
        their recreation needs. The reference schedules them into the forked
        snapshot via HintingSimulator.TrySchedulePods (ScheduleAnywhere —
        taints keep them off draining nodes, planner.go:296-300); here each
        pod host-places onto the first node that passes the exact-oracle
        predicates with device-true free capacity (cap − alloc, which already
        includes this loop's simulated placements), and the summed charge is
        applied to the node-allocation tensor in one device op. Pods that fit
        nowhere are counted (the reference logs the same condition).

        Perf (ADVICE r5): each pod first narrows its candidates with one
        dense numpy pass — the capacity row (free >= req) plus, for
        non-lossy specs, the selector/taint planes
        (ops/predicates.host_predicate_row) — and the exact oracle runs only
        on the survivors, still in index order, so placements stay
        byte-identical to the unfiltered scan. The prefilter only ever
        DROPS nodes the oracle would reject (capacity/validity literally,
        selector/taints exactly for non-lossy encodings); lossy specs fall
        back to the capacity-only mask. `inject_prefilter=False` keeps the
        unfiltered walk for A/B (tests/test_planner_hostpath.py)."""
        import copy as _copy

        from kubernetes_autoscaler_tpu.models.encode import (
            _encode_pod_spec,
            pod_request_vector,
        )
        from kubernetes_autoscaler_tpu.ops.predicates import host_predicate_row
        from kubernetes_autoscaler_tpu.utils import oracle
        from kubernetes_autoscaler_tpu.utils.oracle_cache import ConfirmOracle

        view = self._fetch_host(enc, {
            "nodes.cap": enc.nodes.cap, "nodes.alloc": enc.nodes.alloc,
            "nodes.valid": enc.nodes.valid, "nodes.ready": enc.nodes.ready,
        })
        cap = view["nodes.cap"].astype(np.int64)
        alloc = view["nodes.alloc"].astype(np.int64)
        free = cap - alloc
        ok_node = view["nodes.valid"] & view["nodes.ready"]
        n_real = len(nodes)
        by_node: dict[str, list] = {}
        for q in enc.scheduled_pods:
            if q is None:
                continue
            by_node.setdefault(q.node_name, []).append(q)
        # constraint checks ride the incremental oracle world (O(domains)
        # per verdict instead of an O(nodes × pods) walk per candidate);
        # capacity stays on the device-true free tensor below, which the
        # world cannot see. The world OWNS by_node from here (moves update
        # both the lists and the domain counts).
        world = ConfirmOracle(list(nodes), by_node, registry=enc.registry,
                              namespaces=enc.namespaces)
        by_node = world.pods_by_node
        delta = np.zeros_like(alloc)
        injected = failed = 0
        placed_pods: list = []
        survivors = oracle_nodes = 0
        label_hash = taint_exact = taint_key = None
        if self.inject_prefilter:
            planes = self._fetch_host(enc, {
                "nodes.label_hash": enc.nodes.label_hash,
                "nodes.taint_exact": enc.nodes.taint_exact,
                "nodes.taint_key": enc.nodes.taint_key,
            })
            label_hash = planes["nodes.label_hash"][:n_real]
            taint_exact = planes["nodes.taint_exact"][:n_real]
            taint_key = planes["nodes.taint_key"][:n_real]
        for pod in pods:
            p = _copy.copy(pod)
            p.node_name = ""                      # ClearPodNodeNames
            req, req_lossy = pod_request_vector(p, enc.registry)
            if self.inject_prefilter:
                mask = ok_node[:n_real] & (free[:n_real] >= req).all(axis=1)
                spec = _encode_pod_spec(p, enc.dims)
                if not (spec.lossy or req_lossy):
                    mask &= host_predicate_row(label_hash, taint_exact,
                                               taint_key, spec)
                cand = [int(i) for i in np.nonzero(mask)[0]]
            else:
                cand = [i for i in range(n_real)
                        if ok_node[i] and (free[i] >= req).all()]
            survivors += len(cand)
            placed = False
            for i in cand:
                nd = nodes[i]
                oracle_nodes += 1
                # predicate-only exact checks (capacity came from the
                # device-true free tensor above, which check_pod_in_cluster's
                # own resource pass cannot see)
                if not oracle.node_schedulable(nd):
                    continue
                if not oracle.selector_matches(p, nd):
                    continue
                if not oracle.taints_tolerated(p, nd):
                    continue
                if not oracle.ports_free(p, by_node.get(nd.name, [])):
                    continue
                if not world.check_constraints(p, nd):
                    continue
                free[i] -= req
                delta[i] += req
                p.node_name = nd.name
                world.move(p, "", nd.name)
                placed = True
                break
            if placed:
                injected += 1
                placed_pods.append(p)
            else:
                failed += 1
        self.state.evictions_prefilter_survivors = survivors
        self.state.evictions_oracle_nodes = oracle_nodes
        self.phases.bump("inject_oracle_nodes", oracle_nodes)
        if injected:
            enc.nodes = enc.nodes.replace(
                alloc=enc.nodes.alloc + jnp.asarray(delta, dtype=enc.nodes.alloc.dtype))
        self.state.evictions_injected = injected
        self.state.evictions_uninjectable = failed
        self.state.injected_pods = placed_pods

    # ---- per-loop state update (reference: UpdateClusterState :120) ----

    def update(self, enc: EncodedCluster, nodes: list[Node],
               now: float | None = None,
               inject_pods: list | None = None,
               precomputed: FusedScaleDown | None = None) -> PlannerState:
        now = time.time() if now is None else now
        self.state.evictions_injected = 0
        self.state.evictions_uninjectable = 0
        self.state.injected_pods = []
        self.state.evictions_prefilter_survivors = 0
        self.state.evictions_oracle_nodes = 0
        self.state.drain_fail_detail = {}
        # eager TTL sweep so the unremovable cache stays bounded by the live
        # node set across loops (expired entries of vanished nodes would
        # otherwise only fall out on a contains() probe that never comes)
        self.unremovable.update(now)
        if inject_pods:
            self._inject_evicted(enc, nodes, inject_pods)
            # evicted-pod injection mutates enc.nodes.alloc AFTER the fused
            # program ran — its utilization/drain outputs describe a world
            # that no longer exists; fall back to phased dispatches (the
            # phased oracle takes the same branch, so decisions still match)
            precomputed = None
        n_real = len(nodes)
        util = self._utilization(
            enc, nodes,
            precomputed_util=None if precomputed is None else precomputed.util)
        defaults = _ng_defaults(self.options)

        # Double buffer: the candidate-pool sort below needs the scheduled-pod
        # occupancy planes; issue their batched fetch NOW so the device→host
        # copy rides under the Python eligibility screen instead of stalling
        # after it (mirror hits make this free; the span on the loop trace
        # shows the overlap window). Gated on last loop's outcome so an IDLE
        # cluster (zero eligible nodes loop after loop) does not pay a
        # speculative transfer for data the branch below never reads — it
        # falls back to the old lazy sync fetch on the loop that first finds
        # candidates, and prefetches again from the next loop on.
        sv_handle = None
        if self._prefetch_occupancy:
            sv_handle = self._fetch_host_async(enc, {
                "scheduled.valid": enc.scheduled.valid,
                "scheduled.node_idx": enc.scheduled.node_idx,
            })

        eligible_idx: list[int] = []
        group_deletable: dict[str, int] = {}
        for i, nd in enumerate(nodes):
            self.state.utilization[nd.name] = float(util[i])
            if nd.annotations.get(SCALE_DOWN_DISABLED_KEY) == "true":
                self._mark(nd.name, "ScaleDownDisabledAnnotation", now)
                continue
            if not nd.ready and not self.options.scale_down_unready_enabled:
                self._mark(nd.name, "ScaleDownUnreadyDisabled", now)
                continue
            g = self.provider.node_group_for_node(nd)
            if g is None:
                self._mark(nd.name, "NotAutoscaled", now)
                continue
            room = group_deletable.setdefault(g.id(), g.target_size() - g.min_size())
            if room <= 0:
                self._mark(nd.name, "NodeGroupMinSizeReached", now)
                continue
            opts = g.get_options(defaults)
            threshold = (opts.scale_down_utilization_threshold
                         or defaults.scale_down_utilization_threshold)
            if nd.ready and util[i] >= threshold:
                # screening reasons are re-evaluated every loop (NOT cached in
                # the TTL registry — a node must become a candidate the moment
                # it idles; the reference's recheck timeout applies only to
                # simulation failures)
                continue
            group_deletable[g.id()] -= 1
            eligible_idx.append(i)

        # Candidate-pool policy (reference: processors/scaledowncandidates —
        # previous candidates sorted first so their unneeded clocks keep
        # running, then empty nodes so cheap deletions come first, pool
        # capped at max(ratio x cluster, min) via
        # --scale-down-candidates-pool-ratio, FAQ.md:1117).
        # harvest (overlapped with the screen above) even when nothing is
        # eligible — an issued AsyncFetch owns an open trace span; lazy sync
        # fetch when the idle heuristic skipped the prefetch
        sv = sv_handle.get() if sv_handle is not None else None
        self._prefetch_occupancy = bool(eligible_idx)
        if eligible_idx:
            if sv is None:
                sv = self._fetch_host(enc, {
                    "scheduled.valid": enc.scheduled.valid,
                    "scheduled.node_idx": enc.scheduled.node_idx,
                })
            occupied = {
                int(x) for x in sv["scheduled.node_idx"][sv["scheduled.valid"]]
            }
            prev = self.unneeded_nodes.since
            eligible_idx.sort(key=lambda i: (nodes[i].name not in prev,
                                             i in occupied))
            if self.options.scale_down_candidates_pool_ratio < 1.0:
                pool = max(
                    int(self.options.scale_down_candidates_pool_ratio * n_real),
                    self.options.scale_down_candidates_pool_min_count,
                )
                eligible_idx = eligible_idx[:pool]
            # cap candidates that need a DRAIN simulation with pods to move
            # (reference: --scale-down-non-empty-candidates-count; empty
            # nodes are cheap and exempt). 0 = unlimited.
            cap = self.options.scale_down_non_empty_candidates_count
            if cap > 0:
                kept, non_empty = [], 0
                for i in eligible_idx:
                    if i in occupied:
                        if non_empty >= cap:
                            continue
                        non_empty += 1
                    kept.append(i)
                eligible_idx = kept

        if not eligible_idx:
            self.state.unneeded = []
            self.state.removal = None
            self.unneeded_nodes.update([], now)
            if self.latency_tracker is not None:
                # clear candidate clocks — otherwise a node that idles again
                # much later would resume a stale clock
                self.latency_tracker.observe_candidates([], now)
            return self.state

        cand = np.asarray(eligible_idx, dtype=np.int32)
        # Destinations include other candidates (reference: GetPodDestinations
        # defaults to all nodes, planner.go:768-774) — consolidation onto
        # fellow candidates is what lets 400 nodes at 40% drain down to 160.
        # The per-candidate device verdict is "in isolation"; the sequential
        # confirmation pass in nodes_to_delete() resolves interactions.
        dest_allowed = np.ones((enc.nodes.n,), dtype=bool)
        if precomputed is not None:
            # fused path: the all-nodes sweep already ran inside the fused
            # program; gather the candidate rows on device and fetch them in
            # one transfer. Per-candidate verdicts are computed in isolation,
            # so row i of the all-N sweep IS the verdict the phased subset
            # dispatch would produce (tests/test_fused_loop.py pins this).
            with self.phases.phase("fetch", candidates=len(eligible_idx),
                                   fused=1):
                removal = self._subset_removal(precomputed.removal_dev, cand)
        else:
            with self.phases.phase("dispatch", candidates=len(eligible_idx)):
                removal = simulate_removals(
                    enc.nodes, enc.specs, enc.scheduled,
                    jnp.asarray(cand), jnp.asarray(dest_allowed),
                    max_pods_per_node=self.options.max_pods_per_node,
                    chunk=self.options.drain_chunk,
                    planes=enc.planes,
                    max_zones=enc.dims.max_zones,
                    with_constraints=enc.has_constraints,
                )
            # ONE device->host transfer for the whole verdict (the fields are
            # consumed host-side here and in nodes_to_delete; per-leaf
            # device_get costs one tunnel round trip EACH — 7 leaves ≈ 0.5 s
            # per loop over the TPU tunnel)
            with self.phases.phase("fetch"):
                removal = fetch_result(removal, phases=self.phases)
        drainable = np.asarray(removal.drainable)
        # LAZY reason pass over the FAILED candidates only (ops/drain.
        # failure_reasons): which pod shape found no destination, or shape
        # overflow — zero extra dispatches when every candidate drains
        failed_rows = [k for k in range(len(eligible_idx)) if not drainable[k]]
        detail_by_row: dict[int, str] = {}
        if failed_rows:
            from kubernetes_autoscaler_tpu.ops import drain as drain_ops

            with self.phases.phase("reason_extract", failed=len(failed_rows)):
                self.phases.bump("reason_extraction_dispatches")
                rr = drain_ops.failure_reasons(
                    enc.nodes, enc.specs, enc.scheduled,
                    jnp.asarray(cand[failed_rows]), jnp.asarray(dest_allowed),
                    max_pods_per_node=self.options.max_pods_per_node,
                    chunk=self.options.drain_chunk)
                rr = fetch_pytree(rr, phases=self.phases)
            greq = self._fetch_host(enc, {"specs.req": enc.specs.req})["specs.req"]
            for j, k in enumerate(failed_rows):
                code = int(rr.reason[j])
                if code == drain_ops.DRAIN_NO_PLACE_FOR_GROUP:
                    fg = int(rr.fail_group[j])
                    req = greq[fg] if 0 <= fg < greq.shape[0] else None
                    detail_by_row[k] = (
                        f"no destination has room for pod group {fg}"
                        + (f" (req cpu={int(req[0])}m mem={int(req[1])}Mi)"
                           if req is not None else "")
                        + f"; {int(rr.n_unplaced[j])} pods unplaced")
                elif code == drain_ops.DRAIN_TOO_MANY_SHAPES:
                    detail_by_row[k] = (
                        "more distinct pod shapes than max_groups_per_node; "
                        "conservatively unremovable")
                elif code == drain_ops.DRAIN_OK:
                    # the plain-capacity re-placement succeeds → the failure
                    # came from topology constraints the explanatory pass
                    # does not model
                    detail_by_row[k] = "pods blocked by topology constraints"
        unneeded = []
        for k, i in enumerate(eligible_idx):
            if drainable[k]:
                unneeded.append(nodes[i].name)
                # a drainable node is not unremovable — clear any stale
                # verdict (e.g. last loop's NotUnneededLongEnough) instead
                # of letting it linger until TTL expiry; downstream passes
                # re-mark if confirmation fails this loop
                self.unremovable.drop(nodes[i].name)
            else:
                reason = ("BlockedByPod" if bool(removal.has_blocker[k])
                          else "NoPlaceToMovePods")
                detail = detail_by_row.get(k, "")
                if detail:
                    self.state.drain_fail_detail[nodes[i].name] = detail
                self._mark(nodes[i].name, reason, now, message=detail)
        self.unneeded_nodes.update(unneeded, now)
        if self.latency_tracker is not None:
            self.latency_tracker.observe_candidates(unneeded, now)
        self.state.unneeded = unneeded
        self.state.removal = removal
        self.state.candidate_indices = cand
        return self.state

    def _subset_removal(self, removal_dev, cand: np.ndarray) -> RemovalResult:
        """Gather the candidate rows out of the fused all-nodes drain sweep
        and fetch them in ONE batched transfer. The gather index is padded to
        a drain_chunk multiple (repeating the last candidate) so the tiny
        device gather keys one executable shape per chunk bucket, mirroring
        simulate_removals' own cache-stability contract."""
        chunk = max(self.options.drain_chunk, 1)
        c = int(cand.shape[0])
        pad_c = max(((c + chunk - 1) // chunk) * chunk, chunk)
        idx = np.zeros((pad_c,), np.int32)
        idx[:c] = cand
        if c:
            idx[c:] = cand[-1]
        gidx = jnp.asarray(idx)
        sub = RemovalResult(
            drainable=removal_dev.drainable[gidx],
            has_blocker=removal_dev.has_blocker[gidx],
            n_moved=removal_dev.n_moved[gidx],
            n_failed=removal_dev.n_failed[gidx],
            dest_node=removal_dev.dest_node[gidx],
            pod_slot=removal_dev.pod_slot[gidx],
            feas=removal_dev.feas,
        )
        host = fetch_result(sub, phases=self.phases)
        return host.replace(
            drainable=host.drainable[:c],
            has_blocker=host.has_blocker[:c],
            n_moved=host.n_moved[:c],
            n_failed=host.n_failed[:c],
            dest_node=host.dest_node[:c],
            pod_slot=host.pod_slot[:c],
        )

    def _mark(self, name: str, reason: str, now: float,
              message: str = "") -> None:
        """One unremovable verdict onto every planner-owned surface: the TTL
        cache (→ status histogram + unremovable_nodes_count{reason}) and a
        deduped NoScaleDown event (reference: the scale-down event recorder
        posts per-node skip reasons)."""
        self.unremovable.add(name, reason, now)
        if self.event_sink is not None:
            self.event_sink.emit("NoScaleDown", obj=name, reason=reason,
                                 message=message, now=now)

    # ---- constrained-tier marshalling (cached across RunOnce loops) ----

    @staticmethod
    def _exemplar_sig(p) -> tuple:
        """Constraint-content signature of one exemplar pod — everything the
        G×G match matrices and the native-model validity bails read. Two
        exemplars with equal signatures marshal identically, so a row whose
        exemplar OBJECT churns (first member evicted, an equivalence-equal
        sibling takes over) does not invalidate the cache."""
        return (
            p.namespace,
            tuple(sorted(p.labels.items())),
            tuple((int(c.max_skew), c.topology_key,
                   tuple(sorted(c.match_labels.items())),
                   tuple(c.match_label_keys or ()), int(c.min_domains),
                   c.node_affinity_policy, c.node_taints_policy)
                  for c in p.spread_constraints()),
            tuple((t.topology_key, tuple(sorted(t.match_labels.items())),
                   tuple(t.namespaces or ()),
                   tuple(sorted(t.namespace_selector.items()))
                   if t.namespace_selector is not None else None)
                  for t in p.anti_affinity),
            tuple((t.topology_key, tuple(sorted(t.match_labels.items())),
                   tuple(t.namespaces or ()),
                   tuple(sorted(t.namespace_selector.items()))
                   if t.namespace_selector is not None else None)
                  for t in p.pod_affinity),
        )

    def _exemplars_and_fp(self, enc, g_total: int) -> tuple[dict, tuple]:
        """Exemplar pod per equivalence row (resident first, then pending —
        identical pick order to the old per-call scan, but the resident scan
        is one numpy unique over the group_ref mirror instead of a Python
        walk over every scheduled pod) + the composition fingerprint that
        keys the marshal cache."""
        exemplars: dict[int, object] = {}
        view = self._fetch_host(enc, {
            "scheduled.group_ref": enc.scheduled.group_ref,
            "scheduled.valid": enc.scheduled.valid,
        })
        grf = view["scheduled.group_ref"]
        # occupied slot ⇔ valid (freed slots drop pod AND valid together —
        # models/incremental._remove_resident; full encode pads valid False)
        m = min(len(enc.scheduled_pods), grf.shape[0])
        nz = np.nonzero(view["scheduled.valid"][:m])[0]
        if nz.size:
            uniq, first = np.unique(grf[:m][nz], return_index=True)
            for r, k in zip(uniq, first):
                p = enc.scheduled_pods[int(nz[k])]
                if p is not None:      # defensive: hole despite valid
                    exemplars[int(r)] = p
        for row, idxs in enumerate(enc.group_pods):
            if idxs:
                exemplars.setdefault(row, enc.pending_pods[idxs[0]])
        ns_sig = (None if enc.namespaces is None else
                  tuple(sorted((ns, tuple(sorted(lbls.items())))
                               for ns, lbls in enc.namespaces.items())))
        rows = sorted(exemplars)
        sigs = self._exemplar_sig_memo.refresh(
            [exemplars[r] for r in rows])
        fp = (g_total,
              tuple(sorted(zip(rows, sigs))),
              ns_sig)
        return exemplars, fp

    def _marshal_artifacts(self, enc, feas) -> _MarshalArtifacts:
        """The G×G matrices + per-group constraint vectors for the native
        tier, rebuilt only when group COMPOSITION changes (count-only churn
        is a cache hit — acceptance-tested by test_planner_hostpath)."""
        from kubernetes_autoscaler_tpu.models.api import (
            labels_match,
            term_matches_pod,
        )
        from kubernetes_autoscaler_tpu.utils.oracle import (
            HOSTNAME_KEY,
            ZONE_KEY,
            ZONE_KEY_BETA,
        )

        g_total = feas.shape[0]
        exemplars, fp = self._exemplars_and_fp(enc, g_total)
        art = self._marshal_cache
        if art is not None and art.fp == fp:
            self.marshal_cache_hits += 1
            self.phases.bump("marshal_cache_hit")
            return art
        self.marshal_cache_misses += 1
        self.phases.bump("marshal_cache_miss")

        view = self._fetch_host(enc, {
            "specs.spread_kind": enc.specs.spread_kind,
            "specs.max_skew": enc.specs.max_skew,
            "specs.spread_self": enc.specs.spread_self,
            "specs.aff_kind": enc.specs.aff_kind,
            "specs.aff_self": enc.specs.aff_self,
        })
        sk = view["specs.spread_kind"]
        spread_kind = np.where((sk == 1) | (sk == 2), sk, 0).astype(np.uint8)
        max_skew = view["specs.max_skew"].astype(np.int32)
        spread_self = view["specs.spread_self"].astype(np.uint8)
        ak = view["specs.aff_kind"]
        aff_kind = np.where((ak == 1) | (ak == 2), ak, 0).astype(np.uint8)
        aff_self = view["specs.aff_self"].astype(np.uint8)
        has_anti_host = np.zeros((g_total,), np.uint8)
        has_anti_zone = np.zeros((g_total,), np.uint8)
        m_spread = np.zeros((g_total, g_total), np.uint8)
        m_anti_h = np.zeros((g_total, g_total), np.uint8)
        m_anti_z = np.zeros((g_total, g_total), np.uint8)
        m_aff = np.zeros((g_total, g_total), np.uint8)
        model_bad = np.zeros((g_total,), bool)
        zone_keys = (ZONE_KEY, ZONE_KEY_BETA)
        for a, ex_a in exemplars.items():
            # shapes beyond the tier's model are FLAGGED, not bailed on:
            # whether they sink the native pass depends on this call's
            # routing, which the cached artifacts must stay independent of
            # (an exotic constraint on an unmoved group must not push the
            # whole confirm off the native tier — its counts still track;
            # its checks never run)
            if spread_kind[a]:
                cons = ex_a.spread_constraints()
                if (len(cons) != 1 or int(cons[0].min_domains) > 1
                        or cons[0].node_affinity_policy != "Honor"
                        or cons[0].node_taints_policy != "Ignore"):
                    model_bad[a] = True     # beyond the tier's model
                if cons:
                    sel = cons[0].merged_selector(ex_a.labels)
                    for b, ex_b in exemplars.items():
                        m_spread[a, b] = (ex_b.namespace == ex_a.namespace
                                          and labels_match(sel, ex_b.labels))
            if aff_kind[a] and ex_a.pod_affinity:
                term = ex_a.pod_affinity[0]
                if (len(ex_a.pod_affinity) > 1
                        or term.namespace_selector is not None):
                    model_bad[a] = True     # lossy shapes (defensive: hostcheck'd)
                for b, ex_b in exemplars.items():
                    m_aff[a, b] = term_matches_pod(term, ex_a, ex_b,
                                                   enc.namespaces)
            host_terms, zone_terms = [], []
            for t in ex_a.anti_affinity:
                if t.topology_key == HOSTNAME_KEY:
                    host_terms.append(t)
                elif t.topology_key in zone_keys:
                    zone_terms.append(t)
                else:
                    model_bad[a] = True     # unmodeled topology key
            has_anti_host[a] = bool(host_terms)
            has_anti_zone[a] = bool(zone_terms)
            if not host_terms and not zone_terms:
                continue       # keep the matrix build O(anti-groups x R)
            for b, ex_b in exemplars.items():
                if any(term_matches_pod(t, ex_a, ex_b, enc.namespaces)
                       for t in host_terms):
                    m_anti_h[a, b] = 1
                if any(term_matches_pod(t, ex_a, ex_b, enc.namespaces)
                       for t in zone_terms):
                    m_anti_z[a, b] = 1
        art = _MarshalArtifacts(
            fp=fp, g_total=g_total,
            spread_kind=spread_kind, max_skew=max_skew,
            spread_self=spread_self, aff_kind=aff_kind, aff_self=aff_self,
            has_anti_host=has_anti_host, has_anti_zone=has_anti_zone,
            m_spread=np.ascontiguousarray(m_spread),
            m_anti_h=np.ascontiguousarray(m_anti_h),
            m_anti_z=np.ascontiguousarray(m_anti_z),
            m_aff=np.ascontiguousarray(m_aff),
            model_bad=model_bad,
        )
        self._marshal_cache = art
        return art

    def _elig_plane(self, enc) -> np.ndarray:
        """selector_match × node validity, fetched from the device once per
        NODE/SPEC-TENSOR identity: the loop replaces whole tensors when node
        labels, validity or group selectors change (and only then), so
        holding the array refs and comparing with `is` is exact — the same
        contract `_hostarr`'s mirror token uses. Saves one device dispatch +
        one tunnel round trip per confirm on the steady path."""
        import jax

        from kubernetes_autoscaler_tpu.ops import predicates as preds

        key = (enc.nodes.label_hash, enc.nodes.valid,
               enc.specs.sel_req, enc.specs.sel_neg)
        cached = self._elig_cache
        if cached is not None and len(cached[0]) == len(key) and all(
                a is b for a, b in zip(cached[0], key)):
            self.elig_cache_hits += 1
            self.phases.bump("elig_cache_hit")
            return cached[1]
        self.elig_cache_misses += 1
        self.phases.bump("elig_cache_miss")
        with self.phases.phase("dispatch"):
            sel_dev = preds.selector_match(enc.nodes.label_hash, enc.specs)
        with self.phases.phase("fetch"):
            sel = np.asarray(jax.device_get(sel_dev))
        elig = sel & _hostarr(enc, "nodes.valid", enc.nodes.valid)[None, :]
        elig = np.ascontiguousarray(elig.astype(np.uint8))
        self._elig_cache = (key, elig)
        return elig

    def _build_constraint_block(self, enc, feas, con_path, moved_groups,
                                oracle_moved, one_per_node):
        """Constrained-tier marshalling for the native pass: count planes
        from the host mirrors, zone/eligibility tables, and group-to-group
        match matrices from the equivalence exemplars — the matrices and
        eligibility plane come from the cross-loop caches above. Returns
        None when a routed group's constraints exceed the native tier's
        model (the caller then falls back to the Python pass)."""
        if not np.array_equal(con_path, oracle_moved | one_per_node):
            raise ValueError(
                "tier routing desynchronized: con_path must equal "
                "need_exact | limit_g")
        from kubernetes_autoscaler_tpu.core.scaledown.native_confirm import (
            ConstraintBlock,
        )

        if enc.specs.spread_kind is None:
            return None    # constraint tensors absent -> python pass decides
        g_total = feas.shape[0]
        art = self._marshal_artifacts(enc, feas)
        # the strict validity bails apply only to groups that will actually
        # PLACE pods this pass (routed = con_path ∩ moved)
        routed = np.zeros((g_total,), bool)
        mg = np.asarray(moved_groups, dtype=np.int64)
        if mg.size:
            routed[mg[mg < g_total]] = True
        routed &= con_path.astype(bool)
        if bool((art.model_bad & routed).any()):
            return None     # beyond the tier's model — python pass decides

        if enc.planes is None:
            # no count planes -> the tier would start every domain at zero
            # and under-count residents; the Python oracle pass decides
            return None
        elig = self._elig_plane(enc)
        planes = self._fetch_host(enc, {
            "planes.spread_cnt": enc.planes.spread_cnt,
            "planes.anti_host_cnt": enc.planes.anti_host_cnt,
            "planes.anti_zone_cnt": enc.planes.anti_zone_cnt,
            "planes.aff_cnt": enc.planes.aff_cnt,
            "nodes.zone_id": enc.nodes.zone_id,
        })
        # per-call COPIES: the kernel mutates the count planes in place
        cnt_node = np.ascontiguousarray(planes["planes.spread_cnt"],
                                        np.int32).copy()
        anti_host_node = np.ascontiguousarray(planes["planes.anti_host_cnt"],
                                              np.int32).copy()
        anti_zone_node = np.ascontiguousarray(planes["planes.anti_zone_cnt"],
                                              np.int32).copy()
        aff_node = np.ascontiguousarray(planes["planes.aff_cnt"],
                                        np.int32).copy()
        return ConstraintBlock(
            one_per_node=np.ascontiguousarray(one_per_node.astype(np.uint8)),
            oracle_moved=np.ascontiguousarray(oracle_moved.astype(np.uint8)),
            n_zones=int(enc.dims.max_zones),
            zone_id=np.ascontiguousarray(planes["nodes.zone_id"], np.int32),
            spread_kind=art.spread_kind,
            max_skew=art.max_skew,
            spread_self=art.spread_self,
            has_anti_host=art.has_anti_host,
            has_anti_zone=art.has_anti_zone,
            aff_kind=art.aff_kind,
            aff_self=art.aff_self,
            elig=elig,
            cnt_node=cnt_node,
            anti_host_node=anti_host_node,
            anti_zone_node=anti_zone_node,
            aff_node=aff_node,
            m_spread=art.m_spread,
            m_anti_h=art.m_anti_h,
            m_anti_z=art.m_anti_z,
            m_aff=art.m_aff,
            con_path=np.ascontiguousarray(con_path.astype(np.uint8)),
        )

    def _native_confirm_pass(self, enc, nodes, ordered, drainable, by_index,
                             name_to_i, node_gid, seen_groups, defaults,
                             ds_by_node, feas, node_valid, greq, pod_slot,
                             movable_f, group_ref, now, pdbs=(),
                             con_needed=False, need_exact=None, limit_g=None,
                             moved_groups=None, *, host):
        """Marshal the pre-screened candidate list into the C++ pass. PDB
        budgets ride as a per-slot multi-word membership bitmask (any
        count) — the all-PDB cluster stays on the millisecond native path.
        `host` is the caller's batched host view (nodes.cap/alloc/valid)."""
        from kubernetes_autoscaler_tpu.core.scaledown import native_confirm

        con = None
        if con_needed:
            # route exactly the groups the Python pass would run through the
            # oracle (need_exact | limit_g) through the native per-pod tier
            con_path = (need_exact | limit_g)
            with self.phases.phase("marshal"):
                con = self._build_constraint_block(enc, feas, con_path,
                                                   moved_groups,
                                                   oracle_moved=need_exact,
                                                   one_per_node=limit_g)
            if con is None:
                return None      # beyond the tier — python pass decides

        # policy pre-screen: drainable verdict + matured unneeded clock
        cand_rows: list[tuple[int, int]] = []    # (node idx, sweep row)
        for name in ordered:
            i = name_to_i.get(name)
            if i is None or i not in by_index or not drainable[by_index[i]]:
                continue
            g = seen_groups.get(node_gid.get(name))
            if g is None:
                continue
            nd = nodes[i]
            opts = g.get_options(defaults)
            unneeded_time = (
                (opts.scale_down_unneeded_time_s if nd.ready
                 else opts.scale_down_unready_time_s)
                or (defaults.scale_down_unneeded_time_s if nd.ready
                    else defaults.scale_down_unready_time_s)
            )
            if self.unneeded_nodes.removable_at(name, now, unneeded_time):
                cand_rows.append((i, by_index[i]))
            else:
                # reference: simulator.UnremovableReason NotUnneededLongEnough
                self._mark(name, "NotUnneededLongEnough", now)
        if not cand_rows:
            return []

        # per-candidate movable slot lists (vectorized over the sweep's
        # windows — row-major compress preserves per-candidate grouping)
        cand_node = []
        cand_group_idx = []
        room_index: dict[str, int] = {}
        room_vals: list[int] = []
        for i, _ in cand_rows:
            gid = node_gid.get(nodes[i].name)
            if gid not in room_index:
                g = seen_groups[gid]
                room_index[gid] = len(room_vals)
                room_vals.append(g.target_size() - g.min_size())
            cand_node.append(i)
            cand_group_idx.append(room_index[gid])
        ks = np.asarray([k for _, k in cand_rows], np.int64)
        sl = pod_slot[ks]                                   # [C, MPN]
        valid_sl = (sl >= 0) & movable_f[np.maximum(sl, 0)]
        counts = valid_sl.sum(axis=1)
        slot_off = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        flat = sl[valid_sl]
        slot_ids = flat.astype(np.int32)
        slot_groups = group_ref[flat].astype(np.int32)

        quota_totals = quota_min = None
        node_cap = host["nodes.cap"].astype(np.int64)
        if self.quota is not None:
            cap_sum = node_cap[host["nodes.valid"]].sum(axis=0)
            quota_totals = cap_sum.astype(np.int64)
            quota_min = self._quota_min_vector(enc)

        # cap from the batched view; alloc is the device-true value the same
        # single fetch brought back (post-placement state, `_hostarr` contract)
        free = (node_cap - host["nodes.alloc"].astype(np.int64))
        group_room = np.asarray(room_vals, np.int32)
        max_slot = int(slot_ids.max()) if slot_ids.size else 0
        slot_pdb_mask = pdb_remaining = None
        if pdbs:
            words = (len(pdbs) + 63) // 64
            slot_pdb_mask = np.zeros((max_slot + 1, words), np.uint64)
            # memoized by (namespace, label signature): clusters have few
            # distinct label sets, so the per-slot cost collapses to a dict
            # hit (the naive per-pod matching loop was ~80% of the pass).
            # Masks are arbitrary-width python ints split into u64 words —
            # the former single-word layout capped budgets at 64 (r4 Weak #3)
            mask_cache: dict[tuple, int] = {}
            word_mask = (1 << 64) - 1
            for s in np.unique(slot_ids):
                pod = (enc.scheduled_pods[int(s)]
                       if int(s) < len(enc.scheduled_pods) else None)
                if pod is None:
                    continue
                key = (pod.namespace, tuple(sorted(pod.labels.items())))
                mask = mask_cache.get(key)
                if mask is None:
                    mask = 0
                    for pi in self.pdb_tracker.matching_pdbs(pod):
                        mask |= 1 << pi
                    mask_cache[key] = mask
                m = mask
                for w in range(words):
                    slot_pdb_mask[int(s), w] = m & word_mask
                    m >>= 64
            # the tracker's LIVE remaining counts, not the static allowance
            # — concurrent actuator drains may have deducted already
            pdb_remaining = np.asarray(
                self.pdb_tracker.remaining_snapshot(), np.int64)
        with self.phases.phase("confirm"):
            accept, reason, dest = native_confirm.confirm(
                free, feas, node_valid, greq,
                np.asarray(cand_node, np.int32),
                slot_ids, slot_groups,
                slot_off.astype(np.int32),
                np.asarray(cand_group_idx, np.int32),
                group_room, quota_totals, quota_min, node_cap,
                self.options.max_empty_bulk_delete,
                self.options.max_drain_parallelism,
                self.options.max_scale_down_parallelism,
                max_slot,
                slot_pdb_mask=slot_pdb_mask, pdb_remaining=pdb_remaining,
                con=con,
            )
        reasons = {1: "NoPlaceToMovePods", 2: "NodeGroupMinSizeReached",
                   3: "MinimalResourceLimitExceeded", 5: "NotEnoughPdb"}
        out: list[NodeToRemove] = []
        for j, (i, _) in enumerate(cand_rows):
            nd = nodes[i]
            if not accept[j]:
                r = reasons.get(int(reason[j]))
                if r:
                    self._mark(nd.name, r, now)
                continue
            orig = [int(s) for s in slot_ids[slot_off[j]: slot_off[j + 1]]]
            self.unremovable.drop(nd.name)   # accepted: verdict resolved
            out.append(NodeToRemove(
                nd, not orig, pods_to_move=orig,
                destinations={s: int(dest[s]) for s in orig if dest[s] >= 0},
                ds_to_evict=ds_by_node.get(nd.name, [])))
        return out

    def _quota_min_vector(self, enc) -> np.ndarray:
        """Limiter min-limits mapped onto the resource axis (cpu in MILLI
        cores, memory in MiB, extended resources by registry slot)."""
        from kubernetes_autoscaler_tpu.models import resources as res

        limiter = self.quota.limiter
        qmin = np.zeros((res.NUM_RESOURCES,), np.int64)
        qmin[res.CPU] = int(limiter.min_for("cpu", 0)) * 1000
        qmin[res.MEMORY] = int(limiter.min_for("memory", 0))
        for name, slot in enc.registry.slots.items():
            qmin[slot] = int(limiter.min_for(name, 0))
        return qmin

    def _utilization(self, enc: EncodedCluster, nodes: list[Node],
                     precomputed_util: np.ndarray | None = None) -> np.ndarray:
        """Per-node dominant-resource utilization, with daemonset and mirror
        pod usage excluded per the flags (reference: utilization/info.go
        CalculateUtilization skipDaemonSetPods/skipMirrorPods).

        `precomputed_util` is the fused decision's host copy of the same
        `node_utilization` program output — identical values, no dispatch."""
        n_real = len(nodes)
        if precomputed_util is not None:
            util = np.asarray(precomputed_util)[:n_real]
        else:
            with self.phases.phase("dispatch"):
                util_dev = util_ops.node_utilization(enc.nodes)
            with self.phases.phase("fetch"):
                util = np.asarray(util_dev)[:n_real]
        defaults = _ng_defaults(self.options)
        ignore_mirror = self.options.ignore_mirror_pods_utilization
        ignore_ds_ids: set[int] = set()
        for i, nd in enumerate(nodes):
            g = self.provider.node_group_for_node(nd)
            if g is None:
                continue
            flag = g.get_options(defaults).ignore_daemonsets_utilization
            if flag is None:
                flag = defaults.ignore_daemonsets_utilization
            if flag:
                ignore_ds_ids.add(i)
        if not ignore_mirror and not ignore_ds_ids:
            return util
        from kubernetes_autoscaler_tpu.models.resources import CPU, MEMORY

        view = self._fetch_host(enc, {
            "nodes.cap": enc.nodes.cap,
            "nodes.alloc": enc.nodes.alloc,
            "scheduled.req": enc.scheduled.req,
        })
        cap = view["nodes.cap"].astype(np.float64)[:n_real]
        alloc = view["nodes.alloc"].astype(np.float64)[:n_real].copy()
        reqs = view["scheduled.req"].astype(np.float64)
        for j, p in enumerate(enc.scheduled_pods):
            if p is None:  # freed slot (incremental encoder hole)
                continue
            ni = enc.node_index.get(p.node_name, -1)
            if ni < 0 or ni >= n_real:
                continue
            skip = (ignore_mirror and p.is_mirror()) or (
                ni in ignore_ds_ids and p.is_daemonset())
            if skip:
                alloc[ni] -= reqs[j]
        ratio = alloc / np.maximum(cap, 1.0)
        return np.maximum(ratio[:, CPU], ratio[:, MEMORY])

    # ---- final selection (reference: NodesToDelete :151) ----

    def nodes_to_delete(self, enc: EncodedCluster, nodes: list[Node],
                        now: float | None = None) -> list[NodeToRemove]:
        now = time.time() if now is None else now
        if self.state.removal is None or self.state.candidate_indices is None:
            return []
        defaults = _ng_defaults(self.options)
        removal = self.state.removal
        cand = self.state.candidate_indices
        drainable = np.asarray(removal.drainable)
        pod_slot = np.asarray(removal.pod_slot)
        feas = np.asarray(removal.feas)              # bool[G, N]
        by_index = {int(c): k for k, c in enumerate(cand)}
        name_to_i = {nd.name: i for i, nd in enumerate(nodes)}
        # host-pass wall-clock budget (reference: ScaleDownSimulationTimeout,
        # planner.go:297) — candidates not reached retry next loop
        confirm_deadline = (time.monotonic()
                            + self.options.scale_down_simulation_timeout_s)

        # Sequential confirmation: walk unneeded nodes (oldest clock first),
        # re-placing each candidate's pods — original AND any received from
        # earlier confirmed drains — against a host-side running free tensor
        # and the device-computed predicate plane. This reproduces the
        # reference's commit-on-success sequencing (each successful removal's
        # moves are committed into the working snapshot before the next
        # candidate is simulated, simulator/cluster.go:174-188), which the
        # independent per-candidate device sweep deliberately omits.
        # ONE batched host view for everything the confirmation pass reads:
        # mirror hits are free, every miss (always nodes.alloc; every key on
        # the non-incremental path once the loop replaced a tensor) shares a
        # single fetch_pytree transfer instead of one round trip each
        items: dict[str, object] = {
            "scheduled.req": enc.scheduled.req,
            "specs.req": enc.specs.req,
            "scheduled.group_ref": enc.scheduled.group_ref,
            "scheduled.movable": enc.scheduled.movable,
            "scheduled.valid": enc.scheduled.valid,
            "specs.needs_host_check": enc.specs.needs_host_check,
            "nodes.valid": enc.nodes.valid,
            "nodes.ready": enc.nodes.ready,
            "nodes.schedulable": enc.nodes.schedulable,
            "nodes.cap": enc.nodes.cap,
            "nodes.alloc": enc.nodes.alloc,
        }
        if enc.specs.spread_kind is not None:
            items.update({
                "specs.spread_kind": enc.specs.spread_kind,
                "specs.aff_kind": enc.specs.aff_kind,
                "specs.anti_self_zone": enc.specs.anti_self_zone,
            })
        if enc.planes is not None:
            items.update({
                "planes.anti_host_cnt": enc.planes.anti_host_cnt,
                "planes.anti_zone_cnt": enc.planes.anti_zone_cnt,
            })
        host = self._fetch_host(enc, items)
        reqs = host["scheduled.req"]
        greq = host["specs.req"]
        group_ref = host["scheduled.group_ref"]
        movable_f = host["scheduled.movable"]
        h = enc.host_arrays
        if h is not None and "specs.anti_affinity_self" in h:
            # one_per_node from the mirrors (a device compute + fetch saved)
            limit_g = (np.asarray(h["specs.anti_affinity_self"])
                       | (np.asarray(h["specs.port_hash"]) != 0).any(axis=-1))
        else:
            limit_g = np.asarray(enc.specs.one_per_node())
        # Groups whose dense feasibility row is not the whole truth — lossy
        # encodings and topology-coupled constraints — get every destination
        # double-checked by the exact oracle during confirmation (the analog
        # of the reference running real scheduler plugins for each move).
        need_exact = host["specs.needs_host_check"].copy()
        if enc.specs.spread_kind is not None:
            need_exact |= (host["specs.spread_kind"] > 0)
            need_exact |= (host["specs.aff_kind"] > 0)
            need_exact |= host["specs.anti_self_zone"]
        if enc.planes is not None:
            need_exact |= host["planes.anti_host_cnt"].sum(axis=1) > 0
            need_exact |= host["planes.anti_zone_cnt"].sum(axis=1) > 0
        # same destination gates the device sweep applies (ops/drain.py):
        # valid & ready & schedulable — a cordoned or unready node must not
        # absorb paper capacity during confirmation
        node_valid = (host["nodes.valid"]
                      & host["nodes.ready"]
                      & host["nodes.schedulable"])
        ds_by_node: dict[str, list[int]] = {}
        for j, p in enumerate(enc.scheduled_pods):
            if p is None:  # freed slot (incremental encoder hole)
                continue
            if p.is_daemonset():
                ds_by_node.setdefault(p.node_name, []).append(j)
        ordered = sorted(self.state.unneeded, key=lambda n: self.unneeded_nodes.since.get(n, now))

        # Atomic-group pre-screen (reference: AtomicResizeFilteringProcessor):
        # a ZeroOrMaxNodeScaling group drains all-or-nothing, so unless EVERY
        # registered node of the group is an unneeded candidate, skip its
        # nodes up front — before they consume budgets or destination
        # capacity that plain candidates need.
        unneeded_set = set(ordered)
        # one provider lookup per node (node_group_for_node may be an RPC)
        node_gid: dict[str, str | None] = {}
        gid_members: dict[str, list[str]] = {}
        atomic_gids: set[str] = set()
        seen_groups: dict[str, object] = {}
        for nd in nodes:
            g0 = self.provider.node_group_for_node(nd)
            gid = g0.id() if g0 is not None else None
            node_gid[nd.name] = gid
            if gid is not None:
                gid_members.setdefault(gid, []).append(nd.name)
                if gid not in seen_groups:
                    seen_groups[gid] = g0
                    if g0.get_options(defaults).zero_or_max_node_scaling:
                        atomic_gids.add(gid)
        atomic_blocked: set[str] = set()
        # budgets cannot fit a partial atomic group either: if the whole
        # group exceeds what this round may delete, skip it up front
        # (reference: budgets.go CropNodes keeps/drops atomic groups whole)
        budget_cap = min(self.options.max_scale_down_parallelism,
                         self.options.max_empty_bulk_delete
                         + self.options.max_drain_parallelism)
        for gid in atomic_gids:
            members = gid_members.get(gid, [])
            if (not all(m in unneeded_set for m in members)
                    or len(members) > budget_cap):
                atomic_blocked.add(gid)
        atomic_groups = {name: node_gid.get(name) for name in ordered
                         if node_gid.get(name) in atomic_gids}
        for name in list(unneeded_set):
            if atomic_groups.get(name) in atomic_blocked:
                self._mark(name, "AtomicScaleDownFailed", now)
        ordered = [n for n in ordered
                   if atomic_groups.get(n) not in atomic_blocked]

        # NATIVE FAST PATH (sidecar/native/kaconfirm.cc): the identical
        # sequential pass in C++ for the common case AND the constrained
        # tier — zone/host topology spread, host/zone required anti-affinity
        # AND required pod affinity (first-pod exception included) ride as
        # incrementally-maintained count planes (round-4 verdict item 4: the
        # all-constrained confirm was ~37 s host-side at 5k nodes / 50k
        # pods; native is milliseconds). Still python: lossy encodings,
        # host ports, atomic groups, phantoms.
        # tests/test_native_confirm.py proves plan-equality vs the Python
        # pass below.
        pdbs = self.pdb_tracker.get_pdbs() if self.pdb_tracker else []
        if not atomic_gids and not self.state.injected_pods:
            from kubernetes_autoscaler_tpu.core.scaledown import native_confirm

            moved_groups = np.unique(group_ref[
                host["scheduled.valid"] & movable_f])
            if moved_groups.size:
                hostcheck = host["specs.needs_host_check"]
                # spread (host/zone), anti-affinity (host/zone), required
                # pod affinity AND one-per-node port/anti groups are all
                # native now; only lossy shapes (hostcheck) route to the
                # Python pass
                native_ok_g = ~hostcheck
                eligible = bool(native_ok_g[moved_groups].all())
                con_needed = bool(need_exact[moved_groups].any()
                                  or limit_g[moved_groups].any())
            else:
                eligible, con_needed = True, False
            if (eligible and native_confirm.available()
                    and time.monotonic() <= confirm_deadline):
                out = self._native_confirm_pass(
                    enc, nodes, ordered, drainable, by_index, name_to_i,
                    node_gid, seen_groups, defaults, ds_by_node,
                    feas, node_valid, greq, pod_slot, movable_f, group_ref,
                    now, pdbs, con_needed=con_needed,
                    need_exact=need_exact, limit_g=limit_g,
                    moved_groups=moved_groups, host=host)
                if out is not None:
                    return out

        # The confirmation pass runs as ATTEMPTS: if an atomic group fails
        # mid-pass (one member can't place its pods), everything it consumed
        # — budgets, destination capacity, PDB reservations — is poisoned,
        # so the whole pass re-runs from scratch with that group excluded.
        # Bounded by the number of atomic groups; the common case is one
        # attempt. This is the unit semantics of the reference's
        # budgets.go CropNodes + AtomicResizeFilteringProcessor.
        excluded_gids: set[str] = set()
        # KA_CONFIRM_TRACE=1: per-placement records on stderr, matching the
        # native kernel's trace — diff the two when chasing plan equality
        import os as _os
        import sys as _sys

        _trace = _os.environ.get("KA_CONFIRM_TRACE")

        # cap from the host mirror; alloc is the device-true value the
        # batched view fetched once for the whole confirmation (the device
        # state cannot change mid-pass — attempts re-COPY, never re-fetch)
        free_base = (host["nodes.cap"].astype(np.int64)
                     - host["nodes.alloc"].astype(np.int64))

        def attempt(names: list[str]) -> tuple[list[NodeToRemove], dict[int, int], set[str]]:
            free = free_base.copy()
            deleted_mask = np.zeros((enc.nodes.n,), dtype=bool)
            # Incremental fits cache: fits_m[g, n] = predicate plane AND
            # capacity, built once (G x N x R) and patched per move (only the
            # destination column changes) — keeps the host pass O(moves x G x R)
            # instead of O(moves x N x R) at 5k nodes / 50k pods.
            fits_m = (feas & node_valid[None, :]
                      & (free[None, :, :] >= greq[:, None, :]).all(axis=2))

            def charge(d: int, req_vec: np.ndarray, sign: int) -> None:
                free[d] -= sign * req_vec
                fits_m[:, d] = (feas[:, d] & node_valid[d]
                                & (free[d][None, :] >= greq).all(axis=1))
            # oracle world for exact-checked moves (rebuilt per attempt):
            # the ConfirmOracle maintains per-constraint domain counts
            # incrementally, so each destination verdict is O(domains)
            # instead of O(nodes x pods) (round-3 review Weak #4)
            from kubernetes_autoscaler_tpu.utils.oracle_cache import (
                ConfirmOracle,
            )

            by_node: dict[str, list] = {}
            for q in enc.scheduled_pods:
                if q is None:  # freed slot (incremental encoder hole)
                    continue
                by_node.setdefault(q.node_name, []).append(q)
            # anticipated (injected) evicted pods are residents of the
            # oracle world too — their alloc charge is already in `free`
            for q in self.state.injected_pods:
                by_node.setdefault(q.node_name, []).append(q)
            oracle_world = ConfirmOracle(
                list(nodes), by_node, registry=enc.registry,
                namespaces=enc.namespaces)
            del by_node  # the oracle world owns it from here
            received_slots: dict[int, list[int]] = {}
            moved_marks: set[tuple[int, int]] = set()
            final_dest: dict[int, int] = {}
            # anticipated evicted-pod phantoms by CURRENT host (their alloc
            # charge rides the node they were injected onto; removing that
            # node must re-home them or fail, else consolidation reclaims
            # exactly the capacity the injection reserved)
            phantom_on: dict[str, list] = {}
            for q in self.state.injected_pods:
                phantom_on.setdefault(q.node_name, []).append(q)
            quota_status = None
            if self.quota is not None:
                quota_status = self.quota.status_from_encoded(enc)
            empty_budget = self.options.max_empty_bulk_delete
            drain_budget = self.options.max_drain_parallelism
            total_budget = self.options.max_scale_down_parallelism
            out: list[NodeToRemove] = []
            group_room: dict[str, int] = {}
            pdb_reserved: dict[int, int] = {}
            for name in names:
                if len(out) >= total_budget:
                    break
                if time.monotonic() > confirm_deadline:
                    break  # --scale-down-simulation-timeout: retry next loop
                i = name_to_i.get(name)
                if i is None or i not in by_index:
                    continue
                k = by_index[i]
                if not drainable[k]:
                    continue
                nd = nodes[i]
                g = seen_groups.get(node_gid.get(name))
                if g is None:
                    continue
                opts = g.get_options(defaults)
                unneeded_time = (
                    (opts.scale_down_unneeded_time_s if nd.ready
                     else opts.scale_down_unready_time_s)
                    or (defaults.scale_down_unneeded_time_s if nd.ready
                        else defaults.scale_down_unready_time_s)
                )
                if not self.unneeded_nodes.removable_at(name, now, unneeded_time):
                    self._mark(name, "NotUnneededLongEnough", now)
                    continue
                room = group_room.setdefault(g.id(), g.target_size() - g.min_size())
                if room <= 0:
                    self._mark(name, "NodeGroupMinSizeReached", now)
                    continue
                if quota_status is not None and not self.quota.nodes_removable(
                    quota_status, nd
                ):
                    self._mark(name, "MinimalResourceLimitExceeded", now)
                    continue

                orig_slots = [
                    int(pod_slot[k, s]) for s in range(pod_slot.shape[1])
                    if int(pod_slot[k, s]) >= 0 and movable_f[int(pod_slot[k, s])]
                ]
                victim_slots = orig_slots + received_slots.get(i, [])
                is_empty = not victim_slots
                if is_empty:
                    if empty_budget <= 0:
                        continue
                else:
                    if drain_budget <= 0:
                        continue

                # PDB gate (reference: planner consults the shared
                # RemainingPdbTracker before confirming a drain; the actuator
                # deducts at eviction time). Only pods physically on the node
                # are evicted — received slots were accounted when their own
                # node was confirmed. Need is accumulated across the
                # candidates confirmed in THIS pass so two drains can't
                # jointly overdraw one budget.
                pdb_need: dict[int, int] = {}
                if orig_slots and self.pdb_tracker is not None:
                    victims = [enc.scheduled_pods[s] for s in orig_slots]
                    if not self.pdb_tracker.can_remove_pods(victims, pdb_reserved):
                        self._mark(name, "NotEnoughPdb", now)
                        continue
                    pdb_need = self.pdb_tracker.reservation(victims)

                # Re-place every victim (original + received) over live free
                # capacity — first feasible node in index order (the device
                # packer's tie-break). Identical pods of a group place as one
                # BLOCK via the cumulative-fit trick (one numpy pass per
                # group instead of per pod: this bound the pass at 5k nodes /
                # 50k pods — round-2 review Weak #6); exact-oracle and
                # one-per-node groups keep the per-pod path.
                moves: dict[int, int] = {}
                local_marks: set[tuple[int, int]] = set()
                local_pod_moves: list[tuple[object, str, object]] = []
                phantom_moves: list[tuple[object, np.ndarray, int]] = []
                ok = True
                slots_by_group: dict[int, list[int]] = {}
                for slot in victim_slots:
                    slots_by_group.setdefault(int(group_ref[slot]), []).append(slot)
                for g_ref, slots_g in sorted(slots_by_group.items()):
                    if not (need_exact[g_ref] or limit_g[g_ref]):
                        want = len(slots_g)
                        gr = greq[g_ref]
                        fits = fits_m[g_ref] & ~deleted_mask
                        fits[i] = False
                        per_r = np.where(gr[None, :] > 0,
                                         np.maximum(free, 0) // np.maximum(gr[None, :], 1),
                                         1 << 30)
                        fit = np.clip(per_r.min(axis=1), 0, want)
                        fit = np.where(fits, fit, 0)
                        cum = np.cumsum(fit)
                        place = np.clip(want - (cum - fit), 0, fit)
                        if int(place.sum()) < want:
                            ok = False
                            break
                        dests = np.repeat(np.nonzero(place)[0],
                                          place[place > 0].astype(int))
                        for slot, d in zip(slots_g, dests):
                            charge(int(d), reqs[slot], +1)
                            moves[slot] = int(d)
                            if _trace:
                                print(f"[pyconfirm] cand={i} blk slot={slot} "
                                      f"g={g_ref} -> {int(d)}",
                                      file=_sys.stderr)
                        continue
                    for slot in slots_g:
                        req = reqs[slot]
                        fits = fits_m[g_ref] & ~deleted_mask
                        fits[i] = False
                        if limit_g[g_ref]:
                            for (gm, dm) in moved_marks | local_marks:
                                if gm == g_ref:
                                    fits[dm] = False
                        pod_obj = (enc.scheduled_pods[slot]
                                   if slot < len(enc.scheduled_pods) else None)
                        if need_exact[g_ref] and pod_obj is not None:
                            # unschedule from the oracle world, then exact-check
                            # each dense-feasible destination in index order
                            # the pod is being drained off THIS node: for
                            # received (cascaded) slots pod_obj.node_name is
                            # its long-gone original host — using it
                            # corrupted the oracle's domain counts (caught by
                            # the native-tier plan-equality property test)
                            src_name = nd.name
                            oracle_world.move(pod_obj, src_name, "")
                            d = -1
                            for cand_d in np.nonzero(fits)[0]:
                                if oracle_world.check(pod_obj,
                                                      nodes[int(cand_d)]):
                                    d = int(cand_d)
                                    break
                            if d < 0:
                                # restore the world
                                oracle_world.move(pod_obj, "", src_name)
                                ok = False
                                break
                            oracle_world.move(pod_obj, "", nodes[d].name)
                            local_pod_moves.append(
                                (pod_obj, src_name, nodes[d].name))
                        else:
                            d = int(np.argmax(fits))
                            if not fits[d]:
                                ok = False
                                break
                        charge(d, reqs[slot], +1)
                        moves[slot] = d
                        if _trace:
                            print(f"[pyconfirm] cand={i} con slot={slot} "
                                  f"g={g_ref} -> {d}", file=_sys.stderr)
                        if limit_g[g_ref]:
                            local_marks.add((g_ref, d))
                    if not ok:
                        break
                # re-home anticipated evicted-pod phantoms riding this node:
                # their reserved capacity must survive the node's removal or
                # the removal must not happen (without this, deleting the
                # node they were injected onto silently reclaims exactly the
                # capacity the injection protects)
                if ok and phantom_on.get(name):
                    from kubernetes_autoscaler_tpu.models.encode import (
                        pod_request_vector,
                    )

                    for q in phantom_on[name]:
                        qreq, _ = pod_request_vector(q, enc.registry)
                        cand_d = np.nonzero(
                            node_valid & ~deleted_mask
                            & (free >= qreq[None, :]).all(axis=1))[0]
                        d_found = -1
                        for d in cand_d:
                            d = int(d)
                            if d == i:
                                continue
                            # rows beyond the real node list are injected
                            # template capacity — capacity-only check there
                            if d < len(nodes) and not oracle_world.check(
                                    q, nodes[d]):
                                continue
                            d_found = d
                            break
                        if d_found < 0:
                            ok = False
                            break
                        dst_name = (nodes[d_found].name
                                    if d_found < len(nodes) else "")
                        oracle_world.move(q, name, dst_name)
                        local_pod_moves.append((q, name, dst_name))
                        charge(d_found, qreq, +1)
                        phantom_moves.append((q, qreq, d_found))
                if not ok:
                    # revert charges; try again next loop (destinations taken
                    # by an earlier candidate this round)
                    for slot, d in moves.items():
                        charge(d, reqs[slot], -1)
                    for q, qreq, d in phantom_moves:
                        charge(d, qreq, -1)
                    for pod_obj, src_name, dst_name in local_pod_moves:
                        oracle_world.move(pod_obj, dst_name, src_name)
                    self._mark(name, "NoPlaceToMovePods", now)
                    continue

                # FINAL acceptance: only now deduct from the quota running
                # totals so skipped candidates never consume headroom
                # (reference: min-quota tracker deducts per confirmed removal)
                if quota_status is not None:
                    self.quota.deduct(quota_status, nd)
                for i_pdb, n_pdb in pdb_need.items():
                    pdb_reserved[i_pdb] = pdb_reserved.get(i_pdb, 0) + n_pdb
                group_room[g.id()] -= 1
                if is_empty:
                    empty_budget -= 1
                else:
                    drain_budget -= 1
                deleted_mask[i] = True
                # node gone (daemonset leftovers vanish with it)
                oracle_world.remove_node(nd.name)
                for slot, d in moves.items():
                    received_slots.setdefault(d, []).append(slot)
                    final_dest[slot] = d
                moved_marks |= local_marks
                if phantom_moves:
                    phantom_on.pop(name, None)
                    for q, _qreq, d in phantom_moves:
                        dst = (nodes[d].name if d < len(nodes)
                               else f"__injected-row-{d}")
                        phantom_on.setdefault(dst, []).append(q)
                # The actuator evicts only pods physically on the node;
                # received slots were capacity bookkeeping for the pass.
                out.append(NodeToRemove(nd, bool(is_empty),
                                        pods_to_move=orig_slots,
                                        ds_to_evict=ds_by_node.get(nd.name, [])))

            # backstop: an atomic group that only PARTIALLY confirmed (a
            # member failed mid-pass) must not ship partial deletions
            dropped: set[str] = set()
            selected_per_gid: dict[str, int] = {}
            for r in out:
                gid = node_gid.get(r.node.name)
                if gid in atomic_gids:
                    selected_per_gid[gid] = selected_per_gid.get(gid, 0) + 1
            for gid, n_sel in selected_per_gid.items():
                if n_sel != len(gid_members.get(gid, [])):
                    dropped.add(gid)
            return out, final_dest, dropped

        while True:
            names = [n for n in ordered
                     if node_gid.get(n) not in excluded_gids]
            with self.phases.phase("confirm"):
                out, final_dest, dropped = attempt(names)
            if not dropped:
                break
            # the failed group's budget/capacity consumption poisoned the
            # pass — exclude it and redo from scratch (fresh budgets), so
            # plain candidates behind it are not starved
            excluded_gids |= dropped
            for name in ordered:
                if node_gid.get(name) in dropped:
                    self._mark(name, "AtomicScaleDownFailed", now)

        # A destination chosen early can itself be confirmed for deletion
        # later in the pass (its received pods were then re-placed); report
        # each pod's FINAL destination, never a deleted node.
        for r in out:
            r.destinations = {s: final_dest[s] for s in r.pods_to_move
                              if s in final_dest}
            self.unremovable.drop(r.node.name)   # accepted: verdict resolved
        return out
