"""Unneeded-node time tracking for scale-down.

Reference counterpart: core/scaledown/unneeded/nodes.go (330 LoC) — per-node
"unneeded since" timestamps, compared against per-nodegroup
ScaleDownUnneededTime / ScaleDownUnreadyTime, reloadable from
DeletionCandidate taints after a restart (planner.go:91-93).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class UnneededNodes:
    since: dict[str, float] = field(default_factory=dict)

    def update(self, unneeded_now: list[str], now: float) -> None:
        """Keep timestamps for still-unneeded nodes; start clocks for new ones;
        drop nodes that became needed (reference: unneeded.Nodes.Update)."""
        current = set(unneeded_now)
        self.since = {n: t for n, t in self.since.items() if n in current}
        for n in current:
            self.since.setdefault(n, now)

    def removable_at(self, node: str, now: float, unneeded_time_s: float) -> bool:
        t = self.since.get(node)
        return t is not None and now - t >= unneeded_time_s

    def load_from_taints(self, tainted_since: dict[str, float]) -> None:
        """Crash recovery: resume clocks from DeletionCandidate taints
        (reference: LoadFromExistingTaints)."""
        for n, t in tainted_since.items():
            self.since.setdefault(n, t)

    def drop(self, node: str) -> None:
        self.since.pop(node, None)


@dataclass
class UnremovableNodes:
    """TTL cache of recently-unremovable nodes + reason (reference:
    core/scaledown/unremovable/, reasons enum simulator/cluster.go:63-103).

    Expired entries are swept eagerly on every `add`/`update` — not only on
    the `contains` read path — so the cache stays bounded by the live node
    set across loops even for nodes that are never probed again (a deleted
    node's entry would otherwise live forever)."""

    ttl_s: float = 5 * 60.0
    entries: dict[str, tuple[float, str]] = field(default_factory=dict)
    # next time add() owes a sweep — amortizes the full-dict rebuild so a
    # loop marking C nodes costs O(C), not O(C²) (update() sweeps eagerly
    # once per loop regardless)
    next_sweep: float = 0.0

    def sweep(self, now: float) -> None:
        """Drop every entry whose TTL elapsed (reference: unremovable.Nodes
        Update rebuilds the map from the still-valid entries each loop)."""
        self.entries = {n: e for n, e in self.entries.items() if e[0] >= now}
        self.next_sweep = now + self.ttl_s

    def update(self, now: float) -> None:
        """Per-loop maintenance hook (planner.update calls it once)."""
        self.sweep(now)

    def add(self, node: str, reason: str, now: float) -> None:
        if now >= self.next_sweep:
            self.sweep(now)
        self.entries[node] = (now + self.ttl_s, reason)

    def drop(self, node: str) -> None:
        """A verdict resolved (the node became drainable / was accepted for
        deletion): its refusal must leave every reason surface now, not at
        TTL expiry."""
        self.entries.pop(node, None)

    def contains(self, node: str, now: float) -> bool:
        e = self.entries.get(node)
        if e is None:
            return False
        if now > e[0]:
            del self.entries[node]
            return False
        return True

    def reason(self, node: str) -> str:
        e = self.entries.get(node)
        return e[1] if e else ""

    def reason_counts(self, now: float) -> dict[str, int]:
        """Per-reason histogram of the live entries — feeds the status
        document and the unremovable_nodes_count{reason=...} gauge family
        (reference: metrics.UpdateUnremovableNodesCount)."""
        self.sweep(now)
        counts: dict[str, int] = {}
        for _, reason in self.entries.values():
            counts[reason] = counts.get(reason, 0) + 1
        return counts
