"""Asynchronous node-group creation: the loop never blocks on the cloud.

Reference counterpart: core/scaleup/orchestrator/orchestrator.go:453
CreateNodeGroupAsync + async_initializer.go (applies the initial scale-up once
creation completes) + the AsyncNodeGroupStateChecker processor row (SURVEY.md
§2.6), which lets upcoming capacity from a still-creating group count toward
the snapshot so the next loops neither re-create the group nor re-scale for
the same pods.
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
from dataclasses import dataclass

from kubernetes_autoscaler_tpu.cloudprovider.provider import NodeGroup
from kubernetes_autoscaler_tpu.models.api import Node


@dataclass
class AsyncGroupState:
    group_id: str
    initial_delta: int      # scale-up to apply the moment creation completes
    template: Node
    started: float


class AsyncNodeGroupCreator:
    """Owns the background create → initial-scale-up pipeline and answers the
    AsyncNodeGroupStateChecker question: which groups are 'upcoming by
    creation' right now, and how much capacity was promised on them."""

    def __init__(self, cluster_state=None, max_workers: int = 4):
        self._lock = threading.Lock()
        self._states: dict[str, AsyncGroupState] = {}
        self._futures: list[concurrent.futures.Future] = []
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=max_workers)
        self.cluster_state = cluster_state
        self.errors: dict[str, str] = {}

    # ---- AsyncNodeGroupStateChecker surface ----

    def is_upcoming(self, group_id: str) -> bool:
        with self._lock:
            return group_id in self._states

    def upcoming(self) -> dict[str, AsyncGroupState]:
        """Snapshot of in-flight creations (group id → promised state)."""
        with self._lock:
            return dict(self._states)

    # ---- the async pipeline (reference: async_initializer.go) ----

    def create_async(self, group: NodeGroup, delta: int,
                     now: float | None = None) -> bool:
        """Start creating `group` and scale it to `delta` when ready. Returns
        False if a creation for this id is already in flight (idempotent)."""
        now = time.time() if now is None else now
        gid = group.id()
        with self._lock:
            if gid in self._states:
                return False
            self._states[gid] = AsyncGroupState(
                group_id=gid, initial_delta=delta,
                template=group.template_node_info(), started=now)
        self._futures.append(self._pool.submit(self._run, group, gid, delta))
        return True

    def _run(self, group: NodeGroup, gid: str, delta: int) -> None:
        try:
            created = group.create() if not group.exist() else group
            created.increase_size(delta)
            if self.cluster_state is not None:
                self.cluster_state.register_scale_up(created, delta, time.time())
        except Exception as e:  # noqa: BLE001 — ANY failure must be recorded:
            # an unexpected exception escaping into a never-inspected Future
            # would silently drop the promised capacity AND skip the backoff,
            # letting the broken group win the next loop again
            self.errors[gid] = f"{type(e).__name__}: {e}"
            if self.cluster_state is not None:
                try:
                    self.cluster_state.register_failed_scale_up(group, time.time())
                except Exception:
                    pass
        finally:
            with self._lock:
                self._states.pop(gid, None)

    def wait_idle(self, timeout: float = 30.0) -> None:
        """Drain in-flight creations (tests and shutdown)."""
        done, _ = concurrent.futures.wait(self._futures, timeout=timeout)
        self._futures = [f for f in self._futures if f not in done]
