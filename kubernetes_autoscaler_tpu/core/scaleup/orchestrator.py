"""Scale-up orchestrator: from pending pods to node-group increases.

Reference counterpart: core/scaleup/orchestrator/orchestrator.go —
`ScaleUp` (:88-203): build equivalence groups, filter valid node groups,
compute an expansion option per group via the estimator (:379-414), pick via
the expander (:~1090), balance similar groups (:652), cap by quotas
(:205-217), then execute increases in parallel (executor.go:63-143).

TPU re-design: option computation for ALL node groups happens in one device
program (ops/binpack.estimate_all) instead of a serial per-group loop; the
expander's numeric scores ride the same kernel (ops/scoring). The host layer
here is pure policy: validity filtering, quota caps, winner verification
(exact string semantics for lossily-encoded pods), and cloud actuation.
"""

from __future__ import annotations

import concurrent.futures
import time
from dataclasses import dataclass, field

import numpy as np

from kubernetes_autoscaler_tpu.cloudprovider.provider import (
    CloudProvider,
    NodeGroup,
    NodeGroupError,
)
from kubernetes_autoscaler_tpu.clusterstate.registry import (
    ClusterStateRegistry,
    _ng_defaults,
)
from kubernetes_autoscaler_tpu.config.options import AutoscalingOptions
from kubernetes_autoscaler_tpu.estimator.estimator import (
    BinpackingEstimator,
    ClusterCapacityThresholdLimiter,
    SngCapacityThresholdLimiter,
    StaticThresholdLimiter,
)
from kubernetes_autoscaler_tpu.expander.strategies import (
    ChainStrategy,
    Option,
    options_from_scores,
)
from kubernetes_autoscaler_tpu.models.encode import (
    EncodedCluster,
    encode_node_groups,
)
from kubernetes_autoscaler_tpu.ops import scoring
from kubernetes_autoscaler_tpu.resourcequotas.tracker import QuotaTracker


@dataclass
class ScaleUpResult:
    scaled_up: bool
    increases: dict[str, int] = field(default_factory=dict)
    errors: dict[str, str] = field(default_factory=dict)
    pods_helped: int = 0
    pods_remaining: int = 0
    considered_options: list[Option] = field(default_factory=list)
    best: Option | None = None


@dataclass
class ScaleUpPrep:
    """Host-side scale-up inputs assembled BEFORE the fused dispatch
    (docs/FUSED_LOOP.md): the valid-group list, the marshalled group
    tensors, and the limiter cap vector the fused program applies on
    device. `limit_cap` is `combined_limit_vec` composed on the host with
    numpy — all three built-in limiters are pure integer functions of the
    cluster size and each group's max_new, both of which the host already
    knows — so the fused program needs no limiter objects inside the trace
    and the cap doubles as a stable speculation-key component."""

    groups: list
    upcoming_only: bool
    templates: list
    group_tensors: object        # NodeGroupTensors (device)
    estimator: BinpackingEstimator
    gpu_slot: int | None
    limit_cap: np.ndarray        # i32[NG] host copy
    limit_cap_dev: object        # i32[NG] device upload (jit input)


@dataclass
class FusedScaleUp:
    """Precomputed scale-up decision inputs harvested from a FusedDecision:
    host numpy est rows + scores. `ScaleUpOrchestrator.scale_up` consumes
    these instead of dispatching its estimate/score program — the rest of
    the policy path (options, expander, balancing, quota, execution,
    refusal reasons) is byte-for-byte the phased code."""

    prep: ScaleUpPrep
    est: object                  # .node_count i32[NG], .scheduled i32[NG, G]
    scores: object               # OptionScores with host numpy leaves
    pending_total: int = 0       # post-filter pending pods (decision tensor)


class ScaleUpOrchestrator:
    def __init__(
        self,
        provider: CloudProvider,
        options: AutoscalingOptions,
        cluster_state: ClusterStateRegistry,
        expander: ChainStrategy,
        quota: QuotaTracker | None = None,
        node_group_list_processor=None,
        node_group_manager=None,
        async_creator=None,
    ):
        from kubernetes_autoscaler_tpu.processors.nodegroups import (
            IdentityNodeGroupListProcessor,
            NodeGroupManager,
        )

        from kubernetes_autoscaler_tpu.metrics.phases import PhaseStats

        self.provider = provider
        self.options = options
        self.cluster_state = cluster_state
        self.expander = expander
        self.quota = quota
        # per-phase wall-clock breakdown of the scale-up host path, the
        # mirror of Planner.phases on the scale-down side: encode (template
        # tensors), dispatch (estimate + scoring programs), fetch (score
        # readback), confirm (lossy-winner oracle verification)
        self.phases = PhaseStats(owner="scaleup")
        # optional device mesh threaded into the estimator (NG options over
        # PODS_AXIS; parallel/mesh.py) — None = single-device program
        self.mesh = None
        # reason plane (events.EventSink wired by StaticAutoscaler): per-loop
        # NoScaleUp verdicts — {reason: pod count} for the gauge family and
        # the per-group detail list for status/snapshotz. Populated by the
        # LAZY reason pass (_explain_refused): one masked dispatch over the
        # refused groups only, zero dispatches when everything schedules.
        self.event_sink = None
        self.last_noscaleup: dict[str, int] = {}
        self.last_noscaleup_groups: list[dict] = []
        # shadow-audit gate (audit/shadow.py, wired by StaticAutoscaler):
        # while a persistent audit divergence is unhealed, every scale-up
        # option is derived from a verdict plane the audit proved corrupt —
        # options are REFUSED with the AuditDivergence reason instead of
        # actuated (the scale-down analog is the supervisor's safe-action
        # gating). None = no auditor.
        self.audit_gate = None
        self.node_group_list_processor = (
            node_group_list_processor or IdentityNodeGroupListProcessor()
        )
        self.node_group_manager = node_group_manager or NodeGroupManager()
        # AsyncNodeGroupCreator when --async-node-group-creation is on
        # (reference: CreateNodeGroupAsync orchestrator.go:453)
        self.async_creator = async_creator
        # template-tensor cache: the static planes (cap/labels/taints/zone)
        # change only when templates change; max_new/price change every
        # accepted scale-up and are refreshed as two small arrays instead of
        # re-encoding + re-uploading the whole NodeGroupTensors per loop
        self._group_tensor_cache: tuple | None = None
        # last group-tensor fingerprint, exported as a value-based
        # speculation-key component (docs/FUSED_LOOP.md)
        self._last_group_fp: tuple | None = None
        # composition-fingerprint memos (utils/canonical.IdentityMemo): the
        # template-tensor cache key used to re-walk every template's labels/
        # taints/capacity and every DaemonSet's spec each loop; per-object
        # identity memoization makes the key O(churn) under the repo-wide
        # replace-on-update contract (the WorldStore discipline extended to
        # this encode-path cache — docs/WORLD_STORE.md)
        from kubernetes_autoscaler_tpu.utils.canonical import IdentityMemo

        self._template_sig_memo = IdentityMemo(self._template_sig)
        self._workload_sig_memo = IdentityMemo(self._workload_sig)
        # DaemonSet workloads for template DS-overhead charging (set per
        # loop by StaticAutoscaler; reference: node_info_utils.go:45 threads
        # the daemonset lister into every sanitized template)
        self.daemonsets: list = []

    # ---- node-group validity (reference: filterValidScaleUpNodeGroups :152) ----

    def _valid_groups(self, now: float) -> list[NodeGroup]:
        valid = []
        for g in self.provider.node_groups():
            if not g.exist():
                continue
            if g.target_size() >= g.max_size():
                continue
            if not self.cluster_state.is_node_group_safe_to_scale_up(g, now):
                continue
            valid.append(g)
        return valid

    # ---- the main entry (reference: ScaleUp :88) ----

    def _candidate_groups(self, enc: EncodedCluster,
                          now: float) -> tuple[list[NodeGroup], bool]:
        groups = self._valid_groups(now)
        # candidate extension (reference: NodeGroupListProcessor — the
        # autoprovisioning variant appends not-yet-existing groups)
        groups = self.node_group_list_processor.process(
            self.provider, groups, enc.pending_pods
        )
        upcoming_only = False
        if self.async_creator is not None:
            # a group whose creation is still in flight must not be
            # re-proposed (reference: AsyncNodeGroupStateChecker gating)
            before = groups
            groups = [g for g in before
                      if not self.async_creator.is_upcoming(g.id())]
            upcoming_only = bool(before) and not groups
        return groups, upcoming_only

    def _build_estimator(self, enc: EncodedCluster) -> BinpackingEstimator:
        return BinpackingEstimator(
            enc.dims,
            max_new_nodes_static=self.options.max_new_nodes_static,
            limiters=[
                StaticThresholdLimiter(self.options.max_nodes_per_scaleup),
                ClusterCapacityThresholdLimiter(self.options.max_nodes_total),
                SngCapacityThresholdLimiter(),
            ],
            planes=enc.planes,
            nodes=enc.nodes,
            with_constraints=enc.has_constraints,
            mesh=self.mesh,
        )

    def _templates_for(self, groups: list[NodeGroup]) -> list:
        templates = []
        for g in groups:
            tmpl = g.template_node_info()
            if self.options.scale_from_unschedulable and tmpl.unschedulable:
                # reference: --scale-from-unschedulable ignores
                # .spec.unschedulable in node templates
                tmpl.unschedulable = False
            templates.append((tmpl, g.max_size() - g.target_size(),
                              getattr(g, "price_per_node", 1.0)))
        return templates

    def prepare_fused(self, enc: EncodedCluster, nodes_count: int,
                      now: float) -> ScaleUpPrep | None:
        """Assemble the scale-up half of the fused program's inputs before
        dispatch. Returns None when no candidate node group exists (the
        fused loop then runs phased — there are no group tensors to trace
        over). The host-composed `limit_cap` replicates
        `combined_limit_vec` over the three built-in limiters exactly;
        tests/test_fused_loop.py pins the equivalence."""
        import jax.numpy as jnp

        groups, upcoming_only = self._candidate_groups(enc, now)
        if not groups:
            return None
        estimator = self._build_estimator(enc)
        templates = self._templates_for(groups)
        with self.phases.phase("encode", groups=len(groups)):
            group_tensors = self._group_tensors(templates, enc)
        max_new = np.zeros((int(group_tensors.ng),), np.int32)
        for i, (_tmpl, mx, _pr) in enumerate(templates):
            max_new[i] = mx
        cap = np.full_like(max_new, np.int32(1 << 30))
        cap = np.minimum(cap, np.int32(self.options.max_nodes_per_scaleup))
        if self.options.max_nodes_total > 0:
            cap = np.minimum(cap, np.int32(
                max(self.options.max_nodes_total - nodes_count, 0)))
        cap = np.minimum(cap, np.maximum(max_new, 0))
        gpu_slot = enc.registry.slots.get(self.provider.gpu_resource_name())
        # cache the device upload on the cap BYTES: steady loops reuse the
        # same buffer (zero h2d), and a byte change is a speculation-key miss
        cached = getattr(self, "_limit_cap_cache", None)
        if cached is not None and np.array_equal(cached[0], cap):
            cap_dev = cached[1]
        else:
            cap_dev = jnp.asarray(cap)
            self._limit_cap_cache = (cap, cap_dev)
        return ScaleUpPrep(groups=groups, upcoming_only=upcoming_only,
                           templates=templates, group_tensors=group_tensors,
                           estimator=estimator, gpu_slot=gpu_slot,
                           limit_cap=cap, limit_cap_dev=cap_dev)

    def scale_up(self, enc: EncodedCluster, nodes_count: int,
                 now: float | None = None,
                 precomputed: FusedScaleUp | None = None) -> ScaleUpResult:
        now = time.time() if now is None else now
        self.last_noscaleup = {}
        self.last_noscaleup_groups = []
        if precomputed is not None:
            # the fused decision tensors carry the post-filter pending count;
            # reading enc.specs.count here would force a device sync
            pending_total = int(precomputed.pending_total)
        else:
            pending_total = int(np.asarray(enc.specs.count).sum())
        if pending_total == 0:
            return ScaleUpResult(scaled_up=False)

        if self.audit_gate is not None and self.audit_gate():
            # persistent shadow-audit divergence: refuse rather than scale
            # on corrupt verdict bits. Every pending group gets the
            # AuditDivergence verdict on all four reason surfaces (event /
            # status / unschedulable_pods_count{reason} / snapshotz) — no
            # device dispatch, the plane is exactly what is not trusted.
            self._refuse_all_pending(enc, "AuditDivergence", now)
            return ScaleUpResult(scaled_up=False,
                                 pods_remaining=pending_total)

        if precomputed is not None:
            groups = precomputed.prep.groups
            upcoming_only = precomputed.prep.upcoming_only
        else:
            groups, upcoming_only = self._candidate_groups(enc, now)
        if not groups:
            # no candidate group exists — every pending group gets the
            # summary reason without any device dispatch. If candidates
            # exist but are all still being created, "no node group can
            # help" would be false — capacity for these pods is in flight —
            # so no refusal verdict is recorded.
            if not upcoming_only:
                self._note_no_groups(enc, now)
            return ScaleUpResult(scaled_up=False, pods_remaining=pending_total)

        if precomputed is not None:
            # fused path: est/scores were computed INSIDE the fused program
            # and harvested with the loop's single decision fetch — no
            # dispatch here. The estimator still re-estimates for lossy
            # winner verification; point it at the post-placement world the
            # phased estimator would have been built from.
            estimator = precomputed.prep.estimator
            estimator.planes = enc.planes
            estimator.nodes = enc.nodes
            group_tensors = precomputed.prep.group_tensors
            gpu_slot = precomputed.prep.gpu_slot
            est = precomputed.est
            scores = precomputed.scores
        else:
            estimator = self._build_estimator(enc)
            templates = self._templates_for(groups)
            with self.phases.phase("encode", groups=len(groups)):
                group_tensors = self._group_tensors(templates, enc)
            with self.phases.phase("dispatch", groups=len(groups),
                                   pending=pending_total):
                est = estimator.estimate_all_groups(enc.specs, group_tensors,
                                                    nodes_count)
                scores = scoring.score_options(est, group_tensors,
                                               specs=enc.specs)
            # non-allocating lookup: try_slot_for would BURN one of the four
            # extended slots for the GPU name even on GPU-less clusters (any
            # GPU-bearing template/node already allocated it at encode time)
            gpu_slot = enc.registry.slots.get(self.provider.gpu_resource_name())
        with self.phases.phase("fetch"):
            options = options_from_scores(scores, [g.id() for g in groups],
                                          groups=groups, gpu_slot=gpu_slot,
                                          phases=self.phases)
        with self.phases.phase("confirm"):
            options = self._verify_lossy_winners(
                options, est, enc, groups, estimator, group_tensors,
                nodes_count
            )
        if not options:
            self._explain_refused(enc, est, group_tensors, now)
            return ScaleUpResult(scaled_up=False, pods_remaining=pending_total,
                                 considered_options=[])

        # per-loop context for filters that need it (price expander's
        # preferred-node heuristic scales with cluster size)
        for f in self.expander.filters:
            set_ctx = getattr(f, "set_loop_context", None)
            if set_ctx is not None:
                set_ctx(nodes_count)
        best = self.expander.best_option(options)
        if best is None:
            self._explain_refused(enc, est, group_tensors, now)
            return ScaleUpResult(scaled_up=False, pods_remaining=pending_total,
                                 considered_options=options)

        # ZeroOrMaxNodeScaling winners scale all-or-nothing and are excluded
        # from similar-group balancing — balancing several atomic groups
        # would blow each to max (reference: atomic groups bypass the
        # BalancingNodeGroupSetProcessor and use AtomicIncreaseSize).
        winner = groups[best.group_index]
        if self._ng_opts(winner).zero_or_max_node_scaling:
            plan = {winner.id(): winner.max_size() - winner.target_size()}
        else:
            # similar-group balancing (reference: balanceScaleUps :652 via
            # BalancingNodeGroupSetProcessor) — split the winning delta
            # across groups similar to the winner.
            plan = self._balance(best, groups, est, enc)

        # quota caps (reference: applyLimits :205-217)
        plan = self._apply_quota(plan, groups, enc)
        if not plan:
            return ScaleUpResult(scaled_up=False, pods_remaining=pending_total,
                                 considered_options=options, best=best)

        result = self._execute(plan, groups, now)
        result.considered_options = options
        result.best = best
        result.pods_helped = best.pod_count
        result.pods_remaining = max(pending_total - best.pod_count, 0)
        if result.pods_remaining > 0:
            # pods are left behind even after the winning option — attribute
            # them (groups no template could host; the lazy reason pass)
            self._explain_refused(enc, est, group_tensors, now)
        return result

    # ---- the reason plane (lazy NoScaleUp extraction) ----

    def _note_no_groups(self, enc: EncodedCluster, now: float) -> None:
        """Every pending pod group is refused because no valid node group
        exists at all — the summary reason needs no device dispatch."""
        from kubernetes_autoscaler_tpu.ops.predicates import NO_NODE_IN_GROUP

        self._refuse_all_pending(enc, NO_NODE_IN_GROUP, now)

    def _refuse_all_pending(self, enc: EncodedCluster, reason: str,
                            now: float) -> None:
        """One whole-loop refusal verdict (`reason`) for every valid
        pending group, onto all the orchestrator-owned surfaces."""
        counts = np.asarray(enc.specs.count)
        valid = np.asarray(enc.specs.valid)
        for gi in np.nonzero(valid & (counts > 0))[0]:
            self._record_noscaleup(enc, int(gi), int(counts[gi]),
                                   reason, {}, now)

    def _explain_refused(self, enc: EncodedCluster, est, group_tensors,
                         now: float) -> None:
        """Lazy reason extraction for refused pod groups: one masked
        `reason_mask_for_groups` dispatch over the TEMPLATE plane (uint16
        bits per group × node group) + one batched fetch, only when at least
        one pending group no expansion option could schedule. A loop where
        every pod is helped performs ZERO extra dispatches — the
        `reason_extraction_dispatches` event counter (mirrored into
        `phase_events_total` and the trace) proves it, and CI asserts it on
        the all-schedulable bench smoke world."""
        from kubernetes_autoscaler_tpu.estimator.estimator import (
            explain_refused_groups,
        )
        from kubernetes_autoscaler_tpu.ops import predicates as preds

        counts = np.asarray(enc.specs.count)
        valid = np.asarray(enc.specs.valid)
        scheduled = np.asarray(est.scheduled)          # [NG, G]
        helped = (scheduled.max(axis=0) if scheduled.size
                  else np.zeros_like(counts))
        refused = valid & (counts > 0) & (helped <= 0)
        if not refused.any():
            return
        with self.phases.phase("reason_extract",
                               refused_groups=int(refused.sum())):
            self.phases.bump("reason_extraction_dispatches")
            bits = explain_refused_groups(enc.specs, group_tensors, refused,
                                          enc.dims)
        gvalid = np.asarray(group_tensors.valid)
        for gi in np.nonzero(refused)[0]:
            headline, per = preds.summarize_reason_row(bits[gi], gvalid)
            self._record_noscaleup(enc, int(gi), int(counts[gi]), headline,
                                   per, now)

    def _record_noscaleup(self, enc: EncodedCluster, gi: int, pods: int,
                          reason: str, constraints: dict[str, int],
                          now: float) -> None:
        """One refused group's verdict onto every surface the orchestrator
        owns: the per-reason totals (→ unschedulable_pods_count{reason}),
        the per-group detail list (→ status document + /snapshotz), and a
        deduped NoScaleUp event keyed by the group's exemplar pod (the
        reference emits the same verdict per pod; equivalence rows make one
        event per shape)."""
        exemplar = ""
        if gi < len(enc.group_pods) and enc.group_pods[gi]:
            exemplar = enc.pending_pods[enc.group_pods[gi][0]].name
        obj = exemplar or f"pod-group-{gi}"
        self.last_noscaleup[reason] = self.last_noscaleup.get(reason, 0) + pods
        self.last_noscaleup_groups.append({
            "group": gi, "exemplarPod": obj, "pods": pods,
            "reason": reason, "constraints": constraints,
        })
        if self.event_sink is not None:
            from kubernetes_autoscaler_tpu.ops.predicates import (
                CAPPED_BY_LIMITS,
                NO_NODE_IN_GROUP,
            )

            detail = ", ".join(f"{k}×{v}" for k, v in constraints.items())
            if reason == CAPPED_BY_LIMITS:
                # the opposite of a constraint refusal: a template CAN host
                # the group, the option was capped/crowded out
                msg = (f"{pods} pending pods fit a node group template, but "
                       f"option capping (max_new / limiter stack / crowded "
                       f"bins) left them behind")
            elif reason == NO_NODE_IN_GROUP:
                msg = f"{pods} pending pods; no candidate node group exists"
            elif reason == "AuditDivergence":
                msg = (f"{pods} pending pods; scale-up refused — the "
                       f"shadow audit proved the device verdict plane "
                       f"diverges from the host oracle and the divergence "
                       f"survived a forced re-encode (docs/OBSERVABILITY"
                       f".md \"Shadow audit\")")
            else:
                msg = (f"{pods} pending pods; no node group can host them"
                       + (f" (refusing templates: {detail})" if detail
                          else ""))
            self.event_sink.emit("NoScaleUp", obj=obj, reason=reason,
                                 message=msg, now=now)

    # ---- winner verification (the host-check tier) ----

    def _verify_lossy_winners(self, options, est, enc: EncodedCluster, groups,
                              estimator, group_tensors, nodes_count: int):
        """Exact-check lossily-encoded pod groups against each option's
        template. Options relying on refuted pods are RE-ESTIMATED with those
        pods masked out so node_count/waste/price reflect only pods that will
        actually schedule. Plays the role of the reference's real scheduler
        framework run — predicate truth always comes from exact semantics
        before actuation. The oracle sees the FULL cluster (nodes + resident
        pods), so topology spread / inter-pod affinity / multi-term node
        affinity are all evaluated exactly (check_pod_on_new_node).

        Re-estimation is BATCHED: all oracle checks run first, then options
        sharing a refuted-pod mask share one estimate_all dispatch (refuted
        sets are template-determined, so similar templates coalesce) — the
        device round trips scale with distinct masks, not flagged options."""
        import jax.numpy as jnp

        flagged = np.asarray(enc.specs.needs_host_check)
        if not flagged.any():
            return options
        all_nodes, pods_by_node = enc.all_nodes_and_pods()
        # incremental constraint cache: the full oracle walks nodes x pods
        # PER exemplar check — seconds per flagged option at 5k x 50k
        from kubernetes_autoscaler_tpu.utils.oracle_cache import ConfirmOracle

        oracle_world = ConfirmOracle(all_nodes, pods_by_node,
                                     registry=enc.registry,
                                     namespaces=enc.namespaces)
        scheduled = np.asarray(est.scheduled)  # [NG, G]
        # --max-binpacking-time bounds the whole option computation; once the
        # budget is gone, options needing a re-estimate are dropped rather
        # than shipped unverified (reference: BinpackingLimiter stops
        # computing further options)
        deadline = time.monotonic() + self.options.max_binpacking_time_s
        gpu_slot = enc.registry.slots.get(self.provider.gpu_resource_name())
        from kubernetes_autoscaler_tpu.utils.daemonset import (
            daemonset_pods_for_node,
        )

        # pass 1: oracle-check every option, bucketing by refuted-pod mask
        resolved: dict[int, Option | None] = {}
        by_mask: dict[tuple, list[Option]] = {}
        for opt in options:
            g_t = groups[opt.group_index].template_node_info()
            # the exact tier sees the same DS-loaded fresh node the dense
            # capacity rows encode (node_info_utils.go:45)
            ds_pods = daemonset_pods_for_node(g_t, self.daemonsets) \
                if self.daemonsets else None
            refuted: list[int] = []
            for gi in np.nonzero(flagged)[0]:
                if scheduled[opt.group_index, gi] <= 0:
                    continue
                if gi < len(enc.group_pods) and enc.group_pods[gi]:
                    exemplar = enc.pending_pods[enc.group_pods[gi][0]]
                    if not oracle_world.check_on_new_node(
                            exemplar, g_t, resident_pods=ds_pods):
                        refuted.append(int(gi))
            if not refuted:
                resolved[id(opt)] = opt
            else:
                by_mask.setdefault(tuple(sorted(refuted)), []).append(opt)
                self.phases.bump("lossy_reestimate_options")

        # pass 2: one re-estimate per DISTINCT refuted mask, consumed by
        # every surviving option that shares it
        from kubernetes_autoscaler_tpu.models.resources import CPU, MEMORY

        for refuted, opts_b in by_mask.items():
            if time.monotonic() > deadline:
                continue  # budget exhausted: unverifiable options are dropped
            self.phases.bump("lossy_reestimate_dispatches")
            count = np.asarray(enc.specs.count).copy()
            count[list(refuted)] = 0
            masked = enc.specs.replace(count=jnp.asarray(count))
            redo = estimator.estimate_all_groups(masked, group_tensors,
                                                 nodes_count)
            sc = scoring.fetch_scores(
                scoring.score_options(redo, group_tensors, specs=masked))
            helped = np.asarray(sc.helped_req)
            for opt in opts_b:
                i = opt.group_index
                if not bool(sc.valid[i]):
                    continue
                resolved[id(opt)] = Option(
                    group_index=i, group_id=opt.group_id,
                    node_count=int(sc.nodes[i]), pod_count=int(sc.pods[i]),
                    waste=float(sc.waste[i]), price=float(sc.price[i]),
                    template=opt.template, exists=opt.exists,
                    helped_cpu_milli=float(helped[i, CPU]),
                    helped_mem_mib=float(helped[i, MEMORY]),
                    # from the re-estimate, like cpu/mem — the pre-mask value
                    # would overstate GPU help for options with refuted pods
                    helped_gpus=(float(helped[i, gpu_slot])
                                 if gpu_slot is not None else 0.0),
                )
        # original option order preserved (expander tie-breaks see the same
        # sequence the serial path produced)
        return [resolved[id(o)] for o in options
                if resolved.get(id(o)) is not None]

    @staticmethod
    def _template_sig(tmpl) -> tuple:
        """Content signature of one template node for the group-tensor cache
        key (memoized by object identity via IdentityMemo — providers that
        return a cached template object pay O(1) per loop; providers that
        mint a fresh Node per call recompute, exactly the old behavior)."""
        return (tmpl.name, tuple(sorted(tmpl.labels.items())),
                tuple((t.key, t.value, t.effect) for t in tmpl.taints),
                tuple(sorted((k, float(v))
                             for k, v in tmpl.alloc_or_cap().items())))

    @staticmethod
    def _workload_sig(w) -> tuple:
        """DS churn changes the charged capacity rows — every field
        daemonset_overhead consults: requests + overhead (the charge),
        selector/affinity/tolerations (the node match)."""
        return (w.namespace, w.name, w.uid,
                (tuple(sorted((k, float(v))
                              for k, v in w.template.requests.items())),
                 tuple(sorted((k, float(v))
                              for k, v in w.template.overhead.items())),
                 tuple(sorted(w.template.node_selector.items())),
                 tuple(tuple((r.key, r.operator, r.values) for r in term)
                       for term in w.template.affinity_node_terms()),
                 tuple((t.key, t.value, t.effect, t.operator)
                       for t in w.template.tolerations))
                if w.template is not None else None)

    def _group_tensors(self, templates, enc):
        """encode_node_groups with the static planes cached across loops."""
        import jax.numpy as jnp

        from kubernetes_autoscaler_tpu.models.cluster_state import pad_to

        fp = (
            tuple(self._template_sig_memo.refresh(
                [tmpl for tmpl, _mx, _pr in templates])),
            # the full MAPPINGS, not their sizes: a rebuild can reassign
            # the same number of slot/zone ids in a different first-seen
            # order
            tuple(sorted(enc.registry.slots.items())),
            tuple(sorted(enc.zone_table.ids.items())),
            enc.dims,
            tuple(self._workload_sig_memo.refresh(self.daemonsets)),
        )
        # value-based fingerprint for the speculation key: the cache-hit
        # path below rebuilds max_new/price arrays every loop, so object
        # identity on the tensors never holds across loops
        self._last_group_fp = fp
        cached = self._group_tensor_cache
        if cached is not None and cached[0] == fp:
            gt = cached[1]
            ng_pad = pad_to(max(len(templates), 1), 8)
            if gt.ng == ng_pad:
                self.phases.bump("group_tensor_cache_hit")
                max_new = np.zeros((ng_pad,), np.int32)
                price = np.zeros((ng_pad,), np.float32)
                for i, (_tmpl, mx, pr) in enumerate(templates):
                    max_new[i] = mx
                    price[i] = pr
                gt = gt.replace(max_new=jnp.asarray(max_new),
                                price_per_node=jnp.asarray(price))
                self._group_tensor_cache = (fp, gt)
                return gt
        # a miss re-encodes + re-uploads the whole NodeGroupTensors — a
        # recompile-risk event on the trace (new tensor identities feed the
        # estimator's jit)
        self.phases.bump("group_tensor_cache_miss")
        gt = encode_node_groups(templates, enc.registry, enc.zone_table,
                                enc.dims, daemonsets=self.daemonsets)
        self._group_tensor_cache = (fp, gt)
        # HBM residency ledger (metrics/device.py): the marshalled group
        # tensors are device arrays held across loops by this cache
        from kubernetes_autoscaler_tpu.metrics import device

        if device.LEDGER is not None:
            device.LEDGER.track("marshal", "group_tensors", gt)
        return gt

    # ---- similar-group balancing (reference: compare_nodegroups.go:105) ----

    def _balance(self, best: Option, groups: list[NodeGroup], est,
                 enc=None) -> dict[str, int]:
        if not self.options.balance_similar_node_groups:
            return {best.group_id: best.node_count}
        target = groups[best.group_index]
        tmpl = target.template_node_info()
        free = _group_exemplar_free(enc, groups, self.provider) \
            if enc is not None else {}
        similar = [target]
        for i, g in enumerate(groups):
            if g.id() == target.id():
                continue
            if self._ng_opts(g).zero_or_max_node_scaling:
                continue  # an atomic sibling cannot absorb a partial split
            t = g.template_node_info()
            if _similar_templates(tmpl, t, self.options,
                                  free_a=free.get(target.id()),
                                  free_b=free.get(g.id())) \
                    and g.target_size() < g.max_size():
                similar.append(g)
        total = best.node_count
        plan: dict[str, int] = {}
        # even split honoring current target sizes (fill smallest first);
        # groups at max size drop out of the rotation, they don't stop it
        sizes = {g.id(): g.target_size() for g in similar}
        caps = {g.id(): g.max_size() for g in similar}
        for _ in range(total):
            open_groups = {k: v for k, v in sizes.items() if v < caps[k]}
            if not open_groups:
                break
            gid = min(open_groups, key=lambda k: open_groups[k])
            sizes[gid] += 1
            plan[gid] = plan.get(gid, 0) + 1
        return plan or {best.group_id: best.node_count}

    # ---- quota caps ----

    def _ng_opts(self, g: NodeGroup):
        return g.get_options(_ng_defaults(self.options))

    def _apply_quota(self, plan: dict[str, int], groups: list[NodeGroup],
                     enc: EncodedCluster) -> dict[str, int]:
        capped = dict(plan)
        if self.quota is not None:
            status = self.quota.status_from_encoded(enc)
            for gid in list(capped):
                g = next(gr for gr in groups if gr.id() == gid)
                allowed = self.quota.max_nodes_addable(
                    status, g.template_node_info(), capped[gid]
                )
                if allowed < capped[gid]:
                    from kubernetes_autoscaler_tpu.metrics.metrics import (
                        default_registry,
                    )

                    default_registry.counter("skipped_scale_events_count").inc(
                        direction="up", reason="ResourceLimits")
                if allowed < capped[gid] and self._ng_opts(g).zero_or_max_node_scaling:
                    # an atomic group cannot partially scale: all or nothing
                    del capped[gid]
                elif allowed <= 0:
                    del capped[gid]
                elif allowed < capped[gid]:
                    capped[gid] = allowed
        return capped

    # ---- execution (reference: executor.go:96-143, parallel per group) ----

    def _execute(self, plan: dict[str, int], groups: list[NodeGroup],
                 now: float) -> ScaleUpResult:
        by_id = {g.id(): g for g in groups}
        result = ScaleUpResult(scaled_up=False)

        def one(gid: str, delta: int):
            g = by_id[gid]
            if not g.exist():
                if (self.async_creator is not None
                        and self.options.async_node_group_creation):
                    # fire-and-track: creation + initial scale-up happen off
                    # the loop thread; capacity counts as upcoming meanwhile
                    # (reference: CreateNodeGroupAsync + async_initializer.go)
                    self.async_creator.create_async(g, delta, now)
                    return gid, delta, True
                # winner is an auto-provisioning candidate: create first
                # (reference: orchestrator CreateNodeGroup before IncreaseSize)
                self.node_group_manager.create_node_group(g)
            if self._ng_opts(g).zero_or_max_node_scaling:
                g.atomic_increase_size(delta)
            else:
                g.increase_size(delta)
            return gid, delta, False

        workers = 8 if self.options.parallel_scale_up else 1
        with concurrent.futures.ThreadPoolExecutor(max_workers=workers) as ex:
            futures = {ex.submit(one, gid, d): gid for gid, d in plan.items()}
            for fut in concurrent.futures.as_completed(futures):
                gid = futures[fut]
                try:
                    _, delta, async_pending = fut.result()
                    result.increases[gid] = delta
                    if not async_pending:
                        # async creations register with the CSR when the
                        # creator's pipeline completes, not here
                        self.cluster_state.register_scale_up(by_id[gid], delta, now)
                    result.scaled_up = True
                except NodeGroupError as e:
                    result.errors[gid] = str(e)
                    self.cluster_state.register_failed_scale_up(by_id[gid], now)
        return result

    # ---- min-size enforcement (reference: ScaleUpToNodeGroupMinSize :223) ----

    def scale_up_to_min_sizes(self, now: float | None = None) -> ScaleUpResult:
        now = time.time() if now is None else now
        result = ScaleUpResult(scaled_up=False)
        for g in self._valid_groups(now):
            delta = g.min_size() - g.target_size()
            if delta > 0:
                try:
                    g.increase_size(delta)
                    self.cluster_state.register_scale_up(g, delta, now)
                    result.increases[g.id()] = delta
                    result.scaled_up = True
                except NodeGroupError as e:
                    result.errors[g.id()] = str(e)
                    self.cluster_state.register_failed_scale_up(g, now)
        return result


def _group_exemplar_free(enc, groups, provider) -> dict[str, "np.ndarray"]:
    """Per-group FREE resource vector from a live exemplar node (reference:
    compare_nodegroups.go:109-121 builds free = allocatable - requested from
    the groups' exemplar NodeInfos). Groups without a registered node have
    no exemplar — free comparison is skipped for them (a template is empty
    by construction, so template-vs-template free degenerates to allocatable,
    which is already compared).

    nodes.group_id holds indices into the FULL provider.node_groups()
    enumeration (static_autoscaler._node_group_index), not into the filtered
    `groups` list — map through the provider ordering."""
    gid_arr = np.asarray(enc.nodes.group_id)
    valid = np.asarray(enc.nodes.valid)
    free_all = np.asarray(enc.nodes.cap) - np.asarray(enc.nodes.alloc)
    provider_index = {g.id(): i for i, g in enumerate(provider.node_groups())}
    out: dict[str, np.ndarray] = {}
    for g in groups:
        pi = provider_index.get(g.id())
        if pi is None:
            continue
        rows = np.nonzero(valid & (gid_arr == pi))[0]
        if rows.size:
            out[g.id()] = free_all[rows[0]]
    return out


def _similar_templates(a, b, options: AutoscalingOptions | None = None,
                       free_a=None, free_b=None) -> bool:
    """Reference similarity: capacity within --max-allocatable-difference-ratio
    (memory within --memory-difference-ratio), exemplar FREE resources within
    --max-free-difference-ratio, same labels ignoring zone/hostname plus
    --balancing-ignore-label entries; --balancing-label switches to comparing
    ONLY the listed labels
    (processors/nodegroupset/compare_nodegroups.go:100-153 + flags)."""
    IGNORE = {"kubernetes.io/hostname", "topology.kubernetes.io/zone",
              "failure-domain.beta.kubernetes.io/zone"}
    ratio = options.max_allocatable_difference_ratio if options else 0.05
    mem_ratio = options.memory_difference_ratio if options else 0.015
    if options:
        IGNORE = IGNORE | set(options.balancing_ignore_labels)

    def caps(n):
        return {k: float(v) for k, v in n.alloc_or_cap().items()}

    ca, cb = caps(a), caps(b)
    if set(ca) != set(cb):
        return False
    for k in ca:
        hi = max(ca[k], cb[k])
        limit = mem_ratio if k == "memory" else ratio
        if hi > 0 and abs(ca[k] - cb[k]) / hi > limit:
            return False
    if free_a is not None and free_b is not None and options is not None:
        free_ratio = options.max_free_difference_ratio
        for fa, fb in zip(free_a.tolist(), free_b.tolist()):
            hi = max(fa, fb)
            if hi > 0 and abs(fa - fb) / hi > free_ratio:
                return False
    if options and options.balancing_labels:
        keys = options.balancing_labels
        return all(a.labels.get(k) == b.labels.get(k) for k in keys)
    la = {k: v for k, v in a.labels.items() if k not in IGNORE}
    lb = {k: v for k, v in b.labels.items() if k not in IGNORE}
    return la == lb
