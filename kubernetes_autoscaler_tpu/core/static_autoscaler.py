"""StaticAutoscaler: the control-loop body — one RunOnce per tick.

Reference counterpart: core/static_autoscaler.go:296-624 RunOnce:
state refresh → snapshot build → health gating → unregistered-node cleanup →
upcoming-node injection (:499) → pod-list processing (:530, filter-out-
schedulable) → scale-up dispatch (:589) → scale-down dispatch (:604,:749) →
status reporting.

TPU re-design: the snapshot build lowers the cluster to tensors once
(models/encode); filter-out-schedulable, option estimation and the drain sweep
are device programs; everything else here is thin host policy glue. The
ClusterDataSource seam abstracts the kube API (informers/listers in the
reference; a fake cluster in tests; the gRPC sidecar feed in deployment).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Protocol

import numpy as np

from kubernetes_autoscaler_tpu.cloudprovider.provider import CloudProvider
from kubernetes_autoscaler_tpu.clusterstate.registry import ClusterStateRegistry
from kubernetes_autoscaler_tpu.config.options import AutoscalingOptions
from kubernetes_autoscaler_tpu.core.scaledown.actuator import Actuator
from kubernetes_autoscaler_tpu.core.scaledown.latencytracker import NodeLatencyTracker
from kubernetes_autoscaler_tpu.core.scaledown.pdb import RemainingPdbTracker
from kubernetes_autoscaler_tpu.core.scaledown.planner import (
    FusedScaleDown,
    Planner,
)
from kubernetes_autoscaler_tpu.core.scaleup.orchestrator import (
    FusedScaleUp,
    ScaleUpOrchestrator,
    ScaleUpResult,
)
from kubernetes_autoscaler_tpu.expander.strategies import build_expander
from kubernetes_autoscaler_tpu.metrics import device as device_obs
from kubernetes_autoscaler_tpu.metrics import trace
from kubernetes_autoscaler_tpu.metrics.metrics import HealthCheck, Registry, default_registry
from kubernetes_autoscaler_tpu.metrics.trace import FlightRecorder
from kubernetes_autoscaler_tpu.models.api import Node, Pod
from kubernetes_autoscaler_tpu.models.encode import encode_cluster
from kubernetes_autoscaler_tpu.ops import hostfetch
from kubernetes_autoscaler_tpu.observers.nodegroupchange import (
    NodeGroupChangeObserverList,
)
from kubernetes_autoscaler_tpu.processors.processors import (
    AutoscalingProcessors,
    ProcessorContext,
)
from kubernetes_autoscaler_tpu.resourcequotas.tracker import (
    QuotaTracker,
    merge_flag_limits,
)
from kubernetes_autoscaler_tpu.simulator.drainability.rules import (
    DrainOptions,
    apply_drainability,
)
from kubernetes_autoscaler_tpu.simulator.snapshot import TensorClusterSnapshot


class ClusterDataSource(Protocol):
    """reference: utils/kubernetes listers (obtainNodeLists :331, listPods :342)."""

    def list_nodes(self) -> list[Node]: ...

    def list_pods(self) -> list[Pod]: ...


@dataclass
class RunOnceStatus:
    ran: bool = True
    aborted_reason: str = ""
    scale_up: ScaleUpResult | None = None
    scale_down_deleted: list[str] = field(default_factory=list)
    unneeded_nodes: list[str] = field(default_factory=list)
    pending_pods: int = 0
    # run_loop's catch records a failed loop here instead of dying with it
    # (reference: loop/run.go RunAutoscalerOnce wrapper)
    error: str = ""
    # safe-action gating: scale-down actuation withheld because the backend
    # supervisor does not trust the simulation (degraded/recovering or an
    # unverified world) — the would-be victims carry BackendDegraded marks
    scale_down_withheld: bool = False
    backend_state: str = ""
    # device-memory pprof snapshot persisted by an OOM-failed loop (the
    # flight-recorder-adjacent evidence; "" = no OOM / no dump dir)
    hbm_dump_path: str = ""
    # shadow audit (audit/shadow.py): True when this loop's sampled device
    # verdicts diverged from the host oracle; the bundle path mirrors
    # hbm_dump_path so run_loop's failed-status path and the restart
    # record both carry the evidence pointer across a crash
    audit_divergence: bool = False
    audit_bundle_path: str = ""
    # fused single-dispatch loop (docs/FUSED_LOOP.md): which mode this loop
    # actually ran ("fused" / "phased"), the device round trips it cost
    # (counted at the hostfetch layer), and the speculation outcome for the
    # fused program harvested this loop ("hit" / "discard" / "none")
    fused_mode: str = "phased"
    loop_device_round_trips: int = 0
    speculation: str = "none"


class StaticAutoscaler:
    def __init__(
        self,
        provider: CloudProvider,
        source: ClusterDataSource,
        options: AutoscalingOptions | None = None,
        processors: AutoscalingProcessors | None = None,
        registry: Registry | None = None,
        eviction_sink=None,
        expander_priorities: dict[int, list[str]] | None = None,
        debugging_snapshotter=None,
        status_sink=None,
        walltime: Callable[[], float] = time.time,
    ):
        self.options = options or AutoscalingOptions()
        self.provider = provider
        self.source = source
        # the RunOnce `now` domain (wall clock in production, logical time in
        # harnesses). Threaded into the Actuator so eviction timestamps live
        # in the SAME domain run_once(now=...) prunes recent_evictions with —
        # otherwise the 15-min eviction TTL never fires under logical time
        # and unknown-owner phantoms are re-injected forever (ADVICE r5)
        self.walltime = walltime
        self.processors = processors or AutoscalingProcessors.default()
        self.metrics = registry or default_registry
        self.health = HealthCheck(
            max_inactivity_s=self.options.max_inactivity_s,
            max_failing_time_s=self.options.max_failing_time_s,
            max_startup_time_s=self.options.max_startup_time_s,
        )
        # debugging /snapshotz collector (reference: debuggingsnapshot/)
        self.debugging_snapshotter = debugging_snapshotter
        # status-document sink (reference: WriteStatusConfigMap each loop)
        self.status_sink = status_sink
        self.last_status = None
        # scale event broadcast (reference: observers/nodegroupchange)
        self.node_group_change_observers = NodeGroupChangeObserverList()
        self.cluster_state = ClusterStateRegistry(provider, self.options)
        # flag-level cores/memory/GPU caps merge into the provider's limiter
        # (reference: resourcequotas default provider wraps --cores-total etc.)
        limiter = merge_flag_limits(provider.get_resource_limiter(), self.options)
        self.quota = (QuotaTracker(limiter, None)  # registry set per loop
                      if self.options.capacity_quotas_enabled else None)
        grpc_call = None
        if self.options.grpc_expander_url and "grpc" in self.options.expander:
            from kubernetes_autoscaler_tpu.expander.grpc_transport import (
                grpc_expander_call,
            )

            grpc_call = grpc_expander_call(
                url=self.options.grpc_expander_url,
                cert_file=self.options.grpc_expander_cert)
        expander = build_expander(self.options.expander, expander_priorities,
                                  grpc_call=grpc_call,
                                  pricing=provider.pricing())
        # auto-provisioning wiring (reference: builder picks the
        # autoprovisioning NodeGroupListProcessor when the flag is on)
        from kubernetes_autoscaler_tpu.processors.nodegroups import (
            AutoprovisioningNodeGroupListProcessor,
            NodeGroupManager,
        )

        self.node_group_manager = NodeGroupManager()
        ng_list_proc = (
            AutoprovisioningNodeGroupListProcessor(
                self.options.max_autoprovisioned_node_group_count
            )
            if self.options.node_autoprovisioning_enabled else None
        )
        # async group creation (reference: CreateNodeGroupAsync + the
        # AsyncNodeGroupStateChecker processor row)
        self.async_creator = None
        if self.options.async_node_group_creation:
            from kubernetes_autoscaler_tpu.core.scaleup.async_groups import (
                AsyncNodeGroupCreator,
            )

            self.async_creator = AsyncNodeGroupCreator(self.cluster_state)
        self.scale_up_orchestrator = ScaleUpOrchestrator(
            provider, self.options, self.cluster_state, expander, None,
            node_group_list_processor=ng_list_proc,
            node_group_manager=self.node_group_manager,
            async_creator=self.async_creator,
        )
        # shared scale-down trackers (reference: planner & actuator share one
        # RemainingPdbTracker; latency spans plan→delete)
        self.pdb_tracker = RemainingPdbTracker()
        self.latency_tracker = (
            NodeLatencyTracker()
            if self.options.node_removal_latency_tracking_enabled else None)
        self.planner = Planner(provider, self.options, None,
                               pdb_tracker=self.pdb_tracker,
                               latency_tracker=self.latency_tracker)
        # per-phase host-path breakdown rides the normal metrics exposition
        # (both directions: scale-down planner and scale-up orchestrator)
        self.planner.phases.registry = self.metrics
        self.scale_up_orchestrator.phases.registry = self.metrics
        # reason plane: one throttled/deduped event sink shared by both
        # directions (NoScaleUp from the orchestrator, NoScaleDown from the
        # planner); reason-labelled gauges track which labels were set last
        # loop so stale reasons zero out instead of lingering
        from kubernetes_autoscaler_tpu.events import EventSink

        self.event_sink = EventSink(registry=self.metrics)
        self.planner.event_sink = self.event_sink
        self.scale_up_orchestrator.event_sink = self.event_sink
        # backend supervisor (core/supervisor.py): the healthy → suspect →
        # degraded → recovering ladder around the device phases. Always
        # constructed — with the default phase deadline of 0 the guards run
        # inline (no watchdog threads) but raised phases still drive the
        # ladder and the safe-action gating below.
        from kubernetes_autoscaler_tpu.core import supervisor as supervisor_mod

        self._supervisor_mod = supervisor_mod
        self.supervisor = supervisor_mod.BackendSupervisor(
            registry=self.metrics, event_sink=self.event_sink,
            phase_deadline_s=self.options.backend_phase_deadline_s,
            probe_deadline_s=self.options.backend_probe_deadline_s,
            suspect_threshold=self.options.backend_suspect_threshold,
            recovery_probes=self.options.backend_recovery_probes,
            recovery_hysteresis_loops=(
                self.options.backend_recovery_hysteresis_loops))
        self._last_unsched_reasons: set[str] = set()
        self._last_unremovable_reasons: set[str] = set()
        # always-on flight recorder: ring of the last N RunOnce traces,
        # persisted when a loop breaches its budget, raises, or served an
        # armed /snapshotz (metrics/trace.py; capacity 0 = tracing off)
        self.flight_recorder = FlightRecorder(
            capacity=self.options.flight_recorder_capacity,
            dump_dir=self.options.flight_recorder_dir)
        # device-side observability (metrics/device.py): the HBM residency
        # ledger census is published per loop and the leak watchdog watches
        # the UNTAGGED remainder (device bytes no owner registered); a
        # loop-SLO breach arms the device profiler so the NEXT RunOnce runs
        # under a bounded jax.profiler.trace capture
        if self.options.device_ledger:
            device_obs.enable_ledger()
        self._hbm_watchdog = device_obs.LeakWatchdog(
            k=self.options.hbm_watchdog_loops, registry=self.metrics)
        if self.options.device_profile_dir:
            device_obs.install_profiler(self.options.device_profile_dir,
                                        registry=self.metrics)
        self.last_hbm_report: dict | None = None
        # path of the device-memory pprof snapshot persisted by the most
        # recent OOM-failed loop ("" = none); run_loop surfaces it on the
        # failed RunOnceStatus
        self.last_oom_dump: str = ""
        # online shadow audit (audit/shadow.py): budget-bounded sampled
        # re-verification of device verdicts against the host oracle each
        # loop; a divergence writes an evidence bundle, drives the
        # supervisor ladder (cause=audit_divergence) and forces a
        # WorldStore heal + re-audit of the same sample
        self.shadow_auditor = None
        self.last_audit_bundle: str = ""
        self._audit_divergent_loop = False
        if self.options.shadow_audit:
            from kubernetes_autoscaler_tpu.audit.shadow import ShadowAuditor

            self.shadow_auditor = ShadowAuditor(
                registry=self.metrics, event_sink=self.event_sink,
                samples=self.options.shadow_audit_samples,
                budget_ms=self.options.shadow_audit_budget_ms,
                bundle_dir=(self.options.shadow_audit_dir
                            or self.options.flight_recorder_dir))
            # persistent divergence refuses scale-up: every option would
            # be derived from a verdict plane the audit proved corrupt
            self.scale_up_orchestrator.audit_gate = \
                self.shadow_auditor.scale_up_untrusted
        # deterministic flight journal (replay/): every RunOnce recorded as
        # a self-contained snapshot/delta record, replayable bit-for-bit by
        # `python -m kubernetes_autoscaler_tpu.replay` (--journal-dir /
        # --journal-max-mb; "" = off). The journal cursor (loop index +
        # record digest) is stamped onto the trace root span, /snapshotz
        # payloads and flight-recorder dumps so any retained evidence names
        # its replayable record.
        from kubernetes_autoscaler_tpu.replay import journal as journal_mod

        self._journal_mod = journal_mod
        self.journal = None
        if self.options.journal_dir:
            self.journal = journal_mod.JournalWriter(
                self.options.journal_dir,
                max_mb=self.options.journal_max_mb,
                registry=self.metrics, options=self.options)
        # replay harness sets this to capture the verdict plane without a
        # writer; the plane fetch is one tiny int32[G] device read.
        # last_verdict_keys maps plane rows to equivalence keys — row
        # NUMBERING is encode-path-dependent (the incremental encoder keeps
        # historical rows, a full encode renumbers per listing), so
        # cross-encode-mode byte comparison must key rows by group identity
        self.capture_verdicts = False
        self.last_verdict_plane = None
        self.last_verdict_keys = None
        self._journal_cursor: tuple[int, str] | None = None
        # live decision lineage (lineage/index.py): the bounded per-object
        # provenance ring served on /whyz + /snapshotz. Fed once per loop
        # from the SAME collect_outputs dict the journal seals — pure
        # observer, zero extra device dispatches (--lineage-ring)
        self.lineage_ring = None
        if self.options.lineage_ring:
            from kubernetes_autoscaler_tpu.lineage.index import LineageRing

            self.lineage_ring = LineageRing(
                objects=self.options.lineage_ring_objects,
                loops=self.options.lineage_ring_loops,
                registry=self.metrics, event_sink=self.event_sink)
        self._async_group_of: dict[str, str] = {}
        self.actuator = Actuator(provider, self.options, eviction_sink,
                                 pdb_tracker=self.pdb_tracker,
                                 latency_tracker=self.latency_tracker,
                                 walltime=walltime)
        # pods on still-draining nodes join the pending list pre-scale-up
        # (reference chain slot: after the expendable filter,
        # pod_list_processor.go:28-32)
        from kubernetes_autoscaler_tpu.processors.processors import (
            CurrentlyDrainedNodesProcessor,
        )

        self.processors.pod_list_processors.insert(
            min(2, len(self.processors.pod_list_processors)),
            CurrentlyDrainedNodesProcessor(self.actuator.tracker))
        self.last_scale_down_delete: float = 0.0
        self.last_scale_down_fail: float = 0.0
        # one-time crash recovery on the first loop (reference:
        # cleanUpIfRequired static_autoscaler.go:258 + planner.go:91-93)
        self._startup_recovery_done = False
        # the rehydrated restart record, kept for provenance (its journal
        # cursor names the recorded loop the clocks came from)
        self._restored_restart = None
        # device-resident world state (models/world_store.py wrapping the
        # incremental encoder, models/incremental.py); created lazily so
        # DrainOptions reflect the live flag values. `_encoder` stays the
        # underlying IncrementalEncoder for compatibility and the
        # DRA/CSI invalidate path.
        self._world_store = None
        self._encoder = None
        self._last_lowering_key = None
        # fused-loop state (docs/FUSED_LOOP.md): the per-loop context built
        # by _fused_dispatch, the in-flight speculative dispatch issued at
        # the END of the previous loop, the last discarded speculation
        # (kept for the mismatch-injection test to compare against the
        # committed decision), and the fused program's last observed
        # compile-cache size (growth = a recompile event)
        self._fused_ctx = None
        self._speculation = None
        self.last_speculation = None
        self._fused_cache_size = 0
        self._fused_census = None

        # ProvisioningRequest wiring (reference: builder/autoscaler.go wraps
        # the scale-up orchestrator when ProvReq support is on) — active when
        # the data source exposes requests
        # capacity buffers (reference: InitializeAndRunDefaultBufferController,
        # builder/autoscaler.go:209) — reconcile every loop when the source
        # exposes buffers; fake-pod INJECTION has its own independent gate
        self.buffer_controller = None
        self._list_buffers = (getattr(source, "list_capacity_buffers", None)
                              if self.options.capacity_buffer_controller_enabled
                              else None)
        if self._list_buffers is not None:
            from kubernetes_autoscaler_tpu.capacitybuffer.controller import (
                BufferController,
                BufferPodListProcessor,
            )

            self.buffer_controller = BufferController([])
            if self.options.capacity_buffer_pod_injection_enabled:
                self.processors.pod_list_processors.append(
                    BufferPodListProcessor(self.buffer_controller))

        self.provreq_wrapper = None
        list_provreqs = (getattr(source, "list_provisioning_requests", None)
                         if self.options.enable_provisioning_requests else None)
        if list_provreqs is not None:
            from kubernetes_autoscaler_tpu.provisioningrequest.orchestrator import (
                ProvReqOrchestrator,
                ProvReqPodListProcessor,
                WrapperOrchestrator,
            )

            orch = ProvReqOrchestrator(
                provider,
                node_bucket=self.options.node_shape_bucket,
                group_bucket=self.options.group_shape_bucket,
                max_new_nodes_static=self.options.max_new_nodes_static,
            )
            self.provreq_wrapper = WrapperOrchestrator(orch, list_provreqs)
            self.processors.pod_list_processors.append(
                ProvReqPodListProcessor(list_provreqs)
            )

    # ---- the loop body (reference: RunOnce :296) ----

    def run_once(self, now: float | None = None) -> RunOnceStatus:
        now = self.walltime() if now is None else now
        # trace ownership: an already-active tracer (bench.py --trace, an
        # embedding harness) gets a nested RunOnce span and keeps recording
        # responsibility; otherwise this loop owns a fresh trace and records
        # it into the flight recorder on the way out
        outer = trace.current_tracer()
        tracer = outer
        if tracer is None and self.flight_recorder.capacity > 0:
            tracer = trace.Tracer()
            trace.activate(tracer)
        dbg = self.debugging_snapshotter
        armed = dbg is not None and dbg.is_data_collection_allowed()
        root = tracer.begin("RunOnce", cat="loop", now=now) \
            if tracer is not None else None
        t0 = time.perf_counter()
        error: Exception | None = None
        self._journal_cursor = None
        self.last_oom_dump = ""
        self._audit_divergent_loop = False
        try:
            prof = device_obs.PROFILER
            if prof is not None and prof.armed:
                # breach-armed capture: this whole RunOnce runs under one
                # bounded jax.profiler.trace session (the capture dir is
                # stamped with the arming trace id + journal cursor)
                out, cap_path = prof.capture(
                    lambda: self._run_once_inner(now))
                if cap_path and tracer is not None:
                    tracer.annotate(device_capture=cap_path)
                return out
            return self._run_once_inner(now)
        except Exception as e:
            # liveness + errors_total (reference: errors surface through
            # metrics.RegisterError and fail the HealthCheck's failing clock)
            error = e
            self.health.mark_failed(now)
            self.metrics.counter("errors_total").inc(type=type(e).__name__)
            if device_obs.is_oom(e):
                # a device OOM is an allocator post-mortem: persist the
                # per-allocation pprof snapshot next to the flight-recorder
                # evidence BEFORE the supervisor ladder (and its re-encodes)
                # churn the heap; run_loop surfaces the path on the failed
                # RunOnceStatus
                dump_dir = (self.options.flight_recorder_dir
                            or self.options.device_profile_dir)
                if dump_dir:
                    self.last_oom_dump = device_obs.dump_memory_profile(
                        dump_dir, tag="loop-oom", registry=self.metrics) or ""
                    if self.last_oom_dump:
                        self.event_sink.emit(
                            "HbmOomDump", "device", "ResourceExhausted",
                            message=self.last_oom_dump, now=now)
            # flush-on-error: an armed /snapshotz must never hang on a loop
            # that raised — resolve it with the partial payload + the error
            if dbg is not None and dbg.is_data_collection_allowed():
                self._feed_snapshot_observability(dbg, tracer)
                dbg.flush(now, error=f"{type(e).__name__}: {e}")
            raise
        finally:
            loop_s = time.perf_counter() - t0
            if self.shadow_auditor is not None:
                # loop-walltime EWMA: the adaptive audit budget's
                # denominator (the audit spends ~0.5% of this per loop)
                self.shadow_auditor.note_loop_ms(loop_s * 1000.0)
            if self.journal is not None:
                # a loop that raised or returned before its outputs existed
                # leaves its staged record behind — drop it, counted
                self.journal.abort("error" if error is not None
                                   else "aborted-loop")
            # the budget is an SLO, not a tracing feature: breaches count
            # even with the recorder disabled or under an outer tracer
            budget = self.options.loop_wallclock_budget_s
            breach = 0.0 < budget < loop_s
            if breach:
                self.metrics.counter("loop_slo_breaches_total").inc()
                if device_obs.PROFILER is not None:
                    # the loop-SLO breach arms the device profiler: the
                    # NEXT RunOnce captures a real device timeline linked
                    # to this loop's trace id + journal cursor
                    device_obs.PROFILER.arm(
                        "loop_slo_breach",
                        trace_id=tracer.trace_id if tracer else "",
                        journal_cursor=self._journal_cursor)
            # HBM residency census (metrics/device.py): publish the
            # owner/tenant-tagged gauges and feed the leak watchdog the
            # untagged remainder — K loops of monotonic growth is device
            # memory NOBODY tagged, the canonical slow-leak signature
            leak = None
            if self.options.device_ledger and device_obs.LEDGER is not None:
                rec = device_obs.LEDGER.reconcile(registry=self.metrics)
                self.last_hbm_report = rec
                leak = self._hbm_watchdog.observe(rec["untagged_bytes"])
                if leak is not None:
                    self.event_sink.emit(
                        "HbmLeakSuspect", "device", "UntaggedGrowth",
                        message=f"untagged device bytes grew "
                                f"{leak['grew_bytes']}b over "
                                f"{leak['loops']} loops "
                                f"(now {leak['untagged_bytes']}b)",
                        now=now)
            if tracer is not None:
                cur = self._journal_cursor
                tracer.end(root, loop_s=round(loop_s, 6),
                           **({"journal_loop": cur[0],
                               "journal_digest": cur[1]}
                              if cur is not None else {}),
                           **({"error": type(error).__name__}
                              if error is not None else {}))
                if outer is None:
                    trace.activate(None)
                    reason = ("error" if error is not None
                              else "slo_breach" if breach
                              else "audit_divergence"
                              if self._audit_divergent_loop
                              else "hbm_leak" if leak is not None
                              else "snapshotz" if armed else "")
                    if self.flight_recorder.record(tracer, dump_reason=reason):
                        self.metrics.counter(
                            "flight_recorder_dumps_total").inc(reason=reason)

    def _run_once_inner(self, now: float) -> RunOnceStatus:
        status = RunOnceStatus()
        status.backend_state = self.supervisor.state
        # per-loop device round-trip meter (counted where the transfers
        # actually happen — the hostfetch layer; docs/FUSED_LOOP.md)
        hostfetch.reset_round_trips()
        self._fused_ctx = None
        self.event_sink.begin_loop()
        # recovery probe when the ladder is off healthy (no-op otherwise);
        # may advance degraded → recovering or demote suspect → degraded
        self.supervisor.begin_loop()
        with self.metrics.time_function("main"):
            # finished async deletions first: their bookkeeping (and any
            # failed-node taint rollback) must land before this loop reads
            # cluster state
            self._drain_deletion_results(now)
            with self.metrics.time_function("cloud_provider_refresh"):
                self.provider.refresh()
            nodes = self.source.list_nodes()
            pods = self.source.list_pods()
            self.last_verdict_plane = None
            if self.journal is not None:
                # serialize the input world NOW, before the loop body
                # mutates anything in place (soft taints, lowering passes).
                # The journal-only prep (group states, fidelity probe) is
                # charged to overhead_ns too — the ≤2% bound CI asserts
                # must cover ALL journal-gated work, not just begin/commit
                jt0 = time.perf_counter_ns()
                gs = self._journal_mod.groups_state(self.provider, nodes)
                fid = self._journal_fidelity()
                self.journal.overhead_ns += time.perf_counter_ns() - jt0
                self.journal.begin(nodes, pods, gs, now, fidelity=fid)

            if self.processors.actionable_cluster.should_abort(
                nodes, self.provider.node_groups()
            ):
                status.ran = False
                status.aborted_reason = "no nodes"
                return status
            if not self.options.scale_up_from_zero and not any(
                nd.ready for nd in nodes
            ):
                status.ran = False
                status.aborted_reason = "no ready nodes (--scale-up-from-zero=false)"
                return status

            # crash recovery (first loop only): resume unneeded clocks from
            # DeletionCandidate soft taints — the scale-down WAL — and clear
            # stale ToBeDeleted taints a crashed predecessor left behind
            if not self._startup_recovery_done:
                self._recover_scale_down_state(nodes, now)
                self._startup_recovery_done = True
            self.processors.custom_resources.filter_ready(nodes)

            self.cluster_state.update_nodes(nodes, now)
            for cb in self.processors.on_loop_start:
                cb(now)

            # unregistered-instance reaping (reference: removeOldUnregisteredNodes :976)
            self._clean_long_unregistered(now)
            # failed-boot reaping (reference: deleteCreatedNodesWithErrors
            # static_autoscaler.go:1081 — instances stuck in a create-error
            # state are deleted immediately and the group backed off)
            self._delete_created_nodes_with_errors(nodes, now)

            if not self.cluster_state.is_cluster_healthy():
                status.ran = False
                status.aborted_reason = "cluster unhealthy"
                return status

            # min-size enforcement (reference: ScaleUpToNodeGroupMinSize :223,
            # gated by --enforce-node-group-min-size)
            if self.options.enforce_node_group_min_size:
                self.scale_up_orchestrator.scale_up_to_min_sizes(now)

            # DaemonSet workloads: charged on every simulated new node
            # (reference threads the DS lister into template NodeInfos,
            # node_info_utils.go:45; round-4 verdict Missing #2)
            lw = getattr(self.source, "list_workloads", None)
            self._ds_workloads = [
                w for w in lw()
                if getattr(w, "kind", "") == "DaemonSet"
            ] if lw is not None else []
            self.scale_up_orchestrator.daemonsets = self._ds_workloads
            if self.provreq_wrapper is not None:
                self.provreq_wrapper.provreq.daemonsets = self._ds_workloads

            # ProvisioningRequests on alternating turns (reference:
            # WrapperOrchestrator, provisioningrequest/orchestrator/)
            if self.provreq_wrapper is not None:
                self.provreq_wrapper.maybe_run(
                    nodes, [p for p in pods if p.node_name], now
                )

            # buffer reconciliation (status updates happen even when pod
            # injection is disabled — two independent reference flags)
            if self.buffer_controller is not None:
                self.buffer_controller.buffers = list(self._list_buffers())
                self.buffer_controller.reconcile()

            # host-side pod pipeline
            ctx = ProcessorContext(
                self.options, self.provider, now,
                list_workloads=getattr(self.source, "list_workloads", None),
            )
            source_pods = pods     # pre-pipeline list (recreation checks)
            pods = self.processors.run_pod_list(pods, ctx)

            # PDB refresh (reference: planner.go builds the RemainingPdbTracker
            # from the PDB lister each loop)
            list_pdbs = getattr(self.source, "list_pdbs", None)
            self.pdb_tracker.set_pdbs(list_pdbs() if list_pdbs else [])

            # DRA / CSI lowering (reference: DraProvider/CsiProvider.Snapshot
            # at static_autoscaler.go:313-328, joined into NodeInfos) — device
            # claims and volume limits fold into the resource axis pre-encode
            dra_snapshot_fn = (getattr(self.source, "dra_snapshot", None)
                               if self.options.enable_dynamic_resource_allocation
                               else None)
            lowering_key = None
            if dra_snapshot_fn is not None:
                from kubernetes_autoscaler_tpu.simulator.dynamicresources import (
                    apply_dra,
                )

                lowering_key = (apply_dra(nodes, pods, dra_snapshot_fn()),)
            csi_snapshot_fn = (getattr(self.source, "csi_snapshot", None)
                               if self.options.enable_csi_node_aware_scheduling
                               else None)
            if csi_snapshot_fn is not None:
                from kubernetes_autoscaler_tpu.simulator.csi import apply_csi

                lowering_key = (lowering_key,
                                apply_csi(nodes, pods, csi_snapshot_fn()))
            # DRA/CSI lowering REWRITES the same objects in place each loop;
            # identity diffing cannot see that. The passes return a
            # fingerprint of everything they WROTE (which depends on the pod
            # set too — claim residency, PVC sharing — not just the
            # snapshots), and any change forces the encoder to rebuild.
            if (self._encoder is not None
                    and lowering_key != self._last_lowering_key):
                self._encoder.invalidate()
            self._last_lowering_key = lowering_key

            # tensor snapshot — incrementally maintained across loops by
            # default (models/incremental.py; reference rationale:
            # DeltaSnapshotStore, store/delta.go:33-54), full re-encode when
            # --incremental-encode=false
            node_group_ids = self._node_group_index(nodes)
            drain_opts = DrainOptions(
                skip_nodes_with_system_pods=self.options.skip_nodes_with_system_pods,
                skip_nodes_with_local_storage=self.options.skip_nodes_with_local_storage,
                skip_nodes_with_custom_controller_pods=self.options.skip_nodes_with_custom_controller_pods,
                min_replica_count=self.options.min_replica_count,
            )
            pdb_names = self.pdb_tracker.namespaced_names_with_pdb(
                [p for p in pods if p.node_name]
            )
            # namespace labels (for affinity namespaceSelector exactness);
            # sources without Namespace objects leave it None
            list_ns = getattr(self.source, "list_namespaces", None)
            ns_labels = list_ns() if list_ns is not None else None
            from kubernetes_autoscaler_tpu.models.world_store import (
                ENCODES_HELP,
                H2D_HELP,
            )

            with self.metrics.time_function("snapshot_build"), \
                    self.planner.phases.phase("encode"):
                if self.options.incremental_encode:
                    if self._world_store is None or \
                            self._world_store.drain_opts != drain_opts:
                        from kubernetes_autoscaler_tpu.models.world_store import (
                            WorldStore,
                        )

                        self._world_store = WorldStore(
                            registry=self.metrics,
                            node_bucket=self.options.node_shape_bucket,
                            group_bucket=self.options.group_shape_bucket,
                            drain_opts=drain_opts,
                            resync_loops=self.options.incremental_resync_loops,
                            verify_loops=self.options.incremental_verify_loops,
                        )
                        self._encoder = self._world_store.encoder
                    # post-incident residency audit (WorldStore.heal):
                    # digest-probe the resident device planes against the
                    # host mirrors before trusting them again; device loss
                    # forces the encode below full with cause=device_lost
                    # instead of simming against stale planes
                    if self.supervisor.world_stale \
                            and self.supervisor.state != "degraded":
                        # an unhealed audit divergence FORCES the rebuild:
                        # a miscompiled kernel corrupts outputs, not the
                        # resident planes, so an intact digest probe is
                        # not an acquittal — the single re-audit of the
                        # same sample must run against a cold re-encode
                        force = (self.shadow_auditor is not None
                                 and self.shadow_auditor.pending_recheck
                                 is not None)
                        healed = self._world_store.heal(force=force)
                        if force:
                            # the rebuild the re-audit protocol demanded
                            # ran — the pending sample may now be
                            # re-checked (and a second divergence really
                            # means persistent)
                            self.shadow_auditor.note_healed()
                        self.supervisor.world_healed(
                            healed["outcome"],
                            {"lostPlanes": healed["lostPlanes"][:8]})
                    fails_before = self._encoder.verify_failures
                    enc = self.supervisor.guard(
                        "encode",
                        lambda: self._world_store.encode(
                            nodes, pods, node_group_ids=node_group_ids,
                            now=now,
                            pdb_namespaced_names=frozenset(pdb_names),
                            namespaces=ns_labels))
                    if self._world_store.last_mode == "full":
                        # a full re-encode rebuilds device tensors from
                        # scratch — the loop-level recompile-risk event the
                        # trace/registry counters track (the REASONED
                        # breakdown rides encoder_encodes_total{mode,cause},
                        # emitted by the store itself)
                        self.planner.phases.bump("encoder_full_encodes")
                    if self._encoder.verify_failures > fails_before:
                        self.metrics.counter(
                            "incremental_verify_failures_total").inc(
                            self._encoder.verify_failures - fails_before)
                else:
                    if self.supervisor.world_stale:
                        # nothing resident to distrust: every loop here
                        # re-lowers + re-uploads the whole world anyway —
                        # which is also exactly the cold re-encode the
                        # audit's re-check protocol demands
                        if self.shadow_auditor is not None:
                            self.shadow_auditor.note_healed()
                        self.supervisor.world_healed("full-encode")

                    def _full_encode():
                        e = encode_cluster(
                            nodes, pods,
                            node_group_ids=node_group_ids,
                            node_bucket=self.options.node_shape_bucket,
                            group_bucket=self.options.group_shape_bucket,
                            namespaces=ns_labels,
                        )
                        apply_drainability(e, drain_opts, now=now,
                                           pdb_namespaced_names=pdb_names)
                        return e

                    enc = self.supervisor.guard("encode", _full_encode)
                    # counter parity with the store-enabled path: every
                    # loop here is a full re-encode + full re-upload
                    self.metrics.counter(
                        "encoder_encodes_total", help=ENCODES_HELP).inc(
                        mode="full", cause="forced")
                    self.metrics.counter(
                        "world_store_h2d_bytes_total", help=H2D_HELP).inc(
                        sum(int(v.nbytes)
                            for v in (enc.host_arrays or {}).values()))
            if self.shadow_auditor is not None:
                # pin the pre-placement tensors + mirrors the verdicts are
                # computed from; the sample seed is the journal cursor at
                # the TOP of this loop (record k-1's digest — the cursor a
                # replay of this loop runs under; docs/REPLAY.md)
                self.shadow_auditor.capture_world(
                    enc, parent_digest=(self.journal._last_digest
                                        if self.journal is not None
                                        else ""))
            if self.quota is not None:
                self.quota.registry = enc.registry
            self.scale_up_orchestrator.quota = self.quota
            self.planner.quota = self.quota
            snapshot = TensorClusterSnapshot(enc)

            # upcoming nodes (reference: addUpcomingNodesToClusterSnapshot :499)
            upcoming = self.cluster_state.upcoming_nodes()
            for gid, count in upcoming.items():
                self._inject_template_nodes(snapshot, gid, count, "upcoming")
            # capacity promised on still-creating groups (reference:
            # AsyncNodeGroupStateChecker → upcoming accounting)
            if self.async_creator is not None:
                for gid, st in self.async_creator.upcoming().items():
                    self._inject_template_nodes(
                        snapshot, gid, st.initial_delta, "async-upcoming",
                        template=st.template)

            # debugging snapshot collection (reference:
            # static_autoscaler.go:299-300,404 — only when /snapshotz armed)
            dbg = self.debugging_snapshotter
            if dbg is not None and dbg.is_data_collection_allowed():
                by_node: dict[str, list[Pod]] = {}
                for p in pods:
                    if p.node_name:
                        by_node.setdefault(p.node_name, []).append(p)
                dbg.set_cluster_nodes(nodes, by_node)
                dbg.set_template_nodes({
                    g.id(): g.template_node_info()
                    for g in self.provider.node_groups()
                })

            # filter-out-schedulable (reference: PodListProcessor.Process :530)
            # — under --fused-loop the filter, the scale-up sim across every
            # expansion option and the scale-down drain screen run as ONE
            # compiled device program whose compact decision tensors are
            # harvested in a single batched fetch (docs/FUSED_LOOP.md);
            # host code below becomes pure policy over ~KB of numpy
            fused = None
            if self.options.fused_loop:
                fused = self._fused_dispatch(enc, snapshot, nodes, pods, now)
            self._fused_ctx = fused
            if fused is None:
                with self.metrics.time_function("filter_out_schedulable"):
                    packed = self.supervisor.guard(
                        "dispatch", snapshot.schedule_pending_on_existing)
                    snapshot.apply_placement(packed.placed)
                packed_scheduled = packed.scheduled
            else:
                # the fused program already applied the placement on device;
                # swap its post-placement resident tensors into the snapshot
                # (same arithmetic as apply_placement — pinned by
                # tests/test_fused_loop.py)
                snapshot.state.nodes = fused["nodes"]
                snapshot.state.specs = fused["specs"]
                packed_scheduled = fused["resident"].verdict
            if self.journal is not None or self.capture_verdicts \
                    or self.shadow_auditor is not None:
                # the filter-out-schedulable verdict plane, byte-preserved
                # into the journal record (one tiny int32[G] fetch, charged
                # to the journal's overhead meter)
                jt0 = time.perf_counter_ns()
                if fused is not None:
                    # the verdict already rode the decision fetch — this is
                    # a host-side copy, not a device read
                    plane = np.asarray(
                        fused["decision"].verdict).astype(np.int32)
                else:
                    plane = np.asarray(packed_scheduled).astype(np.int32)
                from kubernetes_autoscaler_tpu.sidecar import faults

                if faults.PLAN is not None:
                    # the audit-visible corruption hook (sidecar/faults.py
                    # `flip_bit`): corrupts the FETCHED copy every
                    # downstream consumer reads while the device array
                    # keeps the truth — exactly the silent-corruption
                    # shape the shadow audit exists to catch
                    plane = faults.PLAN.fire("verdict_plane",
                                             payload=plane,
                                             registry=self.metrics)
                self.last_verdict_plane = plane
                if self.shadow_auditor is not None:
                    self.shadow_auditor.capture_verdict(
                        packed_scheduled, plane)
                if self.journal is not None:
                    self.journal.overhead_ns += time.perf_counter_ns() - jt0
                if self.capture_verdicts:
                    from kubernetes_autoscaler_tpu.models.encode import (
                        equivalence_key,
                    )

                    keys = [None] * int(self.last_verdict_plane.shape[0])
                    for row, idxs in enumerate(enc.group_pods):
                        if idxs and row < len(keys):
                            keys[row] = equivalence_key(
                                enc.pending_pods[idxs[0]])
                    self.last_verdict_keys = keys
            # the loop's first device→host sync point: a hung tunnel that
            # survived the (async) dispatch manifests HERE (the fused path
            # already paid it inside _fused_dispatch's guarded harvest)
            if fused is not None:
                remaining = int(fused["decision"].pending_after.sum())
            else:
                remaining = self.supervisor.guard(
                    "fetch",
                    lambda: int(np.asarray(snapshot.state.specs.count).sum()))
            if dbg is not None and dbg.is_data_collection_allowed():
                scheduled_counts = (
                    np.asarray(fused["decision"].verdict) if fused is not None
                    else np.asarray(packed_scheduled))
                fitting = [
                    p for gi, slots in enumerate(enc.group_pods)
                    if gi < scheduled_counts.shape[0] and scheduled_counts[gi] > 0
                    for p in (enc.pending_pods[s] for s in slots)
                ]
                dbg.set_unscheduled_pods_can_be_scheduled(fitting)
            status.pending_pods = remaining
            self.metrics.gauge("unschedulable_pods_count").set(remaining)
            if remaining == 0:
                # no scale-up dispatch this loop → last loop's NoScaleUp
                # verdicts are resolved; the reason surfaces must clear
                self.scale_up_orchestrator.last_noscaleup = {}
                self.scale_up_orchestrator.last_noscaleup_groups = []
            # Sync the post-placement view unconditionally: the planner must see
            # the capacity charged to simulated placements even when every pod
            # fit (the reference keeps placements in the snapshot for the same
            # reason — a node about to receive pending pods is not "unneeded").
            enc.specs = snapshot.state.specs
            enc.nodes = snapshot.state.nodes

            # scale-up (reference: runSingleScaleUp :589 / runScaleUpSalvo
            # :669 — salvo iterates under a time budget, re-injecting the
            # scaled-up capacity into the snapshot each round :723)
            scaled_up = False
            if remaining > 0:
                with self.metrics.time_function("scale_up"):
                    result = self._dispatch_scale_up(
                        enc, snapshot, nodes, now,
                        precomputed=(fused["fused_up"]
                                     if fused is not None else None))
                status.scale_up = result
                scaled_up = result.scaled_up
                for cb in self.processors.on_scale_up_status:
                    cb(result)
                for gid, delta in result.increases.items():
                    self.node_group_change_observers.register_scale_up(
                        gid, delta, now
                    )
                for gid, err in result.errors.items():
                    self.node_group_change_observers.register_failed_scale_up(
                        gid, err, now
                    )
                if result.scaled_up:
                    self.metrics.counter("scaled_up_nodes_total").inc(
                        sum(result.increases.values())
                    )
                    gpu_nodes = sum(
                        d for gid, d in result.increases.items()
                        if self._group_has_gpu(gid)
                    )
                    if gpu_nodes:
                        self.metrics.counter("scaled_up_gpu_nodes_total").inc(gpu_nodes)

            # scale-down (reference: scaleDown :749; delay gating :604)
            sd_due = (self.options.scale_down_enabled and not scaled_up
                      and self._scale_down_allowed(now))
            if sd_due and not self.supervisor.scale_down_safe():
                # safe-action gating: while the backend is degraded/
                # recovering or the resident world is unverified, the
                # simulation cannot be trusted to name deletion victims —
                # withhold ACTUATION (scale-up above stayed available:
                # adding capacity on a stale view is recoverable, deleting
                # is not). The standing unneeded set keeps its clocks (the
                # `since` stamps are untouched, so recovery resumes the
                # countdowns, not resets them) and every would-be victim is
                # marked BackendDegraded on all four reason surfaces
                # (events / status / registry gauge / snapshotz).
                status.scale_down_withheld = True
                status.unneeded_nodes = list(self.planner.state.unneeded)
                # a backend degraded BY the shadow audit marks its victims
                # with the audit's own reason — dashboards distinguish "the
                # device hung" from "the device computed wrong bits"
                audit_deg = (self.shadow_auditor is not None
                             and self.shadow_auditor.degraded)
                reason = "AuditDivergence" if audit_deg \
                    else "BackendDegraded"
                why = (f"scale-down withheld: backend "
                       f"{self.supervisor.state}"
                       + (", shadow audit divergence unhealed"
                          if audit_deg else "")
                       + (", world unverified"
                          if self.supervisor.world_stale else ""))
                for name in status.unneeded_nodes:
                    self.planner._mark(name, reason, now, message=why)
                self.metrics.gauge("unneeded_nodes_count").set(
                    len(status.unneeded_nodes))
            elif sd_due:
                with self.metrics.time_function("scale_down_update"):
                    self.planner.update(
                        enc, nodes, now,
                        inject_pods=self._evicted_pods_to_inject(
                            source_pods, now),
                        precomputed=(fused["fused_down"]
                                     if fused is not None else None))
                status.unneeded_nodes = list(self.planner.state.unneeded)
                # persist scale-down intent as soft taints (reference:
                # actuation/softtaint.go UpdateSoftDeletionTaints) so a
                # restart resumes the unneeded clocks instead of zeroing them
                with self.metrics.time_function("soft_taint_unneeded"):
                    self._sync_soft_taints(nodes)
                self.metrics.gauge("unneeded_nodes_count").set(
                    len(status.unneeded_nodes)
                )
                with self.metrics.time_function("scale_down_confirm"):
                    to_remove = self.planner.nodes_to_delete(enc, nodes, now)
                if to_remove:
                    pods_by_slot = {
                        j: p for j, p in enumerate(enc.scheduled_pods)
                        if p is not None  # incremental-encoder slot holes
                    }
                    # group membership resolved BEFORE deletion unmaps the node
                    group_of = {}
                    for r in to_remove:
                        g = self.provider.node_group_for_node(r.node)
                        group_of[r.node.name] = g.id() if g else ""
                    if self.options.async_node_deletion:
                        self._async_group_of.update(group_of)
                    with self.metrics.time_function("scale_down_actuate"):
                        results = self.actuator.start_deletion(
                            to_remove, pods_by_slot, now,
                            detach=self.options.async_node_deletion,
                        )
                    for r in results:
                        if r.ok:
                            status.scale_down_deleted.append(r.node)
                            self.cluster_state.register_scale_down(
                                r.node, now, group_of.get(r.node, "")
                            )
                            self.last_scale_down_delete = now
                            self.node_group_change_observers.register_scale_down(
                                group_of.get(r.node, ""), r.node, now
                            )
                        else:
                            self.last_scale_down_fail = now
                            self.node_group_change_observers.register_failed_scale_down(
                                group_of.get(r.node, ""), r.node, r.reason, now
                            )
                    self.metrics.counter("scaled_down_nodes_total").inc(
                        len(status.scale_down_deleted)
                    )
                    gpu_deleted = sum(
                        1 for n in status.scale_down_deleted
                        if self._group_has_gpu(group_of.get(n, ""))
                    )
                    if gpu_deleted:
                        self.metrics.counter("scaled_down_gpu_nodes_total").inc(gpu_deleted)

            # reap empty autoprovisioned groups (reference: NodeGroupManager
            # cleanup in the default processors chain)
            if self.options.node_autoprovisioning_enabled:
                self.node_group_manager.remove_unneeded_node_groups(self.provider)

            # reason plane → registry: per-reason gauge families. Labels set
            # last loop but absent now are zeroed (a gauge that silently
            # keeps a stale reason value would claim pods/nodes still refuse
            # for a reason that no longer applies).
            noscaleup = dict(self.scale_up_orchestrator.last_noscaleup)
            unsched_gauge = self.metrics.gauge(
                "unschedulable_pods_count",
                help="Pending pods; with a reason label, pods no node group "
                     "can help and the constraint that refused them")
            for r in self._last_unsched_reasons - set(noscaleup):
                unsched_gauge.set(0.0, reason=r)
            for r, n in noscaleup.items():
                unsched_gauge.set(float(n), reason=r)
            self._last_unsched_reasons = set(noscaleup)
            unremovable_reasons = self.planner.unremovable.reason_counts(now)
            unrem_gauge = self.metrics.gauge(
                "unremovable_nodes_count",
                help="Nodes the scale-down planner refused to remove, by "
                     "reason (reference unremovable enum)")
            for r in self._last_unremovable_reasons - set(unremovable_reasons):
                unrem_gauge.set(0.0, reason=r)
            for r, n in unremovable_reasons.items():
                unrem_gauge.set(float(n), reason=r)
            self._last_unremovable_reasons = set(unremovable_reasons)

            # status document (reference: WriteStatusConfigMap every loop,
            # static_autoscaler.go:418-421; gated by --write-status-configmap)
            from kubernetes_autoscaler_tpu.clusterstate.api import build_status

            self.last_status = build_status(
                self.cluster_state, now,
                scale_down_candidates=status.unneeded_nodes,
                config_map_name=self.options.status_config_map_name,
                unschedulable_reasons=noscaleup,
                unremovable_reasons=unremovable_reasons,
            )
            if self.status_sink is not None and self.options.write_status_configmap:
                try:
                    self.status_sink(self.last_status)
                except Exception:
                    pass

            # commit the journal record once every decision surface is
            # settled, so the cursor exists before /snapshotz flushes and
            # before the trace root span closes
            # fused-loop surfaces settle before the journal commit so the
            # record carries them (top-level annotations — surface digests
            # stay mode-independent, so a record written fused replays
            # clean on the phased oracle; docs/REPLAY.md)
            status.fused_mode = "fused" if fused is not None else "phased"
            status.speculation = (fused["spec_outcome"]
                                  if fused is not None else "none")
            status.loop_device_round_trips = hostfetch.round_trips()
            self.metrics.gauge(
                "loop_device_round_trips",
                help="Device round trips this loop, counted at the "
                     "hostfetch layer (fused steady state: 1)").set(
                float(status.loop_device_round_trips))
            if self.journal is not None:
                self.journal.loop_annotations = {
                    "fusedMode": status.fused_mode,
                    "loopDeviceRoundTrips": status.loop_device_round_trips,
                    "speculation": status.speculation,
                }
                jt0 = time.perf_counter_ns()
                outputs = self._journal_mod.collect_outputs(self, status)
                self.journal.overhead_ns += time.perf_counter_ns() - jt0
                self._journal_cursor = self.journal.commit(outputs)

            # online shadow audit (audit/shadow.py): re-verify the sampled
            # device verdicts against the host oracle, AFTER the journal
            # commit (the bundle names this loop's cursor) and BEFORE
            # supervisor.end_loop (a divergent loop must not read as clean)
            lineage_audit = None
            if self.shadow_auditor is not None:
                tr = trace.current_tracer()
                # audit-only fetches are observability overhead, not part of
                # the decision loop's round-trip budget
                with hostfetch.suppress_counting():
                    rep = self.shadow_auditor.run_once_audit(
                        planner=self.planner, cursor=self._journal_cursor,
                        now=now, trace_id=tr.trace_id if tr else "")
                if rep is not None and rep["divergent"]:
                    self._audit_divergent_loop = True
                    status.audit_divergence = True
                    status.audit_bundle_path = rep.get("bundlePath", "")
                    if status.audit_bundle_path:
                        self.last_audit_bundle = status.audit_bundle_path
                    lineage_audit = {
                        "bundlePath": rep.get("bundlePath", ""),
                        "traceId": tr.trace_id if tr else "",
                        "persistent": rep["persistent"],
                        "surfaces": sorted(
                            {d["surface"] for d in rep["divergences"]})}
                    # the ladder: healthy→suspect on first divergence,
                    # →degraded when the post-heal re-audit diverged again
                    self.supervisor.audit_divergence(
                        detail={"surfaces": sorted(
                            {d["surface"] for d in rep["divergences"]})},
                        persistent=rep["persistent"])
                    # a speculative dispatch still in flight was issued
                    # over the now-divergent world: it must never be
                    # harvested against the healed planes next loop, even
                    # if the heal re-uploads value-identical buffers
                    self._discard_speculation("audit-divergence")

            # live lineage feed: the same outputs surface the journal
            # seals (reused when the journal already collected it — one
            # collect per loop either way), metered inside observe()
            if self.lineage_ring is not None:
                louts = outputs if self.journal is not None else \
                    self._journal_mod.collect_outputs(self, status)
                cur = self._journal_cursor
                self.lineage_ring.observe(
                    loop=cur[0] if cur is not None else None,
                    digest=cur[1] if cur is not None else "",
                    now=now, outputs=louts,
                    annotations={
                        "fusedMode": status.fused_mode,
                        "loopDeviceRoundTrips":
                            status.loop_device_round_trips,
                    },
                    audit=lineage_audit,
                    backend_state=self.supervisor.state)

            if self.debugging_snapshotter is not None:
                if self.debugging_snapshotter.is_data_collection_allowed():
                    self._feed_snapshot_observability(
                        self.debugging_snapshotter, trace.current_tracer())
                self.debugging_snapshotter.flush(now)

            # per-loop metric sweep (reference: metrics.Update* calls spread
            # through RunOnce; per-nodegroup series behind the flag)
            from kubernetes_autoscaler_tpu.metrics.parity import (
                emit_cluster_metrics,
            )

            emit_cluster_metrics(
                self.metrics, self.cluster_state, self.provider, self.options,
                enc, now, health=self.health,
                latency_tracker=self.latency_tracker)
            self.metrics.gauge("unremovable_nodes_count").set(
                float(len(self.planner.unremovable.entries)))
            self.metrics.gauge("pending_node_deletions").set(
                float(self.actuator.tracker.in_flight()))
            self.metrics.gauge("scale_down_in_cooldown").set(
                0.0 if self._scale_down_allowed(now) else 1.0)

            # crash-consistent restart record: the unneeded-since clocks +
            # in-flight scale-ups, keyed to this loop's journal cursor —
            # one atomic rewrite per loop (reference analog: the soft-taint
            # WAL, which the per-loop taint budget makes lossy; this record
            # is exact and also covers scale-ups, which have no taint)
            if self.options.restart_state_path:
                try:
                    self._supervisor_mod.save_restart_state(
                        self.options.restart_state_path, now=now,
                        journal_cursor=self._journal_cursor,
                        unneeded_since=self.planner.unneeded_nodes.since,
                        scale_up_requests=self.cluster_state.scale_up_requests,
                        audit_bundle=self.last_audit_bundle)
                except OSError:
                    self.metrics.counter(
                        "restart_state_errors_total",
                        help="Restart-record writes that failed (the "
                             "previous intact record stays)").inc()

            # speculative next-loop overlap (docs/FUSED_LOOP.md): dispatch
            # loop k+1's fused program on the current resident world NOW so
            # it computes while the host actuates; harvested next loop only
            # on an exact composition-fingerprint match
            if fused is not None:
                self._maybe_speculate(now)

            # a loop that reached here had no guarded-phase incident: it
            # advances suspect → healthy / the recovering hysteresis count
            self.supervisor.end_loop()
            status.backend_state = self.supervisor.state
            self.health.mark_active(now)
            self.event_sink.end_loop()
        return status

    # ---- fused single-dispatch loop (docs/FUSED_LOOP.md) ----

    def _fused_statics(self, enc) -> dict:
        """The fused program's static (compile-keying) arguments — all
        process-stable except `dims`, which moves only on a shape-bucket
        regrowth (itself a recompile event on every path)."""
        return {
            "dims": enc.dims,
            "max_new_nodes": self.options.max_new_nodes_static,
            "max_pods_per_node": self.options.max_pods_per_node,
            "chunk": self.options.drain_chunk,
            "with_constraints": enc.has_constraints,
        }

    def _fused_group_sig(self, prep) -> tuple:
        """Value signature of everything the scale-up half of the fused
        program read from the group side. Object identity cannot gate a
        speculation harvest here — the group-tensor cache refreshes
        max_new/price as fresh device uploads every loop — so the signature
        digests VALUES: the template/registry fingerprint plus the raw
        max_new / price vectors and the composed limiter cap."""
        mx = np.asarray([t[1] for t in prep.templates], np.int64)
        pr = np.asarray([t[2] for t in prep.templates], np.float64)
        return (self.scale_up_orchestrator._last_group_fp,
                mx.tobytes(), pr.tobytes(), prep.limit_cap.tobytes())

    def _fused_defer(self, cause: str, now: float) -> None:
        """A fused→phased deferral is a round-trip-cap regression: the loop
        silently re-gains the phased ladder's device round trips. Make it
        observable (counter + one event per dedup window) and drop any
        armed speculation — a dispatch left in flight across a deferred
        loop must never survive to a later harvest."""
        self.metrics.counter(
            "fused_deferrals_total",
            help="Loops where the fused single-dispatch program deferred "
                 "to the phased ladder, by cause (steady state: 0)").inc(
            cause=cause)
        self.event_sink.emit(
            "Warning", "autoscaler", "FusedDeferral",
            f"fused RunOnce deferred to the phased ladder ({cause}); "
            "the 1-round-trip loop budget does not apply this loop",
            now=now)
        self._discard_speculation(cause)

    def _discard_speculation(self, cause: str) -> None:
        """Unconditionally drop an armed speculative dispatch (deferral,
        audit divergence, shutdown) — counted like a harvest-gate discard
        so the speculation ledger stays complete."""
        spec, self._speculation = self._speculation, None
        if spec is None:
            return
        self.metrics.counter(
            "speculative_discards_total",
            help="Speculative fused dispatches discarded on a "
                 "fingerprint/input mismatch").inc()
        self.last_speculation = {"outcome": "discard",
                                 "handle": spec["handle"],
                                 "resident": spec["resident"],
                                 "key": spec["key"], "cause": cause}

    def _fused_dispatch(self, enc, snapshot, nodes: list[Node],
                        pods: list[Pod], now: float) -> dict | None:
        """Dispatch run_once_fused — or harvest last loop's speculative
        dispatch of it — and build the precomputed consumables for the host
        policy path. Returns None when the fused program cannot run this
        loop (multi-device mesh sharding, or no candidate node group to
        trace over); the caller then takes the phased path, which remains
        decision-identical (tests/test_fused_loop.py)."""
        from kubernetes_autoscaler_tpu.ops import autoscale_step

        if self.scale_up_orchestrator.mesh is not None:
            # the sharded estimator owns mesh placement; the fused program
            # is a single-device composition
            self._fused_defer("mesh-sharded", now)
            return None
        prep = self.scale_up_orchestrator.prepare_fused(enc, len(nodes), now)
        if prep is None:
            self._fused_defer("no-candidate-groups", now)
            return None
        import jax

        st = snapshot.state
        statics = self._fused_statics(enc)
        world_fp = (self._world_store.composition_fingerprint(nodes, pods)
                    if self._world_store is not None else None)
        key = (world_fp, self._fused_group_sig(prep))
        leaves = jax.tree_util.tree_leaves(
            (st.nodes, st.specs, st.scheduled, st.planes))

        spec, self._speculation = self._speculation, None
        spec_outcome = "none"
        decision = resident = None
        if spec is not None:
            # harvest gate: exact key match AND every traced input leaf is
            # the very same device buffer the speculative program read —
            # anything else discards, and a discard never influences a
            # decision (the mismatch-injection test pins this)
            match = (world_fp is not None
                     and spec["key"] == key
                     and spec["statics"] == statics
                     and len(spec["leaves"]) == len(leaves)
                     and all(a is b
                             for a, b in zip(spec["leaves"], leaves)))
            if match:
                with self.metrics.time_function("fused_harvest"), \
                        self.planner.phases.phase("fetch", fused=1,
                                                  speculative=1):
                    decision = self.supervisor.guard(
                        "fetch", spec["handle"].get)
                resident = spec["resident"]
                spec_outcome = "hit"
                self.metrics.counter(
                    "speculative_hits_total",
                    help="Speculative fused dispatches harvested on an "
                         "exact composition-fingerprint match").inc()
            else:
                spec_outcome = "discard"
                self.metrics.counter(
                    "speculative_discards_total",
                    help="Speculative fused dispatches discarded on a "
                         "fingerprint/input mismatch").inc()
                self.last_speculation = {"outcome": "discard",
                                         "handle": spec["handle"],
                                         "resident": spec["resident"],
                                         "key": spec["key"]}
        if decision is None:
            if self._fused_census is None:
                import os

                self._fused_census = device_obs.CompileCensus(
                    registry=self.metrics,
                    mode=os.environ.get("KA_DEVICE_CENSUS", "cost"),
                    sync_analysis=False)
            args = (st.nodes, st.specs, st.scheduled, prep.group_tensors,
                    prep.limit_cap_dev)
            kwargs = dict(statics, planes=st.planes)
            with self.metrics.time_function("fused_dispatch"), \
                    self.planner.phases.phase("dispatch", fused=1):
                dec_dev, resident = self.supervisor.guard(
                    "dispatch",
                    lambda: self._fused_census.dispatch(
                        "run_once_fused", autoscale_step.run_once_fused,
                        args, kwargs))
            size = autoscale_step.run_once_fused._cache_size()
            if size > self._fused_cache_size:
                self.metrics.counter(
                    "fused_program_compiles_total",
                    help="Compiles of the fused RunOnce program (steady "
                         "state: 0 growth)").inc(
                    size - self._fused_cache_size)
                self._fused_cache_size = size
            # the loop's ONE decision fetch: ~KB of bit-packed verdict /
            # option / drain tensors in a single batched transfer
            with self.metrics.time_function("fused_harvest"), \
                    self.planner.phases.phase("fetch", fused=1):
                decision = self.supervisor.guard(
                    "fetch",
                    lambda: hostfetch.fetch_pytree(
                        dec_dev, phases=self.planner.phases))

        from types import SimpleNamespace

        fused_up = FusedScaleUp(
            prep=prep,
            est=SimpleNamespace(node_count=decision.est_node_count,
                                scheduled=decision.est_scheduled),
            scores=decision.scores,
            pending_total=int(decision.pending_after.sum()))
        fused_down = FusedScaleDown(util=decision.util,
                                    removal_dev=resident.removal)
        # post-placement resident tensors, built like apply_placement: only
        # alloc/count swap for the program's outputs; every OTHER leaf stays
        # the original encoder array so the planner's host-mirror identity
        # checks keep hitting (the jit returns fresh buffers for all outputs,
        # including value-unchanged passthroughs — wholesale adoption of
        # resident.nodes would silently turn every mirror read back into a
        # device round trip)
        res_nodes = st.nodes.replace(alloc=resident.nodes.alloc)
        res_specs = st.specs.replace(count=resident.specs.count)
        # host mirrors for the planner's always-fetch views: nodes_to_delete
        # reads post-placement alloc + pending counts, both already in the
        # decision tensors — seeding them makes that read transfer-free
        self.planner.seed_fused_overrides({
            "nodes.alloc": (resident.nodes.alloc,
                            np.asarray(decision.alloc_after)),
            "specs.count": (resident.specs.count,
                            np.asarray(decision.pending_after)),
        })
        return {"prep": prep, "decision": decision, "resident": resident,
                "nodes": res_nodes, "specs": res_specs,
                "inputs": (st.nodes, st.specs, st.scheduled, st.planes),
                "leaves": leaves, "statics": statics, "key": key,
                "spec_outcome": spec_outcome,
                "fused_up": fused_up, "fused_down": fused_down}

    def _maybe_speculate(self, now: float) -> None:
        """Speculative next-loop overlap: dispatch loop k+1's fused program
        on the CURRENT resident world (the pre-placement tensors loop k
        just ran on) so the device computes during host actuation time.
        Issued only from a healthy backend over a verified world; harvested
        next loop only through _fused_dispatch's exact-match gate."""
        ctx = self._fused_ctx
        if ctx is None or self._world_store is None:
            return
        if self.supervisor.state != "healthy" or self.supervisor.world_stale:
            return
        from kubernetes_autoscaler_tpu.ops import autoscale_step

        nodes_t, specs_t, sched_t, planes_t = ctx["inputs"]
        prep = ctx["prep"]

        def _issue():
            dec_dev, resident = autoscale_step.run_once_fused(
                nodes_t, specs_t, sched_t, prep.group_tensors,
                prep.limit_cap_dev,
                planes=planes_t, **ctx["statics"])
            # trace=False: the loop's trace spans close LIFO before the
            # speculative result exists — the fetch span rides next loop's
            # harvest instead
            return (hostfetch.AsyncFetch(dec_dev, phases=None, trace=False),
                    resident)

        # under the SAME dispatch guard the phased loop uses: with
        # speculation on, this is where the loop's program dispatch actually
        # happens, so a hung device must book its incident here (PR 13
        # semantics) and propagate like any other guarded-phase abort
        try:
            handle, resident = self.supervisor.guard("dispatch", _issue)
        except Exception:
            self.metrics.counter(
                "speculative_errors_total",
                help="Speculative fused dispatches that failed to issue").inc()
            raise
        self._speculation = {"key": ctx["key"], "statics": ctx["statics"],
                             "leaves": ctx["leaves"], "handle": handle,
                             "resident": resident, "issued_at": now}
        self.metrics.counter(
            "speculative_dispatches_total",
            help="Speculative fused dispatches issued").inc()

    def _feed_snapshot_observability(self, dbg, tracer) -> None:
        """Attach the loop's phase breakdown + trace id + reason plane to an
        armed /snapshotz payload so the JSON links to the Perfetto timeline
        AND says which constraint refused which pods / what blocked each
        unremovable node."""
        dbg.set_phase_stats({
            "planner": self.planner.phases.snapshot(),
            "scale_up": self.scale_up_orchestrator.phases.snapshot(),
        })
        dbg.set_reason_plane({
            "noScaleUp": list(self.scale_up_orchestrator.last_noscaleup_groups),
            "unremovableNodes": {
                n: {"reason": e[1]} for n, e in
                self.planner.unremovable.entries.items()
            },
            "drainFailDetail": dict(self.planner.state.drain_fail_detail),
            "events": self.event_sink.snapshot(),
            # shadow-audit section: check/divergence counts, the pending
            # re-audit, the last evidence bundle (docs/OBSERVABILITY.md)
            **({"audit": self.shadow_auditor.snapshot_payload()}
               if self.shadow_auditor is not None else {}),
            # lineage section: the live ring's per-object digest (the
            # same store /whyz serves — docs/LINEAGE.md)
            **({"lineage": self.lineage_ring.snapshot_summary()}
               if self.lineage_ring is not None else {}),
        })
        if tracer is not None:
            dbg.set_trace_id(tracer.trace_id)
        if self._journal_cursor is not None:
            dbg.set_journal_cursor(*self._journal_cursor)

    def _journal_fidelity(self) -> dict | None:
        """Source surfaces the v1 record format does not carry (PDBs,
        workloads, buffers, provreqs, DRA/CSI): the record is still written,
        but the harness surfaces the lossiness in its report instead of
        claiming a bit-exact replay it cannot deliver."""
        src = self.source
        lossy = []
        for name in ("list_pdbs", "list_workloads", "list_capacity_buffers",
                     "list_provisioning_requests", "list_namespaces"):
            fn = getattr(src, name, None)
            try:
                # emptiness probe, not a materialized listing — this runs
                # every journaled loop
                if fn is not None and next(iter(fn()), None) is not None:
                    lossy.append(name)
            except Exception:
                lossy.append(name)
        if getattr(src, "dra_snapshot", None) is not None \
                and self.options.enable_dynamic_resource_allocation:
            lossy.append("dra_snapshot")
        if getattr(src, "csi_snapshot", None) is not None \
                and self.options.enable_csi_node_aware_scheduling:
            lossy.append("csi_snapshot")
        return {"unrecordedSources": lossy} if lossy else None

    # ---- scale-up dispatch (single vs salvo) ----

    def _drain_deletion_results(self, now: float) -> None:
        """Apply completed DETACHED deletions' bookkeeping at the top of
        RunOnce — on the control-loop thread (reference: RunOnce consumes
        NodeDeletionTracker.DeletionResults; r4 advisor flagged the previous
        worker-thread callback racing ClusterStateRegistry/observers)."""
        for res in self.actuator.drain_completed():
            gid = self._async_group_of.pop(res.node, "")
            if res.ok:
                self.cluster_state.register_scale_down(res.node, now, gid)
                self.last_scale_down_delete = now
                self.node_group_change_observers.register_scale_down(
                    gid, res.node, now)
                self.metrics.counter("scaled_down_nodes_total").inc()
            else:
                self.last_scale_down_fail = now
                self.node_group_change_observers.register_failed_scale_down(
                    gid, res.node, res.reason, now)

    def _evicted_pods_to_inject(self, live_pods: list[Pod],
                                now: float) -> list[Pod]:
        """Recently evicted, recreatable, NOT-yet-recreated pods — the
        planner injects these before scale-down planning (reference:
        planner.go:239-260 injectRecentlyEvictedPods + filterOutRecreatedPods
        with per-controller replica checks via controller.go getReplicas).

        Recreation detection: a pod whose (namespace, name) is live again is
        recreated; for owners with a known Workload, at most
        (target − current) replicas are injected per owner (current = live
        non-terminal owned pods, the stand-in for the controller's
        Status.Replicas); unknown owners inject unconditionally — "to be on
        the safe side in case there is some custom controller" (planner.go
        :250-253)."""
        recent = self.actuator.tracker.recent_evictions(now)
        if not recent:
            return []
        from kubernetes_autoscaler_tpu.models.api import is_recreatable

        live_keys = {(p.namespace, p.name) for p in live_pods
                     if p.phase not in ("Succeeded", "Failed")}
        workloads = []
        lw = getattr(self.source, "list_workloads", None)
        if lw is not None:
            workloads = list(lw())
        target_of: dict[tuple, int] = {}
        for w in workloads:
            target_of[(w.kind, w.namespace, w.name)] = w.replicas
            if getattr(w, "uid", ""):
                target_of[("uid", w.uid)] = w.replicas
        current: dict[tuple, int] = {}
        for p in live_pods:
            if p.owner is None or p.phase in ("Succeeded", "Failed"):
                continue
            for key in ((p.owner.kind, p.namespace, p.owner.name),
                        ("uid", p.owner.uid) if p.owner.uid else None):
                if key is not None and key in target_of:
                    current[key] = current.get(key, 0) + 1
        added: dict[tuple, int] = {}
        out: list[Pod] = []
        for p in recent:
            if not is_recreatable(p):
                continue
            if (p.namespace, p.name) in live_keys:
                continue                       # literally recreated (e.g. STS)
            key = None
            if p.owner is not None:
                for k in (("uid", p.owner.uid) if p.owner.uid else None,
                          (p.owner.kind, p.namespace, p.owner.name)):
                    if k is not None and k in target_of:
                        key = k
                        break
            if key is None:
                out.append(p)                  # unknown controller: inject
                continue
            gap = target_of[key] - current.get(key, 0)
            if added.get(key, 0) < gap:
                added[key] = added.get(key, 0) + 1
                out.append(p)
        return out

    def _dispatch_scale_up(self, enc, snapshot, nodes: list[Node],
                           now: float, precomputed=None) -> ScaleUpResult:
        # round 1 consumes the fused decision tensors when available; salvo
        # rounds re-inject capacity and re-dispatch, so they always run the
        # phased estimate/score path against the updated snapshot
        result = self.scale_up_orchestrator.scale_up(enc, len(nodes), now,
                                                     precomputed=precomputed)
        if not self.options.scale_up_salvo_enabled or not result.scaled_up:
            return result
        deadline = time.monotonic() + self.options.salvo_time_budget_s
        rounds = 1
        last_increases = dict(result.increases)   # only the LATEST round's
        while (
            result.pods_remaining > 0
            and rounds < self.options.salvo_max_rounds
            and time.monotonic() < deadline
        ):
            # re-inject the capacity this salvo round just bought (reference:
            # :723) so the next round only scales for still-unplaced pods
            injected = 0
            for gid, delta in last_increases.items():
                injected += self._inject_template_nodes(
                    snapshot, gid, delta, f"salvo-{rounds}"
                )
            if injected == 0:
                break
            packed = snapshot.schedule_pending_on_existing()
            snapshot.apply_placement(packed.placed)
            enc.specs = snapshot.state.specs
            enc.nodes = snapshot.state.nodes
            remaining = int(np.asarray(enc.specs.count).sum())
            if remaining == 0:
                result.pods_remaining = 0
                break
            # cluster size includes what earlier rounds already bought, so
            # the cluster-capacity limiter caps against the true total
            grown = len(nodes) + sum(result.increases.values())
            nxt = self.scale_up_orchestrator.scale_up(enc, grown, now)
            rounds += 1
            if not nxt.scaled_up:
                result.pods_remaining = nxt.pods_remaining
                result.errors.update(nxt.errors)
                break
            for gid, delta in nxt.increases.items():
                result.increases[gid] = result.increases.get(gid, 0) + delta
            last_increases = dict(nxt.increases)
            result.pods_helped += nxt.pods_helped
            result.pods_remaining = nxt.pods_remaining
            result.errors.update(nxt.errors)
        return result

    # ---- helpers ----

    def _inject_template_nodes(self, snapshot, gid: str, count: int,
                               prefix: str, template: Node | None = None) -> int:
        """Add `count` sanitized template nodes of group `gid` to the
        snapshot (upcoming-node, async-creation and salvo re-injection share
        this). `template` overrides the provider lookup for groups that do
        not exist yet (async creation in flight)."""
        tmpl = template
        if tmpl is None:
            g = next((x for x in self.provider.node_groups() if x.id() == gid), None)
            if g is None:
                return 0
            tmpl = g.template_node_info()
        # fresh nodes start DS-loaded (node_info_utils.go:45)
        alloc_row = None
        if getattr(self, "_ds_workloads", None):
            from kubernetes_autoscaler_tpu.utils.daemonset import (
                daemonset_overhead,
            )

            ov = daemonset_overhead(tmpl, self._ds_workloads,
                                    snapshot.enc.registry)
            if ov.any():
                alloc_row = ov
        for k in range(count):
            t = self.processors.template_node_info_provider.sanitize(tmpl, gid)
            t.name = f"{prefix}-{gid}-{k}"
            snapshot.add_node(t, group_id=-1, alloc_row=alloc_row)
        return count

    def _group_has_gpu(self, gid: str) -> bool:
        g = next((x for x in self.provider.node_groups() if x.id() == gid), None)
        if g is None:
            return False
        cap = g.template_node_info().alloc_or_cap()
        return float(cap.get(self.provider.gpu_resource_name(), 0.0)) > 0

    def _node_group_index(self, nodes: list[Node]) -> dict[str, int]:
        group_ids = {g.id(): i for i, g in enumerate(self.provider.node_groups())}
        out = {}
        for nd in nodes:
            g = self.provider.node_group_for_node(nd)
            if g is not None:
                out[nd.name] = group_ids.get(g.id(), -1)
        return out

    def _recover_scale_down_state(self, nodes: list[Node], now: float) -> None:
        """First-loop WAL replay: DeletionCandidate taint values are the
        epoch timestamps the clocks started at (actuator writes them);
        leftover ToBeDeleted taints from a crashed run are removed so the
        nodes become schedulable again (reference: cleanUpIfRequired)."""
        from kubernetes_autoscaler_tpu.models.api import (
            DELETION_CANDIDATE_TAINT,
            TO_BE_DELETED_TAINT,
        )

        # crash-consistent restart record first (core/supervisor.py): exact
        # unneeded-since clocks + the in-flight scale-ups soft taints never
        # carried. Records older than --restart-state-max-age are discarded
        # wholesale (premature-deletion guard), restored clocks apply only
        # to nodes still present, and the fresh planner re-verifies
        # unneededness before any deletion — a node that became busy during
        # the downtime keeps its clock entry but never reaches actuation.
        # Taint-based recovery below still runs: setdefault semantics let
        # the exact record win where both exist.
        if self.options.restart_state_path:
            import os as _os

            rec = self._supervisor_mod.load_restart_state(
                self.options.restart_state_path, now=now,
                max_age_s=self.options.restart_state_max_age_s)
            rehydrate_help = ("Restart-record rehydrations by outcome "
                              "(restored / discarded stale-or-corrupt)")
            if rec is not None:
                live = {nd.name for nd in nodes}
                self.planner.unneeded_nodes.load_from_taints({
                    n: t for n, t in rec["unneededSince"].items()
                    if n in live and t <= now})
                from kubernetes_autoscaler_tpu.clusterstate.registry import (
                    ScaleUpRequest,
                )

                groups = {g.id() for g in self.provider.node_groups()}
                for r in rec["scaleUpRequests"]:
                    gid = str(r.get("group", ""))
                    if gid in groups \
                            and gid not in self.cluster_state.scale_up_requests:
                        self.cluster_state.scale_up_requests[gid] = \
                            ScaleUpRequest(gid, int(r["increase"]),
                                           float(r["time"]),
                                           float(r["expectedAddTime"]))
                # inherit the predecessor's shadow-audit evidence pointer:
                # without this, the first post-restart save would rewrite
                # the record with auditBundle="" and erase the pointer the
                # crash was supposed to preserve (docs/REPLAY.md)
                self.last_audit_bundle = (rec.get("auditBundle", "")
                                          or self.last_audit_bundle)
                self._restored_restart = rec
                self.metrics.counter("restart_state_total",
                                     help=rehydrate_help).inc(
                    event="rehydrated")
            elif _os.path.exists(self.options.restart_state_path):
                self._restored_restart = None
                self.metrics.counter("restart_state_total",
                                     help=rehydrate_help).inc(
                    event="discarded")

        ttl = self.options.node_deletion_candidate_ttl_s
        tainted_since: dict[str, float] = {}
        for nd in nodes:
            for t in nd.taints:
                if t.key == DELETION_CANDIDATE_TAINT:
                    try:
                        since = float(t.value)
                    except ValueError:
                        continue
                    # stale intent is discarded, fresh clocks resume
                    # (reference: --node-deletion-candidate-ttl)
                    if ttl <= 0 or now - since <= ttl:
                        tainted_since[nd.name] = since
                    else:
                        self.actuator.untaint(nd, DELETION_CANDIDATE_TAINT)
            # a ToBeDeleted taint is stale ONLY if no deletion is actually in
            # flight for the node — detached deletions this process started
            # (or a test armed) before the first loop must keep theirs
            if not self.actuator.tracker.is_deleting(nd.name) and any(
                    t.key == TO_BE_DELETED_TAINT for t in nd.taints):
                self.actuator.untaint(nd, TO_BE_DELETED_TAINT)
        if tainted_since:
            self.planner.unneeded_nodes.load_from_taints(tainted_since)

    def _sync_soft_taints(self, nodes: list[Node]) -> None:
        """Make DeletionCandidate taints mirror the unneeded set: taint newly
        unneeded nodes, clean taints off nodes that became needed again.
        Bounded per loop by --max-bulk-soft-taint-count updates and
        --max-bulk-soft-taint-time wall clock (reference: softtaint.go
        UpdateSoftDeletionTaints budgets) — the rest catches up next loop."""
        from kubernetes_autoscaler_tpu.models.api import DELETION_CANDIDATE_TAINT

        budget = self.options.max_bulk_soft_taint_count
        deadline = time.monotonic() + self.options.max_bulk_soft_taint_time_s
        unneeded = set(self.planner.state.unneeded)
        for nd in nodes:
            if budget <= 0 or time.monotonic() > deadline:
                break
            has = any(t.key == DELETION_CANDIDATE_TAINT for t in nd.taints)
            if nd.name in unneeded and not has:
                self.actuator.taint_deletion_candidate(
                    nd, since=self.planner.unneeded_nodes.since.get(nd.name))
                budget -= 1
            elif has and nd.name not in unneeded:
                self.actuator.untaint(nd, DELETION_CANDIDATE_TAINT)
                if self.actuator.on_taint:
                    self.actuator.on_taint(nd, "")
                budget -= 1

    def _scale_down_allowed(self, now: float) -> bool:
        o = self.options
        if now - self.cluster_state.last_scale_up_time < o.scale_down_delay_after_add_s:
            return False
        if now - self.last_scale_down_delete < o.scale_down_delay_after_delete_s:
            return False
        if now - self.last_scale_down_fail < o.scale_down_delay_after_failure_s:
            return False
        return True

    def _clean_long_unregistered(self, now: float) -> None:
        """reference: removeOldUnregisteredNodes (static_autoscaler.go:976):
        without --force-delete-unregistered-nodes, removal is capped by group
        min size; with it, min size is ignored and the provider's forceful
        path is used (ForceDeleteNodes, :1018 — base impl falls back to
        DeleteNodes)."""
        by_group: dict[str, list] = {}
        for u in self.cluster_state.long_unregistered(now):
            by_group.setdefault(u.group_id, []).append(u)
        for gid, us in by_group.items():
            g = next((x for x in self.provider.node_groups() if x.id() == gid),
                     None)
            if g is None:
                continue
            if not self.options.force_delete_unregistered_nodes:
                possible = g.target_size() - g.min_size()
                if possible <= 0:
                    continue
                us = us[:possible]
            try:
                nodes = [Node(name=u.name) for u in us]
                if self.options.force_delete_unregistered_nodes:
                    g.force_delete_nodes(nodes)
                else:
                    g.delete_nodes(nodes)
                self.metrics.counter(
                    "old_unregistered_nodes_removed_count").inc(len(us))
            except Exception:
                pass

    def _delete_created_nodes_with_errors(self, nodes: list[Node],
                                          now: float) -> None:
        """Reap instances that failed to boot (create-error status): delete
        them so the target size drops, and back off the group so the next
        loop expands elsewhere (reference: deleteCreatedNodesWithErrors
        static_autoscaler.go:1081 + RegisterFailedScaleUp)."""
        registered = {n.name for n in nodes}
        for g in self.provider.node_groups():
            errored = [
                i for i in g.nodes()
                if i.error_class and i.name not in registered
            ]
            if not errored:
                continue
            # back off FIRST — even if deletion fails (e.g. min-size guard),
            # a group producing create-errors must stop winning scale-ups
            self.cluster_state.register_failed_scale_up(g, now)
            self.metrics.counter("failed_node_creations_total").inc(len(errored))
            try:
                g.delete_nodes([Node(name=i.name) for i in errored])
            except Exception:
                pass
