"""Backend supervisor: the control loop's survival layer.

Reference counterpart: loop/run.go:32 RunAutoscalerOnce (the health-check +
recover wrapper around every loop) and clusterstate's health gating — applied
to the problem the reference never has: the autoscaler's OWN accelerator.
The simulation kernels run on a device behind a tunnel that can hang at
dispatch, drop its buffers on a restart, or flap. Before this layer, a hung
device op wedged RunOnce forever (the exact failure mode that kept five
bench rounds null before the bench grew `InitBudget`/`with_timeout` — bench
machinery that production `run_once` never had), and the first raising loop
killed the driver thread.

The supervisor is a four-state ladder:

    healthy ──phase timeout/error──▶ suspect
    suspect ──another failure / failed probe──▶ degraded
    suspect ──clean loop──▶ healthy
    degraded ──`recovery_probes` consecutive probe successes──▶ recovering
    recovering ──`recovery_hysteresis_loops` clean loops──▶ healthy
    recovering ──any failure──▶ degraded

  * **Phase guards** (`guard(phase, fn)`): encode/dispatch/fetch run under a
    per-phase wall-clock deadline on a sacrificial daemon worker (the same
    escape hatch bench.py's `with_timeout` uses — a hung device op cannot be
    interrupted, only abandoned). A deadline hit aborts the LOOP, not the
    driver: `PhaseDeadlineExceeded` propagates to `run_loop`'s catch and the
    supervisor records the incident. `phase_deadline_s == 0` (the default)
    keeps the guard inline — zero threads, zero behavior change — while
    still converting raised phases into ladder transitions.
  * **Probe-based recovery with hysteresis**: while not healthy, each loop
    starts with a tiny device op under its own deadline. Leaving `degraded`
    takes `recovery_probes` consecutive successes, and `recovering` holds
    scale-down withheld for `recovery_hysteresis_loops` more clean loops —
    a flapping tunnel oscillates between degraded and recovering without
    thrashing full re-encodes (the world heal runs only on the way out).
  * **Safe-action gating** (`scale_down_safe()`): while degraded/recovering
    or while the resident world is stale, scale-down actuation is withheld
    (StaticAutoscaler marks the would-be victims `BackendDegraded` on every
    reason surface) while conservative scale-up stays available — never
    delete nodes off a possibly-wrong simulation; adding capacity on a
    stale view is recoverable, deleting is not.
  * **Crash-consistent restart** (`save_restart_state`/`load_restart_state`):
    the planner's unneeded-since clocks and the registry's in-flight
    scale-ups persist per loop as one atomic JSON record keyed to the
    flight-journal cursor, and rehydrate on startup — a restart neither
    resets scale-down countdowns (delayed scale-down) nor inherits stale
    ones (premature deletion: records older than `max_age_s` are discarded
    wholesale, and restored clocks only ever apply to nodes the fresh
    planner still finds unneeded).

Every transition is stamped three ways: the `backend_state` gauge +
`backend_transitions_total{from,to,cause}` on the registry, a
`BackendTransition` event on the event sink, and a closed span on the
active tracer. Chaos evidence rides `bench.py --chaos-local`
(docs/ROBUSTNESS.md "Control loop").
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from kubernetes_autoscaler_tpu.metrics import trace as _trace
from kubernetes_autoscaler_tpu.sidecar import faults

STATES = ("healthy", "suspect", "degraded", "recovering")
STATE_IDX = {s: i for i, s in enumerate(STATES)}

GUARDED_PHASES = ("encode", "dispatch", "fetch")

STATE_HELP = ("Backend supervisor ladder state "
              "(0=healthy 1=suspect 2=degraded 3=recovering)")
TRANSITIONS_HELP = ("Backend supervisor ladder transitions, by from/to "
                    "state and cause")
TIMEOUTS_HELP = "Guarded control-loop phases aborted at their deadline"
PROBES_HELP = "Backend recovery probes, by outcome"

# hard cap on ABANDONED (deadline-hit, still-wedged) guard/probe workers:
# during a sustained outage each loop would otherwise leak one daemon
# thread pinning a stack and an in-flight device op — over a 15h tunnel
# outage that is thousands of wedged threads and the process dies of the
# exact failure the supervisor exists to survive. At the cap, guards and
# probes fail FAST without spawning: the backend is self-evidently hung.
MAX_ABANDONED_WORKERS = 8


class PhaseDeadlineExceeded(RuntimeError):
    """A guarded phase (encode/dispatch/fetch) outlived its wall-clock
    deadline: the device op is abandoned on its daemon worker and the loop
    aborts — the driver thread survives and the supervisor ladder holds the
    incident."""

    def __init__(self, phase: str, seconds: float):
        super().__init__(
            f"{phase} phase exceeded its {seconds:.1f}s deadline "
            f"(hung device op?) — loop aborted, backend marked suspect")
        self.phase = phase
        self.seconds = seconds


def _default_probe() -> bool:
    """One tiny device round trip: dispatch + fetch of an 8-element sum.
    Exercises the same tunnel the sim kernels ride without touching their
    jit caches. The `local_probe` fault hook makes probe outcomes part of a
    seeded chaos schedule."""
    import jax
    import jax.numpy as jnp

    if faults.PLAN is not None:
        faults.PLAN.fire("local_probe")
    return int(jax.device_get(jnp.arange(8, dtype=jnp.int32).sum())) == 28


class BackendSupervisor:
    """healthy → suspect → degraded → recovering ladder around the control
    loop's device phases. Owned and driven by the control-loop thread; the
    only other threads it creates are sacrificial guard/probe workers."""

    def __init__(self, registry=None, event_sink=None,
                 phase_deadline_s: float = 0.0,
                 probe_deadline_s: float = 5.0,
                 suspect_threshold: int = 2,
                 recovery_probes: int = 2,
                 recovery_hysteresis_loops: int = 2,
                 probe=None, clock=time.monotonic):
        self.registry = registry
        self.event_sink = event_sink
        self.phase_deadline_s = phase_deadline_s
        self.probe_deadline_s = probe_deadline_s
        self.suspect_threshold = max(int(suspect_threshold), 1)
        self.recovery_probes = max(int(recovery_probes), 1)
        self.recovery_hysteresis_loops = max(int(recovery_hysteresis_loops), 0)
        self._probe = probe or _default_probe
        self.clock = clock

        self.state = "healthy"
        self.consecutive_failures = 0
        self.probe_successes = 0
        self.clean_loops = 0
        self.incidents = 0
        # the resident device world is untrusted after any incident until
        # StaticAutoscaler digest-probes/heals it (world_healed())
        self.world_stale = False
        self.last_incident: dict | None = None
        self.last_heal: dict | None = None
        # set when an incident is booked on a loop that still COMPLETES
        # (shadow-audit divergence): end_loop must not read that loop as
        # clean and advance suspect→healthy in the same breath
        self._loop_incident = False
        self.transitions: deque = deque(maxlen=64)
        # deadline-hit workers still wedged on the device op (daemon
        # threads); reaped as they die, capped by MAX_ABANDONED_WORKERS
        self._abandoned: list[threading.Thread] = []
        self._set_gauge()

    def _abandoned_live(self) -> int:
        self._abandoned = [t for t in self._abandoned if t.is_alive()]
        return len(self._abandoned)

    # ---- the per-phase guard ------------------------------------------

    def guard(self, phase: str, fn, deadline_s: float | None = None):
        """Run one device phase under the supervisor's watch. With a
        positive deadline the op runs on a daemon worker and is abandoned
        at the deadline (`PhaseDeadlineExceeded`); with deadline 0 it runs
        inline (zero overhead) but a raise still books the incident. Either
        way the active tracer is preserved so phase spans keep landing on
        the loop's timeline."""
        deadline = (self.phase_deadline_s if deadline_s is None
                    else deadline_s)
        hook = f"local_{phase}"
        if deadline <= 0:
            try:
                if faults.PLAN is not None:
                    faults.PLAN.fire(hook, registry=self.registry)
                return fn()
            except Exception as e:
                self.record_failure(phase, f"error-{type(e).__name__}")
                raise
        if self._abandoned_live() >= MAX_ABANDONED_WORKERS:
            # the wedged-worker population says the backend is hung without
            # asking it again — fail fast, leak nothing more
            self.record_failure(phase, "timeout")
            raise PhaseDeadlineExceeded(phase, deadline)
        tracer = _trace.current_tracer()
        result: list = []
        error: list = []

        def run():
            # the worker inherits the loop's tracer so nested phase spans
            # stay on one timeline; on a deadline hit the hung worker keeps
            # its activation — it is a daemon and its late spans are the
            # least of a wedged tunnel's problems
            if tracer is not None:
                _trace.activate(tracer)
            try:
                if faults.PLAN is not None:
                    faults.PLAN.fire(hook, registry=self.registry)
                result.append(fn())
            except Exception as e:  # noqa: BLE001 — forwarded to the loop
                error.append(e)
            finally:
                if tracer is not None:
                    _trace.activate(None)

        t = threading.Thread(target=run, daemon=True,
                             name=f"ka-phase-{phase}")
        t.start()
        t.join(timeout=deadline)
        if t.is_alive():
            self._abandoned.append(t)
            if self.registry is not None:
                self.registry.counter(
                    "backend_phase_timeouts_total",
                    help=TIMEOUTS_HELP).inc(phase=phase)
            self.record_failure(phase, "timeout")
            raise PhaseDeadlineExceeded(phase, deadline)
        if error:
            self.record_failure(phase, f"error-{type(error[0]).__name__}")
            raise error[0]
        return result[0]

    # ---- ladder bookkeeping -------------------------------------------

    def record_failure(self, phase: str, cause: str) -> None:
        """One guarded-phase incident: any in-flight recovery resets and
        the resident world is untrusted until healed."""
        self.consecutive_failures += 1
        self.probe_successes = 0
        self.clean_loops = 0
        self.world_stale = True
        self.incidents += 1
        self.last_incident = {"phase": phase, "cause": cause,
                              "at": self.clock()}
        full_cause = f"{phase}-{cause}"
        if self.state == "healthy":
            self._transition("suspect", full_cause)
        elif self.state == "suspect" \
                and self.consecutive_failures >= self.suspect_threshold:
            self._transition("degraded", full_cause)
        elif self.state == "recovering":
            self._transition("degraded", full_cause)

    def audit_divergence(self, detail: dict | None = None,
                         persistent: bool = False) -> None:
        """A shadow-audit divergence (audit/shadow.py) is a guarded-phase
        incident in every way but its discovery path: the device answered
        fast and WRONG instead of hanging. The resident world is untrusted
        (the next loop heals it with a FORCED full re-encode) and the
        ladder moves — healthy→suspect on first divergence, →degraded when
        the post-heal re-audit of the same sample diverges again
        (`persistent`): a divergence that survives a cold re-encode means
        the backend itself cannot be trusted to actuate."""
        self.probe_successes = 0
        self.clean_loops = 0
        self.world_stale = True
        self.incidents += 1
        self._loop_incident = True
        self.last_incident = {"phase": "audit", "cause": "audit_divergence",
                              "at": self.clock(), **(detail or {})}
        if persistent:
            if self.state != "degraded":
                self._transition("degraded", "audit_divergence")
        elif self.state == "healthy":
            self._transition("suspect", "audit_divergence")
        elif self.state == "recovering":
            self._transition("degraded", "audit_divergence")

    def begin_loop(self) -> None:
        """Top-of-RunOnce hook: a healthy backend costs one attribute read;
        any other state runs the recovery probe under its deadline."""
        self._loop_incident = False
        if self.state == "healthy":
            return
        ok = self.run_probe()
        if self.state == "suspect":
            if not ok:
                self._transition("degraded", "probe-failed")
        elif self.state == "degraded":
            if ok:
                self.probe_successes += 1
                if self.probe_successes >= self.recovery_probes:
                    self._transition("recovering", "probe-ok")
            else:
                self.probe_successes = 0
        elif self.state == "recovering":
            if not ok:
                self.clean_loops = 0
                self.probe_successes = 0
                self._transition("degraded", "probe-failed")

    def end_loop(self) -> None:
        """A loop that completed without a guarded-phase incident."""
        if self._loop_incident:
            # the loop finished, but an audit divergence was booked on it —
            # it is NOT clean evidence for suspect→healthy / hysteresis
            self._loop_incident = False
            return
        self.consecutive_failures = 0
        if self.state == "suspect":
            self._transition("healthy", "clean-loop")
        elif self.state == "recovering":
            self.clean_loops += 1
            if self.clean_loops >= self.recovery_hysteresis_loops:
                self._transition("healthy", "recovered")

    def run_probe(self) -> bool:
        """The probe under its own daemon-worker deadline; hang == failure.
        At the abandoned-worker cap no new worker spawns — a backend that
        wedged that many probes/guards needs no further evidence."""
        ok = False
        if self._abandoned_live() < MAX_ABANDONED_WORKERS:
            result: list = []

            def run():
                try:
                    result.append(bool(self._probe()))
                except Exception:  # noqa: BLE001 — a raising probe is a failed probe
                    result.append(False)

            t = threading.Thread(target=run, daemon=True, name="ka-probe")
            t.start()
            t.join(timeout=max(self.probe_deadline_s, 0.1))
            if t.is_alive():
                self._abandoned.append(t)
            ok = bool(result and result[0])
        if self.registry is not None:
            self.registry.counter("backend_probes_total",
                                  help=PROBES_HELP).inc(
                outcome="ok" if ok else "failed")
        return ok

    # ---- safe-action gating -------------------------------------------

    def scale_down_safe(self) -> bool:
        """Never delete nodes off a possibly-wrong simulation: scale-down
        actuation is withheld while degraded/recovering (the hysteresis
        window included) or while the resident world is unverified after an
        incident. Scale-up is never gated here — adding capacity on a stale
        view is recoverable."""
        return self.state not in ("degraded", "recovering") \
            and not self.world_stale

    def world_healed(self, outcome: str, detail: dict | None = None) -> None:
        """StaticAutoscaler verified (or rebuilt) the resident world."""
        self.world_stale = False
        self.last_heal = {"outcome": outcome, "at": self.clock(),
                          **(detail or {})}

    # ---- surfaces ------------------------------------------------------

    def _transition(self, to: str, cause: str) -> None:
        frm, self.state = self.state, to
        if to != "suspect":
            # entering suspect keeps the failure streak (it decides
            # suspect→degraded); every other arrival starts a fresh ledger
            self.consecutive_failures = 0
        if to == "recovering":
            self.clean_loops = 0
        self.transitions.append(
            {"from": frm, "to": to, "cause": cause, "at": self.clock()})
        if self.registry is not None:
            self.registry.counter(
                "backend_transitions_total",
                help=TRANSITIONS_HELP).inc(
                **{"from": frm, "to": to, "cause": cause})
        self._set_gauge()
        if self.event_sink is not None:
            self.event_sink.emit(
                "BackendTransition", obj="backend", reason=to,
                message=f"{frm} -> {to}: {cause}")
        tr = _trace.current_tracer()
        if tr is not None:
            tr.add_span("backend_transition", cat="supervisor",
                        **{"from": frm, "to": to, "cause": cause})

    def _set_gauge(self) -> None:
        if self.registry is not None:
            self.registry.gauge("backend_state", help=STATE_HELP).set(
                float(STATE_IDX[self.state]))

    def stats(self) -> dict:
        return {
            "state": self.state,
            "abandonedWorkers": self._abandoned_live(),
            "incidents": self.incidents,
            "worldStale": self.world_stale,
            "lastIncident": self.last_incident,
            "lastHeal": self.last_heal,
            "transitions": list(self.transitions),
        }


# ---- crash-consistent restart record -----------------------------------
#
# One atomic JSON file, rewritten each loop: the scale-down WAL that soft
# taints cannot fully carry (the per-loop taint budget lags behind the
# unneeded set) plus the in-flight scale-ups that have NO taint analog at
# all, keyed to the flight-journal cursor so retained evidence names the
# exact loop the record describes (docs/REPLAY.md).

RESTART_RECORD_VERSION = 1


def save_restart_state(path: str, *, now: float,
                       journal_cursor: tuple | None,
                       unneeded_since: dict,
                       scale_up_requests: dict,
                       audit_bundle: str = "") -> None:
    """Persist the restart record atomically (write + fsync + rename — a
    crash mid-save leaves the previous intact record, never a torn one).
    `now` is the RunOnce clock domain (wall or logical), and staleness at
    load time is judged in the same domain. `audit_bundle` is the most
    recent shadow-audit divergence bundle path (mirroring the journal
    cursor: a restarted process inherits the pointer to the evidence its
    predecessor's last divergence produced)."""
    rec = {
        "version": RESTART_RECORD_VERSION,
        "savedAt": float(now),
        "journalCursor": (list(journal_cursor)
                          if journal_cursor is not None else None),
        **({"auditBundle": audit_bundle} if audit_bundle else {}),
        "unneededSince": {str(k): float(v)
                          for k, v in unneeded_since.items()},
        "scaleUpRequests": [
            {"group": r.group_id, "increase": int(r.increase),
             "time": float(r.time),
             "expectedAddTime": float(r.expected_add_time)}
            for r in scale_up_requests.values()
        ],
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(rec, f, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_restart_state(path: str, *, now: float,
                       max_age_s: float) -> dict | None:
    """Load and screen a restart record. Returns None (cold start) when the
    file is missing, unparseable, from a future clock domain, or older than
    `max_age_s` — stale countdown clocks from a long-dead predecessor must
    not cause premature deletions, so an over-age record is discarded
    WHOLESALE rather than trusted partially."""
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(rec, dict) \
            or rec.get("version") != RESTART_RECORD_VERSION:
        return None
    saved = rec.get("savedAt")
    if not isinstance(saved, (int, float)):
        return None
    age = now - float(saved)
    if age < 0 or (max_age_s > 0 and age > max_age_s):
        return None
    if not isinstance(rec.get("unneededSince"), dict) \
            or not isinstance(rec.get("scaleUpRequests"), list):
        return None
    return rec
