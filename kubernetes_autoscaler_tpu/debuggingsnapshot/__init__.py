from kubernetes_autoscaler_tpu.debuggingsnapshot.snapshotter import (
    DebuggingSnapshotter,
)

__all__ = ["DebuggingSnapshotter"]
