"""Debugging snapshot: on-demand JSON dump of the autoscaler's internal view.

Reference counterpart: cluster-autoscaler/debuggingsnapshot/ (SURVEY.md §2.7)
— the `/snapshotz` HTTP endpoint arms a snapshotter; during the next RunOnce
the loop feeds it node/pod state (static_autoscaler.go:299-300, 404, 527) and
the pending HTTP request receives the JSON once the loop completes.

Same protocol here: `request_snapshot()` arms it (returns a handle to await),
StaticAutoscaler calls the setters only when armed (`is_data_collection_
allowed`), and `flush()` resolves the handle.

A RunOnce that RAISES mid-loop must still resolve the handle — otherwise the
snapshotter stays armed forever and the `/snapshotz` caller hangs on a dead
loop. `flush(error=...)` ships whatever partial payload was collected plus
the error string. Every snapshot also carries the loop's observability keys:
`phaseStats` (metrics/phases.PhaseStats.snapshot() per owner) and `traceId`
(the flight-recorder trace covering this loop, metrics/trace.py) so the JSON
links directly to the Perfetto timeline that explains it.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any

from kubernetes_autoscaler_tpu.models.api import Node, Pod


def _pod_view(p: Pod) -> dict[str, Any]:
    return {
        "name": p.name,
        "namespace": p.namespace,
        "requests": dict(p.requests),
        "nodeName": p.node_name,
        "phase": p.phase,
        "owner": p.owner.kind if p.owner else "",
        "priority": p.priority,
    }


def _node_view(n: Node, pods: list[Pod]) -> dict[str, Any]:
    return {
        "name": n.name,
        "ready": n.ready,
        "labels": dict(n.labels),
        "allocatable": dict(n.alloc_or_cap()),
        "taints": [vars(t) for t in n.taints],
        "pods": [_pod_view(p) for p in pods],
    }


class _Handle:
    def __init__(self):
        self.event = threading.Event()
        self.payload: str = ""

    def wait(self, timeout: float | None = None) -> str:
        self.event.wait(timeout)
        return self.payload


class DebuggingSnapshotter:
    """Armed/disarmed snapshot collector (reference:
    debugging_snapshotter.go DebuggingSnapshotterImpl state machine)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: _Handle | None = None
        self._data: dict[str, Any] = {}

    # ---- consumer side (the /snapshotz handler) ----

    def request_snapshot(self) -> _Handle:
        with self._lock:
            if self._armed is None:
                self._armed = _Handle()
                self._data = {}
            return self._armed

    # ---- producer side (RunOnce) ----

    def is_data_collection_allowed(self) -> bool:
        with self._lock:
            return self._armed is not None

    def set_cluster_nodes(self, nodes: list[Node], pods_by_node: dict[str, list[Pod]]) -> None:
        with self._lock:
            if self._armed is None:
                return
            self._data["nodeList"] = [
                _node_view(n, pods_by_node.get(n.name, [])) for n in nodes
            ]

    def set_unscheduled_pods_can_be_scheduled(self, pods: list[Pod]) -> None:
        with self._lock:
            if self._armed is None:
                return
            self._data["unscheduledPodsCanBeScheduled"] = [
                _pod_view(p) for p in pods
            ]

    def set_template_nodes(self, templates: dict[str, Node]) -> None:
        with self._lock:
            if self._armed is None:
                return
            self._data["templateNodes"] = {
                gid: _node_view(t, []) for gid, t in templates.items()
            }

    def set_errors(self, errors: list[str]) -> None:
        with self._lock:
            if self._armed is None:
                return
            self._data["errors"] = list(errors)

    def set_phase_stats(self, phases: dict[str, Any]) -> None:
        """Per-owner PhaseStats.snapshot() dicts for the serving loop."""
        with self._lock:
            if self._armed is None:
                return
            self._data["phaseStats"] = phases

    def set_trace_id(self, trace_id: str) -> None:
        with self._lock:
            if self._armed is None:
                return
            self._data["traceId"] = trace_id

    def set_journal_cursor(self, loop: int, digest: str) -> None:
        """The flight-journal cursor covering this loop (replay/journal.py)
        — the snapshot resolves to the exact record
        `python -m kubernetes_autoscaler_tpu.replay` re-executes."""
        with self._lock:
            if self._armed is None:
                return
            self._data["journalLoop"] = int(loop)
            self._data["journalDigest"] = digest

    def set_reason_plane(self, payload: dict[str, Any]) -> None:
        """The loop's explainable verdicts: refused pod groups with their
        constraint bits, unremovable nodes with reasons + drain-failure
        detail, and the event-sink ring — so a /snapshotz dump of a breached
        loop says WHICH constraint refused WHICH pods."""
        with self._lock:
            if self._armed is None:
                return
            self._data["reasonPlane"] = payload

    def flush(self, now: float | None = None, error: str | None = None) -> None:
        """End of RunOnce: resolve the armed handle (reference: Flush).
        `error` is the flush-on-error path — the loop raised, so the caller
        gets the PARTIAL payload plus the error instead of hanging forever
        on a snapshotter nothing will ever flush again."""
        with self._lock:
            if self._armed is None:
                return
            if error is None and not self._data:
                # armed mid-loop AFTER this loop's collection points: stay
                # armed and serve the NEXT full loop instead of resolving
                # with an empty payload (error flushes always resolve — a
                # raised loop must never leave the caller hanging)
                return
            if error is not None:
                self._data["error"] = error
            self._data["timestamp"] = time.time() if now is None else now
            self._armed.payload = json.dumps(self._data, indent=2, default=str)
            self._armed.event.set()
            self._armed = None
            self._data = {}
