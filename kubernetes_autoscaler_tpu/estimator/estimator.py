"""Estimator: scale-up sizing behind the reference's EstimatorBuilder seam.

Reference counterpart: estimator/estimator.go:53-75 — `Estimate(pods,
nodeTemplate, nodeGroup) → (nodeCount, scheduledPods)`, with "binpacking" the
only registered implementation (BinpackingNodeEstimator,
binpacking_estimator.go:102). This module keeps that per-node-group call shape
for drop-in parity; the orchestrator prefers the batched all-groups kernel
(ops/binpack.estimate_all) and only falls back here when a processor injects a
custom estimator.

Threshold limiters mirror estimator/threshold_based_limiter.go and friends:
a static cap (--max-nodes-per-scaleup), cluster-capacity and per-group caps,
composed as min().
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol

import jax.numpy as jnp
import numpy as np

from kubernetes_autoscaler_tpu.models.cluster_state import (
    Dims,
    NodeGroupTensors,
    PodGroupTensors,
)
from kubernetes_autoscaler_tpu.ops.binpack import EstimateResult, estimate_all


class EstimationLimiter(Protocol):
    """reference: estimator/estimation_limiter — node-count cap per estimation."""

    def max_nodes(self, cluster_size: int, group_max_new: int) -> int: ...


@dataclass
class StaticThresholdLimiter:
    """reference: estimator/static_threshold.go (--max-nodes-per-scaleup)."""

    max_nodes_per_scaleup: int = 1000

    def max_nodes(self, cluster_size: int, group_max_new: int) -> int:
        return self.max_nodes_per_scaleup


@dataclass
class ClusterCapacityThresholdLimiter:
    """reference: estimator/cluster_capacity_threshold.go (--max-nodes-total)."""

    max_nodes_total: int = 0

    def max_nodes(self, cluster_size: int, group_max_new: int) -> int:
        if self.max_nodes_total <= 0:
            return 1 << 30
        return max(self.max_nodes_total - cluster_size, 0)


@dataclass
class SngCapacityThresholdLimiter:
    """reference: estimator/sng_capacity_threshold.go (maxSize - targetSize)."""

    def max_nodes(self, cluster_size: int, group_max_new: int) -> int:
        return max(group_max_new, 0)


def combined_limit(limiters: list[EstimationLimiter], cluster_size: int,
                   group_max_new: int) -> int:
    """reference: thresholdBasedEstimationLimiter composes via min."""
    return min(l.max_nodes(cluster_size, group_max_new) for l in limiters)


class BinpackingEstimator:
    """Per-node-group Estimate() parity wrapper over the batched kernel."""

    def __init__(self, dims: Dims, max_new_nodes_static: int = 1024,
                 limiters: list[EstimationLimiter] | None = None,
                 planes=None, nodes=None, with_constraints: bool = False):
        self.dims = dims
        self.max_new_nodes_static = max_new_nodes_static
        self.limiters = limiters or [StaticThresholdLimiter()]
        # topology-coupled constraint context (ops/constrained.py): the real
        # cluster's resident planes + node tensors, threaded into estimate_all
        self.planes = planes
        self.nodes = nodes
        self.with_constraints = with_constraints

    def estimate(
        self,
        specs: PodGroupTensors,
        group_tensors: NodeGroupTensors,
        group_index: int,
        cluster_size: int = 0,
    ) -> tuple[int, np.ndarray]:
        """(node_count, scheduled[G]) for one node group — the reference
        Estimate() signature (estimator.go:63)."""
        limit = combined_limit(
            self.limiters, cluster_size,
            int(group_tensors.max_new[group_index]),
        )
        capped = group_tensors.replace(
            max_new=group_tensors.max_new.at[group_index].min(limit)
        )
        result = estimate_all(specs, capped, self.dims, self.max_new_nodes_static,
                              planes=self.planes, nodes=self.nodes,
                              with_constraints=self.with_constraints)
        return int(result.node_count[group_index]), np.asarray(result.scheduled[group_index])

    def estimate_all_groups(
        self,
        specs: PodGroupTensors,
        group_tensors: NodeGroupTensors,
        cluster_size: int = 0,
    ) -> EstimateResult:
        """The batched path the orchestrator actually uses: every group's
        option in one device program, with per-group caps applied."""
        caps = [
            combined_limit(self.limiters, cluster_size, int(m))
            for m in np.asarray(group_tensors.max_new)
        ]
        capped = group_tensors.replace(
            max_new=jnp.minimum(group_tensors.max_new, jnp.asarray(caps, jnp.int32))
        )
        return estimate_all(specs, capped, self.dims, self.max_new_nodes_static,
                            planes=self.planes, nodes=self.nodes,
                            with_constraints=self.with_constraints)


def build_estimator(name: str, dims: Dims, **kw) -> BinpackingEstimator:
    """reference: estimator.NewEstimatorBuilder (estimator.go:75)."""
    if name != "binpacking":
        raise ValueError(f"unknown estimator {name!r} (only 'binpacking' exists, "
                         "mirroring the reference)")
    return BinpackingEstimator(dims, **kw)
