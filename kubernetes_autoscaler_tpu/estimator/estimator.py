"""Estimator: scale-up sizing behind the reference's EstimatorBuilder seam.

Reference counterpart: estimator/estimator.go:53-75 — `Estimate(pods,
nodeTemplate, nodeGroup) → (nodeCount, scheduledPods)`, with "binpacking" the
only registered implementation (BinpackingNodeEstimator,
binpacking_estimator.go:102). This module keeps that per-node-group call shape
for drop-in parity; the orchestrator prefers the batched all-groups kernel
(ops/binpack.estimate_all) and only falls back here when a processor injects a
custom estimator.

Threshold limiters mirror estimator/threshold_based_limiter.go and friends:
a static cap (--max-nodes-per-scaleup), cluster-capacity and per-group caps,
composed as min().
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol

import jax.numpy as jnp
import numpy as np

from kubernetes_autoscaler_tpu.models.cluster_state import (
    Dims,
    NodeGroupTensors,
    PodGroupTensors,
)
from kubernetes_autoscaler_tpu.ops.binpack import EstimateResult, estimate_all


class EstimationLimiter(Protocol):
    """reference: estimator/estimation_limiter — node-count cap per estimation.

    Limiters may additionally implement `max_nodes_vec(cluster_size,
    max_new)` returning an i32[NG] device array; the batched estimator path
    composes those without any per-group host arithmetic. Limiters without
    it fall back to a per-group `max_nodes` loop (one host fetch)."""

    def max_nodes(self, cluster_size: int, group_max_new: int) -> int: ...


@dataclass
class StaticThresholdLimiter:
    """reference: estimator/static_threshold.go (--max-nodes-per-scaleup)."""

    max_nodes_per_scaleup: int = 1000

    def max_nodes(self, cluster_size: int, group_max_new: int) -> int:
        return self.max_nodes_per_scaleup

    def max_nodes_vec(self, cluster_size: int, max_new) -> jnp.ndarray:
        return jnp.full_like(max_new, jnp.int32(self.max_nodes_per_scaleup))


@dataclass
class ClusterCapacityThresholdLimiter:
    """reference: estimator/cluster_capacity_threshold.go (--max-nodes-total)."""

    max_nodes_total: int = 0

    def max_nodes(self, cluster_size: int, group_max_new: int) -> int:
        if self.max_nodes_total <= 0:
            return 1 << 30
        return max(self.max_nodes_total - cluster_size, 0)

    def max_nodes_vec(self, cluster_size: int, max_new) -> jnp.ndarray:
        cap = (1 << 30) if self.max_nodes_total <= 0 \
            else max(self.max_nodes_total - cluster_size, 0)
        return jnp.full_like(max_new, jnp.int32(cap))


@dataclass
class SngCapacityThresholdLimiter:
    """reference: estimator/sng_capacity_threshold.go (maxSize - targetSize)."""

    def max_nodes(self, cluster_size: int, group_max_new: int) -> int:
        return max(group_max_new, 0)

    def max_nodes_vec(self, cluster_size: int, max_new) -> jnp.ndarray:
        return jnp.maximum(max_new, 0)


def combined_limit(limiters: list[EstimationLimiter], cluster_size: int,
                   group_max_new: int) -> int:
    """reference: thresholdBasedEstimationLimiter composes via min."""
    return min(l.max_nodes(cluster_size, group_max_new) for l in limiters)


def combined_limit_vec(limiters: list[EstimationLimiter], cluster_size: int,
                       max_new) -> jnp.ndarray:
    """Vectorized min-composition over all groups at once: the whole limiter
    stack stays on device for built-in limiters — no per-group host loop on
    the estimate path. A processor-injected limiter without `max_nodes_vec`
    degrades to one bounded host loop for that limiter only."""
    cap = jnp.full_like(max_new, jnp.int32(1 << 30))
    for lim in limiters:
        vec = getattr(lim, "max_nodes_vec", None)
        if vec is not None:
            cap = jnp.minimum(cap, vec(cluster_size, max_new))
        else:
            host = np.asarray(
                [min(lim.max_nodes(cluster_size, int(m)), 1 << 30)
                 for m in np.asarray(max_new)], np.int32)
            cap = jnp.minimum(cap, jnp.asarray(host))
    return cap


class BinpackingEstimator:
    """Per-node-group Estimate() parity wrapper over the batched kernel."""

    def __init__(self, dims: Dims, max_new_nodes_static: int = 1024,
                 limiters: list[EstimationLimiter] | None = None,
                 planes=None, nodes=None, with_constraints: bool = False,
                 mesh=None):
        self.dims = dims
        self.max_new_nodes_static = max_new_nodes_static
        self.limiters = limiters or [StaticThresholdLimiter()]
        # topology-coupled constraint context (ops/constrained.py): the real
        # cluster's resident planes + node tensors, threaded into estimate_all
        self.planes = planes
        self.nodes = nodes
        self.with_constraints = with_constraints
        # optional device mesh: NG options sharded over PODS_AXIS
        self.mesh = mesh

    def estimate(
        self,
        specs: PodGroupTensors,
        group_tensors: NodeGroupTensors,
        group_index: int,
        cluster_size: int = 0,
    ) -> tuple[int, np.ndarray]:
        """(node_count, scheduled[G]) for one node group — the reference
        Estimate() signature (estimator.go:63)."""
        limit = combined_limit(
            self.limiters, cluster_size,
            int(group_tensors.max_new[group_index]),
        )
        capped = group_tensors.replace(
            max_new=group_tensors.max_new.at[group_index].min(limit)
        )
        result = estimate_all(specs, capped, self.dims, self.max_new_nodes_static,
                              planes=self.planes, nodes=self.nodes,
                              with_constraints=self.with_constraints,
                              mesh=self.mesh)
        return int(result.node_count[group_index]), np.asarray(result.scheduled[group_index])

    def estimate_all_groups(
        self,
        specs: PodGroupTensors,
        group_tensors: NodeGroupTensors,
        cluster_size: int = 0,
    ) -> EstimateResult:
        """The batched path the orchestrator actually uses: every group's
        option in one device program, with per-group caps applied — the
        limiter stack composes vectorized (combined_limit_vec), so no
        per-group host arithmetic sits on the loop path."""
        capped = group_tensors.replace(
            max_new=jnp.minimum(
                group_tensors.max_new,
                combined_limit_vec(self.limiters, cluster_size,
                                   group_tensors.max_new))
        )
        return estimate_all(specs, capped, self.dims, self.max_new_nodes_static,
                            planes=self.planes, nodes=self.nodes,
                            with_constraints=self.with_constraints,
                            mesh=self.mesh)


def explain_refused_groups(
    specs: PodGroupTensors,
    group_tensors: NodeGroupTensors,
    refused: np.ndarray,         # bool[G] — groups no expansion option helped
    dims: Dims,
) -> np.ndarray:
    """The estimator layer's reason pass: uint16[G, NG] refusal bits for the
    refused pod groups against every node group's template (fresh empty
    node — capacity vs template allocatable, predicates vs template
    labels/taints). The reference reports this per pod from the estimator's
    scheduling errors ("pod didn't fit on node group …"); here it is ONE
    lazy masked dispatch + one batched fetch over refused groups only, so a
    loop where every option helps performs zero extra dispatches
    (`reason_extraction_dispatches` — the caller counts)."""
    import jax.numpy as jnp

    from kubernetes_autoscaler_tpu.ops import predicates as preds

    tmpl_nodes = group_tensors.as_node_tensors(dims)
    return np.asarray(preds.reason_mask_for_groups(
        tmpl_nodes, specs, jnp.asarray(np.asarray(refused, bool))))


def build_estimator(name: str, dims: Dims, **kw) -> BinpackingEstimator:
    """reference: estimator.NewEstimatorBuilder (estimator.go:75)."""
    if name != "binpacking":
        raise ValueError(f"unknown estimator {name!r} (only 'binpacking' exists, "
                         "mirroring the reference)")
    return BinpackingEstimator(dims, **kw)
