"""Throttled, deduplicated autoscaling event sink — the reason plane's
human-facing surface.

Reference counterpart: the kube EventRecorder calls spread through the
autoscaler — per-pod NoScaleUp events with skip reasons
(core/scaleup/orchestrator posts "pod didn't trigger scale-up" with the
per-nodegroup reasons), per-node NoScaleDown/ScaleDownFailed events
(core/scaledown), all spam-bounded by the API server's event aggregation and
the klogx logging quotas (utils/klogx, hinting_simulator.go:57).

Here: one in-process sink shared by the scale-up orchestrator and the
scale-down planner. Emission is

  * deduplicated by (kind, object, reason) — a repeat inside the dedup
    window bumps the stored event's count instead of producing a new one
    (the reference gets this from kube event aggregation);
  * throttled per loop through a klogx.LoggingQuota — the first N distinct
    events per loop reach the log/store, the overflow is COUNTED (the
    `dropped` field rides bench.py's JSON so a reason-plane regression that
    floods events is visible in the perf trajectory) and summarized with the
    klogx "... and N more" frame;
  * bounded in memory by `capacity` (oldest evicted first).

Counters ride an attached metrics.Registry: `scale_events_total{kind,reason}`
and `scale_events_dropped_total`. Dedup-aggregated repeats ALSO increment
`scale_events_total` — the stored events' count aggregates and the counter
deltas describe the same stream (pinned by test), so lineage can trust
either. The stored ring is exported by `snapshot()` into `/snapshotz`
payloads so a flight-recorder investigation sees the same verdicts the
events carried, and `history(kind, obj)` serves the per-object view the
lineage join reads without scanning the whole ring.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.utils import klogx

NO_SCALE_UP = "NoScaleUp"
NO_SCALE_DOWN = "NoScaleDown"

_EVENTS_HELP = "Autoscaling reason events emitted, by kind and reason"
_DROPPED_HELP = "Reason events dropped by the per-loop klogx quota"


@dataclass
class Event:
    kind: str        # NoScaleUp | NoScaleDown
    obj: str         # pod (scale-up) or node (scale-down) name
    reason: str      # taxonomy string (ops/predicates.REASON_BITS names,
                     # or the reference unremovable enum values)
    message: str = ""
    count: int = 1
    first_ts: float = 0.0
    last_ts: float = 0.0

    def to_dict(self) -> dict:
        return {
            "kind": self.kind, "object": self.obj, "reason": self.reason,
            "message": self.message, "count": self.count,
            "firstTimestamp": self.first_ts, "lastTimestamp": self.last_ts,
        }


@dataclass
class EventSink:
    per_loop_quota: int = 20
    dedup_window_s: float = 600.0
    capacity: int = 512
    history_objects: int = 256          # (kind, obj) keys in the side index
    registry: object | None = None      # optional metrics.Registry
    events: "OrderedDict[tuple, Event]" = field(default_factory=OrderedDict)
    dropped: int = 0
    deduped: int = 0
    emitted: int = 0
    _quota: klogx.LoggingQuota = field(init=False)
    # (kind, obj) -> {reason: Event} — the same Event objects the ring
    # holds, so a dedup count bump is visible here for free; LRU-bounded
    # by key count, pruned when the ring evicts
    _by_obj: "OrderedDict[tuple, OrderedDict]" = field(init=False)

    def __post_init__(self):
        self._quota = klogx.LoggingQuota(self.per_loop_quota)
        self._by_obj = OrderedDict()

    # ---- loop framing (RunOnce calls both) ----

    def begin_loop(self) -> None:
        self._quota.reset()

    def end_loop(self) -> None:
        """Overflow summary + quota reset (klogx frame contract)."""
        klogx.frame_up(self._quota, "scale events")

    # ---- emission ----

    def emit(self, kind: str, obj: str, reason: str, message: str = "",
             now: float = 0.0) -> None:
        key = (kind, obj, reason)
        ev = self.events.get(key)
        if ev is not None and now - ev.last_ts <= self.dedup_window_s:
            # aggregation: same verdict again — count it, keep one event.
            # The counter still moves: the stored count aggregate and the
            # scale_events_total delta must describe the same stream.
            ev.count += 1
            ev.last_ts = now
            if message:
                ev.message = message
            self.deduped += 1
            self.events.move_to_end(key)
            if self.registry is not None:
                self.registry.counter("scale_events_total",
                                      help=_EVENTS_HELP).inc(kind=kind,
                                                             reason=reason)
            return
        klogx.v(self._quota, "%s %s: %s%s", kind, obj, reason,
                f" ({message})" if message else "")
        if self._quota.left < 0:
            # over the loop quota: counted, not stored (the klogx frame
            # prints the "... and N more" line at end_loop)
            self.dropped += 1
            if self.registry is not None:
                self.registry.counter("scale_events_dropped_total",
                                      help=_DROPPED_HELP).inc()
            return
        stored = Event(kind=kind, obj=obj, reason=reason,
                       message=message, first_ts=now, last_ts=now)
        self.events[key] = stored
        self.events.move_to_end(key)
        while len(self.events) > self.capacity:
            old_key, _ = self.events.popitem(last=False)
            self._unindex(old_key)
        self._index(key, stored)
        self.emitted += 1
        if self.registry is not None:
            self.registry.counter("scale_events_total",
                                  help=_EVENTS_HELP).inc(kind=kind,
                                                         reason=reason)

    # ---- per-object side index ----

    def _index(self, key: tuple, ev: Event) -> None:
        okey = (key[0], key[1])
        bucket = self._by_obj.get(okey)
        if bucket is None:
            bucket = OrderedDict()
            self._by_obj[okey] = bucket
            while len(self._by_obj) > self.history_objects:
                self._by_obj.popitem(last=False)
        bucket[key[2]] = ev
        self._by_obj.move_to_end(okey)

    def _unindex(self, key: tuple) -> None:
        okey = (key[0], key[1])
        bucket = self._by_obj.get(okey)
        if bucket is not None:
            bucket.pop(key[2], None)
            if not bucket:
                del self._by_obj[okey]

    def history(self, kind: str | None, obj: str) -> list[dict]:
        """The bounded per-object view lineage joins at query time — O(its
        own reasons), never O(ring). kind=None merges both kinds for the
        object name (lineage's node kind sees NoScaleDown; pod-group sees
        NoScaleUp)."""
        keys = [(kind, obj)] if kind is not None else \
            [(NO_SCALE_UP, obj), (NO_SCALE_DOWN, obj)]
        out: list[dict] = []
        for okey in keys:
            bucket = self._by_obj.get(okey)
            if bucket:
                out.extend(ev.to_dict() for ev in bucket.values())
        out.sort(key=lambda d: d["lastTimestamp"])
        return out

    # ---- export ----

    def snapshot(self) -> list[dict]:
        """Newest-last list of stored events (rides /snapshotz payloads).

        Ordered by lastTimestamp on EXPORT, not by ring position: the ring
        orders by update sequence, but emitters stamp `now` from their own
        clock domains (planner loop time vs orchestrator wall time), so a
        dedup-aggregated event can hold a fresher timestamp than entries
        updated after it — exporting ring order interleaved stale and fresh
        reasons in /snapshotz event tails. The sort is stable, so equal
        timestamps keep their update order."""
        return [ev.to_dict() for ev in
                sorted(self.events.values(), key=lambda e: e.last_ts)]

    def find(self, kind: str | None = None, obj: str | None = None,
             reason: str | None = None) -> list[Event]:
        return [
            ev for ev in self.events.values()
            if (kind is None or ev.kind == kind)
            and (obj is None or ev.obj == obj)
            and (reason is None or ev.reason == reason)
        ]
