"""gRPC transport for the out-of-process expander.

Reference counterpart: expander/grpcplugin — `service Expander { rpc
BestOptions }` (protos/expander.proto:25-28), dialed with TLS cert + URL
flags. Here: the same wire contract over the repo's generic-bytes JSON gRPC
convention (sidecar/server.py). `serve_expander` hosts a user policy
function; `grpc_expander_call` returns the injectable callable GrpcFilter
expects (expander/strategies.py), so
`build_expander("grpc,least-waste", grpc_call=grpc_expander_call(port))`
reproduces the reference's chain-with-grpc-head composition.
"""

from __future__ import annotations

import json
from dataclasses import asdict

from kubernetes_autoscaler_tpu.expander.strategies import Option

_SERVICE = "grpcplugin.Expander"


def _options_to_wire(options: list[Option]) -> list[dict]:
    return [asdict(o) for o in options]


def _options_from_wire(raw: list[dict]) -> list[Option]:
    return [Option(**o) for o in raw]


def serve_expander(best_options_fn, port: int = 0):
    """Host a policy `fn(list[Option]) -> list[Option]` as the gRPC service.

    Returns (server, bound_port)."""
    import grpc
    from concurrent.futures import ThreadPoolExecutor

    def handler(request: bytes, context):
        try:
            options = _options_from_wire(json.loads(request.decode() or "[]"))
            return json.dumps(_options_to_wire(best_options_fn(options))).encode()
        except Exception as e:
            return json.dumps({"error": str(e)}).encode()

    ident = lambda b: b
    server = grpc.server(ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
        _SERVICE,
        {"BestOptions": grpc.unary_unary_rpc_method_handler(
            handler, request_deserializer=ident, response_serializer=ident)},
    ),))
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    return server, bound


def grpc_expander_call(port: int | None = None, url: str = "",
                       cert_file: str = ""):
    """The injectable callable for GrpcFilter: dials BestOptions.

    `url` + optional `cert_file` mirror the reference's --grpc-expander-url /
    --grpc-expander-cert (expander/grpcplugin); `port` is the local-test
    shorthand."""
    import grpc

    target = url or f"127.0.0.1:{port}"
    if cert_file:
        with open(cert_file, "rb") as f:
            creds = grpc.ssl_channel_credentials(f.read())
        channel = grpc.secure_channel(target, creds)
    else:
        channel = grpc.insecure_channel(target)
    rpc = channel.unary_unary(
        f"/{_SERVICE}/BestOptions",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )

    def call(options: list[Option]) -> list[Option]:
        out = json.loads(rpc(json.dumps(_options_to_wire(options)).encode()))
        if isinstance(out, dict) and out.get("error"):
            raise RuntimeError(out["error"])
        return _options_from_wire(out)

    return call
