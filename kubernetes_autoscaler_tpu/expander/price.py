"""Price-based expander: cost-optimal node-group choice.

Reference counterpart: expander/price/price.go (priceBased.BestOptions) with
preferred.go (SimplePreferredNodeProvider + SimpleNodeUnfitness). The scoring
formula is reproduced exactly:

    priceSubScore = (total_node_price + stab) / (total_pod_price + stab)
    unfitness     = max(preferred_cpu/node_cpu, node_cpu/preferred_cpu)
    suppressed    = (unfitness - 1) * (1 - tanh((node_count - 1)/15)) + 1
    suppressed    = 1000 for GPU groups (gpuUnfitnessOverride)
    score         = suppressed * priceSubScore   (×2 if group doesn't exist)

lowest score wins; ties keep multiple options for the chain tail. Pod prices
use the helped-request totals the device scoring kernel already reduced
(ops/scoring.OptionScores.helped_req) — exact for linear pricing models, the
only kind the reference ships (gce/pricing.go).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.cloudprovider.pricing import PricingModel
from kubernetes_autoscaler_tpu.models.api import Node, Pod

_HOUR_S = 3600.0
_MIB = 1024.0 ** 2
_GIB = 1024.0 ** 3

# reference constants (price.go:52-76)
_STABILIZATION_POD = Pod(name="stabilize", requests={
    "cpu": 0.5, "memory": 500 * _MIB})
_NOT_EXIST_COEFFICIENT = 2.0
_GPU_UNFITNESS_OVERRIDE = 1000.0


def preferred_node_cpu_milli(cluster_size: int) -> int:
    """SimplePreferredNodeProvider (preferred.go:44-66): double the preferred
    node size every ~3x cluster growth."""
    if cluster_size <= 2:
        return 1000
    if cluster_size <= 6:
        return 2000
    if cluster_size <= 20:
        return 4000
    if cluster_size <= 60:
        return 8000
    if cluster_size <= 200:
        return 16000
    return 32000


def node_unfitness(preferred_cpu_milli: float, node_cpu_milli: float) -> float:
    """SimpleNodeUnfitness (preferred.go:86-92): cpu-ratio distance."""
    if node_cpu_milli <= 0 or preferred_cpu_milli <= 0:
        return _GPU_UNFITNESS_OVERRIDE
    return max(preferred_cpu_milli / node_cpu_milli,
               node_cpu_milli / preferred_cpu_milli)


@dataclass
class PriceBasedFilter:
    """Drop-in chain Filter (expander/strategies.py protocol). Needs loop
    context — cluster size changes every loop — which the orchestrator
    provides via set_loop_context before filtering."""

    pricing: PricingModel
    gpu_resource: str = "nvidia.com/gpu"
    cluster_size: int = 0
    horizon_s: float = _HOUR_S

    def set_loop_context(self, cluster_size: int) -> None:
        self.cluster_size = cluster_size

    def _pod_price_total(self, o) -> float:
        """Price of the helped-request totals as one synthetic pod (linear
        models make this exactly Σ pod_price)."""
        synthetic = Pod(name="helped-total", requests={
            "cpu": o.helped_cpu_milli / 1000.0,
            "memory": o.helped_mem_mib * _MIB,
            self.gpu_resource: o.helped_gpus,
        })
        return self.pricing.pod_price(synthetic, 0.0, self.horizon_s)

    def best_options(self, options):
        if not options:
            return []
        stab = self.pricing.pod_price(_STABILIZATION_POD, 0.0, self.horizon_s)
        preferred_cpu = float(preferred_node_cpu_milli(self.cluster_size))
        best: list = []
        best_score = 0.0
        for o in options:
            tmpl: Node | None = o.template
            if tmpl is None:
                continue
            node_price = self.pricing.node_price(tmpl, 0.0, self.horizon_s)
            total_node_price = node_price * o.node_count
            total_pod_price = self._pod_price_total(o)
            sub_score = (total_node_price + stab) / (total_pod_price + stab)
            cap = tmpl.alloc_or_cap()
            unfit = node_unfitness(preferred_cpu, float(cap.get("cpu", 0.0)) * 1000.0)
            suppressed = (unfit - 1.0) * (
                1.0 - math.tanh((o.node_count - 1) / 15.0)) + 1.0
            if float(cap.get(self.gpu_resource, 0.0)) > 0:
                suppressed = _GPU_UNFITNESS_OVERRIDE
            score = suppressed * sub_score
            if not o.exists:
                score *= _NOT_EXIST_COEFFICIENT
            if not best or score == best_score:
                best.append(o)
                best_score = score
            elif score < best_score:
                best = [o]
                best_score = score
        return best or list(options)
