"""Expander strategies: choosing among expansion options.

Reference counterpart: expander/expander.go:55 (Strategy.BestOption) with the
strategy zoo under expander/{random,mostpods,waste,leastnodes,price,priority,
grpcplugin}, composed as a filter chain (factory/chain.go: each Filter narrows
the option list; a final Random picks among survivors).

The numeric scores come precomputed from the device (ops/scoring.py — all
strategies' reductions are evaluated in the same kernel pass); this module is
the policy layer: chain composition, priority-config regexes, randomness, and
the out-of-process gRPC hook.
"""

from __future__ import annotations

import random as _random
import re
from dataclasses import dataclass, field
from typing import Protocol

import numpy as np

from kubernetes_autoscaler_tpu.ops.scoring import OptionScores, fetch_scores


@dataclass
class Option:
    """One expansion option (reference: expander.Option)."""

    group_index: int
    group_id: str
    node_count: int
    pod_count: int
    waste: float
    price: float
    # price-expander context (reference passes NodeInfos + option.Pods into
    # priceBased.BestOptions; here the template + helped-request reductions
    # ride along on the option)
    template: object | None = None     # models.api.Node of the group
    exists: bool = True
    helped_cpu_milli: float = 0.0
    helped_mem_mib: float = 0.0
    helped_gpus: float = 0.0


def options_from_scores(scores: OptionScores, group_ids: list[str],
                        groups: list | None = None,
                        gpu_slot: int | None = None,
                        phases=None) -> list[Option]:
    # one bulk device→host fetch (bool leaves bit-packed); the per-element
    # int()/float() reads below would otherwise each pay a tunnel round trip
    scores = fetch_scores(scores, phases=phases)
    valid = np.asarray(scores.valid)
    helped = (np.asarray(scores.helped_req)
              if scores.helped_req is not None else None)
    from kubernetes_autoscaler_tpu.models.resources import CPU, MEMORY

    out = []
    for i in range(valid.shape[0]):
        if not valid[i]:
            continue
        o = Option(
            group_index=i,
            group_id=group_ids[i] if i < len(group_ids) else str(i),
            node_count=int(scores.nodes[i]),
            pod_count=int(scores.pods[i]),
            waste=float(scores.waste[i]),
            price=float(scores.price[i]),
        )
        if groups is not None and i < len(groups):
            o.template = groups[i].template_node_info()
            o.exists = groups[i].exist()
        if helped is not None:
            o.helped_cpu_milli = float(helped[i, CPU])
            o.helped_mem_mib = float(helped[i, MEMORY])
            if gpu_slot is not None:
                o.helped_gpus = float(helped[i, gpu_slot])
        out.append(o)
    return out


class Filter(Protocol):
    """reference: expander.Filter — narrows options; chain composes filters."""

    def best_options(self, options: list[Option]) -> list[Option]: ...


class MostPodsFilter:
    def best_options(self, options: list[Option]) -> list[Option]:
        if not options:
            return []
        best = max(o.pod_count for o in options)
        return [o for o in options if o.pod_count == best]


class LeastWasteFilter:
    def best_options(self, options: list[Option]) -> list[Option]:
        if not options:
            return []
        best = min(o.waste for o in options)
        return [o for o in options if abs(o.waste - best) < 1e-9]


class LeastNodesFilter:
    def best_options(self, options: list[Option]) -> list[Option]:
        if not options:
            return []
        best = min(o.node_count for o in options)
        return [o for o in options if o.node_count == best]


class PriceFilter:
    """reference: expander/price — min total cost (pricing model × node count)."""

    def best_options(self, options: list[Option]) -> list[Option]:
        if not options:
            return []
        best = min(o.price for o in options)
        return [o for o in options if abs(o.price - best) < 1e-9]


@dataclass
class PriorityFilter:
    """reference: expander/priority — user config of priority→regex lists (the
    cluster-autoscaler-priority-expander ConfigMap); highest priority whose
    regex matches the group id wins."""

    priorities: dict[int, list[str]] = field(default_factory=dict)

    def best_options(self, options: list[Option]) -> list[Option]:
        for prio in sorted(self.priorities, reverse=True):
            pats = [re.compile(p) for p in self.priorities[prio]]
            hits = [o for o in options if any(p.search(o.group_id) for p in pats)]
            if hits:
                return hits
        return list(options)


@dataclass
class RandomFilter:
    """Terminal picker (reference: expander/random, always the chain tail)."""

    seed: int | None = None

    def best_options(self, options: list[Option]) -> list[Option]:
        if not options:
            return []
        rng = _random.Random(self.seed)
        return [rng.choice(options)]


@dataclass
class GrpcFilter:
    """reference: expander/grpcplugin — out-of-process `rpc BestOptions`
    (protos/expander.proto:25-28). Takes a callable so transports (grpcio
    channel, in-process plugin) are injectable; falls back to pass-through on
    error, mirroring the reference's fail-open logging."""

    call: "callable[[list[Option]], list[Option]] | None" = None

    def best_options(self, options: list[Option]) -> list[Option]:
        if self.call is None:
            return list(options)
        try:
            narrowed = self.call(options)
            return narrowed or list(options)
        except Exception:
            return list(options)


_REGISTRY = {
    "most-pods": MostPodsFilter,
    "least-waste": LeastWasteFilter,
    "least-nodes": LeastNodesFilter,
    "price": PriceFilter,
    "random": RandomFilter,
}


@dataclass
class ChainStrategy:
    """reference: expander/factory/chain.go — apply filters in order, then the
    terminal random picker over whatever survives."""

    filters: list
    tail: RandomFilter = field(default_factory=RandomFilter)

    def best_option(self, options: list[Option]) -> Option | None:
        remaining = list(options)
        for f in self.filters:
            remaining = f.best_options(remaining)
            if len(remaining) == 1:
                return remaining[0]
        picked = self.tail.best_options(remaining)
        return picked[0] if picked else None


def build_expander(spec: str, priorities: dict[int, list[str]] | None = None,
                   grpc_call=None, seed: int | None = 0,
                   pricing=None) -> ChainStrategy:
    """reference: factory/expander_factory.go:55-82 — comma-separated names
    compose into a chain. Deterministic seed by default (testability).

    `pricing` (a cloudprovider PricingModel) upgrades the 'price' name to the
    full reference-formula expander (expander/price.py); without a model the
    flat min-total-cost filter is used."""
    filters = []
    for name in [s for s in spec.split(",") if s]:
        if name == "priority":
            filters.append(PriorityFilter(priorities or {}))
        elif name == "grpc":
            filters.append(GrpcFilter(grpc_call))
        elif name == "price" and pricing is not None:
            from kubernetes_autoscaler_tpu.expander.price import PriceBasedFilter

            filters.append(PriceBasedFilter(pricing))
        elif name in _REGISTRY:
            f = _REGISTRY[name]
            filters.append(f(seed) if f is RandomFilter else f())
        else:
            raise ValueError(f"unknown expander {name!r}")
    return ChainStrategy(filters=filters, tail=RandomFilter(seed))
