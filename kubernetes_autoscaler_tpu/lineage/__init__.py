"""Decision lineage: the object-centric provenance join over every
cursor-stamped evidence store (docs/LINEAGE.md).

The stack emits five stores that all stamp the journal cursor — journal
records (replay/), Perfetto flight dumps (metrics/trace.py), audit
bundles (audit/shadow.py), restart records (core/supervisor.py) and
perfwatch triage bundles (perfwatch/triage.py) — plus the event ring
(events.py). This package joins them per (object kind, name) × loop:

  index.py   LineageIndex — incremental, bounded-memory index over a
             journal dir, stitching every artifact it can resolve back
             to a record digest; LineageRing — the live in-process
             variant StaticAutoscaler feeds (served on /whyz,
             /snapshotz and the sidecar Explain RPC).
  query.py   why / timeline / diff renderers (human text + JSON).
  __main__   `python -m kubernetes_autoscaler_tpu.lineage` CLI, with
             --follow tailing a live journal dir.

Everything here is a pure observer: host-side dict work, zero device
dispatches, overhead metered like the journal's.
"""

from kubernetes_autoscaler_tpu.lineage.index import (  # noqa: F401
    LineageIndex,
    LineageRing,
    entries_from_outputs,
)
