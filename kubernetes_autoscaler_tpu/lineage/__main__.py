"""`python -m kubernetes_autoscaler_tpu.lineage` — query a journal dir's
decision lineage offline, or tail a live one.

    lineage DIR why node/worker-3
    lineage DIR timeline --loops 10..20
    lineage DIR diff --loop 14
    lineage DIR runs                     # chain heads in a multi-run dir
    lineage DIR stats
    lineage DIR --run ab12 why ...       # pin a run by head-digest prefix
    lineage DIR --follow [--until-loop N] [--max-wait S] timeline

Exit codes: 0 on success (for `why`, the object must be found; for
--follow --until-loop, the loop must arrive), 1 on not-found/timeout,
2 on usage errors. JSON output with --json on every verb."""

from __future__ import annotations

import argparse
import json
import sys

from kubernetes_autoscaler_tpu.lineage.index import LineageIndex
from kubernetes_autoscaler_tpu.lineage import query as q


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m kubernetes_autoscaler_tpu.lineage",
        description="Query decision lineage over a journal directory.")
    p.add_argument("journal_dir", help="journal directory to index")
    p.add_argument("--run", default=None, metavar="HEAD",
                   help="select a run by chain-head digest prefix "
                        "(default: latest run)")
    p.add_argument("--artifact-dir", action="append", default=[],
                   metavar="DIR",
                   help="extra artifact dir(s) beyond those the journal "
                        "meta names")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--no-verify-seals", action="store_true",
                   help="skip record seal verification while scanning")
    p.add_argument("--follow", action="store_true",
                   help="keep tailing the dir after the first render")
    p.add_argument("--poll", type=float, default=0.25,
                   help="--follow poll interval seconds")
    p.add_argument("--max-wait", type=float, default=None,
                   help="--follow gives up after this many seconds")
    p.add_argument("--until-loop", type=int, default=None,
                   help="--follow exits 0 once this loop is indexed")
    sub = p.add_subparsers(dest="verb")
    w = sub.add_parser("why", help="an object's causal chain")
    w.add_argument("object", help="KIND/NAME (node/…, pod-group/…, "
                                  "nodegroup/…); bare names are pod-groups")
    t = sub.add_parser("timeline", help="per-loop decision summary")
    t.add_argument("--loops", default=None, metavar="A..B",
                   help="loop range (A.., ..B, A..B, or K)")
    d = sub.add_parser("diff", help="object-level delta across one loop")
    d.add_argument("--loop", type=int, required=True)
    sub.add_parser("runs", help="list chain heads found in the dir")
    sub.add_parser("stats", help="index stats + scan problems")
    return p


def _render(args, idx: LineageIndex) -> str:
    if args.verb == "why":
        kind, name = q.parse_object(args.object)
        return q.render_why(idx.why(kind, name), as_json=args.json)
    if args.verb == "timeline":
        lo = hi = None
        if args.loops:
            lo, hi = q.parse_loops(args.loops)
        return q.render_timeline(idx.timeline(lo, hi), as_json=args.json)
    if args.verb == "diff":
        return q.render_diff(idx.diff(args.loop), as_json=args.json)
    if args.verb == "runs":
        return q.render_runs(idx.runs, idx.run_head, as_json=args.json)
    payload = {"stats": idx.stats(), "problems": list(idx.problems),
               "run": idx.run_head,
               "artifactDirs": idx.artifact_dirs()}
    if args.json:
        return json.dumps(payload, indent=2, sort_keys=True)
    lines = [f"run {idx.run_head[:16] or '(none)'}"]
    for k, v in sorted(payload["stats"].items()):
        lines.append(f"  {k}: {v}")
    for pr in payload["problems"]:
        lines.append(f"  problem: {json.dumps(pr, sort_keys=True)}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.verb is None:
        args.verb = "stats"
    try:
        idx = LineageIndex(args.journal_dir, run=args.run,
                           artifact_dirs=args.artifact_dir,
                           verify_seals=not args.no_verify_seals)
    except OSError as exc:
        print(f"lineage: cannot open {args.journal_dir}: {exc}",
              file=sys.stderr)
        return 2
    print(_render(args, idx))
    if args.follow:
        def on_new(n, index):
            sys.stdout.write(f"\n--- +{n} records"
                             f" (loop {index.last_loop}) ---\n")
            sys.stdout.write(_render(args, index) + "\n")
            sys.stdout.flush()
        arrived = q.follow(idx, on_new, poll_s=args.poll,
                           max_wait_s=args.max_wait,
                           until_loop=args.until_loop)
        if args.until_loop is not None:
            return 0 if arrived else 1
        return 0
    if args.verb == "why":
        kind, name = q.parse_object(args.object)
        return 0 if idx.why(kind, name)["found"] else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
