"""LineageIndex / LineageRing: (object kind, name) × loop provenance.

The index is built over the SAME record stream `replay/harness.load_journal`
parses, but with an observer's failure posture: a journal being written to,
rotated under, or torn mid-line must degrade a query, never crash it — bad
lines and bad seals become `problems` entries, and only the selected run's
chain is ingested (a dir holding several runs' files is the normal case,
replay/harness.py:57). The scan is incremental: each refresh() reads only
bytes appended since the last one, which is what makes `--follow` a tail,
not a rescan.

Per record, the object-centric entries come from the record's `outputs`
surface (replay/journal.collect_outputs — the byte-digested decision
surface, so the index derives from exactly what the loop decided):

  pod-group/<exemplar>   refused (headline reason + per-constraint refused
                         counts, the summarize_reason_row vocabulary) and
                         resolved transitions
  nodegroup/<id>         chosen expansion options (won/lost, waste, price),
                         target increases, scale-up errors
  node/<name>            unremovable reasons, drain-failure detail,
                         unneeded verdicts, scale-down actuations

Cursor stitching: every artifact stamped with the journal cursor or a
trace id resolves back to a loop of the selected run —

  audit-NNNNNN-<trace>.json        journalCursor + traceId (+ divergence
                                   detail; persistent ⇒ the derived
                                   suspect→degraded transition)
  flight-<trace>.trace.json        the RunOnce root span's journal_digest
                                   arg (chrome-trace args, metrics/trace)
  perf-<metric>-<key>-<run>.json   perfwatch triage journalCursor
  restart records                  journalCursor + auditBundle pointer
  event-ring entries               attach_events() / the live ring joins
                                   EventSink.history() at query time

Memory is bounded on every axis: objects (LRU-evicted, counted), entries
per object (first entry kept, middle dropped, counted), loop rows and
problems (oldest dropped). The live LineageRing shares the store and adds
a lock + overhead meter: it is fed on the control-loop thread from dicts
the loop already computed — zero extra device dispatches by construction.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import OrderedDict

from kubernetes_autoscaler_tpu.replay import journal as rj

_CHAIN_FILE = re.compile(r"^journal-\d{6}\.jsonl$")
_AUDIT_FILE = re.compile(r"^audit-\d{6}-.*\.json$")
_FLIGHT_FILE = re.compile(r"^flight-.*\.trace\.json$")
_PERF_FILE = re.compile(r"^perf-.*\.json$")

_ROWS_HELP = "Lineage entries currently indexed (live ring)"
_BYTES_HELP = "Approximate lineage index bytes held (live ring)"
_LAG_HELP = "Loops between the journal cursor and the lineage head"
_QUERIES_HELP = "Lineage queries served, by surface"
_OVERHEAD_HELP = "Wall seconds spent feeding the lineage ring"

# event-ring kinds → lineage object kinds (events.py taxonomy)
EVENT_OBJECT_KIND = {"NoScaleUp": "pod-group", "NoScaleDown": "node"}


def entries_from_outputs(loop: int, outputs: dict
                         ) -> list[tuple[tuple[str, str], dict]]:
    """One journaled loop's `outputs` → [((kind, name), entry)]. Pure dict
    work over collect_outputs' shape — shared verbatim by the offline
    index and the live ring, so `why` answers match either way."""
    out: list[tuple[tuple[str, str], dict]] = []
    reasons = outputs.get("reasons") or {}
    for g in reasons.get("groups", ()):
        name = g.get("exemplarPod") or f"row-{g.get('group')}"
        out.append((("pod-group", name), {
            "loop": loop, "event": "refused",
            "reason": g.get("reason", ""),
            "constraints": dict(g.get("constraints") or {}),
            "pods": int(g.get("pods", 0)),
            "row": int(g.get("group", -1)),
        }))
    su = outputs.get("scaleUp")
    if su:
        best = su.get("best") or {}
        for gid, delta in (su.get("increases") or {}).items():
            e = {"loop": loop, "event": "scale-up", "delta": int(delta),
                 "won": gid == best.get("group")}
            if e["won"]:
                e["pods"] = best.get("pods")
                e["waste"] = best.get("waste")
                e["price"] = best.get("price")
            out.append((("nodegroup", gid), e))
        for gid, err in (su.get("errors") or {}).items():
            out.append((("nodegroup", gid),
                        {"loop": loop, "event": "scale-up-error",
                         "error": str(err)}))
    for n, r in (reasons.get("unremovable") or {}).items():
        out.append((("node", n),
                    {"loop": loop, "event": "unremovable", "reason": r}))
    for n, d in (reasons.get("drainFail") or {}).items():
        out.append((("node", n),
                    {"loop": loop, "event": "drain-fail", "detail": d}))
    drain = outputs.get("drain") or {}
    for n in drain.get("unneeded", ()):
        out.append((("node", n), {"loop": loop, "event": "unneeded"}))
    for n in drain.get("deleted", ()):
        out.append((("node", n),
                    {"loop": loop, "event": "scale-down-deleted"}))
    return out


def _loop_row(loop: int, digest: str, now: float, outputs: dict,
              annotations: dict | None) -> dict:
    verdict = outputs.get("verdict") or {}
    su = outputs.get("scaleUp") or {}
    best = su.get("best") or {}
    reasons = outputs.get("reasons") or {}
    drain = outputs.get("drain") or {}
    sched = 0
    try:
        sched = int(rj.decode_verdict_plane(verdict).sum())
    except (ValueError, TypeError):
        pass
    row = {
        "loop": loop, "digest": digest, "now": now,
        "pending": int(verdict.get("pending", 0)),
        "scheduled": sched,
        "refused": len(reasons.get("groups") or ()),
        "scaleUp": ({"won": best.get("group", ""),
                     "increases": dict(su.get("increases") or {})}
                    if su.get("scaledUp") else None),
        "unneeded": len(drain.get("unneeded") or ()),
        "deleted": len(drain.get("deleted") or ()),
        "artifacts": [],
    }
    if outputs.get("aborted"):
        row["aborted"] = outputs["aborted"]
    if annotations:
        row["annotations"] = dict(annotations)
    return row


class _LineageStore:
    """The bounded store both the offline index and the live ring share.
    Not thread-safe here — LineageRing adds the lock."""

    def __init__(self, max_objects: int = 4096, per_object: int = 64,
                 max_loops: int = 1024, max_problems: int = 64):
        self.max_objects = max(int(max_objects), 1)
        self.per_object = max(int(per_object), 2)
        self.max_loops = max(int(max_loops), 1)
        self.max_problems = max(int(max_problems), 1)
        # (kind, name) -> {"entries": [..], "dropped": n, "firstLoop",
        #                  "lastLoop"}; OrderedDict as LRU (recently
        #                  touched last)
        self.objects: OrderedDict[tuple[str, str], dict] = OrderedDict()
        self.loops: OrderedDict[int, dict] = OrderedDict()
        self.problems: list[dict] = []
        # derived backend transitions ({"loop","from","to","cause"})
        self.transitions: list[dict] = []
        self.evicted_objects = 0
        self.dropped_entries = 0
        self.records = 0
        self.entries = 0
        self.bytes = 0
        self.run_head = ""
        self.first_loop: int | None = None
        self.last_loop: int | None = None
        # refusal state for resolved-transition detection
        self._open_refusals: dict[tuple[str, str], int] = {}
        self._last_scale_up: dict | None = None

    # ---- ingestion -------------------------------------------------------

    def _append(self, key: tuple[str, str], entry: dict) -> None:
        obj = self.objects.get(key)
        if obj is None:
            obj = {"entries": [], "dropped": 0,
                   "firstLoop": entry["loop"], "lastLoop": entry["loop"]}
            self.objects[key] = obj
            while len(self.objects) > self.max_objects:
                self.objects.popitem(last=False)
                self.evicted_objects += 1
        obj["lastLoop"] = entry["loop"]
        obj["entries"].append(entry)
        self.entries += 1
        self.bytes += len(json.dumps(entry, separators=(",", ":")))
        if len(obj["entries"]) > self.per_object:
            # keep the FIRST entry (the chain's origin — "pending since
            # loop 12" needs it) and the newest tail; drop from the middle
            dropped = obj["entries"].pop(1)
            obj["dropped"] += 1
            self.dropped_entries += 1
            self.bytes -= len(json.dumps(dropped, separators=(",", ":")))
        self.objects.move_to_end(key)

    def _problem(self, kind: str, **detail) -> None:
        if len(self.problems) >= self.max_problems:
            self.problems.pop(0)
        self.problems.append({"kind": kind, **detail})

    def ingest_outputs(self, loop: int, digest: str, now: float,
                       outputs: dict, annotations: dict | None = None
                       ) -> None:
        """One loop's decision surface into the store (shared by record
        replay below and the live ring's observe())."""
        self.records += 1
        if self.first_loop is None:
            self.first_loop = loop
        self.last_loop = loop
        row = _loop_row(loop, digest, now, outputs, annotations)
        self.loops[loop] = row
        while len(self.loops) > self.max_loops:
            self.loops.popitem(last=False)
        pairs = entries_from_outputs(loop, outputs)
        for key, entry in pairs:
            self._append(key, entry)
        if row["scaleUp"] is not None:
            self._last_scale_up = {"loop": loop, **row["scaleUp"]}
        # resolved transitions: a pod-group refused last loop and absent
        # from this loop's refusals either scheduled or left the pending
        # set — if a scale-up landed since the refusal opened, name it as
        # the cause (the "refused → scale-up won → bound" causal chain)
        if outputs.get("ran"):
            refused_now = {k for k, e in pairs
                           if k[0] == "pod-group" and e["event"] == "refused"}
            for key, since in list(self._open_refusals.items()):
                if key in refused_now:
                    continue
                del self._open_refusals[key]
                entry = {"loop": loop, "event": "resolved",
                         "pendingSince": since}
                su = self._last_scale_up
                if su is not None and su["loop"] >= since:
                    entry["afterScaleUp"] = {"loop": su["loop"],
                                             "won": su["won"]}
                self._append(key, entry)
            for key in refused_now:
                self._open_refusals.setdefault(key, loop)

    def attach_artifact(self, loop: int, art: dict,
                        objects: list[tuple[str, str]] | None = None
                        ) -> None:
        """Stitch one cursor-resolved artifact onto its loop row (and any
        objects it names — e.g. the node in a drain divergence)."""
        row = self.loops.get(loop)
        if row is not None:
            row["artifacts"].append(art)
        for key in objects or ():
            self._append(key, {"loop": loop,
                               "event": f"artifact:{art['kind']}",
                               "path": art.get("path", ""),
                               **({"detail": art["detail"]}
                                  if art.get("detail") else {})})

    def attach_events(self, events: list[dict]) -> None:
        """Join event-ring entries (events.py Event.to_dict shape) onto
        their objects. Loop attribution uses the object's last known loop
        (events carry wall time, not loop indices)."""
        for ev in events:
            kind = EVENT_OBJECT_KIND.get(ev.get("kind", ""), "object")
            key = (kind, ev.get("object", ""))
            obj = self.objects.get(key)
            loop = obj["lastLoop"] if obj else (self.last_loop or 0)
            self._append(key, {
                "loop": loop, "event": "event",
                "eventKind": ev.get("kind", ""),
                "reason": ev.get("reason", ""),
                "count": int(ev.get("count", 1)),
                **({"message": ev["message"]} if ev.get("message") else {}),
            })

    def note_transition(self, loop: int, frm: str, to: str,
                        cause: str = "") -> None:
        self.transitions.append({"loop": loop, "from": frm, "to": to,
                                 **({"cause": cause} if cause else {})})
        if len(self.transitions) > self.max_problems:
            self.transitions.pop(0)

    # ---- queries ---------------------------------------------------------

    def why(self, kind: str, name: str) -> dict:
        key = (kind, name)
        obj = self.objects.get(key)
        loops_of = set()
        entries: list[dict] = []
        dropped = 0
        if obj is not None:
            entries = list(obj["entries"])
            dropped = obj["dropped"]
            loops_of = {e["loop"] for e in entries}
        arts = [dict(a, loop=lp) for lp, row in self.loops.items()
                if lp in loops_of for a in row["artifacts"]]
        return {
            "object": f"{kind}/{name}", "found": obj is not None,
            "run": self.run_head,
            "loops": ([obj["firstLoop"], obj["lastLoop"]]
                      if obj is not None else None),
            "entries": entries, "droppedEntries": dropped,
            "artifacts": arts,
            "transitions": list(self.transitions),
        }

    def timeline(self, lo: int | None = None, hi: int | None = None
                 ) -> list[dict]:
        return [row for lp, row in self.loops.items()
                if (lo is None or lp >= lo) and (hi is None or lp <= hi)]

    def diff(self, loop: int) -> dict:
        """Object-level delta between loop-1 and loop: verdicts that
        appeared, changed or resolved across the boundary."""
        cur = {key: e for key, obj in self.objects.items()
               for e in obj["entries"] if e["loop"] == loop}
        prev = {key: e for key, obj in self.objects.items()
                for e in obj["entries"] if e["loop"] == loop - 1}
        appeared = [{"object": "/".join(k), **cur[k]}
                    for k in sorted(set(cur) - set(prev))]
        gone = [{"object": "/".join(k), "was": prev[k]}
                for k in sorted(set(prev) - set(cur))]
        changed = [{"object": "/".join(k), "was": prev[k], "now": cur[k]}
                   for k in sorted(set(cur) & set(prev))
                   if (prev[k].get("event"), prev[k].get("reason")) !=
                      (cur[k].get("event"), cur[k].get("reason"))]
        row, prow = self.loops.get(loop), self.loops.get(loop - 1)
        return {
            "loop": loop, "run": self.run_head,
            "appeared": appeared, "resolved": gone, "changed": changed,
            "pendingDelta": ((row["pending"] - prow["pending"])
                             if row and prow else None),
            "scaleUp": row["scaleUp"] if row else None,
            "artifacts": row["artifacts"] if row else [],
        }

    def summary(self, limit: int = 32) -> dict:
        """Compact per-object digest (newest-touched first) for /whyz and
        /snapshotz payloads."""
        objs = []
        for key, obj in list(self.objects.items())[::-1][:limit]:
            last = obj["entries"][-1] if obj["entries"] else {}
            objs.append({"object": "/".join(key),
                         "loops": [obj["firstLoop"], obj["lastLoop"]],
                         "entries": len(obj["entries"]),
                         "dropped": obj["dropped"],
                         "last": last})
        return {"run": self.run_head,
                "loops": ([self.first_loop, self.last_loop]
                          if self.last_loop is not None else None),
                "objects": objs, "transitions": list(self.transitions),
                "stats": self.stats()}

    def stats(self) -> dict:
        return {"objects": len(self.objects), "entries": self.entries,
                "records": self.records, "bytes": self.bytes,
                "evictedObjects": self.evicted_objects,
                "droppedEntries": self.dropped_entries,
                "problems": len(self.problems)}


class LineageIndex(_LineageStore):
    """Incremental index over a journal DIRECTORY plus any artifact dirs.

    `run` selects which chain to index when the dir holds several runs:
    None follows the LATEST run (a new chain head resets the index — the
    follow-mode contract: tailing a dir across an autoscaler restart
    follows the new process); a digest prefix pins one run. refresh()
    reads only appended bytes; call it again to tail."""

    def __init__(self, journal_dir: str, run: str | None = None,
                 artifact_dirs: list[str] | None = None,
                 verify_seals: bool = True, **bounds):
        super().__init__(**bounds)
        self.journal_dir = journal_dir
        self.run_select = run or None
        self.verify_seals = verify_seals
        self._extra_dirs = list(artifact_dirs or ())
        self.meta: dict = {}
        self._last_meta: dict = {}
        self._positions: dict[str, int] = {}
        self._parsed_artifacts: dict[str, tuple[float, int]] = {}
        self._selected = run is None        # pre-chain: latest-run mode
        self._seen_any = False
        self._last_digest = ""
        self.runs: list[dict] = []          # every chain head seen
        self.newest_loop_seen: int | None = None
        self.refresh()

    # ---- journal scan ----------------------------------------------------

    def _chain_files(self) -> list[str]:
        if not os.path.isdir(self.journal_dir):
            return []
        return sorted(os.path.join(self.journal_dir, f)
                      for f in os.listdir(self.journal_dir)
                      if _CHAIN_FILE.match(f))

    def refresh(self) -> int:
        """Ingest appended records + newly resolvable artifacts; returns
        the number of NEW records ingested into the selected run."""
        new = 0
        files = self._chain_files()
        for i, fp in enumerate(files):
            new += self._scan_file(fp, is_last=(i == len(files) - 1))
        self._scan_artifacts()
        return new

    def _scan_file(self, fp: str, is_last: bool) -> int:
        try:
            size = os.path.getsize(fp)
        except OSError:
            return 0
        pos = self._positions.get(fp, 0)
        if size <= pos:
            return 0
        try:
            with open(fp, "rb") as f:
                f.seek(pos)
                chunk = f.read(size - pos)
        except OSError:
            return 0
        # complete lines only: a torn tail (writer mid-append, or a kill
        # mid-line) stays unconsumed — the next refresh() retries it, and
        # a tail that never completes on the FINAL file is the classic
        # torn-tail problem load_journal surfaces
        end = chunk.rfind(b"\n")
        if end < 0:
            if not is_last:
                self._problem("torn-tail", file=fp)
                self._positions[fp] = size
            return 0
        self._positions[fp] = pos + end + 1
        new = 0
        for raw in chunk[:end].split(b"\n"):
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                self._problem("bad-line", file=fp)
                continue
            if rec.get("kind") == "meta":
                self._last_meta = rec
                continue
            new += self._ingest_record(rec, fp)
        return new

    def _ingest_record(self, rec: dict, fp: str) -> int:
        if self.verify_seals:
            sealed = rec.get("digest", "")
            if rj.seal_record(dict(rec))["digest"] != sealed:
                self._problem("bad-seal", file=fp, loop=rec.get("loop"))
                return 0
        loop = rec.get("loop")
        if not isinstance(loop, int):
            self._problem("bad-line", file=fp)
            return 0
        self.newest_loop_seen = loop if self.newest_loop_seen is None \
            else max(self.newest_loop_seen, loop)
        boundary = (rec.get("kind") == "snapshot"
                    and rec.get("parent") == "") or not self._seen_any
        self._seen_any = True
        if boundary:
            head = rec.get("digest", "")
            self.runs.append({"head": head, "firstLoop": loop, "records": 0})
            if self.run_select is None:
                # latest-run mode: a fresh chain resets the store (tailing
                # across a restart follows the new process, never splices
                # two runs' cross-loop state into one story)
                if self.run_head:
                    self._reset_store()
                self._selected = True
                self.run_head = head
            else:
                self._selected = head.startswith(self.run_select)
                if self._selected:
                    self.run_head = head
        if self.runs:
            self.runs[-1]["records"] += 1
            self.runs[-1]["lastLoop"] = loop
        if not self._selected:
            return 0
        if self._last_digest and rec.get("parent") != self._last_digest \
                and not boundary:
            self._problem("chain-break", file=fp, loop=loop)
        self._last_digest = rec.get("digest", "")
        if not self.meta and self._last_meta:
            self.meta = self._last_meta
        self.ingest_outputs(
            loop, rec.get("digest", ""), float(rec.get("now", 0.0)),
            rec.get("outputs") or {},
            {k: rec[k] for k in ("fusedMode", "loopDeviceRoundTrips",
                                 "speculation") if k in rec})
        return 1

    def _reset_store(self) -> None:
        keep = (self.max_objects, self.per_object, self.max_loops,
                self.max_problems)
        runs, seen = self.runs, self._seen_any
        _LineageStore.__init__(self, *keep)
        self.runs, self._seen_any = runs, seen
        self.meta = {}           # the new run's meta line governs now
        self._last_digest = ""
        self._parsed_artifacts.clear()

    # ---- artifact stitching ---------------------------------------------

    def artifact_dirs(self) -> list[str]:
        """Journal dir + every evidence dir the recorded options name —
        the meta line carries the full AutoscalingOptions, so the index
        discovers the flight/audit/triage dirs without being told."""
        dirs = [self.journal_dir, *self._extra_dirs]
        opts = (self.meta or self._last_meta).get("options") or {}
        for k in ("flight_recorder_dir", "shadow_audit_dir",
                  "device_profile_dir"):
            if opts.get(k):
                dirs.append(opts[k])
        if opts.get("restart_state_path"):
            dirs.append(os.path.dirname(opts["restart_state_path"]) or ".")
        out, seen = [], set()
        for d in dirs:
            d = os.path.abspath(d)
            if d not in seen and os.path.isdir(d):
                seen.add(d)
                out.append(d)
        return out

    def _digest_loops(self) -> dict[str, int]:
        return {row["digest"]: lp for lp, row in self.loops.items()
                if row.get("digest")}

    def _scan_artifacts(self) -> None:
        by_digest = self._digest_loops()
        restart_path = ((self.meta or self._last_meta).get("options")
                        or {}).get("restart_state_path", "")
        for d in self.artifact_dirs():
            try:
                names = sorted(os.listdir(d))
            except OSError:
                continue
            for name in names:
                path = os.path.join(d, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                tag = (st.st_mtime, st.st_size)
                if self._parsed_artifacts.get(path) == tag:
                    continue
                self._parsed_artifacts[path] = tag
                try:
                    if _AUDIT_FILE.match(name):
                        self._stitch_audit(path, by_digest)
                    elif _FLIGHT_FILE.match(name):
                        self._stitch_flight(path, by_digest)
                    elif _PERF_FILE.match(name):
                        self._stitch_perf(path, by_digest)
                    elif path == os.path.abspath(restart_path) \
                            or name == os.path.basename(restart_path or "§"):
                        self._stitch_restart(path, by_digest)
                except (OSError, json.JSONDecodeError, KeyError,
                        TypeError, ValueError):
                    self._problem("bad-artifact", file=path)

    def _cursor_loop(self, cursor, by_digest: dict[str, int]) -> int | None:
        """A [loop, digest] cursor resolves only against the SELECTED
        run's records — another run's artifacts must not stitch here."""
        if not cursor or len(cursor) != 2:
            return None
        loop = by_digest.get(cursor[1])
        return loop if loop == cursor[0] else None

    def _stitch_audit(self, path: str, by_digest: dict[str, int]) -> None:
        with open(path) as f:
            b = json.load(f)
        if b.get("kind") != "shadow-audit-divergence":
            return
        loop = self._cursor_loop(b.get("journalCursor"), by_digest)
        if loop is None:
            return
        surfaces = sorted({d.get("surface", "") for d in
                           b.get("divergences") or ()})
        art = {"kind": "audit-bundle", "path": path,
               "traceId": b.get("traceId", ""),
               "persistent": bool(b.get("persistent")),
               "detail": ",".join(surfaces)}
        # objects the divergence names outright (drain divergences carry
        # the candidate node)
        named = sorted({("node", d["node"]) for d in
                        b.get("divergences") or () if d.get("node")})
        self.attach_artifact(loop, art, objects=named)
        # derived ladder transitions: a bundle IS the suspect transition's
        # evidence; a persistent bundle is the degrade's
        self.note_transition(loop, "healthy", "suspect",
                             cause="audit_divergence")
        if art["persistent"]:
            self.note_transition(loop, "suspect", "degraded",
                                 cause="audit_divergence")

    def _stitch_flight(self, path: str, by_digest: dict[str, int]) -> None:
        with open(path) as f:
            doc = json.load(f)
        other = doc.get("otherData") or {}
        reasons = other.get("dump_reasons") or other.get("retain_reasons") \
            or {}
        seen: set[tuple[int, str]] = set()
        for ev in doc.get("traceEvents") or ():
            args = ev.get("args") or {}
            dg, tid = args.get("journal_digest"), args.get("trace_id", "")
            if not dg:
                continue
            loop = by_digest.get(dg)
            if loop is None or (loop, tid) in seen:
                continue
            seen.add((loop, tid))
            self.attach_artifact(loop, {
                "kind": "flight-dump", "path": path, "traceId": tid,
                "detail": reasons.get(tid, "")})

    def _stitch_perf(self, path: str, by_digest: dict[str, int]) -> None:
        with open(path) as f:
            b = json.load(f)
        if b.get("kind") != "perf-regression":
            return
        loop = self._cursor_loop(b.get("journalCursor"), by_digest)
        art = {"kind": "perf-triage", "path": path,
               "traceId": b.get("traceId", ""),
               "detail": b.get("metric", "")}
        if loop is not None:
            self.attach_artifact(loop, art)
        elif self.last_loop is not None:
            # a triage bundle without a resolvable cursor still belongs to
            # the evidence story — pinned to the newest loop, flagged
            self.attach_artifact(self.last_loop,
                                 dict(art, cursorResolved=False))

    def _stitch_restart(self, path: str, by_digest: dict[str, int]) -> None:
        with open(path) as f:
            b = json.load(f)
        loop = self._cursor_loop(b.get("journalCursor"), by_digest)
        if loop is None:
            return
        self.attach_artifact(loop, {
            "kind": "restart-record", "path": path,
            "detail": (f"auditBundle={b['auditBundle']}"
                       if b.get("auditBundle") else "")})

    def stats(self) -> dict:
        lag = 0
        if self.newest_loop_seen is not None and self.last_loop is not None:
            lag = max(self.newest_loop_seen - self.last_loop, 0)
        return {**super().stats(), "lagLoops": lag,
                "runs": len(self.runs)}


class LineageRing(_LineageStore):
    """The live, in-process lineage surface StaticAutoscaler feeds once
    per RunOnce — bounded like the flight recorder, locked because /whyz
    and gRPC handlers read it off-thread, metered because it rides the
    control loop (overhead is CI-bounded like the shadow audit's). The
    feed is pure host dict work over outputs the loop already computed:
    it can add ZERO device dispatches by construction."""

    def __init__(self, objects: int = 512, per_object: int = 32,
                 loops: int = 128, registry=None, event_sink=None):
        super().__init__(max_objects=objects, per_object=per_object,
                         max_loops=loops)
        self.registry = registry
        self.event_sink = event_sink
        self._lock = threading.Lock()
        self._loop_seq = 0
        self._backend_state = "healthy"
        self.overhead_ns = 0

    def observe(self, *, loop: int | None, digest: str, now: float,
                outputs: dict, annotations: dict | None = None,
                audit: dict | None = None,
                backend_state: str | None = None) -> None:
        t0 = time.perf_counter_ns()
        with self._lock:
            k = loop if loop is not None else self._loop_seq
            self._loop_seq = k + 1
            if not self.run_head and digest:
                self.run_head = digest     # first cursor = this run's head
            self.ingest_outputs(k, digest, now, outputs, annotations)
            if audit is not None:
                self.attach_artifact(k, {
                    "kind": "audit-bundle",
                    "path": audit.get("bundlePath", ""),
                    "traceId": audit.get("traceId", ""),
                    "persistent": bool(audit.get("persistent")),
                    "detail": ",".join(audit.get("surfaces") or ())})
            if backend_state and backend_state != self._backend_state:
                self.note_transition(k, self._backend_state, backend_state)
                self._backend_state = backend_state
        dt = time.perf_counter_ns() - t0
        self.overhead_ns += dt
        if self.registry is not None:
            self.registry.counter("lineage_overhead_seconds_total",
                                  help=_OVERHEAD_HELP).inc(dt / 1e9)
            self.registry.gauge("lineage_index_rows",
                                help=_ROWS_HELP).set(float(self.entries))
            self.registry.gauge("lineage_index_bytes",
                                help=_BYTES_HELP).set(float(self.bytes))
            # the live ring observes the loop that just committed — a
            # nonzero lag means observes were skipped (aborted loops)
            lag = 0 if loop is None else max(loop - (self.last_loop or 0), 0)
            self.registry.gauge("lineage_index_lag_loops",
                                help=_LAG_HELP).set(float(lag))

    def _count_query(self, surface: str) -> None:
        if self.registry is not None:
            self.registry.counter("lineage_queries_total",
                                  help=_QUERIES_HELP).inc(surface=surface)

    def why(self, kind: str, name: str, surface: str = "api") -> dict:
        self._count_query(surface)
        with self._lock:
            out = super().why(kind, name)
        # join the event ring's bounded per-object history at QUERY time —
        # zero per-loop cost on the control loop
        sink = self.event_sink
        if sink is not None and hasattr(sink, "history"):
            ev_kind = {v: k for k, v in EVENT_OBJECT_KIND.items()}.get(kind)
            evs = sink.history(ev_kind, name) if ev_kind else []
            if evs:
                out["events"] = evs
        return out

    def snapshot_summary(self, limit: int = 32,
                         surface: str = "snapshotz") -> dict:
        self._count_query(surface)
        with self._lock:
            return self.summary(limit)

    def timeline(self, lo=None, hi=None, surface: str = "api"):
        self._count_query(surface)
        with self._lock:
            return super().timeline(lo, hi)

    def diff(self, loop: int, surface: str = "api") -> dict:
        self._count_query(surface)
        with self._lock:
            return super().diff(loop)
