"""Render lineage answers: `why`, `timeline`, `diff` as causal-chain text
or JSON, plus the follow-mode tail loop the CLI and CI smoke share.

The text renderer's job is the one-line story the ISSUE names:

    pending since loop 12: refused cpu×3 templates, taint×2
      -> loop 14 scale-up won option ng-2
      -> bound loop 15

so `why` coalesces an object's raw per-loop entries into SEGMENTS — runs
of identical verdicts become one line with a loop range and aggregated
constraint counts — and renders artifacts/transitions as indented
evidence pointers under the loop they stitch to."""

from __future__ import annotations

import json
import time


def parse_object(spec: str) -> tuple[str, str]:
    """'node/worker-3' → ('node', 'worker-3'). Kind-less specs default to
    pod-group — the kind `why` is asked about most."""
    if "/" in spec:
        kind, name = spec.split("/", 1)
        return kind, name
    return "pod-group", spec


def parse_loops(spec: str) -> tuple[int | None, int | None]:
    """'A..B' | 'A..' | '..B' | 'K' → (lo, hi)."""
    if ".." not in spec:
        k = int(spec)
        return k, k
    lo, hi = spec.split("..", 1)
    return (int(lo) if lo else None), (int(hi) if hi else None)


def coalesce_segments(entries: list[dict]) -> list[dict]:
    """Runs of same-verdict loops → one segment with a loop range. The
    refusal constraint counts aggregate (taint×2 across the run)."""
    segs: list[dict] = []
    for e in entries:
        ev = e.get("event", "")
        prev = segs[-1] if segs else None
        same = (prev is not None and prev["event"] == ev
                and prev.get("reason") == e.get("reason")
                and ev in ("refused", "unneeded", "unremovable")
                and e["loop"] <= prev["loops"][1] + 1)
        if same:
            prev["loops"][1] = e["loop"]
            prev["count"] += 1
            for c, n in (e.get("constraints") or {}).items():
                prev.setdefault("constraints", {})
                prev["constraints"][c] = prev["constraints"].get(c, 0) + n
        else:
            seg = {"event": ev, "loops": [e["loop"], e["loop"]],
                   "count": 1}
            for k in ("reason", "detail", "error", "delta", "won", "pods",
                      "waste", "price", "pendingSince", "afterScaleUp",
                      "path", "eventKind", "message"):
                if k in e:
                    seg[k] = e[k]
            if e.get("constraints"):
                seg["constraints"] = dict(e["constraints"])
            segs.append(seg)
    return segs


def _loops_label(lo: int, hi: int) -> str:
    return f"loop {lo}" if lo == hi else f"loops {lo}..{hi}"


def _constraints_label(counts: dict) -> str:
    return ", ".join(f"{c}×{n}" for c, n in
                     sorted(counts.items(), key=lambda kv: -kv[1]))


def _segment_line(seg: dict) -> str:
    lo, hi = seg["loops"]
    ev = seg["event"]
    where = _loops_label(lo, hi)
    if ev == "refused":
        line = f"pending since loop {lo}: refused {seg.get('reason', '')}"
        if seg.get("constraints"):
            line += f" [{_constraints_label(seg['constraints'])}]"
        if hi != lo:
            line += f" (through loop {hi})"
        return line
    if ev == "resolved":
        line = f"{where}: resolved"
        asu = seg.get("afterScaleUp")
        if asu:
            line += f" after loop {asu['loop']} scale-up won {asu['won']}"
        return line
    if ev == "scale-up":
        line = f"{where}: scale-up +{seg.get('delta', 0)}"
        if seg.get("won"):
            line += (f" — won option (pods={seg.get('pods')},"
                     f" waste={seg.get('waste')}, price={seg.get('price')})")
        return line
    if ev == "scale-up-error":
        return f"{where}: scale-up error {seg.get('error', '')}"
    if ev == "unremovable":
        return f"{where}: unremovable ({seg.get('reason', '')})"
    if ev == "drain-fail":
        return f"{where}: drain failed ({seg.get('detail', '')})"
    if ev == "unneeded":
        return f"{where}: unneeded (scale-down candidate)"
    if ev == "scale-down-deleted":
        return f"{where}: scaled down (deleted)"
    if ev == "event":
        line = (f"{where}: event {seg.get('eventKind', '')}"
                f"/{seg.get('reason', '')} ×{seg.get('count', 1)}")
        if seg.get("message"):
            line += f" — {seg['message']}"
        return line
    if ev.startswith("artifact:"):
        return f"{where}: {ev[len('artifact:'):]} {seg.get('path', '')}"
    return f"{where}: {ev}"


def render_why(ans: dict, as_json: bool = False) -> str:
    ans = dict(ans, segments=coalesce_segments(ans.get("entries") or []))
    if as_json:
        return json.dumps(ans, indent=2, sort_keys=True, default=str)
    lines = [f"why {ans['object']}" +
             (f"  (run {ans['run'][:12]})" if ans.get("run") else "")]
    if not ans.get("found"):
        lines.append("  no lineage recorded for this object")
        return "\n".join(lines)
    if ans.get("droppedEntries"):
        lines.append(f"  [{ans['droppedEntries']} middle entries dropped"
                     " by the per-object bound]")
    for seg in ans["segments"]:
        lines.append("  " + _segment_line(seg))
    arts = ans.get("artifacts") or []
    if arts:
        lines.append("  evidence:")
        for a in arts:
            extra = []
            if a.get("traceId"):
                extra.append(f"trace={a['traceId']}")
            if a.get("persistent"):
                extra.append("persistent")
            if a.get("detail"):
                extra.append(a["detail"])
            lines.append(f"    loop {a.get('loop', '?')}: {a['kind']}"
                         f" {a.get('path', '')}"
                         + (f"  ({', '.join(extra)})" if extra else ""))
    trans = ans.get("transitions") or []
    for t in trans:
        lines.append(f"  backend: loop {t['loop']} {t['from']} -> {t['to']}"
                     + (f" ({t['cause']})" if t.get("cause") else ""))
    for ev in ans.get("events") or []:
        lines.append(f"  event-ring: {ev.get('kind', '')}"
                     f"/{ev.get('reason', '')} ×{ev.get('count', 1)}")
    return "\n".join(lines)


def render_timeline(rows: list[dict], as_json: bool = False) -> str:
    if as_json:
        return json.dumps(rows, indent=2, sort_keys=True, default=str)
    lines = []
    for r in rows:
        bits = [f"loop {r['loop']:>4}", f"pending={r['pending']}",
                f"scheduled={r['scheduled']}"]
        if r.get("refused"):
            bits.append(f"refused={r['refused']}")
        su = r.get("scaleUp")
        if su:
            incs = ",".join(f"{g}+{d}" for g, d in
                            sorted(su.get("increases", {}).items()))
            bits.append(f"scale-up won {su.get('won', '')} [{incs}]")
        if r.get("unneeded"):
            bits.append(f"unneeded={r['unneeded']}")
        if r.get("deleted"):
            bits.append(f"deleted={r['deleted']}")
        if r.get("aborted"):
            bits.append(f"ABORTED({r['aborted']})")
        for a in r.get("artifacts") or ():
            bits.append(f"<{a['kind']}>")
        lines.append("  ".join(bits))
    return "\n".join(lines) if lines else "(no loops in range)"


def render_diff(d: dict, as_json: bool = False) -> str:
    if as_json:
        return json.dumps(d, indent=2, sort_keys=True, default=str)
    lines = [f"diff loop {d['loop'] - 1} -> {d['loop']}"]
    if d.get("pendingDelta") is not None:
        sign = "+" if d["pendingDelta"] >= 0 else ""
        lines.append(f"  pending {sign}{d['pendingDelta']}")
    su = d.get("scaleUp")
    if su:
        lines.append(f"  scale-up won {su.get('won', '')}")
    for e in d.get("appeared") or ():
        lines.append(f"  + {e['object']}: {e.get('event', '')}"
                     + (f" ({e['reason']})" if e.get("reason") else ""))
    for e in d.get("resolved") or ():
        was = e.get("was") or {}
        lines.append(f"  - {e['object']}: was {was.get('event', '')}"
                     + (f" ({was['reason']})" if was.get("reason") else ""))
    for e in d.get("changed") or ():
        was, now = e.get("was") or {}, e.get("now") or {}
        lines.append(f"  ~ {e['object']}: {was.get('event', '')}"
                     f" -> {now.get('event', '')}")
    for a in d.get("artifacts") or ():
        lines.append(f"  evidence: {a['kind']} {a.get('path', '')}")
    if len(lines) == 1:
        lines.append("  (no object-level changes)")
    return "\n".join(lines)


def render_runs(runs: list[dict], selected: str,
                as_json: bool = False) -> str:
    if as_json:
        return json.dumps({"runs": runs, "selected": selected},
                          indent=2, sort_keys=True)
    lines = []
    for r in runs:
        mark = "*" if selected and r["head"] == selected else " "
        lines.append(f"{mark} {r['head'][:16]}  loops"
                     f" {r.get('firstLoop', '?')}..{r.get('lastLoop', '?')}"
                     f"  records={r.get('records', 0)}")
    return "\n".join(lines) if lines else "(no runs found)"


def follow(index, on_new, poll_s: float = 0.25,
           max_wait_s: float | None = None,
           until_loop: int | None = None,
           sleep=time.sleep, clock=time.monotonic) -> bool:
    """Tail a LineageIndex: refresh() until `until_loop` lands in the
    selected run (True) or `max_wait_s` elapses (False; forever when
    None). on_new(count, index) fires after each refresh that ingested
    records — the CLI prints deltas, the CI smoke asserts pickup."""
    deadline = None if max_wait_s is None else clock() + max_wait_s
    while True:
        n = index.refresh()
        if n:
            on_new(n, index)
        if until_loop is not None and index.last_loop is not None \
                and index.last_loop >= until_loop:
            return True
        if deadline is not None and clock() >= deadline:
            return False
        sleep(poll_s)
