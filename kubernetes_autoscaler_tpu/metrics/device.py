"""Device-side observability: HBM residency ledger, compile census, profiler.

Every observability surface before this module was host-side — spans,
lifecycle stamps, journal records all measure what the HOST did around a
dispatch. This module instruments the DEVICE's shared resources
(docs/OBSERVABILITY.md "Device surfaces"):

  ResidencyLedger  who holds how much HBM. Every component that keeps
                   device arrays resident across loops — the WorldStore's
                   DevicePlaneStore, the sidecar tenants' export/device
                   tiers, the StackCache, the orchestrator's marshalled
                   group tensors — registers them under an owner tag (and a
                   tenant, where one exists). The ledger holds WEAK
                   references: a freed buffer falls out of the census by
                   itself, so tagged bytes track LIVE residency, not
                   registration history. `reconcile()` compares the tagged
                   census against `device.memory_stats()` totals
                   (`hbm_bytes_in_use` / `hbm_bytes_limit` /
                   `hbm_headroom_ratio`) — the untagged remainder is the
                   blind spot the LeakWatchdog watches. On backends without
                   memory_stats (CPU) the reconciliation degrades to a
                   host-RSS report with `source: host-fallback`, never null.

  LeakWatchdog     K consecutive loops of monotonic untagged-bytes growth
                   ⇒ a leak suspect: something holds device memory no owner
                   tagged. Fires once per streak (event + flight-recorder
                   dump at the call site), counted by
                   `hbm_leak_suspects_total`.

  CompileCensus    which shape signature compiled, for which tenant, at
                   what cost. Wraps the jit dispatch entry points: when a
                   call grows its function's jit cache, the census records a
                   variant entry keyed by (fn, shape signature) with
                   `cost_analysis()` / `memory_analysis()` figures (flops,
                   bytes accessed, temp HBM) and the tenant the compile was
                   charged to — so `sim_compiles_total` and
                   `recompiles_per_new_tenant` resolve to named variants on
                   Statusz and /metrics instead of bare counts.

  DeviceProfiler   breach-triggered on-device profiling. Armed by the
                   TailSampler retention / SLO-breach path (or the sidecar
                   `Profilez` RPC / an operator), the NEXT dispatch runs
                   under a bounded `jax.profiler.trace` session whose
                   capture directory is stamped (meta.json) with the
                   retained trace id and journal cursor — a slow trace in
                   the tail ring links to a real device timeline. Captures
                   are rate-limited and capped; disarmed costs one module
                   global load at the dispatch site (the PR 12 fault-guard
                   contract, ns/op-measured in CI).

Zero-overhead discipline: `LEDGER`, `PROFILER` and `CENSUS` are module
globals defaulting to None. Hot-path call sites guard with
`if device.LEDGER is not None:` — one global load when the facility is off.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref

HBM_IN_USE_HELP = ("Device memory in use (device.memory_stats().bytes_in_use"
                   "; 0 with source=host-fallback)")
HBM_LIMIT_HELP = ("Device memory limit (device.memory_stats().bytes_limit, "
                  "or the configured --hbm-limit-bytes override)")
HBM_HEADROOM_HELP = ("(limit - in_use) / limit — the admission headroom the "
                     "--hbm-budget-frac gate protects")
RESIDENT_HELP = ("Live device bytes tagged in the residency ledger, by "
                 "owner component and tenant (weakref census: freed buffers "
                 "drop out by themselves)")
TENANT_HBM_HELP = ("Live device bytes attributed to one tenant across every "
                   "owner tag — the projected-residency base the HBM budget "
                   "admission gate charges against")
LEAK_HELP = ("Leak-watchdog firings: K consecutive loops of monotonic "
             "untagged device-byte growth (memory no ledger owner tagged)")
OOM_DUMP_HELP = ("Device-memory pprof snapshots persisted on a "
                 "RESOURCE_EXHAUSTED/OOM dispatch failure")
CENSUS_HELP = ("Compiles recorded by the compile census, by jit entry "
               "point, shape signature and the tenant charged")
PROFILER_CAPTURES_HELP = ("Bounded jax.profiler.trace sessions captured, "
                          "by the reason that armed them")

# module globals (the PR 12 fault-plane pattern): None = facility off, and
# every hot-path site costs exactly one global load + identity test
LEDGER: "ResidencyLedger | None" = None
PROFILER: "DeviceProfiler | None" = None


def memory_stats() -> dict | None:
    """`memory_stats()` of the first addressable device, or None when the
    backend does not report (CPU, some plugins). Never raises."""
    try:
        import jax

        return jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — no backend / early init / plugin gap
        return None


def host_rss_bytes() -> int:
    """CURRENT resident set size of this process — the host-fallback total
    when the device reports no memory_stats. /proc gives the live figure;
    the getrusage fallback is the lifetime PEAK (ru_maxrss — and already
    bytes on macOS), which can only overstate, never hide, growth."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001 — non-linux
        try:
            import resource
            import sys

            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            return int(rss) if sys.platform == "darwin" else int(rss) * 1024
        except Exception:  # noqa: BLE001 — non-unix
            return 0


def _device_leaves(arrays) -> list:
    """Flatten `arrays` (a jax array, dict, list/tuple, or tensor-struct
    pytree) into its DEVICE-array leaves. Host numpy mirrors are ignored —
    this is an HBM ledger, and counting host bytes would corrupt the
    tagged-vs-total reconciliation."""
    import jax

    leaves = jax.tree_util.tree_leaves(arrays)
    return [x for x in leaves if isinstance(x, jax.Array)]


def device_bytes(arrays) -> int:
    """Total bytes of the DEVICE-array leaves of a pytree (the projection
    the serial-tier budget screen prices an assembled world with)."""
    return sum(int(a.nbytes) for a in _device_leaves(arrays))


class ResidencyLedger:
    """Owner/tenant-tagged census of live device arrays.

    Entries are keyed by (owner, tenant, key); each holds weak references
    to the registered device arrays plus their byte sizes. Re-tracking a
    key REPLACES the entry (the upload path's natural idiom: a refreshed
    plane re-registers under the same key). A dead weakref contributes 0 —
    `census()` sweeps entries whose every array died."""

    def __init__(self):
        self._entries: dict[tuple, list] = {}   # key -> [(ref, nbytes), ...]
        self._lock = threading.Lock()
        # last-published gauge label sets PER REGISTRY, for stale-series
        # zeroing (the reason-plane convention): the one process ledger
        # reconciles into BOTH the control loop's registry and the sidecar's
        # — each must see its own vanished series zeroed, so the bookkeeping
        # cannot be shared (weak keys: a dropped registry takes its set)
        self._published = weakref.WeakKeyDictionary()

    def track(self, owner: str, key: str, arrays, tenant: str = "") -> int:
        """Register `arrays` (replacing any prior registration of this
        (owner, tenant, key)). Returns the live bytes registered."""
        refs = []
        total = 0
        for a in _device_leaves(arrays):
            try:
                refs.append((weakref.ref(a), int(a.nbytes)))
                total += int(a.nbytes)
            except TypeError:  # pragma: no cover — unweakrefable leaf
                continue
        with self._lock:
            if refs:
                self._entries[(owner, tenant, key)] = refs
            else:
                self._entries.pop((owner, tenant, key), None)
        return total

    def release(self, owner: str | None = None, tenant: str | None = None,
                key: str | None = None) -> int:
        """Drop every entry matching the given tags (None = wildcard);
        returns how many entries were dropped. Belt-and-braces — weakref
        expiry already removes freed arrays from the census."""
        with self._lock:
            victims = [k for k in self._entries
                       if (owner is None or k[0] == owner)
                       and (tenant is None or k[1] == tenant)
                       and (key is None or k[2] == key)]
            for k in victims:
                del self._entries[k]
            return len(victims)

    def census(self) -> dict:
        """Live tagged bytes: {"by_owner_tenant": {(owner, tenant): bytes},
        "tagged_bytes": total, "entries": live entry count}. Sweeps entries
        whose arrays all died."""
        by: dict[tuple, int] = {}
        with self._lock:
            dead = []
            for (owner, tenant, _key), refs in self._entries.items():
                live = sum(nb for ref, nb in refs if ref() is not None)
                if live == 0 and all(ref() is None for ref, _ in refs):
                    dead.append((owner, tenant, _key))
                    continue
                by[(owner, tenant)] = by.get((owner, tenant), 0) + live
            for k in dead:
                del self._entries[k]
            n = len(self._entries)
        return {"by_owner_tenant": by,
                "tagged_bytes": sum(by.values()),
                "entries": n}

    def tenant_bytes(self, tenant: str) -> int:
        return sum(v for (_o, t), v in
                   self.census()["by_owner_tenant"].items() if t == tenant)

    def tagged_bytes(self) -> int:
        return self.census()["tagged_bytes"]

    # ---- reconciliation + publication ----

    def reconcile(self, registry=None, hbm_limit_bytes: int = 0) -> dict:
        """Tagged census vs the device's own accounting, published as
        gauges when a registry is attached. On backends with memory_stats
        the `untagged_bytes` remainder is real unattributed HBM (allocator
        overhead, XLA temp buffers, anything nobody tagged) — the quantity
        the LeakWatchdog watches; on CPU the report degrades to host RSS
        with `source: host-fallback` and untagged tracks RSS growth
        instead."""
        c = self.census()
        ms = memory_stats()
        if ms and ms.get("bytes_in_use") is not None:
            in_use = int(ms["bytes_in_use"])
            limit = int(hbm_limit_bytes or ms.get("bytes_limit") or 0)
            source = "device"
        else:
            in_use = host_rss_bytes()
            limit = int(hbm_limit_bytes or 0)
            source = "host-fallback"
        untagged = max(in_use - c["tagged_bytes"], 0)
        headroom = ((limit - in_use) / limit) if limit > 0 else None
        out = {
            "source": source,
            "bytes_in_use": in_use,
            "bytes_limit": limit,
            "tagged_bytes": c["tagged_bytes"],
            "untagged_bytes": untagged,
            "headroom_ratio": headroom,
            "entries": c["entries"],
            "by_owner_tenant": {
                f"{o}/{t or 'default'}": v
                for (o, t), v in sorted(c["by_owner_tenant"].items())},
            "tenants": {},
        }
        tenants: dict[str, int] = {}
        for (_o, t), v in c["by_owner_tenant"].items():
            tenants[t] = tenants.get(t, 0) + v
        out["tenants"] = {t or "default": v
                          for t, v in sorted(tenants.items())}
        if registry is not None:
            self._publish(registry, out, c["by_owner_tenant"], tenants)
        return out

    def _publish(self, registry, rec: dict, by_ot: dict,
                 tenants: dict) -> None:
        registry.gauge("hbm_bytes_in_use", help=HBM_IN_USE_HELP).set(
            float(rec["bytes_in_use"] if rec["source"] == "device" else 0.0))
        registry.gauge("hbm_bytes_limit", help=HBM_LIMIT_HELP).set(
            float(rec["bytes_limit"]))
        if rec["headroom_ratio"] is not None:
            registry.gauge("hbm_headroom_ratio", help=HBM_HEADROOM_HELP).set(
                float(rec["headroom_ratio"]))
        with self._lock:
            prev_ot, prev_t = self._published.get(registry, (set(), set()))
        resident = registry.gauge("resident_bytes", help=RESIDENT_HELP)
        live = {(o, t or "default") for (o, t) in by_ot}
        for owner, tenant in prev_ot - live:
            resident.set(0.0, owner=owner, tenant=tenant)
        for (o, t), v in by_ot.items():
            resident.set(float(v), owner=o, tenant=t or "default")
        per_tenant = registry.gauge("tenant_hbm_bytes", help=TENANT_HBM_HELP)
        live_t = {t or "default" for t in tenants}
        for tenant in prev_t - live_t:
            per_tenant.set(0.0, tenant=tenant)
        for t, v in tenants.items():
            per_tenant.set(float(v), tenant=t or "default")
        with self._lock:
            self._published[registry] = (live, live_t)


def enable_ledger() -> ResidencyLedger:
    """Install (or return) the process ledger. Idempotent — the sidecar
    service and the control loop share one census; their registries differ,
    but publication is per-reconcile-call, so both surfaces stay honest."""
    global LEDGER
    if LEDGER is None:
        LEDGER = ResidencyLedger()
    return LEDGER


def disable_ledger() -> None:
    """Tests + the disabled-overhead microbench."""
    global LEDGER
    LEDGER = None


class LeakWatchdog:
    """K consecutive observations of monotonic untagged growth ⇒ suspect.

    `observe(untagged_bytes)` is called once per loop with the
    reconciliation's untagged remainder; growth below `min_growth_bytes`
    per step is jitter (allocator rounding, host RSS noise on the fallback
    path) and RESETS the streak. On firing, returns a report dict (the
    caller emits the event + flight-recorder dump — this module has no
    event sink of its own) and the streak restarts, so a sustained leak
    fires once per K-loop window, not once per loop."""

    def __init__(self, k: int = 5, min_growth_bytes: int = 1 << 20,
                 registry=None):
        self.k = max(int(k), 2)
        self.min_growth_bytes = int(min_growth_bytes)
        self.registry = registry
        self._last: int | None = None
        self._streak = 0
        self._streak_base = 0
        self.fired = 0

    def observe(self, untagged_bytes: int) -> dict | None:
        untagged_bytes = int(untagged_bytes)
        prev = self._last
        self._last = untagged_bytes
        if prev is None or untagged_bytes < prev + self.min_growth_bytes:
            self._streak = 0
            return None
        if self._streak == 0:
            self._streak_base = prev
        self._streak += 1
        if self._streak < self.k:
            return None
        report = {
            "loops": self._streak,
            "grew_bytes": untagged_bytes - self._streak_base,
            "untagged_bytes": untagged_bytes,
        }
        self._streak = 0
        self.fired += 1
        if self.registry is not None:
            self.registry.counter("hbm_leak_suspects_total",
                                  help=LEAK_HELP).inc()
        return report


# ---- compile census -------------------------------------------------------

def shape_signature(args, kwargs=None) -> str:
    """Short, stable signature of a call's tensor shapes: the variant key.
    Array leaves contribute dtype[dims]; non-array leaves (static config)
    contribute their repr — two calls with equal signatures hit the same
    jit cache entry (the signature is a superset of jit's own key content
    for our entry points, which never close over arrays)."""
    import hashlib

    import jax

    parts = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs or {})):
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            parts.append(f"{getattr(leaf, 'dtype', '?')}"
                         f"[{','.join(map(str, shape))}]")
        else:
            parts.append(repr(leaf))
    spec = "|".join(parts)
    lead = ""
    for leaf in jax.tree_util.tree_leaves((args, kwargs or {})):
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            lead = "x".join(map(str, shape))
            break
    return (f"{lead or 'scalar'}/"
            f"{hashlib.sha1(spec.encode()).hexdigest()[:8]}")


def _analysis(fn, args, kwargs, mode: str) -> dict:
    """Best-effort cost/memory analysis of the variant that just compiled.
    `fn.lower()` re-traces (cheap next to the compile that just happened);
    mode "full" additionally AOT-compiles for `memory_analysis()` — on TPU
    the XLA compilation cache makes that a re-hit, on the CPU floor it is
    milliseconds. Any failure degrades to partial figures, never an
    exception on the dispatch path."""
    out: dict = {}
    if mode == "off":
        return out
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
    except Exception:  # noqa: BLE001 — analysis must never sink a dispatch
        return out
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            if "flops" in ca:
                out["flops"] = float(ca["flops"])
            if "bytes accessed" in ca:
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:  # noqa: BLE001
        pass
    if mode != "full":
        return out
    try:
        ma = lowered.compile().memory_analysis()
        if ma is not None:
            out["temp_bytes"] = int(
                getattr(ma, "temp_size_in_bytes", 0) or 0)
            out["argument_bytes"] = int(
                getattr(ma, "argument_size_in_bytes", 0) or 0)
            out["output_bytes"] = int(
                getattr(ma, "output_size_in_bytes", 0) or 0)
            out["code_bytes"] = int(
                getattr(ma, "generated_code_size_in_bytes", 0) or 0)
    except Exception:  # noqa: BLE001
        pass
    return out


class CompileCensus:
    """Per-executable variant table for the jit dispatch entry points.

    `dispatch(label, fn, args, kwargs, tenant=)` runs one call and, when
    the call GREW `fn`'s jit cache (a real compile on the dispatch path,
    not an AOT probe), records a variant entry keyed by (label, shape
    signature): compile count, wall clock, the tenant charged (the fresh
    tenant of a `recompiles_per_new_tenant` window, "" for steady/local
    work) and the cost/memory analysis. Analysis depth rides
    KA_DEVICE_CENSUS = full (default) | cost | off."""

    def __init__(self, registry=None, mode: str | None = None,
                 sync_analysis: bool = True):
        self.registry = registry
        self.mode = (mode or os.environ.get("KA_DEVICE_CENSUS", "full"))
        # sync_analysis=False (the serving default) runs the lower/compile
        # analysis on a daemon thread: mode "full" AOT-compiles the variant
        # for memory figures, and doing that synchronously would roughly
        # DOUBLE every compile stall as seen by the request that triggered
        # it. The variant row (fn/sig/tenant/count) is recorded immediately
        # either way; cost figures merge in when the analysis lands.
        self.sync_analysis = sync_analysis
        self._table: dict[tuple, dict] = {}
        self._lock = threading.Lock()

    def dispatch(self, label: str, fn, args=(), kwargs=None, tenant: str = ""):
        """The census-wrapped dispatch: returns fn(*args, **kwargs);
        records a variant when the call compiled."""
        kwargs = kwargs or {}
        try:
            c0 = fn._cache_size()
        except Exception:  # noqa: BLE001 — not a jit function: no census
            return fn(*args, **kwargs)
        out = fn(*args, **kwargs)
        if fn._cache_size() > c0:
            self.record(label, fn, args, kwargs, tenant=tenant)
        return out

    def record(self, label: str, fn, args=(), kwargs=None,
               tenant: str = "") -> dict:
        sig = shape_signature(args, kwargs)
        entry_key = (label, sig)
        with self._lock:
            e = self._table.get(entry_key)
            first = e is None
            if first:
                e = self._table[entry_key] = {
                    "fn": label, "shape_sig": sig, "compiles": 0,
                    "tenants": [],
                }
            e["compiles"] += 1
            if tenant and tenant not in e["tenants"]:
                e["tenants"].append(tenant)
            rec = dict(e)
        if self.registry is not None:
            self.registry.counter(
                "compile_census_total", help=CENSUS_HELP).inc(
                fn=label, shape_sig=sig, tenant=tenant or "default")
        if first and self.mode != "off":
            if self.sync_analysis:
                rec.update(self._analyze(entry_key, fn, args, kwargs))
            else:
                threading.Thread(
                    target=self._analyze,
                    args=(entry_key, fn, args, kwargs),
                    name="katpu-compile-census", daemon=True).start()
        return rec

    def _analyze(self, entry_key: tuple, fn, args, kwargs) -> dict:
        label, sig = entry_key
        analysis = _analysis(fn, args, kwargs or {}, self.mode)
        with self._lock:
            e = self._table.get(entry_key)
            if e is not None:
                e.update(analysis)
        if self.registry is not None:
            if "flops" in analysis:
                self.registry.gauge(
                    "compile_census_flops",
                    help="cost_analysis flops of the named variant",
                ).set(analysis["flops"], fn=label, shape_sig=sig)
            if "bytes_accessed" in analysis:
                self.registry.gauge(
                    "compile_census_bytes_accessed",
                    help="cost_analysis bytes accessed of the named variant",
                ).set(analysis["bytes_accessed"], fn=label, shape_sig=sig)
            if "temp_bytes" in analysis:
                self.registry.gauge(
                    "compile_census_temp_bytes",
                    help="memory_analysis temp (scratch HBM) bytes of the "
                         "named variant",
                ).set(analysis["temp_bytes"], fn=label, shape_sig=sig)
        return analysis

    def variants(self) -> list[dict]:
        with self._lock:
            return [dict(v) for _k, v in sorted(self._table.items())]

    def zero_tenant(self, tenant: str) -> None:
        """drop_tenant sweep: the census table keeps its variants (the
        compiled programs outlive the tenant) but the tenant's charge
        attribution is removed."""
        with self._lock:
            for e in self._table.values():
                if tenant in e["tenants"]:
                    e["tenants"].remove(tenant)


# ---- breach-triggered profiler -------------------------------------------

class DeviceProfiler:
    """Bounded, rate-limited on-device profiler sessions.

    arm(reason, ...) marks the NEXT guarded dispatch for capture; the call
    site (server._timed_sim, StaticAutoscaler.run_once) wraps that one
    dispatch in `jax.profiler.trace(capture_dir)` and writes meta.json
    stamping the capture with the reason, the retained trace id and the
    journal cursor — the link from tail-ring evidence to a device timeline.
    Rate limiting: one armed session at a time, `min_interval_s` between
    captures, `max_captures` per process lifetime. Disarmed cost at the
    dispatch site is the module-global guard (`device.PROFILER is None` or
    `.armed` False: two attribute loads)."""

    def __init__(self, dir_path: str, min_interval_s: float = 30.0,
                 max_captures: int = 8, registry=None,
                 clock=time.monotonic):
        self.dir = dir_path
        self.min_interval_s = float(min_interval_s)
        self.max_captures = int(max_captures)
        self.registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._armed: dict | None = None
        self._last_capture = -float("inf")
        self.captures: list[dict] = []
        self.throttled = 0

    @property
    def armed(self) -> bool:
        return self._armed is not None

    def arm(self, reason: str, trace_id: str = "",
            journal_cursor=None) -> bool:
        """True when armed; False when throttled (already armed, inside the
        rate-limit window, or the capture cap is spent)."""
        with self._lock:
            if (self._armed is not None
                    or len(self.captures) >= self.max_captures
                    or self._clock() - self._last_capture
                    < self.min_interval_s):
                self.throttled += 1
                return False
            self._armed = {
                "reason": reason,
                "trace_id": trace_id,
                "journal_cursor": (list(journal_cursor)
                                   if journal_cursor else None),
                "armed_at": time.time(),
            }
            return True

    def capture(self, fn):
        """Run `fn` under the armed profiler session (call only when
        `.armed`); returns (result, capture_path|None). The session is
        consumed whether the capture succeeded or not — a broken profiler
        must not re-fire on every subsequent dispatch."""
        with self._lock:
            meta = self._armed
            self._armed = None
            if meta is not None:
                seq = len(self.captures)
                self._last_capture = self._clock()
        if meta is None:      # lost the race with another dispatcher —
            return fn(), None  # run OUTSIDE the lock (fn can be seconds)
        tag = meta["trace_id"] or "manual"
        path = os.path.join(self.dir, f"capture-{seq:03d}-{tag}")
        # the profiler context is entered/exited under its own guards so a
        # broken profiler degrades to a plain call — but an exception from
        # fn() ITSELF always propagates and fn never runs twice (a captured
        # RunOnce that raises must not re-actuate)
        ctx = None
        try:
            import jax

            os.makedirs(path, exist_ok=True)
            ctx = jax.profiler.trace(path)
            ctx.__enter__()
        except Exception:  # noqa: BLE001 — profiling must not sink dispatch
            ctx, path = None, None
        try:
            out = fn()
        finally:
            if ctx is not None:
                try:
                    ctx.__exit__(None, None, None)
                except Exception:  # noqa: BLE001
                    path = None
        if path is not None:
            meta = dict(meta, path=path, seq=seq)
            try:
                with open(os.path.join(path, "meta.json"), "w") as f:
                    json.dump(meta, f, indent=1, sort_keys=True)
            except OSError:
                pass
            with self._lock:
                self.captures.append(meta)
            if self.registry is not None:
                self.registry.counter(
                    "device_profile_captures_total",
                    help=PROFILER_CAPTURES_HELP).inc(reason=meta["reason"])
        return out, path

    def stats(self) -> dict:
        with self._lock:
            return {
                "dir": self.dir,
                "armed": self._armed is not None,
                "armed_reason": (self._armed or {}).get("reason"),
                "captures": len(self.captures),
                "max_captures": self.max_captures,
                "min_interval_s": self.min_interval_s,
                "throttled": self.throttled,
                "last": dict(self.captures[-1]) if self.captures else None,
            }


def install_profiler(dir_path: str, min_interval_s: float = 30.0,
                     max_captures: int = 8, registry=None) -> DeviceProfiler:
    """Install the process profiler (idempotent per directory: re-installing
    with the same dir returns the existing session so sidecar + control
    loop in one process share the rate limiter)."""
    global PROFILER
    if PROFILER is None or PROFILER.dir != dir_path:
        PROFILER = DeviceProfiler(dir_path, min_interval_s=min_interval_s,
                                  max_captures=max_captures,
                                  registry=registry)
    elif registry is not None and PROFILER.registry is None:
        PROFILER.registry = registry
    return PROFILER


def uninstall_profiler() -> None:
    global PROFILER
    PROFILER = None


# ---- OOM evidence ---------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM when allocating")


def is_oom(exc: BaseException) -> bool:
    """Heuristic device-OOM classifier: XLA surfaces allocation failure as
    XlaRuntimeError with RESOURCE_EXHAUSTED / out-of-memory text (there is
    no typed exception across backends)."""
    text = f"{type(exc).__name__}: {exc}"
    return any(m in text for m in _OOM_MARKERS)


def dump_memory_profile(dir_path: str, tag: str = "oom",
                        registry=None) -> str | None:
    """Persist a device-memory pprof snapshot
    (jax.profiler.save_device_memory_profile) next to the flight-recorder
    evidence; returns the path, or None when the profiler/disk failed —
    evidence collection must never sink the failure path it documents."""
    try:
        import jax

        os.makedirs(dir_path, exist_ok=True)
        path = os.path.join(
            dir_path, f"hbm-{tag}-{int(time.time() * 1000)}.pprof")
        jax.profiler.save_device_memory_profile(path)
    except Exception:  # noqa: BLE001
        return None
    if registry is not None:
        registry.counter("hbm_oom_dumps_total", help=OOM_DUMP_HELP).inc()
    return path
