"""Metrics registry: counters/gauges/histograms + prometheus text exposition.

Reference counterpart: metrics/metrics.go — ~45 series under
`cluster_autoscaler_*`, notably the per-phase `function_duration_seconds`
histogram (metrics.go:324) updated around every RunOnce stage, plus the
liveness HealthCheck keyed on loop activity (liveness.go).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)


@dataclass
class Counter:
    name: str
    help: str = ""
    _values: dict[tuple, float] = field(default_factory=dict)
    # updates are read-modify-write and metrics are written from multiple
    # threads (the sidecar's gRPC pool, the parallel scale-up executor) —
    # an unlocked inc under contention silently loses increments
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def total(self) -> float:
        """Sum over EVERY label combination — e.g. the whole-process h2d
        byte total across tenant-labelled series (value() reads exactly one
        series and misses the labelled ones)."""
        with self._lock:
            return sum(self._values.values())

    def zero_matching(self, **labels) -> None:
        """Stale-label zeroing (the reason-plane convention from the status
        layer): every series whose label set CONTAINS `labels` resets to 0 —
        e.g. a dropped tenant's rpc_total{tenant=...} series must not keep
        claiming traffic for a tenant that no longer exists. A counter reset
        to 0 is well-formed prometheus (clients handle counter resets)."""
        items = set(labels.items())
        with self._lock:
            for key in self._values:
                if items <= set(key):
                    self._values[key] = 0.0

    def items(self) -> list[tuple[tuple, float]]:
        """Snapshot of every (label-key-tuple, value) series — the public
        accessor for aggregations over a whole family (audit_stats and
        kin), instead of reaching into the private storage."""
        with self._lock:
            return list(self._values.items())


@dataclass
class Gauge:
    name: str
    help: str = ""
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = v

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def zero_matching(self, **labels) -> None:
        """Stale-label zeroing, gauge edition (the drop_tenant sweep
        contract from the counter/histogram families extended to the
        device-residency gauges): every series whose label set contains
        `labels` resets to 0 — a dropped tenant's `tenant_hbm_bytes` /
        `resident_bytes` must not keep claiming device memory."""
        items = set(labels.items())
        with self._lock:
            for key in self._values:
                if items <= set(key):
                    self._values[key] = 0.0


@dataclass
class Histogram:
    name: str
    help: str = ""
    buckets: tuple = _DEFAULT_BUCKETS
    _counts: dict[tuple, list] = field(default_factory=dict)
    _sums: dict[tuple, float] = field(default_factory=dict)
    # (series key, bucket index) -> (trace_id, observed value): the last
    # exemplar landing in that bucket. Only observations explicitly carrying
    # an exemplar (a tail-sampled trace id) are stored, so every exemplar in
    # the exposition resolves to a RETAINED trace — a bad p99 bucket links
    # straight to its Perfetto evidence instead of a sampled-away id.
    _exemplars: dict[tuple, tuple] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def observe(self, v: float, exemplar: str | None = None, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    bucket = i
                    break
            else:
                counts[-1] += 1
                bucket = len(self.buckets)
            self._sums[key] = self._sums.get(key, 0.0) + v
            if exemplar:
                self._exemplars[(key, bucket)] = (exemplar, v)

    def count(self, **labels) -> int:
        with self._lock:
            return sum(self._counts.get(tuple(sorted(labels.items())), []))

    def exemplars(self, **labels) -> dict[int, tuple]:
        """bucket index -> (trace_id, value) for one series."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            return {b: ex for (k, b), ex in self._exemplars.items()
                    if k == key}

    def zero_matching(self, **labels) -> None:
        """Stale-label zeroing: bucket counts, sums and exemplars of every
        series whose label set contains `labels` reset (see Counter)."""
        items = set(labels.items())
        with self._lock:
            for key in self._counts:
                if items <= set(key):
                    self._counts[key] = [0] * (len(self.buckets) + 1)
                    self._sums[key] = 0.0
            for kb in [kb for kb in self._exemplars if items <= set(kb[0])]:
                del self._exemplars[kb]


class Registry:
    def __init__(self, prefix: str = "cluster_autoscaler"):
        self.prefix = prefix
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help), help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help), help)

    def histogram(self, name: str, help: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get(name, lambda: Histogram(name, help, buckets), help)

    def _get(self, name: str, make, help: str = ""):
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = make()
            m = self._metrics[name]
            # a later accessor may carry the family's help string while the
            # first (hot-path) touch did not — upgrade so the exposition's
            # `# HELP` line does not depend on call order
            if help and not getattr(m, "help", ""):
                m.help = help
            return m

    @contextmanager
    def time_function(self, label: str):
        """reference: function_duration_seconds histogram per FunctionLabel
        (+ the quantile variant, metrics.go function_duration_quantile)."""
        h = self.histogram("function_duration_seconds")
        q = self.histogram("function_duration_quantile_seconds")
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            h.observe(dt, function=label)
            q.observe(dt, function=label)

    def expose_text(self) -> str:
        """Prometheus exposition format (consumed by the /metrics endpoint)."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for name, m in metrics:
            full = f"{self.prefix}_{name}"
            if getattr(m, "help", ""):
                lines.append(f"# HELP {full} {m.help}")
            # snapshot under the metric's own lock: a scrape racing a
            # writer thread must neither see torn values nor die on
            # "dict changed size during iteration"
            if isinstance(m, Counter):
                lines.append(f"# TYPE {full} counter")
                with m._lock:
                    values = list(m._values.items())
                for key, v in values:
                    lines.append(f"{full}{_fmt(key)} {v}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {full} gauge")
                with m._lock:
                    values = list(m._values.items())
                for key, v in values:
                    lines.append(f"{full}{_fmt(key)} {v}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {full} histogram")
                with m._lock:
                    rows = [(key, list(counts), m._sums.get(key, 0.0),
                             {b: ex for (k, b), ex in m._exemplars.items()
                              if k == key})
                            for key, counts in m._counts.items()]
                for key, counts, total, exemplars in rows:
                    cum = 0
                    for i, b in enumerate(m.buckets):
                        cum += counts[i]
                        lines.append(f'{full}_bucket{_fmt(key, le=str(b))} '
                                     f'{cum}{_fmt_exemplar(exemplars.get(i))}')
                    cum += counts[-1]
                    lines.append(
                        f'{full}_bucket{_fmt(key, le="+Inf")} {cum}'
                        f"{_fmt_exemplar(exemplars.get(len(m.buckets)))}")
                    lines.append(f"{full}_sum{_fmt(key)} {total}")
                    lines.append(f"{full}_count{_fmt(key)} {cum}")
        return "\n".join(lines) + "\n"


def _fmt(key: tuple, **extra) -> str:
    items = list(key) + sorted(extra.items())
    if not items:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in items)
    return "{" + inner + "}"


def _fmt_exemplar(ex: tuple | None) -> str:
    """OpenMetrics exemplar suffix for a bucket line:
    `... 17 # {trace_id="abc"} 0.042` — the trace id resolves to a
    tail-sampler-retained Perfetto trace (metrics/trace.TailSampler)."""
    if not ex:
        return ""
    trace_id, v = ex
    return f' # {{trace_id="{trace_id}"}} {v}'


# ---- extra exposition registries (in-process sidecar parity) ----
#
# The control plane's /metrics mux serves `default_registry`; a sidecar
# running IN the same process (bench, tests, single-binary deployments)
# registers its own Registry here so the mux exposes the identical series
# the sidecar `Metricz` RPC serves — one scrape surface, two transports,
# same families (the Metricz RPC conversely appends default_registry).
# Held by WEAK reference: a service that is dropped without close() (or
# that leaks in tests) falls out of the exposition with its registry
# instead of being scraped as a ghost forever.
_extra_expositions: list = []      # list[weakref.ref[Registry]]
_extra_lock = threading.Lock()


def register_exposition(registry: Registry) -> None:
    import weakref

    with _extra_lock:
        _extra_expositions[:] = [r for r in _extra_expositions
                                 if r() is not None]
        if not any(r() is registry for r in _extra_expositions):
            _extra_expositions.append(weakref.ref(registry))


def unregister_exposition(registry: Registry) -> None:
    with _extra_lock:
        _extra_expositions[:] = [r for r in _extra_expositions
                                 if r() is not None and r() is not registry]


def expose_all_text() -> str:
    """default_registry + every live registered extra registry, one
    exposition — what the /metrics mux serves."""
    with _extra_lock:
        extras = [r() for r in _extra_expositions if r() is not None]
    return "".join([default_registry.expose_text()]
                   + [r.expose_text() for r in extras])


@dataclass
class HealthCheck:
    """reference: metrics/liveness.go — fails liveness when the loop stalls
    (--max-inactivity), keeps failing (--max-failing-time), or never completes
    a first successful run (--max-startup-time)."""

    max_inactivity_s: float = 600.0
    max_failing_time_s: float = 900.0
    max_startup_time_s: float = 1200.0
    started: float = field(default_factory=time.time)
    last_activity: float = field(default_factory=time.time)
    last_success: float = 0.0
    last_failure: float = 0.0

    def mark_active(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        self.last_activity = now
        self.last_success = now

    def mark_failed(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        self.last_activity = now
        self.last_failure = now

    def healthy(self, now: float | None = None) -> bool:
        now = time.time() if now is None else now
        if self.last_success == 0.0:
            # never completed a run: bounded by the startup budget
            return now - self.started <= self.max_startup_time_s
        if now - self.last_activity > self.max_inactivity_s:
            return False
        if (self.last_failure > self.last_success
                and now - self.last_success > self.max_failing_time_s):
            return False
        return True


default_registry = Registry()
