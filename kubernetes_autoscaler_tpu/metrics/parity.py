"""Metric-series parity vs the reference's metrics/metrics.go:202-443.

Every series the reference registers appears in exactly one bucket:

  EMITTED — produced by this framework (tests/test_metrics_parity.py runs a
            RunOnce and asserts each appears in the /metrics exposition).
  NA      — reference series tied to machinery this framework deliberately
            lacks, each with the reason.

`emit_cluster_metrics` is the per-loop sweep the reference spreads across
UpdateClusterSafeToAutoscale/UpdateNodesCount/etc.; per-nodegroup gauges
follow the --emit-per-nodegroup-metrics gate (reference: main.go:102
metrics.RegisterAll(EmitPerNodeGroupMetrics)).
"""

from __future__ import annotations

EMITTED = {
    "binpacking_heterogeneity",      # distinct pod shapes per estimate
    "cluster_cpu_current_cores",
    "cluster_memory_current_bytes",
    "cluster_safe_to_autoscale",
    "cpu_limits_cores",              # labels: direction=min|max
    "created_node_groups_total",
    "deleted_node_groups_total",
    "errors_total",
    "evicted_pods_total",
    "failed_gpu_scale_ups_total",
    "failed_node_creations_total",
    "failed_scale_ups_total",
    "function_duration_seconds",
    "function_duration_quantile_seconds",
    "last_activity",
    "max_nodes_count",
    "memory_limits_bytes",
    "node_group_backoff_status",     # per-nodegroup
    "node_group_healthiness",        # per-nodegroup
    "node_group_max_count",          # per-nodegroup
    "node_group_min_count",          # per-nodegroup
    "node_group_target_count",       # per-nodegroup
    "node_groups_count",
    "node_removal_latency_seconds",
    "node_taints_count",
    "nodes_count",                   # labels: state
    "old_unregistered_nodes_removed_count",
    "pending_node_deletions",
    "scale_down_in_cooldown",
    "scaled_down_gpu_nodes_total",
    "scaled_down_nodes_total",
    "scaled_up_gpu_nodes_total",
    "scaled_up_nodes_total",
    "skipped_scale_events_count",    # labels: direction, reason
    "unneeded_nodes_count",
    "unremovable_nodes_count",
    "unschedulable_pods_count",
}

NA = {
    "dra_node_template_resources_mismatch": "DRA lowering rebuilds templates each loop; there is no cached template to drift",
    "inconsistent_instances_migs_count": "GCE-SDK specific",
    "max_node_skip_eval_duration_seconds": "no per-node eval-skip heuristic: the device sweep is exhaustive",
    "overflowing_controllers_count": "pod-injection caps per workload, not per controller cache",
}

# ---- the function_duration_seconds{function=...} FAMILY ----
#
# The reference instruments RunOnce stages with FunctionLabel values
# (metrics.go:46-80, UpdateDurationFromStart call sites). Each label maps to
# the label OUR time_function wrapper observes for the same work — so a
# dashboard ported from the reference can be re-pointed label-for-label.
# Where the reference splits finer than our loop (its scale-down is three
# sequential host passes; ours is one fused device sweep + a confirm pass),
# two labels legitimately land on the same span — documented inline. The
# per-phase decomposition UNDER each function label is ours alone:
# planner_phase_seconds{phase=...} and the flight-recorder trace spans
# (metrics/trace.py) carry what the reference's one histogram cannot.
FUNCTION_DURATION = {
    "main": "main",
    "cloudProviderRefresh": "cloud_provider_refresh",
    "updateClusterState": "snapshot_build",
    "filterOutSchedulable": "filter_out_schedulable",
    "scaleUp": "scale_up",
    # the device drain sweep IS find-unneeded and find-nodes-to-remove in
    # one program; both reference labels map onto its span
    "findUnneeded": "scale_down_update",
    "scaleDown:findNodesToRemove": "scale_down_update",
    "scaleDown": "scale_down_confirm",
    "scaleDown:nodeDeletion": "scale_down_actuate",
    "scaleDown:softTaintUnneeded": "soft_taint_unneeded",
}

# ---- the REASON plane: reference reason-bearing families → ours ----
#
# The reference carries per-verdict reasons on three surfaces (ISSUE 5):
# per-pod NoScaleUp / per-node NoScaleDown events (EventRecorder posts in
# core/scaleup + core/scaledown, spam-bounded by kube event aggregation),
# the ~20-value unremovable enum (simulator/cluster.go:63-103) feeding
# `unremovable_nodes_count{reason}`, and `unschedulable_pods_count`. Each
# maps onto a surface here; PARITY.md carries the same table.
REASON_FAMILIES = {
    # reference family / surface -> ours
    "unschedulable_pods_count": (
        "unschedulable_pods_count — unlabeled total (parity), plus a "
        "{reason} label carrying the predicate-kernel reason taxonomy "
        "(ops/predicates.REASON_BITS + no-node-in-group) for pods no node "
        "group can help"),
    "unremovable_nodes_count": (
        "unremovable_nodes_count — unlabeled total (parity), plus a "
        "{reason} label per unremovable enum value "
        "(UnremovableNodes.reason_counts)"),
    "NoScaleUp/NoScaleDown events": (
        "events.EventSink — deduped by (kind, object, reason), throttled by "
        "a klogx per-loop quota, exported into /snapshotz payloads; "
        "scale_events_total{kind,reason} / scale_events_dropped_total "
        "count the flow"),
    "skipped_scale_events_count": (
        "skipped_scale_events_count{direction,reason} — quota/limit skips, "
        "emitted by the orchestrator's _apply_quota (unchanged family)"),
}

# ---- serving surfaces: reference per-loop families → per-request/tenant ----
#
# The reference is a single-cluster loop: its latency surfaces are per-LOOP
# (function_duration_seconds per stage, pending_pods gauges). The multi-
# tenant sidecar serves a FLEET, so each family gains a per-request,
# per-tenant analog (ISSUE 8; docs/OBSERVABILITY.md "Serving surfaces").
# PARITY.md carries the same table.
SERVING_FAMILIES = {
    # reference per-loop family -> our per-tenant serving analog
    "function_duration_seconds": (
        "katpu_sidecar_request_phase_seconds{phase,tenant} — the per-stage "
        "decomposition of ONE request (encode/queue/form/stack/dispatch/"
        "harvest/assembly/reply, contiguous, sums to e2e) instead of one "
        "process-lifetime histogram per loop stage"),
    "unschedulable_pods_count (pending work)": (
        "admission queue depth + admission_rejects_total{reason} — the "
        "serving-side pending-work surface: queued simulation requests and "
        "explicit sheds, instead of pending pods"),
    "errors_total": (
        "tenant_slo_breaches_total{tenant} + rpc_duration_seconds bucket "
        "EXEMPLARS resolving to tail-sampled Perfetto traces — breaches "
        "carry their evidence instead of a bare error count"),
    "max_node_skip_eval_duration_seconds (work skipped)": (
        "dispatch_gap_seconds{cause} + device_idle_seconds_total + "
        "batch_occupancy_ratio — device time NOT spent on member work "
        "(pipeline stalls, arrival idle, lane padding)"),
    "cluster_safe_to_autoscale (health doc)": (
        "the sidecar Statusz RPC — tenant table with latency percentiles, "
        "SLO budgets/breaches and last-breach exemplar trace ids, queue and "
        "shape-class state, in one human-readable page"),
    "(no reference analog: decision provenance)": (
        "journal_records_total{tenant} / journal_bytes_total{tenant} / "
        "journal_dropped_total{reason,tenant} — the flight journal "
        "(replay/): every world delta and sim verdict as a chained, "
        "digest-sealed record; breach/backpressure persists the ring "
        "(docs/REPLAY.md)"),
}

# ---- device surfaces: reference host-process families → device accounting ----
#
# The reference runs on a kube control plane with NO accelerator: its only
# memory/compile observability is the Go process's own (go runtime metrics
# scraped off-process, no per-component attribution, no compile concept).
# This framework keeps multi-tenant state RESIDENT in device HBM and
# compiles XLA programs on the serving path, so each absent reference
# surface maps onto a device family (metrics/device.py; PARITY.md carries
# the same table; the Metricz ≡ /metrics row-for-row parity test covers
# every family below).
DEVICE_FAMILIES = {
    # absent reference surface -> our device accounting
    "(process RSS, unattributed)": (
        "hbm_bytes_in_use / hbm_bytes_limit / hbm_headroom_ratio — the "
        "device's own totals (memory_stats), plus resident_bytes"
        "{owner,tenant}: a weakref census of every LIVE device array by "
        "owner component (world_store / tenant_export / stack_cache / "
        "marshal) and tenant; the untagged remainder feeds the leak "
        "watchdog (hbm_leak_suspects_total)"),
    "(no per-tenant memory accounting)": (
        "tenant_hbm_bytes{tenant} — live device bytes attributed to one "
        "tenant across every owner tag; the projected-residency base the "
        "--hbm-budget-frac admission gate charges (reject reason "
        "`hbm-budget` in world_validation_rejects_total)"),
    "(no compile concept)": (
        "compile_census_total{fn,shape_sig,tenant} + compile_census_flops/"
        "bytes_accessed/temp_bytes{fn,shape_sig} — every XLA compile on "
        "the dispatch path named by entry point, shape signature and the "
        "tenant charged, with cost_analysis/memory_analysis figures; "
        "sim_compiles_total and recompiles_per_new_tenant resolve to these "
        "variants instead of bare counts"),
    "(no profiler integration)": (
        "device_profile_captures_total{reason} — bounded, rate-limited "
        "jax.profiler.trace sessions armed by SLO-breach/tail retention "
        "(or the Profilez RPC), capture dirs stamped with trace id + "
        "journal cursor; hbm_oom_dumps_total counts the device-memory "
        "pprof snapshots persisted on RESOURCE_EXHAUSTED dispatch "
        "failures"),
}

# ---- shadow-audit surfaces: reference trust model → online verification ----
#
# The reference TRUSTS its own arithmetic: scheduling verdicts are computed
# by the same Go process that actuates them, so there is no "the computer
# lied" failure class and no metric for it. This framework computes verdicts
# on an accelerator behind a tunnel — a silently miscompiled kernel or a
# corrupted HBM buffer emits wrong decisions with healthy-looking metrics —
# so the shadow audit (audit/shadow.py; docs/OBSERVABILITY.md "Shadow
# audit") adds the missing golden-output families. PARITY.md carries the
# same table; the Metricz ≡ /metrics row-for-row parity test covers the
# per-tenant families below.
SHADOW_AUDIT_FAMILIES = {
    # absent reference surface -> our online-verification accounting
    "(no silent-data-corruption detection)": (
        "shadow_audit_checks_total{surface,outcome} — sampled device "
        "verdicts re-derived through the host oracle each loop (surface: "
        "plane / scaleup / drain on the control loop; sidecar-up / "
        "sidecar-down per batched window, tenant-labelled); outcome "
        "divergent is the silent-corruption alarm"),
    "(no verification cost accounting)": (
        "shadow_audit_overhead_seconds_total + "
        "shadow_audit_pending_recheck — the audit's budget spend (token-"
        "bucket bounded, ~1% of loop walltime; exhausted budget counts "
        "outcome=skipped, never stalls the loop) and the one-bit state of "
        "the post-heal re-audit protocol"),
    "(no corruption evidence artifact)": (
        "shadow_audit_bundles_total — self-contained divergence evidence "
        "bundles (journal cursor + sampled inputs + per-bit reason diff + "
        "retained trace id), persisted next to the flight-recorder dumps"),
}

# The reference benchmarks offline (k8s perf-tests / ClusterLoader2 live
# OUTSIDE the autoscaler repo) and records no longitudinal perf series of
# its own simulator. This framework's value proposition IS simulator
# speed, so the perf observatory (perfwatch/; docs/BENCH.md "Trajectory &
# regression gate") banks every bench round and gates on statistical
# regressions. PARITY.md carries the same table; both families ride the
# normal Registry exposition path and are served identically by /metrics
# and Metricz.
PERFWATCH_FAMILIES = {
    # absent reference surface -> our longitudinal perf accounting
    "(no longitudinal bench record)": (
        "bench_runs_total{mode,backend} — every bench.py mode record "
        "appended to the chain-sealed PerfHistory store, labelled by mode "
        "and producing-backend lineage; lineage is part of the row, so a "
        "cpu-floor run can never masquerade as tpu evidence (the PR 7 "
        "bug class, closed structurally)"),
    "(no perf regression detection)": (
        "perf_regressions_total{metric,severity} — confirmed regressions "
        "from the rolling median+MAD detector (perfwatch/detect.py; "
        "severity minor/major/critical), each paired with a self-"
        "contained triage bundle (perfwatch/triage.py)"),
    "(no bench evidence retention accounting)": (
        "perf_history_dropped_total{reason} + perf_triage_bundles_total"
        "{metric} — rotation-pruned and null-valued rows accounted by "
        "reason (never silently vanished), and the evidence bundles "
        "written per confirmed regression"),
}

# The reference explains decisions through scattered events + status
# configmap prose; there is no queryable, object-centric provenance store
# and therefore no metrics for one. The lineage engine (lineage/;
# docs/LINEAGE.md) adds the join over every cursor-stamped evidence store,
# and these families account for it. PARITY.md carries the same table;
# all ride the normal Registry path, served identically by /metrics and
# Metricz.
LINEAGE_FAMILIES = {
    # absent reference surface -> our provenance accounting
    "(no decision provenance store)": (
        "lineage_index_rows + lineage_index_bytes — the live ring's "
        "bounded per-object entry count and approximate retained bytes "
        "(LRU-evicted objects and middle-dropped entries are counted in "
        "the /whyz stats payload, never silently lost)"),
    "(no provenance freshness signal)": (
        "lineage_index_lag_loops — loops between the journal cursor and "
        "the lineage head; nonzero means observes were skipped (aborted "
        "loops) and a why answer may trail the cluster"),
    "(no explanation query accounting)": (
        "lineage_queries_total{surface} + "
        "lineage_overhead_seconds_total — why/timeline/diff queries by "
        "serving surface (whyz / snapshotz / explain / api) and the "
        "metered per-loop cost of feeding the ring (CI-bounded ≤1% like "
        "the shadow audit)"),
}

# The reference UnremovableReason enum values our planner actually produces,
# value-for-value (simulator/cluster.go:63-103). A dashboard filtering the
# reference's unremovable_nodes_count{reason=...} re-points unchanged.
UNREMOVABLE_REASONS = {
    "ScaleDownDisabledAnnotation": "ScaleDownDisabledAnnotation",
    "ScaleDownUnreadyDisabled": "ScaleDownUnreadyDisabled",
    "NotAutoscaled": "NotAutoscaled",
    "NotUnneededLongEnough": "NotUnneededLongEnough",
    "NodeGroupMinSizeReached": "NodeGroupMinSizeReached",
    "MinimalResourceLimitExceeded": "MinimalResourceLimitExceeded",
    "NoPlaceToMovePods": "NoPlaceToMovePods",
    "BlockedByPod": "BlockedByPod",
    "NotEnoughPdb": "NotEnoughPdb",
    # atomic (ZeroOrMaxNodeScaling) groups: the reference filters these in
    # its AtomicResizeFilteringProcessor with the same failure semantics
    "AtomicScaleDownFailed": "AtomicScaleDownFailed",
}

# Reasons THIS framework produces with no reference analog (the reference
# has no accelerator to lose). They ride the same four surfaces as the
# mapped enum values; dashboards filtering on the reference enum simply
# never match them.
UNREMOVABLE_REASONS_LOCAL = {
    "BackendDegraded": "scale-down actuation withheld while the backend "
                       "supervisor distrusts the simulation (degraded/"
                       "recovering ladder state or an unverified resident "
                       "world, core/supervisor.py)",
    # Dual-surface reason: rides unremovable_nodes_count{reason} on the
    # scale-down side AND unschedulable_pods_count{reason} / NoScaleUp
    # events on the scale-up side — both directions refuse to actuate on
    # verdict bits the audit proved corrupt.
    "AuditDivergence": "actuation refused while a shadow-audit divergence "
                       "is unhealed: the device verdict plane diverged "
                       "from the host oracle and the divergence survived "
                       "a forced cold re-encode (audit/shadow.py; "
                       "scale-down withheld, scale-up options refused)",
}

UNREMOVABLE_REASONS_NA = {
    "NoReason": "the TTL cache stores refusals only; an accepted candidate has no entry",
    "CurrentlyBeingDeleted": "deletion in-flight state lives in the actuator's NodeDeletionTracker (pending_node_deletions gauge), not the unremovable cache",
    "NotUnderutilized": "utilization screening re-evaluates every loop and is never cached — the recheck TTL applies only to simulation failures (planner.update comment)",
    "NotUnreadyLongEnough": "unready candidates gate on --scale-down-unready-enabled + ScaleDownUnreadyTime inside removable_at; the shared NotUnneededLongEnough entry covers the immature clock in both readiness states",
    "UnexpectedError": "the device sweep cannot partially fail per node; a raised loop surfaces via errors_total and the flight recorder instead",
}

FUNCTION_DURATION_NA = {
    "scaleDown:miscOperations": "bookkeeping the reference batches between passes is inline host policy here, nanoseconds not a stage",
    "poll": "loop scheduling lives in core/loop.py run_loop, outside RunOnce; scan_interval pacing has no duration to observe",
    "reconfigure": "no in-process config reload: options are immutable per process (flag parity doc)",
    "autoscaling": "the reference's autoscaling = RunOnce minus poll; identical to our 'main' measurement, not re-observed",
    "loopWait": "loop pacing sleep, observable as scan_interval minus main; not a function of the loop body",
}

# The COMPLETE series list of metrics/metrics.go (every `Name:` field,
# :202-443) — the meta-test (tests/test_metrics_parity.py) asserts
# EMITTED ∪ NA covers it exactly, mirroring the flag registry's honesty
# contract: a series added upstream must be classified here before the
# parity claim holds again.
REFERENCE_SERIES = {
    "binpacking_heterogeneity",
    "cluster_cpu_current_cores",
    "cluster_memory_current_bytes",
    "cluster_safe_to_autoscale",
    "cpu_limits_cores",
    "created_node_groups_total",
    "deleted_node_groups_total",
    "dra_node_template_resources_mismatch",
    "errors_total",
    "evicted_pods_total",
    "failed_gpu_scale_ups_total",
    "failed_node_creations_total",
    "failed_scale_ups_total",
    "function_duration_quantile_seconds",
    "function_duration_seconds",
    "inconsistent_instances_migs_count",
    "last_activity",
    "max_node_skip_eval_duration_seconds",
    "max_nodes_count",
    "memory_limits_bytes",
    "node_group_backoff_status",
    "node_group_healthiness",
    "node_group_max_count",
    "node_group_min_count",
    "node_group_target_count",
    "node_groups_count",
    "node_removal_latency_seconds",
    "node_taints_count",
    "nodes_count",
    "old_unregistered_nodes_removed_count",
    "overflowing_controllers_count",
    "pending_node_deletions",
    "scale_down_in_cooldown",
    "scaled_down_gpu_nodes_total",
    "scaled_down_nodes_total",
    "scaled_up_gpu_nodes_total",
    "scaled_up_nodes_total",
    "skipped_scale_events_count",
    "unneeded_nodes_count",
    "unremovable_nodes_count",
    "unschedulable_pods_count",
}


def emit_cluster_metrics(registry, cluster_state, provider, options, enc,
                         now: float, health=None, latency_tracker=None) -> None:
    """The per-loop gauge sweep (reference: static_autoscaler.go RunOnce's
    metrics.Update* calls)."""
    import numpy as np

    from kubernetes_autoscaler_tpu.models import resources as res

    registry.gauge("cluster_safe_to_autoscale").set(
        1.0 if cluster_state.is_cluster_healthy() else 0.0)
    # prefer the incremental encoder's host mirrors (two device→host
    # transfers saved per loop) — but only while the device tensors are
    # still the handed-out arrays (upcoming-node injection replaces them)
    h = enc.host_arrays or {}
    tok = enc.host_mirror_token or {}
    cap = np.asarray(
        h["nodes.cap"] if tok.get("nodes.cap") is enc.nodes.cap
        else enc.nodes.cap, dtype=np.int64)
    valid = np.asarray(
        h["nodes.valid"] if tok.get("nodes.valid") is enc.nodes.valid
        else enc.nodes.valid)
    sums = cap[valid].sum(axis=0) if valid.any() else np.zeros(cap.shape[1])
    registry.gauge("cluster_cpu_current_cores").set(float(sums[res.CPU]) / 1000.0)
    registry.gauge("cluster_memory_current_bytes").set(
        float(sums[res.MEMORY]) * 1024.0 * 1024.0)
    registry.gauge("cpu_limits_cores").set(0.0, direction="minimum")
    registry.gauge("cpu_limits_cores").set(float(options.max_cores_total),
                                           direction="maximum")
    registry.gauge("memory_limits_bytes").set(0.0, direction="minimum")
    registry.gauge("memory_limits_bytes").set(
        float(options.max_memory_total_mib) * 1024.0 * 1024.0,
        direction="maximum")
    registry.gauge("max_nodes_count").set(float(options.max_nodes_total))
    groups = provider.node_groups()
    registry.gauge("node_groups_count").set(float(len(groups)))
    t = cluster_state.total_readiness
    registry.gauge("nodes_count").set(float(t.ready), state="ready")
    registry.gauge("nodes_count").set(float(t.unready), state="unready")
    registry.gauge("nodes_count").set(float(t.not_started), state="notStarted")
    n_tainted = sum(
        1 for nd in enc.node_objs if nd is not None for t in nd.taints
    ) if enc.node_objs else 0
    registry.gauge("node_taints_count").set(float(n_tainted), type="any")
    if health is not None:
        registry.gauge("last_activity").set(health.last_activity, activity="main")
    if latency_tracker is not None:
        pass  # node_removal_latency_seconds observed at deletion time
    registry.gauge("binpacking_heterogeneity").set(
        float((np.asarray(enc.specs.count) > 0).sum()))

    if options.emit_per_nodegroup_metrics:
        for g in groups:
            gid = g.id()
            registry.gauge("node_group_min_count").set(
                float(g.min_size()), node_group=gid)
            registry.gauge("node_group_max_count").set(
                float(g.max_size()), node_group=gid)
            registry.gauge("node_group_target_count").set(
                float(g.target_size()), node_group=gid)
            registry.gauge("node_group_backoff_status").set(
                1.0 if cluster_state.backoff.is_backed_off(gid, now) else 0.0,
                node_group=gid)
            registry.gauge("node_group_healthiness").set(
                1.0 if cluster_state.is_node_group_healthy(gid) else 0.0,
                node_group=gid)
