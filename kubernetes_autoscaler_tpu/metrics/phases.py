"""Per-phase wall-clock accounting for the autoscaler's host paths.

Both hot loops cross the same five cost domains each RunOnce —
  encode    host objects → tensors (models/encode, models/incremental,
            the orchestrator's template-tensor cache)
  dispatch  device program launches (drain sweep, predicate planes,
            estimate_all + scoring on the scale-up side)
  fetch     device → host transfers (ops/hostfetch batched fetches,
            option-score readback)
  marshal   host-side numpy marshalling for the native confirm tier
  confirm   the confirmation pass itself (native C++ or Python fallback;
            scale-up: the lossy-winner oracle verification)
— and a single opaque per-loop number cannot say which one regressed.
`PhaseStats` is a zero-dependency accumulator its owner (scale-down Planner,
ScaleUpOrchestrator) holds; it ALSO mirrors observations into a
metrics.Registry histogram (`planner_phase_seconds{phase=...}`) when one is
attached, so the breakdown rides the normal exposition path. bench.py prints
`snapshot()` next to the headline p50 so the metric ships with its per-phase
decomposition.

Every phase additionally opens a span in the thread's ACTIVE trace
(metrics/trace.current_tracer — set per RunOnce by StaticAutoscaler's flight
recorder, or by bench.py --trace), tagged with the owner as its category, so
planner and orchestrator phases appear on the loop timeline for free. With no
active tracer the extra cost is a single thread-local read.

Phases may nest (a mirror miss inside `marshal` opens a `fetch` span);
totals then overlap — they are per-domain costs, not a partition of wall
clock. `events` is a free-form counter side-channel for cache hit/miss
accounting (the marshal cache, the elig-plane cache, oracle-call counts);
each bump mirrors into the trace's counters and, when a registry is
attached, the `phase_events_total{owner=,event=}` counter — so cache and
transfer accounting are first-class registry metrics, not bench-JSON-only.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from kubernetes_autoscaler_tpu.metrics import trace

PHASES = ("encode", "dispatch", "fetch", "marshal", "confirm")

# steady-state encode/fetch spans sit well under 1 ms (the whole host share
# of a loop is tens of ms at 5k nodes) — the registry's default buckets
# start at 5 ms and would flatten the entire distribution into one bucket
PHASE_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0)
_PHASE_HELP = ("Per-phase host-path wall clock of the scale-down planner and "
               "scale-up orchestrator (encode/dispatch/fetch/marshal/confirm "
               "spans, seconds; sub-ms buckets)")
_EVENTS_HELP = ("Free-form phase event counters (cache hits/misses, batched "
                "device transfers, re-estimate dispatches) keyed by owner")


@dataclass
class PhaseStats:
    totals_s: dict[str, float] = field(default_factory=dict)
    counts: dict[str, int] = field(default_factory=dict)
    events: dict[str, int] = field(default_factory=dict)
    registry: object | None = None      # optional metrics.Registry
    owner: str = ""                     # span category: planner | scaleup | …

    @contextmanager
    def phase(self, name: str, **attrs):
        tracer = trace.current_tracer()
        span = (tracer.begin(name, cat=self.owner or "phase", **attrs)
                if tracer is not None else None)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals_s[name] = self.totals_s.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1
            if tracer is not None:
                tracer.end(span)
            if self.registry is not None:
                self.registry.histogram(
                    "planner_phase_seconds", help=_PHASE_HELP,
                    buckets=PHASE_BUCKETS).observe(dt, phase=name)

    def observe(self, name: str, seconds: float) -> None:
        """Record an externally-timed interval into a phase's totals and the
        registry histogram WITHOUT opening a span — for intervals whose span
        already exists elsewhere on the trace (the async batched fetch opens
        its own `fetch` span at issue time; the blocking remainder measured
        at harvest must still count toward planner_phase_seconds{fetch}, and
        a nested span here would end the still-open async span out of LIFO
        order)."""
        self.totals_s[name] = self.totals_s.get(name, 0.0) + seconds
        self.counts[name] = self.counts.get(name, 0) + 1
        if self.registry is not None:
            self.registry.histogram(
                "planner_phase_seconds", help=_PHASE_HELP,
                buckets=PHASE_BUCKETS).observe(seconds, phase=name)

    def bump(self, event: str, n: int = 1) -> None:
        self.events[event] = self.events.get(event, 0) + n
        tracer = trace.current_tracer()
        if tracer is not None:
            tracer.bump(event, n)
        if self.registry is not None:
            self.registry.counter("phase_events_total", help=_EVENTS_HELP).inc(
                n, owner=self.owner or "phase", event=event)

    def snapshot(self) -> dict:
        """One JSON-friendly view: per-phase totals (ms) + spans + events."""
        return {
            "totals_ms": {k: round(v * 1000.0, 3)
                          for k, v in sorted(self.totals_s.items())},
            "spans": dict(sorted(self.counts.items())),
            "events": dict(sorted(self.events.items())),
        }

    def reset(self) -> None:
        self.totals_s.clear()
        self.counts.clear()
        self.events.clear()
