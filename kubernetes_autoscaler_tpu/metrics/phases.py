"""Per-phase wall-clock accounting for the autoscaler's host paths.

Both hot loops cross the same five cost domains each RunOnce —
  encode    host objects → tensors (models/encode, models/incremental,
            the orchestrator's template-tensor cache)
  dispatch  device program launches (drain sweep, predicate planes,
            estimate_all + scoring on the scale-up side)
  fetch     device → host transfers (ops/hostfetch batched fetches,
            option-score readback)
  marshal   host-side numpy marshalling for the native confirm tier
  confirm   the confirmation pass itself (native C++ or Python fallback;
            scale-up: the lossy-winner oracle verification)
— and a single opaque per-loop number cannot say which one regressed.
`PhaseStats` is a zero-dependency accumulator its owner (scale-down Planner,
ScaleUpOrchestrator) holds; it ALSO mirrors observations into a
metrics.Registry histogram (`planner_phase_seconds{phase=...}`) when one is
attached, so the breakdown rides the normal exposition path. bench.py prints
`snapshot()` next to the headline p50 so the metric ships with its per-phase
decomposition.

Phases may nest (a mirror miss inside `marshal` opens a `fetch` span);
totals then overlap — they are per-domain costs, not a partition of wall
clock. `events` is a free-form counter side-channel for cache hit/miss
accounting (the marshal cache, the elig-plane cache, oracle-call counts).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field

PHASES = ("encode", "dispatch", "fetch", "marshal", "confirm")


@dataclass
class PhaseStats:
    totals_s: dict[str, float] = field(default_factory=dict)
    counts: dict[str, int] = field(default_factory=dict)
    events: dict[str, int] = field(default_factory=dict)
    registry: object | None = None      # optional metrics.Registry

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals_s[name] = self.totals_s.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1
            if self.registry is not None:
                self.registry.histogram("planner_phase_seconds").observe(
                    dt, phase=name)

    def bump(self, event: str, n: int = 1) -> None:
        self.events[event] = self.events.get(event, 0) + n

    def snapshot(self) -> dict:
        """One JSON-friendly view: per-phase totals (ms) + spans + events."""
        return {
            "totals_ms": {k: round(v * 1000.0, 3)
                          for k, v in sorted(self.totals_s.items())},
            "spans": dict(sorted(self.counts.items())),
            "events": dict(sorted(self.events.items())),
        }

    def reset(self) -> None:
        self.totals_s.clear()
        self.counts.clear()
        self.events.clear()
