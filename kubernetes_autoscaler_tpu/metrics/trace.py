"""Span tracer + flight recorder: per-loop timelines, not just totals.

`metrics/phases.py` answers "how much did each cost domain cost this
process-lifetime"; this module answers "what did THIS RunOnce look like" —
the question a breached loop SLO (tests/test_loop_slo.py) actually raises.
Three pieces:

  Tracer         loop-scoped trace id + monotonically ordered begin/end spans
                 with nesting, free-form attributes and counter events.
                 Recording a span is two `perf_counter_ns` calls and a list
                 append; with no active tracer the instrumentation sites
                 (PhaseStats.phase, the sidecar client) reduce to one
                 thread-local read, so tracing-off costs nothing measurable.
  activate()     installs a tracer as the thread's active tracer so deep
                 layers (phase spans, cache counters, RPC clients) find it
                 without plumbing a handle through every call.
  FlightRecorder bounded ring of the last N loop traces, held by
                 StaticAutoscaler. Always on: when a loop breaches its
                 wall-clock budget, raises, or served an armed `/snapshotz`,
                 the evidence is *already recorded* and gets persisted to
                 disk as one Chrome-trace/Perfetto JSON file — debugging
                 after the fact instead of asking the operator to reproduce.

Cross-process traces: the sidecar client stamps the trace id into gRPC
request metadata (sidecar/wire.TRACE_ID_HEADER); the server runs the RPC
under its own Tracer with the SAME id and returns its spans in the response
(`"trace"` field), which `add_remote_spans` merges. Span timestamps are
wall-clock anchored (`time.time_ns` at tracer construction + perf-counter
offsets) so spans from both processes land on one aligned timeline.

Export is the Chrome trace-event format (`{"traceEvents": [...]}` with
"X"-phase complete events, ts/dur in microseconds) — loadable directly in
Perfetto (https://ui.perfetto.dev) and `chrome://tracing`.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque

# spans per trace cap: a pathological loop (e.g. a hot retry loop inside a
# phase) must not grow a trace without bound; drops are counted and visible
MAX_SPANS_PER_TRACE = 100_000

_tls = threading.local()


def current_tracer() -> "Tracer | None":
    """The thread's active tracer, or None (the zero-cost path)."""
    return getattr(_tls, "tracer", None)


def activate(tracer: "Tracer | None") -> "Tracer | None":
    """Install `tracer` as this thread's active tracer; returns the previous
    one so callers can restore it (see `active()` for the with-form)."""
    prev = getattr(_tls, "tracer", None)
    _tls.tracer = tracer
    return prev


class active:
    """`with active(tracer): ...` — scoped activate/restore."""

    def __init__(self, tracer: "Tracer | None"):
        self.tracer = tracer

    def __enter__(self):
        self._prev = activate(self.tracer)
        return self.tracer

    def __exit__(self, *exc):
        activate(self._prev)
        return False


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


class Tracer:
    """One trace (normally: one RunOnce). Spans are stored as mutable lists
    `[name, cat, begin_ns, dur_ns, depth, args|None]` — begin_ns relative to
    the tracer's perf-counter epoch, dur_ns None while the span is open."""

    __slots__ = ("trace_id", "process", "_t0_ns", "wall0_us", "spans",
                 "_stack", "counters", "remote", "dropped")

    def __init__(self, trace_id: str | None = None, process: str = "autoscaler"):
        self.trace_id = trace_id or new_trace_id()
        self.process = process
        self._t0_ns = time.perf_counter_ns()
        self.wall0_us = time.time_ns() // 1000
        self.spans: list[list] = []
        self._stack: list[int] = []
        self.counters: dict[str, int] = {}
        self.remote: list[dict] = []    # merged child-process span groups
        self.dropped = 0

    # ---- span recording ----

    def begin(self, name: str, cat: str = "", **args) -> int:
        """Open a nested span; returns its index for `end()`. Attribute
        values must be JSON-serializable (they ride into the export)."""
        if len(self.spans) >= MAX_SPANS_PER_TRACE:
            self.dropped += 1
            return -2    # sentinel: the matching end() becomes a no-op
        idx = len(self.spans)
        self.spans.append([name, cat, time.perf_counter_ns() - self._t0_ns,
                           None, len(self._stack), args or None])
        self._stack.append(idx)
        return idx

    def end(self, idx: int = -1, **args) -> None:
        """Close the innermost open span (or everything down to and
        including `idx`, which makes phase/except interactions safe: a child
        left open by an exception is closed with its parent)."""
        if idx == -2:    # the begin() was dropped at the span cap
            return
        now = time.perf_counter_ns() - self._t0_ns
        if idx == -1:
            if not self._stack:
                return
            idx = self._stack[-1]
        while self._stack:
            top = self._stack.pop()
            span = self.spans[top]
            if span[3] is None:
                span[3] = now - span[2]
            if top == idx:
                break
        if args and 0 <= idx < len(self.spans):
            span = self.spans[idx]
            span[5] = {**(span[5] or {}), **args}

    class _SpanCtx:
        __slots__ = ("tracer", "idx")

        def __init__(self, tracer, idx):
            self.tracer = tracer
            self.idx = idx

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            self.tracer.end(self.idx)
            return False

    def span(self, name: str, cat: str = "", **args) -> "Tracer._SpanCtx":
        """`with tracer.span("confirm", cat="planner"): ...`"""
        return Tracer._SpanCtx(self, self.begin(name, cat, **args))

    def add_span(self, name: str, cat: str = "",
                 begin_abs_ns: int | None = None, dur_ns: int = 0,
                 **args) -> None:
        """Append an already-timed CLOSED span recorded by another thread —
        e.g. the sidecar's batch scheduler measured a coalesced dispatch
        window and each member RPC's handler tracer adopts it. Timestamps
        are absolute `perf_counter_ns` values (comparable across threads of
        one process); they are rebased onto this tracer's epoch. The span
        does not touch the open-span stack, so it can be added mid-RPC."""
        if len(self.spans) >= MAX_SPANS_PER_TRACE:
            self.dropped += 1
            return
        t0 = (time.perf_counter_ns() if begin_abs_ns is None else begin_abs_ns)
        self.spans.append([name, cat, t0 - self._t0_ns, max(int(dur_ns), 0),
                           len(self._stack), args or None])

    def annotate(self, **args) -> None:
        """Merge attributes into the innermost open span (root span if none
        is open)."""
        if not self.spans:
            return
        idx = self._stack[-1] if self._stack else 0
        span = self.spans[idx]
        span[5] = {**(span[5] or {}), **args}

    def bump(self, event: str, n: int = 1) -> None:
        """Trace-level counter (cache hits/misses, transfer counts, …);
        exported as args on the root span."""
        self.counters[event] = self.counters.get(event, 0) + n

    # ---- cross-process merge ----

    def add_remote_spans(self, group: dict) -> None:
        """Merge a child process's reported spans. `group` is the shape the
        sidecar server returns: {"trace_id", "process", "spans": [{"name",
        "cat", "ts_us", "dur_us", "depth", "args"}]} — ts_us wall-anchored
        in the REMOTE process, valid on this timeline because both processes
        share a wall clock (same machine / NTP domain)."""
        if not isinstance(group, dict) or not group.get("spans"):
            return
        self.remote.append({"process": str(group.get("process", "remote")),
                            "spans": list(group["spans"])})

    # ---- export ----

    def snapshot(self) -> dict:
        """JSON-able view of the trace (closed spans only — an owner
        snapshotting mid-span sees everything already completed)."""
        return {
            "trace_id": self.trace_id,
            "process": self.process,
            "wall0_us": self.wall0_us,
            "spans": [
                {"name": s[0], "cat": s[1],
                 "ts_us": self.wall0_us + s[2] // 1000,
                 "dur_us": s[3] // 1000, "depth": s[4],
                 **({"args": s[5]} if s[5] else {})}
                for s in self.spans if s[3] is not None
            ],
            "counters": dict(self.counters),
            "remote": list(self.remote),
            "dropped": self.dropped,
        }


def chrome_trace_events(snapshots: list[dict]) -> list[dict]:
    """Flatten trace snapshots into Chrome trace events. All local spans ride
    pid 1 / tid 1 (nesting is containment of [ts, ts+dur) intervals, which
    sequential loops preserve); each distinct remote process gets its own
    pid so Perfetto shows the cross-process hop as two aligned tracks."""
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 1,
         "args": {"name": "autoscaler"}},
    ]
    remote_pids: dict[str, int] = {}
    for snap in snapshots:
        tid_args = {"trace_id": snap["trace_id"]}
        for i, s in enumerate(snap.get("spans", ())):
            args = {**tid_args, **s.get("args", {})}
            if i == 0 and snap.get("counters"):
                args["counters"] = snap["counters"]
            events.append({
                "name": s["name"], "cat": s.get("cat") or "span", "ph": "X",
                "ts": s["ts_us"], "dur": max(s["dur_us"], 1),
                "pid": 1, "tid": 1, "args": args,
            })
        for group in snap.get("remote", ()):
            proc = group["process"]
            if proc not in remote_pids:
                remote_pids[proc] = 2 + len(remote_pids)
                events.append({"name": "process_name", "ph": "M",
                               "pid": remote_pids[proc], "tid": 1,
                               "args": {"name": proc}})
            for s in group["spans"]:
                events.append({
                    "name": s["name"], "cat": s.get("cat") or "span",
                    "ph": "X", "ts": s["ts_us"], "dur": max(s["dur_us"], 1),
                    "pid": remote_pids[proc], "tid": 1,
                    "args": {**tid_args, **s.get("args", {})},
                })
    return events


class TailSampler:
    """Tail-based per-request trace retention (the serving-side analog of
    the FlightRecorder's whole-ring dump).

    The FlightRecorder answers "what did the loops before the breach look
    like" by dumping everything; at serving rates (thousands of requests per
    second) retaining every request trace is a memory bomb and dumping the
    ring on each slow request is an I/O bomb. A tail sampler decides AFTER
    the request completes — when its latency and outcome are known — whether
    the full trace is worth keeping:

      * always-keep reasons: `failed`, `backpressure`, `slo_breach` — the
        requests a post-mortem starts from;
      * `slow`: e2e above the rolling slow-quantile estimate (a bounded
        reservoir of recent latencies; quantile re-estimated lazily), so the
        retained set tracks the CURRENT tail, not a static threshold;
      * everything else is dropped (only its latency feeds the reservoir).

    The retained set is a bounded ring with eviction accounting
    (`offered` / `retained` / `evicted` + per-reason counts), exportable as
    one Perfetto file (`to_chrome_trace`) or filtered per tenant
    (`tenant_traces`) for tenant-scoped SLO-breach dumps. `retain()` returns
    the trace id, which the caller attaches as the latency histogram
    bucket's EXEMPLAR — the link from a bad p99 in /metrics to a retained
    trace."""

    # quantile re-estimation stride: the threshold is recomputed from the
    # reservoir every K inserts, not per request — a sort per RPC would
    # serialize all handler threads on the sampler lock doing O(n log n)
    # of redundant work at serving rates
    REESTIMATE_EVERY = 16

    def __init__(self, capacity: int = 64, slow_quantile: float = 0.95,
                 reservoir: int = 512, min_observations: int = 32):
        self.capacity = max(int(capacity), 1)
        self.slow_quantile = float(slow_quantile)
        self.min_observations = int(min_observations)
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lat: deque[float] = deque(maxlen=int(reservoir))
        self._thresh: float | None = None       # cached slow threshold
        self._since_estimate = 0
        self._lock = threading.Lock()
        self.offered = 0
        self.retained = 0
        self.evicted = 0
        self.reasons: dict[str, int] = {}

    # ---- latency feed + slow classification ----

    def observe_latency(self, e2e_s: float) -> bool:
        """Feed the reservoir; True when `e2e_s` sits in the slow tail.
        Before `min_observations` latencies arrive nothing classifies as
        slow (a cold server would otherwise retain its first N requests
        and squat the budget on warmup compiles). The quantile threshold
        is re-estimated lazily, every REESTIMATE_EVERY inserts."""
        with self._lock:
            self._lat.append(float(e2e_s))
            if len(self._lat) < self.min_observations:
                return False
            self._since_estimate += 1
            if (self._thresh is None
                    or self._since_estimate >= self.REESTIMATE_EVERY):
                xs = sorted(self._lat)
                idx = min(int(len(xs) * self.slow_quantile), len(xs) - 1)
                self._thresh = xs[idx]
                self._since_estimate = 0
            return e2e_s >= self._thresh

    def offer(self, snapshot: dict, e2e_s: float,
              reason: str | None = None) -> str | None:
        """Offer one completed request's trace snapshot. `reason` is an
        always-keep override (`failed` / `backpressure` / `slo_breach`);
        with None the rolling quantile decides (`slow`). Returns the trace
        id when retained (→ exemplar), else None."""
        slow = self.observe_latency(e2e_s)
        with self._lock:
            self.offered += 1
            if reason is None and not slow:
                return None
            reason = reason or "slow"
            snap = dict(snapshot)
            snap["retain_reason"] = reason
            snap["e2e_s"] = float(e2e_s)
            if len(self._ring) == self._ring.maxlen:
                self.evicted += 1
            self._ring.append(snap)
            self.retained += 1
            self.reasons[reason] = self.reasons.get(reason, 0) + 1
            return snap.get("trace_id")

    # ---- export ----

    def traces(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def tenant_traces(self, tenant: str) -> list[dict]:
        """Only the retained traces whose request belonged to `tenant` —
        the tenant-scoped SLO-breach dump's content (never the whole
        ring)."""
        return [s for s in self.traces() if s.get("tenant") == tenant]

    def stats(self) -> dict:
        with self._lock:
            return {"offered": self.offered, "retained": self.retained,
                    "evicted": self.evicted, "held": len(self._ring),
                    "reasons": dict(self.reasons)}

    def to_chrome_trace(self, snaps: list[dict] | None = None) -> dict:
        snaps = self.traces() if snaps is None else snaps
        return {
            "traceEvents": chrome_trace_events(snaps),
            "otherData": {
                "sampler": self.stats(),
                "trace_ids": [s["trace_id"] for s in snaps],
                "retain_reasons": {s["trace_id"]: s.get("retain_reason", "")
                                   for s in snaps},
            },
        }

    def dump(self, path: str, snaps: list[dict] | None = None) -> str:
        doc = self.to_chrome_trace(snaps)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


class FlightRecorder:
    """Bounded ring of the last `capacity` loop traces (capacity 0 disables
    tracing entirely — StaticAutoscaler then never constructs a Tracer and
    the instrumentation sites take their no-tracer path).

    `record()` is the single entry: it snapshots the tracer into the ring
    and, when `dump_reason` is set and a `dump_dir` is configured, persists
    the WHOLE ring (the loops leading up to the event are exactly what a
    post-mortem needs) as one Perfetto file."""

    def __init__(self, capacity: int = 8, dump_dir: str = ""):
        self.capacity = max(int(capacity), 0)
        self.dump_dir = dump_dir
        self._ring: deque[dict] = deque(maxlen=self.capacity or 1)
        self._lock = threading.Lock()
        self.recorded = 0
        self.dumps = 0

    def record(self, tracer: Tracer, dump_reason: str = "") -> str | None:
        """Snapshot `tracer` into the ring; returns the dump path when a
        dump fired, else None."""
        if self.capacity == 0:
            return None
        snap = tracer.snapshot()
        if dump_reason:
            snap["dump_reason"] = dump_reason
        with self._lock:
            self._ring.append(snap)
            self.recorded += 1
        if dump_reason and self.dump_dir:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                path = os.path.join(
                    self.dump_dir, f"flight-{tracer.trace_id}.trace.json")
                return self.dump(path)
            except OSError:
                return None    # a full/readonly disk must never sink the loop
        return None

    def traces(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def to_chrome_trace(self) -> dict:
        snaps = self.traces()
        return {
            "traceEvents": chrome_trace_events(snaps),
            "otherData": {
                "recorded_total": self.recorded,
                "trace_ids": [s["trace_id"] for s in snaps],
                "dump_reasons": {s["trace_id"]: s["dump_reason"]
                                 for s in snaps if "dump_reason" in s},
            },
        }

    def dump(self, path: str) -> str:
        """Write the ring as one Chrome-trace JSON file; returns `path`."""
        doc = self.to_chrome_trace()
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)   # readers never observe a half-written dump
        with self._lock:
            self.dumps += 1
        return path
