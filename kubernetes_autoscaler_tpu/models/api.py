"""Host-side object model: the minimal k8s-shaped surface the framework consumes.

The reference consumes full k8s API objects via client-go informers
(cluster-autoscaler/utils/kubernetes/). This framework is standalone, so it
defines a lightweight structural equivalent carrying exactly the fields the
simulation semantics read (the vendored-scheduler plugin inputs distilled in
SURVEY.md §7): resources, labels, selectors, taints/tolerations, affinity,
ports, topology keys, ownership/priority/annotations for drain classification.

These objects are the *boundary* format; they are encoded once per loop into
dense tensors (models/encode.py) and never consulted inside jitted code.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# Taint effects (reference: k8s core/v1; consumed by TaintToleration filter).
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

# Well-known annotations the reference acts on
# (cluster-autoscaler/utils/drain/drain.go, simulator/drainability/rules/).
SAFE_TO_EVICT_KEY = "cluster-autoscaler.kubernetes.io/safe-to-evict"
SCALE_DOWN_DISABLED_KEY = "cluster-autoscaler.kubernetes.io/scale-down-disabled"
# Taints CA itself places (reference: utils/taints/taints.go).
TO_BE_DELETED_TAINT = "ToBeDeletedByClusterAutoscaler"
DELETION_CANDIDATE_TAINT = "DeletionCandidateOfClusterAutoscaler"
# Set by lowering passes (DRA selectored claims, shared claims) whose
# constraint is not dense-encodable: forces the winner-verification tier.
HOST_CHECK_ANNOTATION = "autoscaler.x-k8s.io/host-check"
# which lowering pass set host-check (each clears only its own mark)
DRA_LOSSY_ANNOTATION = "autoscaler.x-k8s.io/host-check-dra"
CSI_LOSSY_ANNOTATION = "autoscaler.x-k8s.io/host-check-csi"

# Well-known topology keys (k8s core/v1). The dense encoding supports these
# two domain kinds; other topology keys route through the host-check tier.
HOSTNAME_KEY = "kubernetes.io/hostname"
ZONE_KEY = "topology.kubernetes.io/zone"
ZONE_KEY_BETA = "failure-domain.beta.kubernetes.io/zone"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""                 # "" + Exists tolerates everything
    operator: str = "Equal"       # Equal | Exists
    value: str = ""
    effect: str = ""              # "" matches all effects


@dataclass(frozen=True)
class OwnerRef:
    kind: str = ""                # ReplicaSet | Job | DaemonSet | StatefulSet | Node(mirror) | ...
    name: str = ""
    uid: str = ""
    controller: bool = True


@dataclass
class AffinityTerm:
    """One required pod-(anti-)affinity term: selector over pod labels within a
    topology domain (reference: vendored InterPodAffinity filter semantics).

    `namespaces` empty means "the pod's own namespace" (k8s default) unless a
    `namespace_selector` is set, which selects namespaces by THEIR labels
    (reference: interpodaffinity/filtering.go:192 merges the selector into the
    namespace set using live Namespace objects; {} selects ALL namespaces).
    Evaluating it needs the cluster's namespace→labels map, so terms carrying
    one ride the host-check tier with the oracle given that map."""

    match_labels: dict[str, str] = field(default_factory=dict)
    topology_key: str = "kubernetes.io/hostname"
    namespaces: tuple[str, ...] = ()
    namespace_selector: Optional[dict[str, str]] = None


@dataclass
class TopologySpreadConstraint:
    """One `whenUnsatisfiable: DoNotSchedule` topologySpreadConstraint
    (reference: vendored PodTopologySpread filter semantics). An empty
    label_selector matches no pods (k8s semantics)."""

    max_skew: int = 1
    topology_key: str = "topology.kubernetes.io/zone"
    match_labels: dict[str, str] = field(default_factory=dict)
    # pod label keys whose (key, pod-value) pairs merge into the selector
    # (reference: podtopologyspread/common.go:96-104 mergeLabelSetWithSelector)
    match_label_keys: tuple[str, ...] = ()
    # global minimum becomes 0 while fewer domains exist than this
    # (filtering.go:54-67; nil → 1)
    min_domains: int = 1
    # node inclusion policies (common.go:42-56; defaults Honor / Ignore)
    node_affinity_policy: str = "Honor"    # Honor | Ignore
    node_taints_policy: str = "Ignore"     # Honor | Ignore

    def merged_selector(self, pod_labels: dict[str, str]) -> dict[str, str]:
        """match_labels + the pod's values for match_label_keys (a key absent
        from the pod contributes nothing — common.go:98-101)."""
        if not self.match_label_keys:
            return self.match_labels
        sel = dict(self.match_labels)
        for k in self.match_label_keys:
            if k in pod_labels:
                sel[k] = pod_labels[k]
        return sel


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str = "In"          # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: tuple[str, ...] = ()


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    # Sum of container requests, pre-aggregated (reference aggregates via
    # resourcehelpers; init-container max() rule applied by the caller/builder).
    requests: dict[str, float] = field(default_factory=dict)  # name -> amount (cpu in cores, memory in bytes)
    # spec.overhead (RuntimeClass pod overhead): ADDED to requests for every
    # fit decision (reference: noderesources/fit.go:299 — "resources defined
    # for Overhead should be added to the calculated Resource request sum")
    overhead: dict[str, float] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    # Single-term sugar: one ANDed requirement list. For the full k8s shape
    # (nodeSelectorTerms = OR of terms, each an AND of requirements) set
    # node_affinity_terms; when it is non-empty it supersedes this field.
    required_node_affinity: list[NodeSelectorRequirement] = field(default_factory=list)
    node_affinity_terms: list[list[NodeSelectorRequirement]] = field(default_factory=list)
    tolerations: list[Toleration] = field(default_factory=list)
    host_ports: tuple[tuple[int, str], ...] = ()              # (port, protocol)
    anti_affinity: list[AffinityTerm] = field(default_factory=list)
    pod_affinity: list[AffinityTerm] = field(default_factory=list)
    # Legacy single-constraint sugar (selector = the pod's own labels);
    # topology_spread supersedes both fields when non-empty.
    topology_spread_max_skew: int = 0                         # 0 = no constraint
    topology_spread_key: str = ""
    topology_spread: list[TopologySpreadConstraint] = field(default_factory=list)
    owner: Optional[OwnerRef] = None
    priority: int = 0
    node_name: str = ""                                       # scheduled destination ("" = pending)
    phase: str = "Pending"                                    # Pending|Running|Succeeded|Failed
    deletion_timestamp: Optional[float] = None
    # spec.terminationGracePeriodSeconds (None = kubelet default 30 s); the
    # actuator caps it by --max-graceful-termination-sec at eviction time
    termination_grace_s: Optional[float] = None
    restart_policy: str = "Always"
    volumes_with_local_storage: int = 0                       # emptyDir/hostPath count (drain rule)
    pvc_refs: tuple[str, ...] = ()
    # names of ResourceClaims this pod references beyond its owned (template)
    # claims — the shared-claim reference edge (reference:
    # pod.spec.resourceClaims; consumed by simulator/dynamicresources.py)
    resource_claims: tuple[str, ...] = ()

    def is_daemonset(self) -> bool:
        return self.owner is not None and self.owner.kind == "DaemonSet"

    def is_mirror(self) -> bool:
        return "kubernetes.io/config.mirror" in self.annotations

    def affinity_node_terms(self) -> list[list[NodeSelectorRequirement]]:
        """OR-of-AND nodeSelectorTerms (node_affinity_terms, or the single-term
        sugar wrapped). Empty list = no required node affinity."""
        if self.node_affinity_terms:
            return self.node_affinity_terms
        if self.required_node_affinity:
            return [self.required_node_affinity]
        return []

    def spread_constraints(self) -> list[TopologySpreadConstraint]:
        """All DoNotSchedule spread constraints, legacy sugar included (its
        selector is the pod's own labels — the dominant real-world shape)."""
        out = list(self.topology_spread)
        if not out and self.topology_spread_max_skew > 0:
            out.append(TopologySpreadConstraint(
                max_skew=self.topology_spread_max_skew,
                topology_key=self.topology_spread_key or "topology.kubernetes.io/zone",
                match_labels=dict(self.labels),
            ))
        return out


def is_recreatable(pod: "Pod") -> bool:
    """Will this pod's controller recreate it after an eviction?
    (reference: utils/pod/pod.go:65 FilterRecreatablePods — skip static,
    mirror and DaemonSet pods, including the
    cluster-autoscaler.kubernetes.io/daemonset-pod annotation form)."""
    if pod.is_mirror():
        return False
    src = pod.annotations.get("kubernetes.io/config.source")
    if src is not None and src != "api":     # static pod (IsStaticPod)
        return False
    if pod.is_daemonset() or pod.annotations.get(
            "cluster-autoscaler.kubernetes.io/daemonset-pod") == "true":
        return False
    return True


def labels_match(selector: dict[str, str], labels: dict[str, str]) -> bool:
    """match_labels subset test. An EMPTY selector matches no pods — both the
    spread and affinity encodings treat {} as 'selects nothing'."""
    if not selector:
        return False
    return all(labels.get(k) == v for k, v in selector.items())


def term_matches_pod(term: AffinityTerm, pod: "Pod", other: "Pod",
                     namespaces: dict[str, dict[str, str]] | None = None
                     ) -> bool:
    """Does `other` match `term` of `pod` (selector + namespace scoping)?

    `namespaces` maps namespace name → its labels, needed only when the term
    carries a namespace_selector (reference merges that selector into the
    namespace set from live Namespace objects, filtering.go:82,192). Without
    the map, a namespace_selector term matches conservatively: nothing — the
    dense/host tiers flag such terms needs_host_check and the control plane
    passes the map where the source provides one."""
    if term.namespace_selector is not None:
        if len(term.namespace_selector) == 0:
            # {} selects ALL namespaces (filtering.go:192 semantics) — no
            # namespace labels needed
            in_ns = True
        else:
            in_ns = other.namespace in term.namespaces
            if not in_ns and namespaces is not None:
                lbls = namespaces.get(other.namespace)
                in_ns = lbls is not None and labels_match(
                    term.namespace_selector, lbls)
        if not in_ns:
            return False
        return labels_match(term.match_labels, other.labels)
    scope = term.namespaces or (pod.namespace,)
    return other.namespace in scope and labels_match(term.match_labels, other.labels)


@dataclass
class Workload:
    """A replica-controller-shaped object (Deployment/ReplicaSet/Job/...).

    Consumed by processors/podinjection (reference: processors/podinjection
    reads Deployments/Jobs/ReplicaSets via listers) and by capacity-buffer
    scalable references (reference: capacitybuffer scalableRef translators)."""

    kind: str
    name: str
    namespace: str = "default"
    uid: str = ""
    replicas: int = 0
    template: Optional[Pod] = None


@dataclass
class Node:
    name: str
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    capacity: dict[str, float] = field(default_factory=dict)
    allocatable: dict[str, float] = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    ready: bool = True
    unschedulable: bool = False
    creation_time: float = 0.0
    provider_id: str = ""

    def zone(self) -> str:
        return self.labels.get("topology.kubernetes.io/zone", self.labels.get("failure-domain.beta.kubernetes.io/zone", ""))

    def alloc_or_cap(self) -> dict[str, float]:
        return self.allocatable if self.allocatable else self.capacity
