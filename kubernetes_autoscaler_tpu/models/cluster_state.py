"""Tensorized cluster state: the L2 state model as JAX pytrees.

Reference counterpart: cluster-autoscaler/simulator/framework/infos.go:57
(framework.NodeInfo/PodInfo wrapping the vendored scheduler's NodeInfo) plus the
DeltaSnapshotStore (simulator/clustersnapshot/store/delta.go:55). The reference
needs a layered-delta store because forking a pointer-graph snapshot is
expensive; here the whole cluster is a handful of dense arrays, so a "fork" is
just holding a reference to an immutable pytree and "commit" is a pointer swap
(see simulator/snapshot.py) — the delta machinery disappears by construction.

String-world constraints are lowered to int32 hash slots (utils/hashing.fold32),
padded with 0 (0 is reserved: never a valid hash). All per-pair predicate
checks in ops/predicates.py are exact over these tensors; anything the dense
encoding cannot express (rare: overflowing label counts, exotic affinity
operators) sets `needs_host_check` and is verified on the host for selected
winners only.

Pending pods are stored as *equivalence groups* (reference:
core/scaleup/equivalence/groups.go:40 — controller UID + spec hash) so the G
axis stays small even at 50k pending pods.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from flax import struct



@dataclass(frozen=True)
class Dims:
    """Static padding dims (compile-time shape bucket)."""

    max_labels: int = 64       # label-hash slots per node (2 per label: pair + key)
    max_taints: int = 6        # taint slots per node
    max_tolerations: int = 8   # toleration slots per pod group
    max_sel_terms: int = 6     # ANDed selector requirements per pod group
    max_sel_alts: int = 4      # OR alternatives inside one requirement (In v1..vk)
    max_neg_terms: int = 4     # NotIn/DoesNotExist hashes per pod group
    max_pod_ports: int = 4     # hostPorts per pod group
    max_node_ports: int = 16   # occupied hostPort slots per node
    max_aff_terms: int = 2     # (anti-)affinity terms per pod group
    max_zones: int = 16        # topology-zone slots (id 0 = "no zone"); more
                               # zones than this routes zone-scoped constraints
                               # through the host-check tier


DEFAULT_DIMS = Dims()


class NodeTensors(struct.PyTreeNode):
    """Dense per-node state, shape leading dim N (padded; `valid` masks real rows)."""

    cap: jax.Array           # i32[N, R] allocatable
    alloc: jax.Array         # i32[N, R] requested by resident pods
    label_hash: jax.Array    # i32[N, L] fold32("k=v") and fold32(key-marker) per label
    taint_exact: jax.Array   # i32[N, T] fold32(key\0value\0effect) for NoSchedule/NoExecute
    taint_key: jax.Array     # i32[N, T] fold32(key\0effect) (Exists-operator coverage)
    used_ports: jax.Array    # i32[N, NP] fold32("port/proto") occupied by resident pods
    zone_id: jax.Array       # i32[N] topology zone index (0 = unknown)
    group_id: jax.Array      # i32[N] node-group index (-1 = none)
    ready: jax.Array         # bool[N]
    schedulable: jax.Array   # bool[N] (= !node.spec.unschedulable && no ToBeDeleted taint)
    valid: jax.Array         # bool[N]

    @property
    def n(self) -> int:
        return self.cap.shape[0]

    def free(self) -> jax.Array:
        return self.cap - self.alloc


class PodGroupTensors(struct.PyTreeNode):
    """Pending-pod equivalence groups, shape leading dim G."""

    req: jax.Array           # i32[G, R]
    count: jax.Array         # i32[G] pods in the group
    sel_req: jax.Array       # i32[G, S, A] ANDed requirements, each an OR over alts (0-padded)
    sel_neg: jax.Array       # i32[G, Sn] hashes that must be absent from node labels
    tol_exact: jax.Array     # i32[G, Tl]
    tol_key: jax.Array       # i32[G, Tl]
    tolerate_all: jax.Array  # bool[G] ({key:"",op:Exists} toleration)
    port_hash: jax.Array     # i32[G, PP]
    anti_affinity_self: jax.Array  # bool[G] pod has self-anti-affinity on hostname
    valid: jax.Array         # bool[G]
    needs_host_check: jax.Array  # bool[G] encoding was lossy; verify winner on host
    # Topology-coupled constraints (None = encoded before these existed /
    # produced by a lowering path without them; kernels treat None as
    # unconstrained). Kinds: 0 = none, 1 = hostname-domain, 2 = zone-domain.
    spread_kind: jax.Array | None = None    # i32[G] topologySpreadConstraint kind
    max_skew: jax.Array | None = None       # i32[G]
    spread_self: jax.Array | None = None    # bool[G] spread selector matches own labels
    aff_kind: jax.Array | None = None       # i32[G] required pod-affinity kind
    aff_self: jax.Array | None = None       # bool[G] affinity selector matches self
    aff_match_any: jax.Array | None = None  # bool[G] >=1 resident matches the selector
    anti_self_zone: jax.Array | None = None  # bool[G] zone-scoped self anti-affinity

    @property
    def g(self) -> int:
        return self.req.shape[0]

    def one_per_node(self) -> jax.Array:
        """bool[G]: at most one pod of the group per node — hostname
        self-anti-affinity, or hostPorts (two siblings request the same
        ports and would conflict; reference: NodePorts filter applied
        pod-by-pod during the serial binpack, binpacking_estimator.go:163)."""
        return self.anti_affinity_self | (self.port_hash != 0).any(axis=-1)


class ScheduledPodTensors(struct.PyTreeNode):
    """Per-pod state for pods already placed on nodes (drain/scale-down path).

    Reference counterpart: NodeInfo.Pods (vendored scheduler) consumed by
    simulator/cluster.go:131 SimulateNodeRemoval. Re-scheduling a drained pod
    uses its group_ref to reuse the group-level predicate tensors.
    """

    req: jax.Array        # i32[Ps, R]
    node_idx: jax.Array   # i32[Ps] current node (-1 = none)
    group_ref: jax.Array  # i32[Ps] index into a PodGroupTensors for predicate data
    movable: jax.Array    # bool[Ps] drainability: evictable, must be rescheduled
    blocks: jax.Array     # bool[Ps] drainability: pod forbids draining its node
    valid: jax.Array      # bool[Ps]

    @property
    def p(self) -> int:
        return self.req.shape[0]


class NodeGroupTensors(struct.PyTreeNode):
    """Per-node-group scale-up template + limits, shape leading dim NG.

    Template rows mirror NodeGroup.TemplateNodeInfo (reference:
    cloudprovider/cloud_provider.go:180+, sanitized as in
    simulator/node_info_utils.go).
    """

    cap: jax.Array           # i32[NG, R]
    label_hash: jax.Array    # i32[NG, L]
    taint_exact: jax.Array   # i32[NG, T]
    taint_key: jax.Array     # i32[NG, T]
    zone_id: jax.Array       # i32[NG]
    max_new: jax.Array       # i32[NG] max nodes this group may still add (maxSize - targetSize)
    price_per_node: jax.Array  # f32[NG] (price expander input; 0 = unknown)
    valid: jax.Array         # bool[NG]

    @property
    def ng(self) -> int:
        return self.cap.shape[0]

    def as_node_tensors(self, dims: Dims) -> NodeTensors:
        """View each template as a (fresh, empty) node row — for predicate reuse."""
        ng = self.ng
        r = self.cap.shape[1]
        return NodeTensors(
            cap=self.cap,
            alloc=jnp.zeros((ng, r), jnp.int32),
            label_hash=self.label_hash,
            taint_exact=self.taint_exact,
            taint_key=self.taint_key,
            used_ports=jnp.zeros((ng, dims.max_node_ports), jnp.int32),
            zone_id=self.zone_id,
            group_id=jnp.arange(ng, dtype=jnp.int32),
            ready=jnp.ones((ng,), bool),
            schedulable=jnp.ones((ng,), bool),
            valid=self.valid,
        )


class AffinityPlanes(struct.PyTreeNode):
    """Resident-derived cross planes for the topology-coupled constraints.

    Counts of RESIDENT pods matching each pending group's selectors, per node.
    Computed once at encode time (models/encode.py) — the device aggregates
    zones from these on the fly (ops/constrained.py). The reference gets the
    same information by walking NodeInfo.Pods inside the vendored
    InterPodAffinity/PodTopologySpread plugins per (pod, node) check.
    """

    aff_cnt: jax.Array        # i32[G, N] residents matching g's pod-affinity term
    anti_host_cnt: jax.Array  # i32[G, N] matching g's hostname-scoped anti terms
    anti_zone_cnt: jax.Array  # i32[G, N] matching g's zone-scoped anti terms
    spread_cnt: jax.Array     # i32[G, N] matching g's spread selector

    @classmethod
    def zeros(cls, g: int, n: int) -> "AffinityPlanes":
        z = jnp.zeros((g, n), jnp.int32)
        return cls(aff_cnt=z, anti_host_cnt=z, anti_zone_cnt=z, spread_cnt=z)


class ClusterTensors(struct.PyTreeNode):
    """The full device-resident snapshot: one immutable pytree.

    Fork/commit/revert (reference clustersnapshot.go:43-105) degenerate to
    holding/swapping references to this value — see simulator/snapshot.py.
    """

    nodes: NodeTensors
    pending: PodGroupTensors
    scheduled: ScheduledPodTensors
    groups: NodeGroupTensors
    planes: AffinityPlanes | None = None


def pad_to(n: int, bucket: int = 64) -> int:
    """Round up to a shape bucket so recompilation is bounded (SURVEY.md §7
    'dynamic shapes' hard part — the reference has no analog; Go has no tracing)."""
    if n <= 0:
        return bucket
    return ((n + bucket - 1) // bucket) * bucket
