"""Host-side lowering: k8s-shaped objects → dense snapshot tensors.

This is the one-time-per-loop string→tensor boundary. Reference counterpart:
PredicateSnapshot.SetClusterState (simulator/clustersnapshot/predicate/
predicate_snapshot.go:72-120), which rebuilds NodeInfos from API objects each
loop; here the rebuild produces numpy arrays that are shipped to the TPU once
and then forked for free.

Encoding conventions (consumed by ops/predicates.py):
  * labels     — each node label (k,v) contributes fold32("k=v") and fold32("k\\x01")
                 (the key-marker enables Exists selectors).
  * selectors  — nodeSelector and required node-affinity lower to ANDed
                 requirements, each an OR over alternative pair hashes (In with
                 multiple values); NotIn/DoesNotExist lower to must-be-absent
                 hashes. Anything wider than the padding dims flags
                 needs_host_check instead of dropping a constraint.
  * taints     — exact item fold32("k\\0v\\0e") plus key item fold32("k\\0e");
                 a toleration covers a taint via the exact hash (Equal) or the
                 key hash (Exists). Empty-effect tolerations expand to both
                 NoSchedule and NoExecute. PreferNoSchedule never blocks
                 (scheduler semantics — it is a score, not a filter).
  * hostPorts  — fold32("port/proto"); conflict = any overlap with the node's
                 occupied-port set.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.models.api import (
    HOSTNAME_KEY,
    NO_EXECUTE,
    NO_SCHEDULE,
    TO_BE_DELETED_TAINT,
    ZONE_KEY,
    ZONE_KEY_BETA,
    AffinityTerm,
    Node,
    Pod,
    labels_match,
    term_matches_pod,
)
from kubernetes_autoscaler_tpu.models.cluster_state import (
    DEFAULT_DIMS,
    AffinityPlanes,
    Dims,
    NodeGroupTensors,
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
    pad_to,
)
from kubernetes_autoscaler_tpu.utils.hashing import fold32

_KEY_MARK = "\x01"


def _device(tree):
    """Ship a host-encoded pytree to the default device (jnp arrays)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(jnp.asarray, tree)


def _label_items(labels: dict[str, str]) -> list[int]:
    out = []
    for k, v in labels.items():
        out.append(fold32(f"{k}={v}"))
        out.append(fold32(k + _KEY_MARK))
    return out


def _taint_hashes(key: str, value: str, effect: str) -> tuple[int, int]:
    return fold32(f"{key}\0{value}\0{effect}"), fold32(f"{key}\0{effect}")


def _fill(row: np.ndarray, items: list[int]) -> bool:
    """Fill a padded int32 row; returns False on overflow (caller flags host check)."""
    k = min(len(items), row.shape[0])
    if k:
        row[:k] = np.array(items[:k], dtype=np.int32)
    return len(items) <= row.shape[0]


@dataclass
class ZoneTable:
    """Interns zone strings to small ids; id 0 is reserved for 'no zone'."""

    ids: dict[str, int] = field(default_factory=dict)

    def id_for(self, zone: str) -> int:
        if not zone:
            return 0
        if zone not in self.ids:
            self.ids[zone] = len(self.ids) + 1
        return self.ids[zone]


def pod_request_vector(
    pod: Pod, registry: res.ExtendedResourceRegistry
) -> tuple[np.ndarray, bool]:
    """Pod spec → (int32[R], lossy). Requests round up (resources.py convention).

    lossy=True when an extended resource did not fit the slot registry — the
    pod must then be verified host-side (needs_host_check)."""
    v = np.zeros((res.NUM_RESOURCES,), dtype=np.int64)
    v[res.PODS] = 1
    lossy = False
    # pod overhead adds to every fit decision (noderesources/fit.go:299)
    items = list(pod.requests.items()) + list(pod.overhead.items())
    for name, amount in items:
        if name == "cpu":
            v[res.CPU] += res.cpu_request_to_milli(amount)
        elif name == "memory":
            v[res.MEMORY] += res.mem_request_to_mib(amount)
        elif name == "ephemeral-storage":
            v[res.EPHEMERAL] += res.mem_request_to_mib(amount)
        else:
            slot = registry.try_slot_for(name)
            if slot is None:
                lossy = True
            else:
                v[slot] += int(np.ceil(amount))
    return v.astype(np.int32), lossy


def node_capacity_vector(node: Node, registry: res.ExtendedResourceRegistry) -> np.ndarray:
    """Node allocatable → int32[R]; capacities round down.

    Unmappable extended resources are dropped — the node simply offers less,
    which can only under-schedule (the conservative direction)."""
    v = np.zeros((res.NUM_RESOURCES,), dtype=np.int64)
    for name, amount in node.alloc_or_cap().items():
        if name == "cpu":
            v[res.CPU] = res.cpu_capacity_to_milli(amount)
        elif name == "memory":
            v[res.MEMORY] = res.mem_capacity_to_mib(amount)
        elif name == "ephemeral-storage":
            v[res.EPHEMERAL] = res.mem_capacity_to_mib(amount)
        elif name == "pods":
            v[res.PODS] = int(amount)
        else:
            slot = registry.try_slot_for(name)
            if slot is not None:
                v[slot] = int(amount)
    if v[res.PODS] == 0:
        v[res.PODS] = 110  # kubelet default max-pods
    return v.astype(np.int32)


@dataclass
class _PodSpecEncoding:
    sel_req: np.ndarray
    sel_neg: np.ndarray
    tol_exact: np.ndarray
    tol_key: np.ndarray
    tolerate_all: bool
    port_hash: np.ndarray
    anti_affinity_self: bool
    lossy: bool
    # topology-coupled constraints (kinds: 0 none, 1 hostname, 2 zone)
    spread_kind: int = 0
    max_skew: int = 0
    spread_self: bool = False
    spread_selector: dict[str, str] | None = None
    aff_kind: int = 0
    aff_self: bool = False
    aff_term: AffinityTerm | None = None
    anti_self_zone: bool = False
    anti_host_terms: list[AffinityTerm] = field(default_factory=list)
    anti_zone_terms: list[AffinityTerm] = field(default_factory=list)
    exemplar: Pod | None = None


def _domain_kind(topology_key: str) -> int:
    """1 = hostname domain, 2 = zone domain, 0 = not dense-encodable."""
    if topology_key == HOSTNAME_KEY:
        return 1
    if topology_key in (ZONE_KEY, ZONE_KEY_BETA):
        return 2
    return 0


def _encode_pod_spec(pod: Pod, dims: Dims) -> _PodSpecEncoding:
    from kubernetes_autoscaler_tpu.models.api import HOST_CHECK_ANNOTATION

    # lowering passes (DRA/CSI) flag constraints the dense encoding can't carry
    lossy = pod.annotations.get(HOST_CHECK_ANNOTATION) == "true"
    # --- selector terms (AND of ORs) ---
    sel_req = np.zeros((dims.max_sel_terms, dims.max_sel_alts), dtype=np.int32)
    sel_neg = np.zeros((dims.max_neg_terms,), dtype=np.int32)
    terms: list[list[int]] = [[fold32(f"{k}={v}")] for k, v in sorted(pod.node_selector.items())]
    negs: list[int] = []
    # NodeAffinity is OR-of-AND (nodeSelectorTerms); the dense AND-of-OR shape
    # carries a single term exactly. Multi-term OR lowers exactly in the
    # common shape where every term is ONE positive requirement — that IS a
    # single OR row (alternatives across keys). Anything wider is dropped
    # from the dense mask (over-admits — never silently blocks) and flagged
    # host-check; the oracle (utils/oracle.selector_matches) is exact there.
    affinity_terms = pod.affinity_node_terms()
    if len(affinity_terms) > 1:
        flat_alts: list[int] | None = []
        for term in affinity_terms:
            if (len(term) == 1 and term[0].operator in ("In", "Exists")
                    and flat_alts is not None):
                r0 = term[0]
                if r0.operator == "In":
                    flat_alts.extend(fold32(f"{r0.key}={v}") for v in r0.values)
                else:
                    flat_alts.append(fold32(r0.key + _KEY_MARK))
            else:
                flat_alts = None
        if flat_alts is not None and len(flat_alts) <= dims.max_sel_alts:
            terms.append(flat_alts)
        else:
            lossy = True
        affinity_terms = []
    for r in (affinity_terms[0] if affinity_terms else []):
        if r.operator == "In":
            terms.append([fold32(f"{r.key}={v}") for v in r.values])
        elif r.operator == "Exists":
            terms.append([fold32(r.key + _KEY_MARK)])
        elif r.operator == "DoesNotExist":
            negs.append(fold32(r.key + _KEY_MARK))
        elif r.operator == "NotIn":
            negs.extend(fold32(f"{r.key}={v}") for v in r.values)
        else:  # Gt/Lt: numeric label compare — host-check tier (oracle exact)
            lossy = True
    if len(terms) > dims.max_sel_terms or len(negs) > dims.max_neg_terms:
        lossy = True
    for i, alts in enumerate(terms[: dims.max_sel_terms]):
        if len(alts) > dims.max_sel_alts:
            lossy = True
        k = min(len(alts), dims.max_sel_alts)
        sel_req[i, :k] = np.array(alts[:k], dtype=np.int32)
    _fill(sel_neg, negs)

    # --- tolerations ---
    tol_exact = np.zeros((dims.max_tolerations,), dtype=np.int32)
    tol_key = np.zeros((dims.max_tolerations,), dtype=np.int32)
    tolerate_all = False
    ex, ky = [], []
    for t in pod.tolerations:
        effects = [t.effect] if t.effect else [NO_SCHEDULE, NO_EXECUTE]
        if t.operator == "Exists":
            if not t.key:
                # empty key = any taint key. With no effect it is the true
                # tolerate-everything flag. Scoped to NoSchedule/NoExecute the
                # dense encoding cannot express "any key of effect e" (taint
                # hashes are key-scoped) → over-admit + host-check (oracle is
                # exact). Scoped to PreferNoSchedule it covers no filterable
                # taint at all → ignore. Found by tests/test_predicate_fuzz.py.
                if not t.effect:
                    tolerate_all = True
                elif t.effect in (NO_SCHEDULE, NO_EXECUTE):
                    tolerate_all = True
                    lossy = True
                continue
            for e in effects:
                ky.append(fold32(f"{t.key}\0{e}"))
        else:
            for e in effects:
                ex.append(fold32(f"{t.key}\0{t.value}\0{e}"))
    if not (_fill(tol_exact, ex) and _fill(tol_key, ky)):
        lossy = True

    # --- host ports ---
    port_hash = np.zeros((dims.max_pod_ports,), dtype=np.int32)
    if not _fill(port_hash, [fold32(f"{p}/{proto or 'TCP'}") for p, proto in pod.host_ports]):
        lossy = True

    # --- inter-pod (anti-)affinity + topology spread: the dense path covers
    #     hostname- and zone-domain terms via resident-count planes
    #     (AffinityPlanes) and placement-coupled waves (ops/constrained.py);
    #     other topology keys / extra terms go through the host-check tier
    #     (SURVEY.md §7 hard part: these break pods×nodes independence). ---
    enc = _PodSpecEncoding(
        sel_req, sel_neg, tol_exact, tol_key, tolerate_all, port_hash,
        anti_affinity_self=False, lossy=lossy, exemplar=pod,
    )
    for term in pod.anti_affinity:
        kind = _domain_kind(term.topology_key)
        if kind == 0:
            enc.lossy = True
            continue
        if term.namespace_selector is not None:
            # namespace-by-labels scoping needs the Namespace world — the
            # dense planes under-count (conservative: over-admits) and the
            # winner rides the host-check tier with the namespaces map
            enc.lossy = True
        self_match = term_matches_pod(term, pod, pod)
        if kind == 1:
            enc.anti_affinity_self = enc.anti_affinity_self or self_match
            enc.anti_host_terms.append(term)
        else:
            enc.anti_self_zone = enc.anti_self_zone or self_match
            enc.anti_zone_terms.append(term)

    if pod.pod_affinity:
        if len(pod.pod_affinity) > 1:
            enc.lossy = True
        term = pod.pod_affinity[0]
        if term.namespace_selector is not None:
            enc.lossy = True
        kind = _domain_kind(term.topology_key)
        if kind == 0:
            enc.lossy = True
        else:
            enc.aff_kind = kind
            enc.aff_term = term
            enc.aff_self = term_matches_pod(term, pod, pod)

    spreads = pod.spread_constraints()
    if spreads:
        if len(spreads) > 1:
            enc.lossy = True  # first constraint enforced densely; rest host-checked
        c = spreads[0]
        kind = _domain_kind(c.topology_key)
        if kind == 0:
            enc.lossy = True
        else:
            enc.spread_kind = kind
            enc.max_skew = max(int(c.max_skew), 1)
            # matchLabelKeys lowers EXACTLY: the merged selector is static
            # per pod (common.go:96-104)
            sel = c.merged_selector(pod.labels)
            enc.spread_selector = dict(sel)
            enc.spread_self = labels_match(sel, pod.labels)
            # knobs the dense kernel does not model (it assumes the default
            # policies: affinity Honor via s_elig, taints Ignore; and a
            # global minimum over currently-populated domains ≡ minDomains=1)
            # → exact host-check tier
            if (int(c.min_domains) > 1
                    or c.node_affinity_policy == "Ignore"
                    or c.node_taints_policy == "Honor"):
                enc.lossy = True
    return enc


def resident_plane_hits(
    enc_row: _PodSpecEncoding, q: Pod
) -> tuple[int, int, int, int]:
    """One resident pod's contribution to group `enc_row`'s constraint planes:
    (aff_cnt, anti_host_cnt, anti_zone_cnt, spread_cnt) 0/1 hits. Shared by
    the full encode (summed over all residents) and the incremental encoder
    (applied as ±1 deltas on resident add/remove)."""
    ex = enc_row.exemplar
    if ex is None:
        return (0, 0, 0, 0)
    aff = int(enc_row.aff_term is not None
              and term_matches_pod(enc_row.aff_term, ex, q))
    anti_h = int(any(term_matches_pod(t, ex, q) for t in enc_row.anti_host_terms))
    anti_z = int(any(term_matches_pod(t, ex, q) for t in enc_row.anti_zone_terms))
    spread = int(enc_row.spread_selector is not None
                 and q.namespace == ex.namespace
                 and labels_match(enc_row.spread_selector, q.labels))
    return (aff, anti_h, anti_z, spread)


def cross_group_hostcheck(
    row_encodings: list[tuple[np.ndarray, _PodSpecEncoding]],
    pending_rows: list[int],
) -> set[int]:
    """Rows whose constraint selectors match pods of a DIFFERENT pending group:
    their placements couple mid-pack, which the device does not model →
    host-check tier. Shared by encode_cluster and the incremental encoder."""
    out: set[int] = set()
    for grow in pending_rows:
        enc_g = row_encodings[grow][1]
        ex_g = enc_g.exemplar
        if ex_g is None:
            continue
        selectors: list[tuple[AffinityTerm | None, dict[str, str] | None]] = []
        if enc_g.spread_kind:
            selectors.append((None, enc_g.spread_selector))
        selectors.extend(
            (t, None) for t in enc_g.anti_host_terms + enc_g.anti_zone_terms)
        if enc_g.aff_term is not None and not enc_g.aff_self:
            # positive affinity satisfiable only by ANOTHER pending group's
            # placements: not modeled on device → host-check tier
            selectors.append((enc_g.aff_term, None))
        if not selectors:
            continue
        for hrow in pending_rows:
            if hrow == grow:
                continue
            ex_h = row_encodings[hrow][1].exemplar
            if ex_h is None:
                continue
            for term, sel in selectors:
                if term is not None:
                    hit = term_matches_pod(term, ex_g, ex_h)
                else:
                    hit = (ex_h.namespace == ex_g.namespace
                           and labels_match(sel or {}, ex_h.labels))
                if hit:
                    out.add(grow)
                    break
            if grow in out:
                break
    return out


def apply_zone_overflow(enc: _PodSpecEncoding, zones_fit: bool) -> None:
    """When the cluster has more zones than Dims.max_zones, zone-scoped
    constraints cannot ride the dense planes: drop the zone coupling and flag
    host-check (the oracle is exact there). Shared with the incremental path."""
    uses_zones = (enc.spread_kind == 2 or enc.aff_kind == 2
                  or enc.anti_self_zone or enc.anti_zone_terms)
    if uses_zones and not zones_fit:
        enc.lossy = True
        if enc.spread_kind == 2:
            enc.spread_kind = 0
        if enc.aff_kind == 2:
            enc.aff_kind = 0
        enc.anti_self_zone = False
        enc.anti_zone_terms = []


def equivalence_key(pod: Pod) -> int:
    """Pods with equal keys are schedulable-equivalent (reference:
    core/scaleup/equivalence/groups.go:40 — controller UID + drop-irrelevant-
    fields spec hash). We hash the predicate-relevant spec directly."""
    parts = [
        pod.namespace,
        # labels matter to equivalence now: they are the targets of affinity/
        # spread selectors and decide self-matching
        repr(sorted(pod.labels.items())),
        repr(sorted(pod.requests.items())),
        repr(sorted(pod.overhead.items())),
        repr(sorted(pod.node_selector.items())),
        repr([[(r.key, r.operator, tuple(r.values)) for r in term]
              for term in pod.affinity_node_terms()]),
        repr([(t.key, t.operator, t.value, t.effect) for t in pod.tolerations]),
        repr(pod.host_ports),
        repr([(sorted(t.match_labels.items()), t.topology_key, t.namespaces,
               sorted(t.namespace_selector.items())
               if t.namespace_selector is not None else None)
              for t in pod.anti_affinity]),
        repr([(sorted(t.match_labels.items()), t.topology_key, t.namespaces,
               sorted(t.namespace_selector.items())
               if t.namespace_selector is not None else None)
              for t in pod.pod_affinity]),
        repr([(c.max_skew, c.topology_key, sorted(c.match_labels.items()),
               c.match_label_keys, c.min_domains,
               c.node_affinity_policy, c.node_taints_policy)
              for c in pod.spread_constraints()]),
        pod.owner.uid if pod.owner else pod.name,
    ]
    return fold32("|".join(parts))


def encode_node_row(
    nd: Node,
    registry: res.ExtendedResourceRegistry,
    zone_table: ZoneTable,
    dims: Dims,
) -> dict[str, np.ndarray | int | bool]:
    """Encode one node into its tensor row pieces (shared by encode_cluster and
    the snapshot's incremental add-node path, simulator/snapshot.py)."""
    label_hash = np.zeros((dims.max_labels,), np.int32)
    taint_exact = np.zeros((dims.max_taints,), np.int32)
    taint_key = np.zeros((dims.max_taints,), np.int32)
    if not _fill(label_hash, _label_items(nd.labels)):
        # Losing label hashes would create false "does not match" — the one
        # direction the encoding contract forbids. Fail fast; the caller
        # re-encodes with a larger Dims.max_labels.
        raise ValueError(
            f"node {nd.name!r}: {len(nd.labels)} labels overflow "
            f"Dims.max_labels={dims.max_labels} (2 slots per label)"
        )
    tx, tk = [], []
    blocked = False
    for t in nd.taints:
        if t.effect not in (NO_SCHEDULE, NO_EXECUTE):
            continue  # PreferNoSchedule: score-only, never filters
        if t.key == TO_BE_DELETED_TAINT:
            blocked = True
        e, k = _taint_hashes(t.key, t.value, t.effect)
        tx.append(e)
        tk.append(k)
    if not (_fill(taint_exact, tx) and _fill(taint_key, tk)):
        # Losing a taint would silently ADMIT intolerant pods — fail fast.
        raise ValueError(
            f"node {nd.name!r}: {len(tx)} filterable taints overflow "
            f"Dims.max_taints={dims.max_taints}"
        )
    return {
        "cap": node_capacity_vector(nd, registry),
        "label_hash": label_hash,
        "taint_exact": taint_exact,
        "taint_key": taint_key,
        "zone_id": zone_table.id_for(nd.zone()),
        "ready": nd.ready,
        "schedulable": not nd.unschedulable and not blocked,
    }


@dataclass
class EncodedCluster:
    """Host handle for one encoded snapshot: tensors + name/index maps."""

    nodes: NodeTensors
    specs: PodGroupTensors          # spec table; `count` counts PENDING pods per row
    scheduled: ScheduledPodTensors  # resident pods, group_ref → specs row
    node_names: list[str]
    node_index: dict[str, int]
    zone_table: ZoneTable
    registry: res.ExtendedResourceRegistry
    dims: Dims
    group_pods: list[list[int]]     # specs row → indices into `pending_pods`
    pending_pods: list[Pod]
    scheduled_pods: list[Pod]
    planes: AffinityPlanes | None = None
    has_constraints: bool = False   # any group carries a topology-coupled
                                    # constraint (selects the constrained
                                    # kernel variants — a STATIC choice)
    node_objs: list[Node] = field(default_factory=list)
    # namespace name → labels (from the source's Namespace objects, when it
    # provides them) — makes affinity namespace_selector terms exact in the
    # host-check tier (reference merges the selector into the namespace set
    # from live Namespace objects, interpodaffinity/filtering.go:192)
    namespaces: dict[str, dict[str, str]] | None = None
    # pre-device numpy arrays, keyed "section.field" — kept so the incremental
    # encoder (models/incremental.py) can seed its mirrors without a device
    # round-trip (device readback over the TPU tunnel is ~70 ms/sync)
    host_arrays: dict | None = None
    # id(device array) per key AT HANDOUT: a mirror may only substitute for
    # a device read while the tensor is still the handed-out object — the
    # loop REPLACES tensors (placement charging, upcoming-node injection,
    # drainability) and the mirrors do not follow those
    host_mirror_token: dict | None = None

    def all_nodes_and_pods(self) -> tuple[list[Node], dict[str, list[Pod]]]:
        """Host view for the exact oracle (utils/oracle.check_pod_in_cluster).

        None entries are slot/row holes left by the incremental encoder
        (freed scheduled slots / removed nodes) — skipped."""
        by_node: dict[str, list[Pod]] = {}
        for p in self.scheduled_pods:
            if p is not None and p.node_name:
                by_node.setdefault(p.node_name, []).append(p)
        return [nd for nd in self.node_objs if nd is not None], by_node


def encode_cluster(
    nodes: list[Node],
    pods: list[Pod],
    registry: res.ExtendedResourceRegistry | None = None,
    dims: Dims = DEFAULT_DIMS,
    node_group_ids: dict[str, int] | None = None,
    node_bucket: int = 64,
    group_bucket: int = 64,
    pod_bucket: int = 256,
    namespaces: dict[str, dict[str, str]] | None = None,
) -> EncodedCluster:
    """Lower a (nodes, pods) world into one EncodedCluster.

    Pods with node_name set and a live node become `scheduled` rows and charge
    their node's alloc/ports; the rest become pending equivalence groups.
    """
    registry = registry or res.ExtendedResourceRegistry()
    zone_table = ZoneTable()
    node_group_ids = node_group_ids or {}

    node_index = {nd.name: i for i, nd in enumerate(nodes)}
    # Terminal pods neither charge capacity nor ask for it (reference: the
    # kube listers feeding RunOnce filter Succeeded/Failed, and drainability's
    # terminal rule skips them — utils/kubernetes + drainability/rules/terminal).
    live = [p for p in pods if p.phase not in ("Succeeded", "Failed")]
    pending = [p for p in live if not p.node_name or p.node_name not in node_index]
    resident = [p for p in live if p.node_name in node_index]

    # ---- nodes ----
    n_pad = pad_to(len(nodes), node_bucket)
    r = res.NUM_RESOURCES
    cap = np.zeros((n_pad, r), np.int32)
    alloc = np.zeros((n_pad, r), np.int32)
    label_hash = np.zeros((n_pad, dims.max_labels), np.int32)
    taint_exact = np.zeros((n_pad, dims.max_taints), np.int32)
    taint_key = np.zeros((n_pad, dims.max_taints), np.int32)
    used_ports = np.zeros((n_pad, dims.max_node_ports), np.int32)
    zone_id = np.zeros((n_pad,), np.int32)
    group_id = np.full((n_pad,), -1, np.int32)
    ready = np.zeros((n_pad,), bool)
    schedulable = np.zeros((n_pad,), bool)
    valid = np.zeros((n_pad,), bool)

    for i, nd in enumerate(nodes):
        row = encode_node_row(nd, registry, zone_table, dims)
        cap[i] = row["cap"]
        label_hash[i] = row["label_hash"]
        taint_exact[i] = row["taint_exact"]
        taint_key[i] = row["taint_key"]
        zone_id[i] = row["zone_id"]
        group_id[i] = node_group_ids.get(nd.name, -1)
        ready[i] = row["ready"]
        schedulable[i] = row["schedulable"]
        valid[i] = True

    # ---- resident pods: charge alloc + ports; collect spec rows ----
    spec_rows: dict[int, int] = {}       # equivalence key -> specs row
    row_encodings: list[tuple[np.ndarray, _PodSpecEncoding]] = []
    row_pending_count: list[int] = []
    group_pods: list[list[int]] = []

    def row_for(pod: Pod) -> int:
        key = equivalence_key(pod)
        if key not in spec_rows:
            spec_rows[key] = len(row_encodings)
            req, req_lossy = pod_request_vector(pod, registry)
            spec = _encode_pod_spec(pod, dims)
            spec.lossy = spec.lossy or req_lossy
            row_encodings.append((req, spec))
            row_pending_count.append(0)
            group_pods.append([])
        return spec_rows[key]

    p_pad = pad_to(len(resident), pod_bucket)
    s_req = np.zeros((p_pad, r), np.int32)
    s_node = np.full((p_pad,), -1, np.int32)
    s_group = np.zeros((p_pad,), np.int32)
    s_movable = np.zeros((p_pad,), bool)
    s_blocks = np.zeros((p_pad,), bool)
    s_valid = np.zeros((p_pad,), bool)
    node_port_lists: dict[int, list[int]] = {}

    for j, pod in enumerate(resident):
        ni = node_index[pod.node_name]
        req, _ = pod_request_vector(pod, registry)
        alloc[ni] += req
        for p, proto in pod.host_ports:
            node_port_lists.setdefault(ni, []).append(fold32(f"{p}/{proto or 'TCP'}"))
        s_req[j] = req
        s_node[j] = ni
        s_group[j] = row_for(pod)
        # Conservative default: every resident pod blocks draining until the
        # drainability rules (simulator/drainability/rules.py) classify it —
        # an unclassified snapshot must never report nodes as freely drainable.
        s_blocks[j] = True
        s_valid[j] = True
    for ni, ports in node_port_lists.items():
        if not _fill(used_ports[ni], ports):
            # Losing an occupied port would admit conflicting pods — fail fast.
            raise ValueError(
                f"node index {ni}: {len(ports)} occupied hostPorts overflow "
                f"Dims.max_node_ports={dims.max_node_ports}"
            )

    # ---- pending pods → groups ----
    for idx, pod in enumerate(pending):
        row = row_for(pod)
        row_pending_count[row] += 1
        group_pods[row].append(idx)

    g_pad = pad_to(max(len(row_encodings), 1), group_bucket)
    g_req = np.zeros((g_pad, r), np.int32)
    g_count = np.zeros((g_pad,), np.int32)
    g_sel_req = np.zeros((g_pad, dims.max_sel_terms, dims.max_sel_alts), np.int32)
    g_sel_neg = np.zeros((g_pad, dims.max_neg_terms), np.int32)
    g_tol_exact = np.zeros((g_pad, dims.max_tolerations), np.int32)
    g_tol_key = np.zeros((g_pad, dims.max_tolerations), np.int32)
    g_tol_all = np.zeros((g_pad,), bool)
    g_ports = np.zeros((g_pad, dims.max_pod_ports), np.int32)
    g_anti_self = np.zeros((g_pad,), bool)
    g_valid = np.zeros((g_pad,), bool)
    g_hostcheck = np.zeros((g_pad,), bool)
    g_spread_kind = np.zeros((g_pad,), np.int32)
    g_max_skew = np.zeros((g_pad,), np.int32)
    g_spread_self = np.zeros((g_pad,), bool)
    g_aff_kind = np.zeros((g_pad,), np.int32)
    g_aff_self = np.zeros((g_pad,), bool)
    g_aff_any = np.zeros((g_pad,), bool)
    g_anti_self_zone = np.zeros((g_pad,), bool)

    # Zone-scoped constraints need every zone to fit the static Z dim; when
    # the cluster has more zones, those groups fall back to host-check (the
    # oracle is exact) and the device drops the zone coupling.
    zones_fit = len(zone_table.ids) + 1 <= dims.max_zones

    for row, (req, enc) in enumerate(row_encodings):
        g_req[row] = req
        g_count[row] = row_pending_count[row]
        g_sel_req[row] = enc.sel_req
        g_sel_neg[row] = enc.sel_neg
        g_tol_exact[row] = enc.tol_exact
        g_tol_key[row] = enc.tol_key
        g_tol_all[row] = enc.tolerate_all
        g_ports[row] = enc.port_hash
        g_anti_self[row] = enc.anti_affinity_self
        g_valid[row] = True
        apply_zone_overflow(enc, zones_fit)
        g_spread_kind[row] = enc.spread_kind
        g_max_skew[row] = enc.max_skew
        g_spread_self[row] = enc.spread_self
        g_aff_kind[row] = enc.aff_kind
        g_aff_self[row] = enc.aff_self
        g_anti_self_zone[row] = enc.anti_self_zone
        g_hostcheck[row] = enc.lossy

    # ---- cross-group coupling: a selector of group g matching pods of a
    # DIFFERENT pending group is not modeled on device (placements of h would
    # change g's constraint state mid-pack) -> host-check tier. ----
    pending_rows = [row for row in range(len(row_encodings))
                    if row_pending_count[row] > 0]
    for grow in cross_group_hostcheck(row_encodings, pending_rows):
        g_hostcheck[grow] = True

    # ---- resident-derived constraint planes ----
    constrained_rows = [
        row for row, (_, enc) in enumerate(row_encodings)
        if (enc.spread_kind or enc.aff_kind or enc.anti_host_terms
            or enc.anti_zone_terms)
    ]
    p_aff = np.zeros((g_pad, n_pad), np.int32)
    p_anti_host = np.zeros((g_pad, n_pad), np.int32)
    p_anti_zone = np.zeros((g_pad, n_pad), np.int32)
    p_spread = np.zeros((g_pad, n_pad), np.int32)
    if constrained_rows:
        for q in resident:
            ni = node_index[q.node_name]
            for row in constrained_rows:
                aff, anti_h, anti_z, spread = resident_plane_hits(
                    row_encodings[row][1], q)
                p_aff[row, ni] += aff
                p_anti_host[row, ni] += anti_h
                p_anti_zone[row, ni] += anti_z
                p_spread[row, ni] += spread
        g_aff_any[:] = p_aff.sum(axis=1) > 0
    has_constraints = bool(constrained_rows)

    # token values are the ARRAY OBJECTS (compared with `is`): holding the
    # reference also pins it, so a freed array's address can never be reused
    # by a different array that would spuriously match (id() would be unsafe)
    host_arrays = {
        "nodes.cap": cap, "nodes.alloc": alloc, "nodes.label_hash": label_hash,
        "nodes.taint_exact": taint_exact, "nodes.taint_key": taint_key,
        "nodes.used_ports": used_ports, "nodes.zone_id": zone_id,
        "nodes.group_id": group_id, "nodes.ready": ready,
        "nodes.schedulable": schedulable, "nodes.valid": valid,
        "specs.req": g_req, "specs.count": g_count, "specs.sel_req": g_sel_req,
        "specs.sel_neg": g_sel_neg, "specs.tol_exact": g_tol_exact,
        "specs.tol_key": g_tol_key, "specs.tolerate_all": g_tol_all,
        "specs.port_hash": g_ports, "specs.anti_affinity_self": g_anti_self,
        "specs.valid": g_valid, "specs.needs_host_check": g_hostcheck,
        "specs.spread_kind": g_spread_kind, "specs.max_skew": g_max_skew,
        "specs.spread_self": g_spread_self, "specs.aff_kind": g_aff_kind,
        "specs.aff_self": g_aff_self, "specs.aff_match_any": g_aff_any,
        "specs.anti_self_zone": g_anti_self_zone,
        "scheduled.req": s_req, "scheduled.node_idx": s_node,
        "scheduled.group_ref": s_group, "scheduled.movable": s_movable,
        "scheduled.blocks": s_blocks, "scheduled.valid": s_valid,
        "planes.aff_cnt": p_aff, "planes.anti_host_cnt": p_anti_host,
        "planes.anti_zone_cnt": p_anti_zone, "planes.spread_cnt": p_spread,
    }

    out_nodes = _device(NodeTensors(
        cap=cap, alloc=alloc, label_hash=label_hash, taint_exact=taint_exact,
        taint_key=taint_key, used_ports=used_ports, zone_id=zone_id,
        group_id=group_id, ready=ready, schedulable=schedulable, valid=valid,
    ))
    out_specs = _device(PodGroupTensors(
        req=g_req, count=g_count, sel_req=g_sel_req, sel_neg=g_sel_neg,
        tol_exact=g_tol_exact, tol_key=g_tol_key, tolerate_all=g_tol_all,
        port_hash=g_ports, anti_affinity_self=g_anti_self, valid=g_valid,
        needs_host_check=g_hostcheck,
        spread_kind=g_spread_kind, max_skew=g_max_skew,
        spread_self=g_spread_self, aff_kind=g_aff_kind, aff_self=g_aff_self,
        aff_match_any=g_aff_any, anti_self_zone=g_anti_self_zone,
    ))
    out_sched = _device(ScheduledPodTensors(
        req=s_req, node_idx=s_node, group_ref=s_group, movable=s_movable,
        blocks=s_blocks, valid=s_valid,
    ))
    out_planes = _device(AffinityPlanes(
        aff_cnt=p_aff, anti_host_cnt=p_anti_host,
        anti_zone_cnt=p_anti_zone, spread_cnt=p_spread,
    ))
    return EncodedCluster(
        nodes=out_nodes,
        specs=out_specs,
        scheduled=out_sched,
        node_names=[nd.name for nd in nodes],
        node_index=node_index,
        zone_table=zone_table,
        registry=registry,
        dims=dims,
        group_pods=group_pods,
        pending_pods=pending,
        scheduled_pods=resident,
        planes=out_planes,
        has_constraints=has_constraints,
        node_objs=list(nodes),
        namespaces=namespaces,
        host_arrays=host_arrays,
        host_mirror_token=mirror_token(out_nodes, out_specs, out_sched,
                                       out_planes),
    )


def mirror_token(nodes_t, specs_t, sched_t, planes_t) -> dict:
    """host_mirror_token over EVERY mirrored field (derived from the same
    field sets both encode paths use — no hand-maintained key list)."""
    out: dict = {}
    for section, tree in (("nodes", nodes_t), ("specs", specs_t),
                          ("scheduled", sched_t), ("planes", planes_t)):
        for f, arr in vars(tree).items():
            if arr is not None and not f.startswith("_"):
                out[f"{section}.{f}"] = arr
    return out


def encode_node_groups(
    templates: list[tuple[Node, int, float]],
    registry: res.ExtendedResourceRegistry,
    zone_table: ZoneTable,
    dims: Dims = DEFAULT_DIMS,
    bucket: int = 8,
    daemonsets: list | None = None,
) -> NodeGroupTensors:
    """Lower node-group templates (template node, max_new, price/node) to tensors.

    Reference: MixedTemplateNodeInfoProvider (processors/nodeinfosprovider)
    produces a NodeInfo per group; sanitization (simulator/node_info_utils.go)
    is mirrored by the caller passing a clean template Node.

    `daemonsets` (Workloads of kind DaemonSet) charge their matching pods'
    requests against each template's capacity row — the reference builds
    template NodeInfos WITH their DS pods (node_info_utils.go:45 via
    daemonset.go:39), so every simulated new node starts DS-loaded.
    """
    ng_pad = pad_to(max(len(templates), 1), bucket)
    r = res.NUM_RESOURCES
    cap = np.zeros((ng_pad, r), np.int32)
    label_hash = np.zeros((ng_pad, dims.max_labels), np.int32)
    taint_exact = np.zeros((ng_pad, dims.max_taints), np.int32)
    taint_key = np.zeros((ng_pad, dims.max_taints), np.int32)
    zone_id = np.zeros((ng_pad,), np.int32)
    max_new = np.zeros((ng_pad,), np.int32)
    price = np.zeros((ng_pad,), np.float32)
    valid = np.zeros((ng_pad,), bool)
    for i, (tmpl, mx, pr) in enumerate(templates):
        cap[i] = node_capacity_vector(tmpl, registry)
        if daemonsets:
            from kubernetes_autoscaler_tpu.utils.daemonset import (
                daemonset_overhead,
            )

            cap[i] = np.maximum(
                cap[i] - daemonset_overhead(tmpl, daemonsets, registry), 0)
        _fill(label_hash[i], _label_items(tmpl.labels))
        tx, tk = [], []
        for t in tmpl.taints:
            if t.effect not in (NO_SCHEDULE, NO_EXECUTE):
                continue
            e, k = _taint_hashes(t.key, t.value, t.effect)
            tx.append(e)
            tk.append(k)
        _fill(taint_exact[i], tx)
        _fill(taint_key[i], tk)
        zone_id[i] = zone_table.id_for(tmpl.zone())
        max_new[i] = mx
        price[i] = pr
        valid[i] = True
    return _device(NodeGroupTensors(
        cap=cap, label_hash=label_hash, taint_exact=taint_exact, taint_key=taint_key,
        zone_id=zone_id, max_new=max_new, price_per_node=price, valid=valid,
    ))
