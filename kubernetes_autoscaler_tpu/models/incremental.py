"""Incremental tensor-snapshot maintenance: the per-loop delta encoder.

Reference counterpart: the DeltaSnapshotStore's whole reason to exist
(simulator/clustersnapshot/store/delta.go:33-54) — the reference avoids
rebuilding its scheduler NodeInfo graph every loop because loop-to-loop
cluster drift is tiny. Here the same argument applies one level down: the
string→tensor lowering (models/encode.py) costs O(pods) Python work per call
(equivalence hashing, spec lowering), which at 50k pods dominates the entire
200 ms RunOnce budget. This module maintains the encoded tensors ACROSS
loops and re-lowers only what changed.

Design:
  * Host mirrors — every tensor of the EncodedCluster is kept as a canonical
    numpy array on the host. Deltas mutate mirrors in place.
  * Device cache — the corresponding jax arrays are cached per field and
    re-uploaded only when dirty. Small deltas ship as device-side scatters
    (`cached.at[idx].set(rows)`) so the tunnel carries kilobytes, not the
    multi-megabyte scheduled/label planes, per loop.
  * Diff, not events — the ClusterDataSource contract stays list_nodes/
    list_pods. Unchanged pods are detected by OBJECT IDENTITY plus a cheap
    mutable-field check (node_name, phase): the k8s object model replaces
    objects on update (new resourceVersion ⇒ new object), which informer-fed
    sources and FakeCluster both honor. Sources that rebuild every object
    each loop still get correct results — every pod just re-encodes (full
    encode_cluster cost, no worse than before).
  * Append-only rows — removed nodes leave invalid ghost rows; equivalence
    rows persist at count 0. A periodic full resync (`resync_loops`)
    compacts. This mirrors the snapshot's own ghost-row convention
    (simulator/snapshot.py remove_node).

Correctness contract: after any sequence of deltas the produced
EncodedCluster is SEMANTICALLY equal to a fresh encode_cluster +
apply_drainability of the same world — same per-name node rows, same
per-pod scheduled state, same equivalence-group content (up to row
numbering), same planes counts. tests/test_incremental_encode.py
property-tests exactly this under randomized churn.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from kubernetes_autoscaler_tpu.models import resources as res
from kubernetes_autoscaler_tpu.models.api import Node, Pod
from kubernetes_autoscaler_tpu.models.cluster_state import (
    DEFAULT_DIMS,
    AffinityPlanes,
    Dims,
    NodeTensors,
    PodGroupTensors,
    ScheduledPodTensors,
    pad_to,
)
from kubernetes_autoscaler_tpu.models.encode import (
    EncodedCluster,
    _encode_pod_spec,
    apply_zone_overflow,
    cross_group_hostcheck,
    encode_cluster,
    encode_node_row,
    equivalence_key,
    pod_request_vector,
    node_capacity_vector,
    resident_plane_hits,
)
from kubernetes_autoscaler_tpu.models.world_store import DevicePlaneStore
from kubernetes_autoscaler_tpu.simulator.drainability.rules import (
    DrainOptions,
    Verdict,
    apply_drainability,
    classify_pod,
    owner_replica_counts,
)
from kubernetes_autoscaler_tpu.utils.canonical import node_fp as _node_fp
from kubernetes_autoscaler_tpu.utils.hashing import fold32

_TERMINAL = ("Succeeded", "Failed")

_NODE_FIELDS = ("cap", "alloc", "label_hash", "taint_exact", "taint_key",
                "used_ports", "zone_id", "group_id", "ready", "schedulable",
                "valid")
_SPEC_FIELDS = ("req", "count", "sel_req", "sel_neg", "tol_exact", "tol_key",
                "tolerate_all", "port_hash", "anti_affinity_self", "valid",
                "needs_host_check", "spread_kind", "max_skew", "spread_self",
                "aff_kind", "aff_self", "aff_match_any", "anti_self_zone")
_SCHED_FIELDS = ("req", "node_idx", "group_ref", "movable", "blocks", "valid")
_PLANE_FIELDS = ("aff_cnt", "anti_host_cnt", "anti_zone_cnt", "spread_cnt")


@dataclass(slots=True)
class _PodRec:
    pod: Pod
    key: tuple[str, str]
    node_name: str | None
    phase: str
    state: str              # "resident" | "pending"
    row: int
    slot: int               # scheduled slot when resident, else -1
    seen: int
    req: np.ndarray
    ports: list[int]
    # deletion_timestamp is mutated in place by control-plane-style sinks
    # just like node_name/phase, and drives the long-terminating drain rule
    # — it belongs in the mutable-field diff (r4 advisor)
    deletion_ts: float | None = None


@dataclass(slots=True)
class _NodeRec:
    node: Node
    idx: int
    fp: tuple
    gid: int


_STD_RES = {0: "cpu", 1: "memory", 2: "ephemeral", 3: "pods"}


def _res_sig(vec, registry) -> tuple:
    inv = {v: k for k, v in registry.slots.items()}
    out = {}
    for i, val in enumerate(np.asarray(vec).tolist()):
        if val:
            out[_STD_RES.get(i) or inv.get(i, f"slot{i}")] = int(val)
    return tuple(sorted(out.items()))


def _nz_sig(a) -> tuple:
    return tuple(sorted(int(x) for x in np.asarray(a).ravel() if x != 0))


def _row_sig(h, row, registry, with_count=True) -> tuple:
    sel = tuple(sorted(
        tuple(sorted(int(x) for x in r if x != 0))
        for r in np.asarray(h["specs.sel_req"][row])
        if any(x != 0 for x in r)
    ))
    sig = (
        _res_sig(h["specs.req"][row], registry), sel,
        _nz_sig(h["specs.sel_neg"][row]), _nz_sig(h["specs.tol_exact"][row]),
        _nz_sig(h["specs.tol_key"][row]), bool(h["specs.tolerate_all"][row]),
        _nz_sig(h["specs.port_hash"][row]),
        bool(h["specs.anti_affinity_self"][row]),
        bool(h["specs.needs_host_check"][row]),
        int(h["specs.spread_kind"][row]), int(h["specs.max_skew"][row]),
        bool(h["specs.spread_self"][row]), int(h["specs.aff_kind"][row]),
        bool(h["specs.aff_self"][row]), bool(h["specs.aff_match_any"][row]),
        bool(h["specs.anti_self_zone"][row]),
    )
    if with_count:
        sig = sig + (int(h["specs.count"][row]),)
    return sig


def semantic_view(enc: EncodedCluster) -> dict:
    """Canonical, row-permutation- and hash-interning-independent view of an
    EncodedCluster — the encoder's correctness contract is that the
    incremental result's view equals a fresh encode's view (module
    docstring). Shared by the churn property test and the runtime
    --incremental-verify-loops check."""
    h = enc.host_arrays
    reg = enc.registry
    inv_zone = {v: k for k, v in enc.zone_table.ids.items()}

    nodes = {}
    for name, i in enc.node_index.items():
        nodes[name] = (
            _res_sig(h["nodes.cap"][i], reg), _res_sig(h["nodes.alloc"][i], reg),
            _nz_sig(h["nodes.label_hash"][i]), _nz_sig(h["nodes.taint_exact"][i]),
            _nz_sig(h["nodes.taint_key"][i]), _nz_sig(h["nodes.used_ports"][i]),
            inv_zone.get(int(h["nodes.zone_id"][i]), ""),
            int(h["nodes.group_id"][i]),
            bool(h["nodes.ready"][i]), bool(h["nodes.schedulable"][i]),
            bool(h["nodes.valid"][i]),
        )

    sched = {}
    live_rows = set()
    # slot/valid coherence is part of the contract: an occupied slot is
    # valid and a freed slot dropped its pod (the scale-down planner's
    # vectorized exemplar scan, planner._exemplars_and_fp, selects slots by
    # the valid mirror alone and would silently mis-marshal on a desync).
    # Reported as view content — NOT asserted — so the sampled runtime
    # verify treats a desync like any other divergence: log + resync,
    # not a hard loop failure.
    n_slots = min(len(enc.scheduled_pods), h["scheduled.valid"].shape[0])
    slot_desync = tuple(
        j for j in range(n_slots)
        if (enc.scheduled_pods[j] is not None)
        != bool(h["scheduled.valid"][j]))
    for j, p in enumerate(enc.scheduled_pods):
        if p is None or not bool(h["scheduled.valid"][j]):
            continue
        row = int(h["scheduled.group_ref"][j])
        live_rows.add(row)
        ni = int(h["scheduled.node_idx"][j])
        sched[(p.namespace, p.name)] = (
            _res_sig(h["scheduled.req"][j], reg),
            enc.node_names[ni],
            bool(h["scheduled.movable"][j]), bool(h["scheduled.blocks"][j]),
            _row_sig(h, row, reg, with_count=False),
        )

    pend = {}
    for row, idxs in enumerate(enc.group_pods):
        for i in idxs:
            p = enc.pending_pods[i]
            pend[(p.namespace, p.name)] = _row_sig(h, row, reg)
            live_rows.add(row)

    planes = {}
    for row in live_rows if "planes.aff_cnt" in h else ():
        sig = _row_sig(h, row, reg, with_count=False)
        for f in ("aff_cnt", "anti_host_cnt", "anti_zone_cnt", "spread_cnt"):
            arr = h[f"planes.{f}"][row]
            for i in np.nonzero(np.asarray(arr))[0]:
                i = int(i)
                name = enc.node_names[i] if i < len(enc.node_names) else f"?{i}"
                k = (sig, f, name)
                planes[k] = planes.get(k, 0) + int(arr[i])
    return {"nodes": nodes, "sched": sched, "pend": pend, "planes": planes,
            "slot_desync": {j: True for j in slot_desync}}


def semantic_diff(a: EncodedCluster, b: EncodedCluster) -> str | None:
    """None when semantically equal, else a description of the first
    diverging part (keys only — values can be large)."""
    va, vb = semantic_view(a), semantic_view(b)
    for part in ("nodes", "sched", "pend", "planes", "slot_desync"):
        if va[part] != vb[part]:
            only_a = {k for k, v in va[part].items() if vb[part].get(k) != v}
            only_b = {k for k, v in vb[part].items() if va[part].get(k) != v}
            return (f"{part} diverged: incremental-only/changed="
                    f"{sorted(map(str, only_a))[:8]} fresh-only/changed="
                    f"{sorted(map(str, only_b))[:8]}")
    return None


class IncrementalEncoder:
    """Maintains one EncodedCluster across control-loop iterations."""

    def __init__(
        self,
        registry: res.ExtendedResourceRegistry | None = None,
        dims: Dims = DEFAULT_DIMS,
        node_bucket: int = 64,
        group_bucket: int = 64,
        pod_bucket: int = 256,
        drain_opts: DrainOptions = DrainOptions(),
        resync_loops: int = 0,
        verify_loops: int = 0,
    ):
        self.registry = registry or res.ExtendedResourceRegistry()
        self.dims = dims
        self.node_bucket = node_bucket
        self.group_bucket = group_bucket
        self.pod_bucket = pod_bucket
        self.drain_opts = drain_opts
        self.resync_loops = resync_loops
        # --incremental-verify-loops: every N loops, diff the maintained
        # tensors against a fresh encode; a mismatch means the SOURCE broke
        # the replace-on-update contract (in-place mutation of dicts the
        # id()-based fingerprints watch) — make that loud, not stale
        self.verify_loops = verify_loops
        self.verify_failures = 0
        self.last_verify_error: str | None = None
        self.loops = 0
        self.full_encodes = 0       # observability: forced/initial full builds
        # device residency layer (models/world_store.DevicePlaneStore): the
        # per-plane device shadow + dirty tracking + scatter/replace upload
        # path, with h2d byte accounting — the WorldStore wrapper reads its
        # per-loop delta-program record to classify the encode mode
        self.device_store = DevicePlaneStore()
        # why the last full encode ran (WorldStore's `cause` label):
        # initial | fingerprint_miss | shape_overflow | forced
        self.last_full_cause: str | None = None
        self.grew_this_loop = False    # any plane crossed its padded bucket
        self._invalidated = False
        self._invalidate_cause = "fingerprint_miss"
        self._seeded = False
        self._seq = 0

    # ------------------------------------------------------------------ API

    def invalidate(self, cause: str = "fingerprint_miss") -> None:
        """Force the next encode() to full-rebuild. The control plane calls
        this when an out-of-band lowering pass (DRA/CSI) mutated the SAME
        Node/Pod objects in place — a change object-identity diffing cannot
        see (the snapshots' content_key comparison drives this) — and the
        backend supervisor calls it with cause="device_lost" when the
        digest probe found resident device planes diverged from (or no
        longer backing) their host mirrors after a backend incident."""
        self._seeded = False
        self._invalidated = True
        self._invalidate_cause = cause

    def encode(
        self,
        nodes: list[Node],
        pods: list[Pod],
        node_group_ids: dict[str, int] | None = None,
        now: float | None = None,
        pdb_namespaced_names: frozenset = frozenset(),
        namespaces: dict[str, dict[str, str]] | None = None,
    ) -> EncodedCluster:
        self.loops += 1
        self.grew_this_loop = False
        node_group_ids = node_group_ids or {}
        self._namespaces = namespaces
        if (not self._seeded
                or (self.resync_loops and self.loops % self.resync_loops == 0)):
            cause = ("initial" if self.full_encodes == 0
                     else self._invalidate_cause if self._invalidated
                     else "forced")
            return self._full(nodes, pods, node_group_ids, now,
                              pdb_namespaced_names, cause=cause)
        try:
            self._apply_diff(nodes, pods, node_group_ids, now,
                             pdb_namespaced_names)
        except _ResyncNeeded as e:
            return self._full(nodes, pods, node_group_ids, now,
                              pdb_namespaced_names, cause=e.reason)
        except Exception:
            # an exception mid-diff (e.g. hostPort/dims overflow) leaves the
            # mirrors half-mutated — poison the state so the NEXT loop full-
            # rebuilds instead of silently diffing from corruption, and let
            # the error surface exactly as encode_cluster would
            self._seeded = False
            raise
        enc = self._handout()
        if self.verify_loops and self.loops % self.verify_loops == 0:
            enc = self._verify_or_resync(enc, nodes, pods, node_group_ids,
                                         now, pdb_namespaced_names)
        return enc

    def _verify_or_resync(self, enc, nodes, pods, node_group_ids, now,
                          pdb_names) -> EncodedCluster:
        """Sampled contract check: semantic diff vs a fresh encode. On a
        mismatch, record the error, force a resync, and return the CORRECT
        encoding for this loop — a violation must never ship stale verdicts
        (round-4 verdict Weak #4: the id()-fingerprint contract was
        unverifiable at runtime)."""
        fresh = encode_cluster(
            nodes, pods, registry=self.registry, dims=self.dims,
            node_group_ids=node_group_ids, node_bucket=self.node_bucket,
            group_bucket=self.group_bucket, pod_bucket=self.pod_bucket,
            namespaces=self._namespaces,
        )
        apply_drainability(fresh, self.drain_opts, now=now,
                           pdb_namespaced_names=pdb_names)
        diff = semantic_diff(enc, fresh)
        if diff is None:
            return enc
        self.verify_failures += 1
        self.last_verify_error = diff
        import logging

        logging.getLogger(__name__).error(
            "incremental-encode contract violation (source mutated objects "
            "in place?) — forcing resync: %s", diff)
        self._seeded = False
        return self._full(nodes, pods, node_group_ids, now, pdb_names,
                          cause="fingerprint_miss")

    # ----------------------------------------------------------- full build

    def _full(self, nodes, pods, node_group_ids, now, pdb_names,
              cause: str = "forced") -> EncodedCluster:
        self.full_encodes += 1
        self.last_full_cause = cause
        self._invalidated = False
        enc = encode_cluster(
            nodes, pods, registry=self.registry, dims=self.dims,
            node_group_ids=node_group_ids, node_bucket=self.node_bucket,
            group_bucket=self.group_bucket, pod_bucket=self.pod_bucket,
            namespaces=self._namespaces,
        )
        # mirrors: own copies (device arrays must never alias a mutating mirror)
        self._m = {k: v.copy() for k, v in enc.host_arrays.items()}
        # seed the device store from the arrays encode_cluster ALREADY
        # uploaded (identical content) — re-uploading the multi-MB planes a
        # second time would double the seed-loop tunnel cost. Only the
        # drainability verdicts (classified below, after this seed) differ:
        # they stay UNseeded so the handout replaces them wholesale.
        devs: dict[str, object] = {}
        for section, tree in (("nodes", enc.nodes), ("specs", enc.specs),
                              ("scheduled", enc.scheduled),
                              ("planes", enc.planes)):
            for f in {"nodes": _NODE_FIELDS, "specs": _SPEC_FIELDS,
                      "scheduled": _SCHED_FIELDS,
                      "planes": _PLANE_FIELDS}[section]:
                devs[f"{section}.{f}"] = getattr(tree, f)
        unseeded = ("scheduled.movable", "scheduled.blocks")
        for key in unseeded:
            devs.pop(key, None)
        self.device_store.seed(
            devs,
            seed_bytes=sum(int(v.nbytes) for k, v in self._m.items()
                           if k not in unseeded))
        for key in unseeded:
            self.device_store.mark_all(key)

        self.zone_table = enc.zone_table
        self._zones_fit = (len(self.zone_table.ids) + 1 <= self.dims.max_zones)
        self._registry_slots = len(self.registry.slots)

        # --- node bookkeeping ---
        self._node_names: list[str] = list(enc.node_names)
        self._node_index: dict[str, int] = dict(enc.node_index)
        self._node_objs: list[Node | None] = list(enc.node_objs)
        self._node_recs: dict[str, _NodeRec] = {}
        for nd in nodes:
            i = self._node_index[nd.name]
            self._node_recs[nd.name] = _NodeRec(
                nd, i, _node_fp(nd), node_group_ids.get(nd.name, -1))

        # --- equivalence rows: rebuild key→row + per-row spec encodings ---
        s_group = self._m["scheduled.group_ref"]
        n_rows = int(self._m["specs.valid"].sum())
        exemplars: dict[int, Pod] = {}
        for row, idxs in enumerate(enc.group_pods):
            if idxs:
                exemplars[row] = enc.pending_pods[idxs[0]]
        for j, p in enumerate(enc.scheduled_pods):
            r = int(s_group[j])
            exemplars.setdefault(r, p)
        self._spec_rows: dict[int, int] = {}
        self._row_encodings: list = [None] * n_rows
        self._base_lossy: list[bool] = [False] * n_rows
        self._row_pending: list[int] = [0] * n_rows
        self._constrained_rows: set[int] = set()
        for row in range(n_rows):
            ex = exemplars.get(row)
            if ex is None:
                continue  # padding row (single empty-world sentinel)
            self._register_row_encoding(row, ex)
        self._n_rows = n_rows

        # --- pod records ---
        self._pods: dict[tuple[str, str], _PodRec] = {}
        self._by_id: dict[int, _PodRec] = {}
        self._slot_recs: list[_PodRec | None] = [None] * self._m[
            "scheduled.valid"].shape[0]
        self._free_slots: list[int] = []
        self._slots_by_node: dict[int, set[int]] = {}
        self._node_ports: dict[int, list[int]] = {}
        self._waiting: dict[str, set[tuple[str, str]]] = {}
        self._deletion_ts_keys: set[tuple[str, str]] = set()
        s_req = self._m["scheduled.req"]
        for j, p in enumerate(enc.scheduled_pods):
            rec = _PodRec(
                pod=p, key=(p.namespace, p.name), node_name=p.node_name,
                phase=p.phase, state="resident", row=int(s_group[j]), slot=j,
                seen=self._seq, req=s_req[j].copy(),
                ports=[fold32(f"{pt}/{proto or 'TCP'}")
                       for pt, proto in p.host_ports],
                deletion_ts=p.deletion_timestamp,
            )
            self._pods[rec.key] = rec
            self._by_id[id(p)] = rec
            self._slot_recs[j] = rec
            ni = int(self._m["scheduled.node_idx"][j])
            self._slots_by_node.setdefault(ni, set()).add(j)
            if rec.ports:
                self._node_ports.setdefault(ni, []).extend(rec.ports)
            if p.deletion_timestamp is not None:
                self._deletion_ts_keys.add(rec.key)
        self._n_slots = len(enc.scheduled_pods)
        for row, idxs in enumerate(enc.group_pods):
            self._row_pending[row] = len(idxs)
        pend_row: dict[int, int] = {}
        for row, idxs in enumerate(enc.group_pods):
            for i in idxs:
                pend_row[i] = row
        for i, p in enumerate(enc.pending_pods):
            rec = _PodRec(
                pod=p, key=(p.namespace, p.name), node_name=p.node_name,
                phase=p.phase, state="pending", row=pend_row[i], slot=-1,
                seen=self._seq, req=None, ports=[],
                deletion_ts=p.deletion_timestamp,
            )
            self._pods[rec.key] = rec
            self._by_id[id(p)] = rec
            if p.node_name:  # bound to a node the snapshot doesn't know
                self._waiting.setdefault(p.node_name, set()).add(rec.key)
            if p.deletion_timestamp is not None:
                self._deletion_ts_keys.add(rec.key)

        # --- drainability into mirrors (replaces apply_drainability) ---
        self._pdb_names = frozenset(pdb_names)
        # owner live-pod counts for the replicacount rule (--min-replica-count;
        # maintained only when the rule is active — zero cost by default)
        self._owner_counts: dict[str, int] = {}
        self._owner_keys: dict[str, set] = {}
        if self.drain_opts.min_replica_count > 0:
            self._owner_counts = owner_replica_counts(
                enc.scheduled_pods, enc.pending_pods)
            for rec in self._pods.values():
                if rec.pod.owner is not None:
                    self._owner_keys.setdefault(
                        rec.pod.owner.uid, set()).add(rec.key)
        for j, p in enumerate(enc.scheduled_pods):
            self._classify_slot(j, p, now)
        self._pending_lists_dirty = False
        self._cached_pending = list(enc.pending_pods)
        self._cached_group_pods = [list(x) for x in enc.group_pods]
        self._seeded = True
        return self._handout()

    def _register_row_encoding(self, row: int, exemplar: Pod) -> None:
        """(Re)derive the host-side spec encoding for an equivalence row."""
        req, req_lossy = pod_request_vector(exemplar, self.registry)
        spec = _encode_pod_spec(exemplar, self.dims)
        spec.lossy = spec.lossy or req_lossy
        apply_zone_overflow(spec, self._zones_fit)
        while len(self._row_encodings) <= row:
            self._row_encodings.append(None)
            self._base_lossy.append(False)
            self._row_pending.append(0)
        self._row_encodings[row] = (req, spec)
        self._base_lossy[row] = bool(spec.lossy)
        self._spec_rows[equivalence_key(exemplar)] = row
        if (spec.spread_kind or spec.aff_kind or spec.anti_host_terms
                or spec.anti_zone_terms):
            self._constrained_rows.add(row)

    # ------------------------------------------------------------- diff pass

    def _apply_diff(self, nodes, pods, node_group_ids, now, pdb_names) -> None:
        self._seq += 1
        seq = self._seq
        self._pending_rows_changed = False

        # --- nodes first (adds make targets available; updates are in-place) ---
        added_nodes: list[str] = []
        node_hits = 0
        for nd in nodes:
            rec = self._node_recs.get(nd.name)
            gid = node_group_ids.get(nd.name, -1)
            if rec is not None:
                node_hits += 1
                fp = _node_fp(nd)
                if rec.node is not nd or fp != rec.fp:
                    self._update_node(rec, nd, fp)
                if gid != rec.gid:
                    self._m["nodes.group_id"][rec.idx] = gid
                    self._mark("nodes.group_id", rec.idx)
                    rec.gid = gid
            else:
                self._add_node(nd, gid)
                added_nodes.append(nd.name)
        if node_hits + len(added_nodes) != len(nodes):
            raise _ResyncNeeded  # duplicate node names — malformed source
        if len(self._node_recs) != len(nodes):
            current = {nd.name for nd in nodes}
            for name in [n for n in self._node_recs if n not in current]:
                self._remove_node(self._node_recs[name])
        # EncodedCluster invariant (encode_cluster line 1): node row i IS
        # nodes[i] of the source list — the planner indexes enc rows by list
        # position. Removals leave ghost rows; compact them away before the
        # handout so the invariant holds every loop.
        if (len(self._node_names) != len(nodes)
                or any(self._node_recs[nd.name].idx != i
                       for i, nd in enumerate(nodes))):
            self._realign_nodes(nodes)

        # --- pods ---
        hits = 0
        changed: list[tuple[_PodRec | None, Pod | None]] = []
        by_id = self._by_id
        pods_map = self._pods
        new_keys: set[tuple[str, str]] = set()
        for p in pods:
            rec = by_id.get(id(p))
            if rec is not None and rec.pod is p:
                if rec.seen == seq:
                    raise _ResyncNeeded  # same pod listed twice
                rec.seen = seq
                hits += 1
                if (rec.node_name != p.node_name or rec.phase != p.phase
                        or rec.deletion_ts != p.deletion_timestamp):
                    changed.append((rec, p))
                continue
            key = (p.namespace, p.name)
            rec = pods_map.get(key)
            if rec is not None:
                if rec.seen == seq:
                    raise _ResyncNeeded  # duplicate pod key — malformed source
                rec.seen = seq
                hits += 1
                changed.append((rec, p))   # object replaced → re-lower
            elif p.phase not in _TERMINAL:
                if key in new_keys:
                    raise _ResyncNeeded    # two new pods share a key
                new_keys.add(key)
                changed.append((None, p))  # new pod
        if hits < len(pods_map):
            for rec in [r for r in pods_map.values() if r.seen != seq]:
                changed.append((rec, None))

        for rec, p in changed:
            self._transition(rec, p, now)

        # --- newly added nodes adopt the pods that were waiting for them ---
        for name in added_nodes:
            for key in list(self._waiting.get(name, ())):
                rec = self._pods.get(key)
                if rec is not None:
                    self._transition(rec, rec.pod, now)

        # --- registry slot growth: refresh node capacity rows (defensive;
        #     a new slot normally implies no existing node offered it) ---
        if len(self.registry.slots) != self._registry_slots:
            self._registry_slots = len(self.registry.slots)
            for nrec in self._node_recs.values():
                self._m["nodes.cap"][nrec.idx] = node_capacity_vector(
                    nrec.node, self.registry)
                self._mark("nodes.cap", nrec.idx)

        # --- PDB churn: reclassify affected residents ---
        pdb_names = frozenset(pdb_names)
        if pdb_names != self._pdb_names:
            flipped = self._pdb_names ^ pdb_names
            self._pdb_names = pdb_names   # classification sees the new set
            for nm in flipped:
                ns, _, name = nm.partition("/")
                rec = self._pods.get((ns, name))
                if rec is not None and rec.state == "resident":
                    self._classify_slot(rec.slot, rec.pod, now)

        # --- time-sensitive drainability (long-terminating rule) ---
        for key in list(self._deletion_ts_keys):
            rec = self._pods.get(key)
            if rec is None:
                self._deletion_ts_keys.discard(key)
            elif rec.state == "resident":
                self._classify_slot(rec.slot, rec.pod, now)

        # --- cross-group coupling (pending-row set or membership changed) ---
        if self._pending_rows_changed:
            self._recompute_coupling()

    # ------------------------------------------------------ pod transitions

    def _transition(self, rec: _PodRec | None, p: Pod | None, now) -> None:
        """Move one pod between absent/pending/resident states."""
        # tear down current state
        if rec is not None:
            if rec.state == "resident":
                self._remove_resident(rec)
            else:
                self._remove_pending(rec)
            if p is None or p.phase in _TERMINAL:
                self._owner_adjust(rec, -1, now)
                del self._pods[rec.key]
                self._by_id.pop(id(rec.pod), None)
                self._deletion_ts_keys.discard(rec.key)
                return
            if rec.pod is not p:         # object replaced → spec may differ
                self._owner_adjust(rec, -1, now)
                self._by_id.pop(id(rec.pod), None)
                self._by_id[id(p)] = rec
                rec.pod = p
                rec.req = None           # forces re-derivation below
                rec.row = -1
                self._owner_adjust(rec, +1, now)
        else:
            if p is None or p.phase in _TERMINAL:
                return
            rec = _PodRec(pod=p, key=(p.namespace, p.name), node_name=None,
                          phase=p.phase, state="pending", row=-1, slot=-1,
                          seen=self._seq, req=None, ports=[])
            self._pods[rec.key] = rec
            self._by_id[id(p)] = rec
            self._owner_adjust(rec, +1, now)
        rec.seen = self._seq
        rec.phase = p.phase
        rec.node_name = p.node_name
        rec.deletion_ts = p.deletion_timestamp
        if p.deletion_timestamp is not None:
            self._deletion_ts_keys.add(rec.key)
        if rec.row < 0:
            rec.row = self._row_for(p)
        ni = self._node_index.get(p.node_name, -1) if p.node_name else -1
        if ni >= 0:
            self._add_resident(rec, ni, now)
        else:
            self._add_pending(rec)

    def _row_for(self, pod: Pod) -> int:
        key = equivalence_key(pod)
        row = self._spec_rows.get(key)
        if row is not None:
            return row
        row = self._n_rows
        self._n_rows += 1
        g_pad = self._m["specs.valid"].shape[0]
        if row >= g_pad:
            self._grow_specs(pad_to(row + 1, self.group_bucket))
        self._register_row_encoding(row, pod)
        req, spec = self._row_encodings[row]
        m = self._m
        m["specs.req"][row] = req
        m["specs.count"][row] = 0
        m["specs.sel_req"][row] = spec.sel_req
        m["specs.sel_neg"][row] = spec.sel_neg
        m["specs.tol_exact"][row] = spec.tol_exact
        m["specs.tol_key"][row] = spec.tol_key
        m["specs.tolerate_all"][row] = spec.tolerate_all
        m["specs.port_hash"][row] = spec.port_hash
        m["specs.anti_affinity_self"][row] = spec.anti_affinity_self
        m["specs.valid"][row] = True
        m["specs.needs_host_check"][row] = self._base_lossy[row]
        m["specs.spread_kind"][row] = spec.spread_kind
        m["specs.max_skew"][row] = spec.max_skew
        m["specs.spread_self"][row] = spec.spread_self
        m["specs.aff_kind"][row] = spec.aff_kind
        m["specs.aff_self"][row] = spec.aff_self
        m["specs.anti_self_zone"][row] = spec.anti_self_zone
        for f in _SPEC_FIELDS:
            self._mark(f"specs.{f}", row)
        if row in self._constrained_rows:
            # plane row over ALL current residents (rare: new constrained kind)
            for nrec in (r for r in self._pods.values()
                         if r.state == "resident"):
                self._bump_planes_one(row, nrec, +1)
            self._pending_rows_changed = True
        return row

    # resident/pending state plumbing ------------------------------------

    def _add_resident(self, rec: _PodRec, ni: int, now) -> None:
        if rec.req is None:
            rec.req = pod_request_vector(rec.pod, self.registry)[0]
            rec.ports = [fold32(f"{pt}/{proto or 'TCP'}")
                         for pt, proto in rec.pod.host_ports]
        slot = self._free_slots.pop() if self._free_slots else None
        if slot is None:
            slot = self._n_slots
            self._n_slots += 1
            if slot >= self._m["scheduled.valid"].shape[0]:
                self._grow_scheduled(pad_to(slot + 1, self.pod_bucket))
        rec.state, rec.slot = "resident", slot
        self._slot_recs[slot] = rec
        m = self._m
        m["scheduled.req"][slot] = rec.req
        m["scheduled.node_idx"][slot] = ni
        m["scheduled.group_ref"][slot] = rec.row
        m["scheduled.valid"][slot] = True
        for f in ("req", "node_idx", "group_ref", "valid"):
            self._mark(f"scheduled.{f}", slot)
        self._classify_slot(slot, rec.pod, now)
        m["nodes.alloc"][ni] += rec.req
        self._mark("nodes.alloc", ni)
        self._slots_by_node.setdefault(ni, set()).add(slot)
        if rec.ports:
            self._node_ports.setdefault(ni, []).extend(rec.ports)
            self._refresh_ports(ni)
        for row in self._constrained_rows:
            self._bump_planes_row(row, rec, ni, +1)

    def _remove_resident(self, rec: _PodRec) -> None:
        slot = rec.slot
        ni = int(self._m["scheduled.node_idx"][slot])
        m = self._m
        m["scheduled.valid"][slot] = False
        m["scheduled.movable"][slot] = False
        m["scheduled.blocks"][slot] = False
        for f in ("valid", "movable", "blocks"):
            self._mark(f"scheduled.{f}", slot)
        self._slot_recs[slot] = None
        self._free_slots.append(slot)
        m["nodes.alloc"][ni] -= rec.req
        self._mark("nodes.alloc", ni)
        self._slots_by_node.get(ni, set()).discard(slot)
        if rec.ports:
            plist = self._node_ports.get(ni, [])
            for h in rec.ports:
                try:
                    plist.remove(h)
                except ValueError:
                    pass
            self._refresh_ports(ni)
        for row in self._constrained_rows:
            self._bump_planes_row(row, rec, ni, -1)
        rec.state, rec.slot = "pending", -1  # transient; caller decides next

    def _add_pending(self, rec: _PodRec) -> None:
        rec.state, rec.slot = "pending", -1
        row = rec.row
        if self._row_pending[row] == 0:
            self._pending_rows_changed = True
        self._row_pending[row] += 1
        self._m["specs.count"][row] += 1
        self._mark("specs.count", row)
        self._pending_lists_dirty = True
        if rec.node_name:
            self._waiting.setdefault(rec.node_name, set()).add(rec.key)

    def _remove_pending(self, rec: _PodRec) -> None:
        row = rec.row
        self._row_pending[row] -= 1
        if self._row_pending[row] == 0:
            self._pending_rows_changed = True
        self._m["specs.count"][row] -= 1
        self._mark("specs.count", row)
        self._pending_lists_dirty = True
        if rec.node_name and rec.node_name in self._waiting:
            self._waiting[rec.node_name].discard(rec.key)

    def _owner_adjust(self, rec: _PodRec, delta: int, now) -> None:
        """Track live pods per controller; when a controller's count crosses
        --min-replica-count, reclassify its resident siblings (their
        replicacount verdict flips)."""
        if self.drain_opts.min_replica_count <= 0 or rec.pod.owner is None:
            return
        uid = rec.pod.owner.uid
        old = self._owner_counts.get(uid, 0)
        new = old + delta
        self._owner_counts[uid] = new
        keys = self._owner_keys.setdefault(uid, set())
        if delta > 0:
            keys.add(rec.key)
        else:
            keys.discard(rec.key)
        thr = self.drain_opts.min_replica_count
        if (old < thr) != (new < thr):
            for key in list(keys):
                sib = self._pods.get(key)
                if sib is not None and sib.state == "resident":
                    self._classify_slot(sib.slot, sib.pod, now)

    def _classify_slot(self, slot: int, pod: Pod, now) -> None:
        v = classify_pod(
            pod, self.drain_opts, now=now,
            has_pdb=f"{pod.namespace}/{pod.name}" in self._pdb_names,
            owner_replicas=(self._owner_counts.get(pod.owner.uid)
                            if pod.owner is not None else None))
        m = self._m
        m["scheduled.movable"][slot] = v is Verdict.DRAIN
        m["scheduled.blocks"][slot] = v is Verdict.BLOCK
        self._mark("scheduled.movable", slot)
        self._mark("scheduled.blocks", slot)

    def _refresh_ports(self, ni: int) -> None:
        row = self._m["nodes.used_ports"][ni]
        row[:] = 0
        ports = self._node_ports.get(ni, [])
        if len(ports) > row.shape[0]:
            raise ValueError(
                f"node index {ni}: {len(ports)} occupied hostPorts overflow "
                f"Dims.max_node_ports={row.shape[0]}")
        if ports:
            row[:len(ports)] = np.asarray(ports, np.int32)
        self._mark("nodes.used_ports", ni)

    def _bump_planes_row(self, row: int, rec: _PodRec, ni: int, sign: int
                         ) -> None:
        aff, anti_h, anti_z, spread = resident_plane_hits(
            self._row_encodings[row][1], rec.pod)
        m = self._m
        if aff:
            m["planes.aff_cnt"][row, ni] += sign
            self._mark("planes.aff_cnt", row)
            any_now = bool(m["planes.aff_cnt"][row].sum() > 0) if sign < 0 \
                else True
            if bool(m["specs.aff_match_any"][row]) != any_now:
                m["specs.aff_match_any"][row] = any_now
                self._mark("specs.aff_match_any", row)
        if anti_h:
            m["planes.anti_host_cnt"][row, ni] += sign
            self._mark("planes.anti_host_cnt", row)
        if anti_z:
            m["planes.anti_zone_cnt"][row, ni] += sign
            self._mark("planes.anti_zone_cnt", row)
        if spread:
            m["planes.spread_cnt"][row, ni] += sign
            self._mark("planes.spread_cnt", row)

    def _bump_planes_one(self, row: int, rec: _PodRec, sign: int) -> None:
        ni = self._node_index.get(rec.pod.node_name, -1)
        if ni >= 0:
            self._bump_planes_row(row, rec, ni, sign)

    def _recompute_coupling(self) -> None:
        pending_rows = [r for r in range(self._n_rows)
                        if self._row_pending[r] > 0]
        coupled = cross_group_hostcheck(self._row_encodings, pending_rows)
        m = self._m["specs.needs_host_check"]
        for r in range(self._n_rows):
            want = self._base_lossy[r] or (r in coupled)
            if bool(m[r]) != want:
                m[r] = want
                self._mark("specs.needs_host_check", r)
        self._pending_rows_changed = False

    # ------------------------------------------------------ node plumbing

    def _add_node(self, nd: Node, gid: int) -> None:
        if nd.name in self._node_index:
            raise _ResyncNeeded  # name reuse of a ghost row — recompact
        idx = len(self._node_names)
        if idx >= self._m["nodes.valid"].shape[0]:
            self._grow_nodes(pad_to(idx + 1, self.node_bucket))
        row = encode_node_row(nd, self.registry, self.zone_table, self.dims)
        if len(self.zone_table.ids) + 1 > self.dims.max_zones \
                and self._zones_fit:
            raise _ResyncNeeded("shape_overflow")  # zone overflow flips
            # the encoding mode (apply_zone_overflow drops zone coupling)
        m = self._m
        m["nodes.cap"][idx] = row["cap"]
        m["nodes.alloc"][idx] = 0
        m["nodes.label_hash"][idx] = row["label_hash"]
        m["nodes.taint_exact"][idx] = row["taint_exact"]
        m["nodes.taint_key"][idx] = row["taint_key"]
        m["nodes.used_ports"][idx] = 0
        m["nodes.zone_id"][idx] = row["zone_id"]
        m["nodes.group_id"][idx] = gid
        m["nodes.ready"][idx] = row["ready"]
        m["nodes.schedulable"][idx] = row["schedulable"]
        m["nodes.valid"][idx] = True
        for f in _NODE_FIELDS:
            self._mark(f"nodes.{f}", idx)
        self._node_names.append(nd.name)
        self._node_objs.append(nd)
        self._node_index[nd.name] = idx
        self._node_recs[nd.name] = _NodeRec(nd, idx, _node_fp(nd), gid)

    def _update_node(self, rec: _NodeRec, nd: Node, fp: tuple) -> None:
        idx = rec.idx
        row = encode_node_row(nd, self.registry, self.zone_table, self.dims)
        if len(self.zone_table.ids) + 1 > self.dims.max_zones \
                and self._zones_fit:
            raise _ResyncNeeded("shape_overflow")
        m = self._m
        for f, v in (("cap", row["cap"]), ("label_hash", row["label_hash"]),
                     ("taint_exact", row["taint_exact"]),
                     ("taint_key", row["taint_key"]),
                     ("zone_id", row["zone_id"]), ("ready", row["ready"]),
                     ("schedulable", row["schedulable"])):
            m[f"nodes.{f}"][idx] = v
            self._mark(f"nodes.{f}", idx)
        rec.node, rec.fp = nd, fp
        self._node_objs[idx] = nd

    def _remove_node(self, rec: _NodeRec) -> None:
        idx = rec.idx
        m = self._m
        m["nodes.valid"][idx] = False
        m["nodes.alloc"][idx] = 0
        m["nodes.used_ports"][idx] = 0
        for f in ("valid", "alloc", "used_ports"):
            self._mark(f"nodes.{f}", idx)
        # residents fall back to pending, waiting for the node to return
        for slot in list(self._slots_by_node.get(idx, ())):
            prec = self._slot_recs[slot]
            if prec is None:
                continue
            self._remove_resident(prec)
            self._add_pending(prec)
        self._slots_by_node.pop(idx, None)
        self._node_ports.pop(idx, None)
        self._node_objs[idx] = None
        # tombstone the ghost row's name so a later re-add of the same node
        # name cannot leave a duplicate in the row-aligned name list
        self._node_names[idx] = f"\x00gone:{idx}:{rec.node.name}"
        del self._node_index[rec.node.name]
        del self._node_recs[rec.node.name]

    def _realign_nodes(self, nodes: list[Node]) -> None:
        """Permute node rows to match the source list order, dropping ghost
        rows (vectorized; runs only on node-churn loops). Everything indexed
        by node row — planes columns, scheduled.node_idx, slot/port maps —
        is remapped with it."""
        old_n = self._m["nodes.valid"].shape[0]
        perm = np.array([self._node_recs[nd.name].idx for nd in nodes],
                        np.int64)
        m = self._m
        for f in _NODE_FIELDS:
            k = f"nodes.{f}"
            old = m[k]
            new = np.full_like(old, -1 if f == "group_id" else 0)
            if len(perm):
                new[:len(perm)] = old[perm]
            m[k] = new
            self.device_store.mark_all(k)
        for f in _PLANE_FIELDS:
            k = f"planes.{f}"
            old = m[k]
            new = np.zeros_like(old)
            if len(perm):
                new[:, :len(perm)] = old[:, perm]
            m[k] = new
            self.device_store.mark_all(k)
        remap = np.full((old_n,), -1, np.int64)
        remap[perm] = np.arange(len(perm))
        ni = m["scheduled.node_idx"]
        m["scheduled.node_idx"] = np.where(
            ni >= 0, remap[np.clip(ni, 0, old_n - 1)], -1).astype(ni.dtype)
        self.device_store.mark_all("scheduled.node_idx")
        self._slots_by_node = {
            int(remap[i]): s for i, s in self._slots_by_node.items()
            if remap[i] >= 0}
        self._node_ports = {
            int(remap[i]): p for i, p in self._node_ports.items()
            if remap[i] >= 0}
        self._node_names = [nd.name for nd in nodes]
        self._node_objs = [self._node_recs[nd.name].node for nd in nodes]
        self._node_index = {nd.name: i for i, nd in enumerate(nodes)}
        for i, nd in enumerate(nodes):
            self._node_recs[nd.name].idx = i

    # --------------------------------------------------------------- growth

    def _grow_nodes(self, new_n: int) -> None:
        self.grew_this_loop = True
        for f in _NODE_FIELDS:
            k = f"nodes.{f}"
            self._m[k] = _grow_axis0(self._m[k], new_n,
                                     fill=-1 if f == "group_id" else 0)
            self.device_store.mark_all(k)
        for f in _PLANE_FIELDS:
            k = f"planes.{f}"
            old = self._m[k]
            grown = np.zeros((old.shape[0], new_n), old.dtype)
            grown[:, :old.shape[1]] = old
            self._m[k] = grown
            self.device_store.mark_all(k)

    def _grow_specs(self, new_g: int) -> None:
        self.grew_this_loop = True
        for f in _SPEC_FIELDS:
            k = f"specs.{f}"
            self._m[k] = _grow_axis0(self._m[k], new_g)
            self.device_store.mark_all(k)
        for f in _PLANE_FIELDS:
            k = f"planes.{f}"
            self._m[k] = _grow_axis0(self._m[k], new_g)
            self.device_store.mark_all(k)

    def _grow_scheduled(self, new_p: int) -> None:
        self.grew_this_loop = True
        for f in _SCHED_FIELDS:
            k = f"scheduled.{f}"
            self._m[k] = _grow_axis0(self._m[k], new_p,
                                     fill=-1 if f == "node_idx" else 0)
            self.device_store.mark_all(k)
        self._slot_recs.extend([None] * (new_p - len(self._slot_recs)))

    # -------------------------------------------------------------- handout

    def _mark(self, key: str, row: int) -> None:
        self.device_store.mark(key, row)

    def _upload(self, key: str):
        # scatter-vs-replace choice, byte accounting and the delta-program
        # record live in the residency layer (world_store.DevicePlaneStore)
        return self.device_store.upload(key, self._m[key])

    def _handout(self) -> EncodedCluster:
        if self._pending_lists_dirty:
            pending: list[Pod] = []
            group_pods: list[list[int]] = [[] for _ in range(
                self._m["specs.valid"].shape[0])]
            for rec in self._pods.values():
                if rec.state == "pending":
                    group_pods[rec.row].append(len(pending))
                    pending.append(rec.pod)
            self._cached_pending = pending
            self._cached_group_pods = group_pods
            self._pending_lists_dirty = False

        nodes = NodeTensors(**{f: self._upload(f"nodes.{f}")
                               for f in _NODE_FIELDS})
        specs = PodGroupTensors(**{f: self._upload(f"specs.{f}")
                                   for f in _SPEC_FIELDS})
        scheduled = ScheduledPodTensors(**{f: self._upload(f"scheduled.{f}")
                                           for f in _SCHED_FIELDS})
        planes = AffinityPlanes(**{f: self._upload(f"planes.{f}")
                                   for f in _PLANE_FIELDS})
        # close the loop's delta program (publishes last_actions +
        # last_h2d_bytes for the WorldStore's mode classification)
        self.device_store.finish_loop()
        token = self.device_store.token()  # array objects, compared with `is`
        return EncodedCluster(
            nodes=nodes, specs=specs, scheduled=scheduled,
            node_names=list(self._node_names),
            node_index=dict(self._node_index),
            zone_table=self.zone_table,
            registry=self.registry,
            dims=self.dims,
            group_pods=self._cached_group_pods,
            pending_pods=self._cached_pending,
            scheduled_pods=[r.pod if r is not None else None
                            for r in self._slot_recs],
            planes=planes,
            has_constraints=bool(self._constrained_rows),
            node_objs=list(self._node_objs),
            namespaces=self._namespaces,
            host_arrays=self._m,
            host_mirror_token=token,
        )


class _ResyncNeeded(Exception):
    """Internal: structural change the delta path does not model — fall back
    to a full encode (same result, just slower this one loop). `reason` is
    the WorldStore's cause label: "shape_overflow" when the encoding's
    static shape assumptions broke (zone-table overflow flips the encoding
    mode), "forced" for malformed-source structural falls (duplicate names,
    ghost-row reuse)."""

    def __init__(self, reason: str = "forced"):
        super().__init__(reason)
        self.reason = reason


def _grow_axis0(a: np.ndarray, new_n: int, fill=0) -> np.ndarray:
    out = np.full((new_n,) + a.shape[1:], fill, a.dtype)
    out[:a.shape[0]] = a
    return out
