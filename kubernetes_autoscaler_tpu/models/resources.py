"""Fixed resource-vector layout for the tensorized cluster state.

The reference models resources as a string→quantity map (k8s resource.Quantity,
consumed via the vendored scheduler's NodeResourcesFit plugin and CA's own
utilization math, cluster-autoscaler/simulator/utilization/info.go:50). The TPU
plane instead fixes a dense int32 vector of length NUM_RESOURCES per node/pod:

  slot 0  cpu        (millicores;   reference uses milli-units throughout)
  slot 1  memory     (MiB)
  slot 2  ephemeral  (MiB)
  slot 3  pods       (count; every pod implicitly requests 1 — mirrors the
                      scheduler's v1.ResourcePods capacity check)
  slots 4..  extended resources (count), mapped by a per-snapshot registry
             (e.g. nvidia.com/gpu, google.com/tpu — reference GPU handling in
              cluster-autoscaler/utils/gpu/ and cloudprovider GpuConfig)

int32 + integer units keeps comparisons exact on the MXU-adjacent VPU (float
rounding could overcommit memory). Quantization is conservative: requests round
UP, capacities round DOWN, so the tensor plane never admits a pod the exact
(reference) check would reject.
"""

from __future__ import annotations

from dataclasses import dataclass, field

CPU, MEMORY, EPHEMERAL, PODS = 0, 1, 2, 3
NUM_STANDARD = 4
NUM_EXTENDED = 4          # default extended-resource slots
NUM_RESOURCES = NUM_STANDARD + NUM_EXTENDED

_MIB = 1024 * 1024


def cpu_request_to_milli(cores: float) -> int:
    """Requests round UP (conservative: simulated pod never under-requests)."""
    import math

    return math.ceil(cores * 1000 - 1e-9)


def cpu_capacity_to_milli(cores: float) -> int:
    """Capacities round DOWN (conservative: simulated node never over-offers)."""
    return int(cores * 1000 + 1e-9)


def mem_request_to_mib(bytes_: float) -> int:
    """Requests round UP (conservative: simulated pod never under-requests)."""
    return -(-int(bytes_) // _MIB)


def mem_capacity_to_mib(bytes_: float) -> int:
    """Capacities round DOWN (conservative: simulated node never over-offers)."""
    return int(bytes_) // _MIB


@dataclass
class ExtendedResourceRegistry:
    """Maps extended-resource names (e.g. 'nvidia.com/gpu') to tensor slots.

    Per-snapshot, first-come-first-served. Unknown resources beyond capacity
    raise — the encoder then marks the pod for host-side exact checking rather
    than silently dropping a constraint.
    """

    slots: dict[str, int] = field(default_factory=dict)
    capacity: int = NUM_EXTENDED

    def slot_for(self, name: str) -> int:
        if name in self.slots:
            return self.slots[name]
        if len(self.slots) >= self.capacity:
            raise KeyError(f"extended-resource slots exhausted; cannot map {name!r}")
        idx = NUM_STANDARD + len(self.slots)
        self.slots[name] = idx
        return idx

    def try_slot_for(self, name: str) -> int | None:
        """slot_for that reports exhaustion instead of raising; callers flag the
        pod/node for host-side exact checking (the documented lossy path)."""
        try:
            return self.slot_for(name)
        except KeyError:
            return None

    def known(self, name: str) -> bool:
        return name in self.slots
