"""Device-resident incremental world state: the WorldStore.

ROADMAP item 3 (the stated unlock for batched serving and the Pallas
kernels): every RunOnce used to re-encode the world from host objects and
re-upload multi-megabyte planes — `encoder_full_encodes` was tracked as a
recompile-risk event, but full encodes were the NORM. This module makes the
encoded planes (requests, capacities, selector/taint bitplanes, group
tensors) RESIDENT on the device across loops and turns each RunOnce into a
small *delta program*: a batch of row-scatter updates derived from the same
listing-order, object-identity loop diff the flight journal records
(utils/canonical.py is the shared vocabulary — journal and store agree on
what "changed" by construction). Reference analog: the snapshot-diffing
ClusterSnapshot (DeltaSnapshotStore, store/delta.go:33-54), applied one
level further down — at the host↔device boundary.

Layering (docs/WORLD_STORE.md):

  * `DevicePlaneStore` — the residency layer. Owns the device shadow of the
    encoder's host mirrors: per-plane jax arrays, dirty-row tracking, and
    the upload path that picks per plane between `cached` (untouched — zero
    bytes), `scatter` (a bucketed row batch via `cached.at[idx].set(rows)`
    — kilobytes), and `replace` (whole-plane upload — growth, realign, or a
    dirty set too large to scatter). Every byte that crosses the tunnel is
    counted (`world_store_h2d_bytes_total`).
  * `IncrementalEncoder` (models/incremental.py) — the diff layer. Computes
    the loop's object-identity delta and mutates the host mirrors, marking
    rows into the DevicePlaneStore it owns.
  * `WorldStore` — the decision + accounting wrapper the control loop
    holds. Classifies every loop into one of three modes with a cause,
    emitted as `encoder_encodes_total{mode,cause}`:

      mode=delta        the norm: resident planes patched by scatters only
      mode=row_refresh  ≥1 plane took a whole-plane replacement upload
                        (shape growth past the padded bucket, node realign,
                        or an oversized dirty set) while the rest stayed
                        resident
      mode=full         the world re-lowered from host objects —
                        cause=initial (first loop), fingerprint_miss (an
                        out-of-band lowering pass or a failed runtime
                        verify invalidated the identity fingerprints),
                        shape_overflow (the encoding's static shape
                        assumptions broke, e.g. zone-table overflow), or
                        forced (periodic resync / malformed source listing)

The composition fingerprint extends PR 1's marshal-artifact fingerprint to
the entire encode path: `composition_fingerprint()` digests the canonical
world through the same object-identity cache the journal uses (O(churn)
per loop), and `plane_digests()` exposes per-plane content digests for the
bit-identity property suite (tests/test_world_store.py) and drift triage.
"""

from __future__ import annotations

import hashlib

import numpy as np

from kubernetes_autoscaler_tpu.metrics import device
from kubernetes_autoscaler_tpu.utils.canonical import canon_map, digest_strs

MODES = ("delta", "row_refresh", "full")
CAUSES = ("initial", "fingerprint_miss", "shape_overflow", "forced", "churn",
          "device_lost", "audit_divergence")

ENCODES_HELP = ("World encodes by mode (delta = resident planes patched by "
                "row scatters; row_refresh = ≥1 whole-plane re-upload; "
                "full = re-lowered from host objects) and cause")
H2D_HELP = ("Host→device bytes moved by the world store (delta scatters + "
            "plane replacements + full-encode seeds) — the world-state "
            "companion of the PR 6 device_transfer/batched_fetch counters")

# scatter batches pad to a shape bucket so the XLA scatter stays
# compile-cached across loops (idx length varies per loop; a fresh shape
# would recompile ~50 ms each — the same trap the sim kernels avoid with
# bucketed padding; buckets grow ×4, so a plane sees ≤ 3-4 distinct
# programs ever). Duplicate trailing indices write the same value twice:
# harmless. 16 floors the bucket: steady churn touches a handful of rows
# and the padding is pure wasted tunnel bytes.
_SCATTER_BUCKET = 16


def _scatter_set(arr, idx, rows):
    return arr.at[idx].set(rows)


_scatter_jit = None


def _scatter():
    """The one scatter program, jitted lazily (importing this module must
    not touch the backend). Dispatching through the jit cache instead of
    eager `at[].set` tracing cuts per-plane dispatch from ~0.5 ms to ~10 µs
    — with ~10 dirty planes per churn loop that is the difference between a
    delta program and a full re-encode on the wall clock."""
    global _scatter_jit
    if _scatter_jit is None:
        import jax

        _scatter_jit = jax.jit(_scatter_set)
    return _scatter_jit


class DevicePlaneStore:
    """Device shadow of the encoder's host mirrors, patched by delta programs.

    Not thread-safe by design: owned by the control-loop thread like the
    encoder's mirrors themselves."""

    def __init__(self):
        self._dev: dict[str, object] = {}
        self._dirty: set[str] = set()
        self._dirty_rows: dict[str, set[int] | None] = {}
        # per-loop delta program record: key -> (kind, n_rows, bytes)
        self.last_actions: dict[str, tuple] = {}
        self._actions: dict[str, tuple] = {}
        self.last_h2d_bytes = 0
        self.h2d_bytes_total = 0
        self.scatter_uploads = 0
        self.replace_uploads = 0
        self.seed_uploads = 0

    # ---- seeding (full-encode loops) ----

    def seed(self, devs: dict, seed_bytes: int = 0) -> None:
        """Adopt the device arrays a full encode already uploaded (identical
        content — re-uploading would double the seed-loop tunnel cost) and
        reset all dirty state. `seed_bytes` charges the full encode's own
        uploads to the h2d meter."""
        self._dev = dict(devs)
        self._dirty.clear()
        self._dirty_rows.clear()
        self._actions = {}
        if seed_bytes:
            self.seed_uploads += 1
            self._charge("(seed)", ("seed", 0, int(seed_bytes)))
        # HBM residency ledger (metrics/device.py): the resident world
        # planes are the control loop's largest standing device allocation
        if device.LEDGER is not None:
            for key, dev in self._dev.items():
                device.LEDGER.track("world_store", key, dev)

    # ---- dirty tracking (the delta program under construction) ----

    def mark(self, key: str, row: int) -> None:
        self._dirty.add(key)
        rows = self._dirty_rows.get(key, _UNSET)
        if rows is _UNSET:
            self._dirty_rows[key] = {row}
        elif rows is not None:
            rows.add(row)

    def mark_all(self, key: str) -> None:
        """Whole-plane invalidation (growth, realign): the next upload
        replaces the resident array."""
        self._dirty.add(key)
        self._dirty_rows[key] = None

    # ---- the upload path ----

    def upload(self, key: str, mirror: np.ndarray):
        """Device array for `key`, applying the cheapest sufficient action:
        cached (clean) → scatter (small dirty row set) → replace."""
        import jax.numpy as jnp

        if key not in self._dirty:
            cached = self._dev.get(key)
            if cached is not None:
                return cached
        rows = self._dirty_rows.get(key)
        cached = self._dev.get(key)
        if (cached is not None and rows is not None
                and cached.shape == mirror.shape
                and 0 < len(rows) <= max(64, mirror.shape[0] // 16)):
            idx = np.fromiter(rows, np.int32, len(rows))
            bucket = _SCATTER_BUCKET
            while bucket < len(idx):
                bucket *= 4
            idx = np.concatenate(
                [idx, np.full(bucket - len(idx), idx[0], np.int32)])
            payload = mirror[idx]
            dev = _scatter()(cached, jnp.asarray(idx), jnp.asarray(payload))
            self.scatter_uploads += 1
            self._charge(key, ("scatter", len(rows),
                               int(payload.nbytes) + int(idx.nbytes)))
        else:
            dev = jnp.asarray(mirror)
            self.replace_uploads += 1
            self._charge(key, ("replace", int(mirror.shape[0]),
                               int(mirror.nbytes)))
        self._dev[key] = dev
        if device.LEDGER is not None:
            device.LEDGER.track("world_store", key, dev)
        return dev

    def _charge(self, key: str, action: tuple) -> None:
        self._actions[key] = action
        self.h2d_bytes_total += action[2]

    def finish_loop(self) -> dict[str, tuple]:
        """Close the loop's delta program: clear dirty state, publish the
        action record and its byte total (`last_actions`/`last_h2d_bytes`)."""
        self._dirty.clear()
        self._dirty_rows.clear()
        self.last_actions, self._actions = self._actions, {}
        self.last_h2d_bytes = sum(a[2] for a in self.last_actions.values())
        return self.last_actions

    def token(self) -> dict:
        """Device-array identity token for the handout (compared with `is`
        by mirror-aware readers — EncodedCluster.host_mirror_token)."""
        return dict(self._dev)

    def verify_against(self, mirrors: dict) -> list[str]:
        """Digest-probe the resident device planes against the host
        mirrors: fetch each device shadow and compare shape/dtype/bytes.
        Returns the keys that diverged — or, when the fetch itself dies
        (a device restart freed the buffers), EVERY resident key, because
        nothing on the device can be trusted. Only runs after a backend
        incident (WorldStore.heal), never on the hot path."""
        lost: list[str] = []
        for key, dev in sorted(self._dev.items()):
            mirror = mirrors.get(key)
            if mirror is None:
                continue
            try:
                host = np.asarray(dev)
                same = (host.shape == mirror.shape
                        and host.dtype == mirror.dtype
                        and host.tobytes() == np.ascontiguousarray(
                            mirror).tobytes())
            except Exception:  # noqa: BLE001 — dead buffer == lost plane
                return sorted(self._dev.keys())
            if not same:
                lost.append(key)
        return lost

    def drop_device_state(self) -> None:
        """Forget every resident device array (device loss): the next full
        encode reseeds from scratch instead of scattering into corpses."""
        self._dev.clear()
        self._dirty.clear()
        self._dirty_rows.clear()
        if device.LEDGER is not None:
            device.LEDGER.release(owner="world_store")

    def stats(self) -> dict:
        return {
            "h2dBytesTotal": self.h2d_bytes_total,
            "lastH2dBytes": self.last_h2d_bytes,
            "scatterUploads": self.scatter_uploads,
            "replaceUploads": self.replace_uploads,
            "seedUploads": self.seed_uploads,
        }


_UNSET = object()


class WorldStore:
    """The control loop's handle on resident world state: wraps the
    incremental encoder, classifies each loop's encode mode, and emits the
    reasoned counters (`encoder_encodes_total{mode,cause}`,
    `world_store_h2d_bytes_total`) into an attached metrics Registry."""

    def __init__(self, registry=None, **encoder_kwargs):
        from kubernetes_autoscaler_tpu.models.incremental import (
            IncrementalEncoder,
        )

        self.encoder = IncrementalEncoder(**encoder_kwargs)
        self.registry = registry
        self.last_mode: str | None = None
        self.last_cause: str | None = None
        self.last_h2d_bytes = 0
        self.mode_counts: dict[tuple, int] = {}
        # object-identity canonical cache for the composition fingerprint —
        # the SAME cache shape the journal rides (utils/canonical.canon_map)
        self._canon_nodes: dict[int, tuple] = {}
        self._canon_pods: dict[int, tuple] = {}

    # convenience passthroughs --------------------------------------------

    @property
    def drain_opts(self):
        return self.encoder.drain_opts

    @property
    def device_store(self) -> DevicePlaneStore:
        return self.encoder.device_store

    def invalidate(self) -> None:
        self.encoder.invalidate()

    # the per-loop entry point --------------------------------------------

    def encode(self, nodes, pods, **kw):
        e = self.encoder
        full_before = e.full_encodes
        enc = e.encode(nodes, pods, **kw)
        actions = e.device_store.last_actions
        if e.full_encodes > full_before:
            mode, cause = "full", (e.last_full_cause or "forced")
        elif any(a[0] == "replace" for a in actions.values()):
            mode = "row_refresh"
            cause = "shape_overflow" if e.grew_this_loop else "churn"
        else:
            mode, cause = "delta", "churn"
        self.last_mode, self.last_cause = mode, cause
        self.last_h2d_bytes = e.device_store.last_h2d_bytes
        self.mode_counts[(mode, cause)] = \
            self.mode_counts.get((mode, cause), 0) + 1
        if self.registry is not None:
            self.registry.counter("encoder_encodes_total",
                                  help=ENCODES_HELP).inc(mode=mode,
                                                         cause=cause)
            self.registry.counter("world_store_h2d_bytes_total",
                                  help=H2D_HELP).inc(self.last_h2d_bytes)
        return enc

    # self-healing ---------------------------------------------------------

    def heal(self, force: bool = False) -> dict:
        """Post-incident residency audit (docs/ROBUSTNESS.md "Control
        loop"): digest-probe every resident device plane against its host
        mirror. Intact planes keep their residency (the incident was a
        hang, not a loss); any divergence or a dead buffer means the
        device restarted underneath us — drop the device state and force
        the next encode full with cause="device_lost", so the loop sims
        against a cold re-lowered world instead of stale planes. Decisions
        after the rebuild are bit-identical to a cold encode (pinned by
        tests/test_supervisor.py).

        `force` is the shadow-audit path (audit/shadow.py): a divergence
        proved the device computes WRONG bits even though every resident
        plane may digest-match its mirror (a miscompiled kernel corrupts
        outputs, not inputs) — drop the device state and rebuild anyway,
        with cause="audit_divergence", so the single re-audit of the same
        sample runs against a cold re-encode."""
        e = self.encoder
        if not getattr(e, "_seeded", False):
            # nothing resident (pre-first-encode, or already invalidated):
            # the next encode is full anyway
            return {"outcome": "not-resident", "lostPlanes": []}
        lost = e.device_store.verify_against(e._m)
        if not lost and not force:
            return {"outcome": "intact", "lostPlanes": []}
        e.device_store.drop_device_state()
        e.invalidate(cause="device_lost" if lost else "audit_divergence")
        return {"outcome": "rebuilt" if lost else "rebuilt-forced",
                "lostPlanes": lost}

    # fingerprints ---------------------------------------------------------

    def composition_fingerprint(self, nodes, pods) -> str:
        """Order-sensitive digest of the input world through the journal's
        OWN canonicalization and identity cache (O(churn) per loop): equal
        fingerprints ⇒ the journal would emit an empty delta ⇒ the store's
        delta program is empty too — one definition of "changed"."""
        from kubernetes_autoscaler_tpu.replay.journal import (
            node_to_dict,
            pod_to_dict,
        )

        self._canon_nodes, node_map = canon_map(
            nodes, lambda nd: nd.name, node_to_dict, self._canon_nodes)
        self._canon_pods, pod_map = canon_map(
            pods, lambda p: f"{p.namespace}/{p.name}", pod_to_dict,
            self._canon_pods)
        return digest_strs(["N", *node_map.values(),
                            "P", *pod_map.values()])

    def plane_digests(self) -> dict[str, str]:
        """Per-plane sha256/16 over the host mirrors — the content identity
        of the resident planes (the bit-identity property suite compares
        these against a cold full encode; a resident device plane always
        matches its mirror bit-for-bit, which the suite also pins)."""
        return {
            key: hashlib.sha256(
                np.ascontiguousarray(arr).tobytes()).hexdigest()[:16]
            for key, arr in sorted(self.encoder._m.items())
        }

    def stats(self) -> dict:
        return {
            "modes": {f"{m}/{c}": n
                      for (m, c), n in sorted(self.mode_counts.items())},
            "fullEncodes": self.encoder.full_encodes,
            "lastMode": self.last_mode,
            "lastCause": self.last_cause,
            **self.encoder.device_store.stats(),
        }
